"""Per-node gradient histograms on TPU.

The hot op of the whole framework: for each tree level, accumulate
(grad, hess) into a (node x feature x bin) tensor. This replaces libxgboost's
OpenMP hist builder + Rabit allreduce (reference hot loop at
algorithm_mode/train.py:367-376 -> C++): here it is a single
``jax.ops.segment_sum`` over a flattened (node, feature, bin) index — XLA
lowers it to a sorted scatter-add — followed by an optional ``lax.psum`` over
the data-parallel mesh axis, which is the entire multi-host story (SURVEY.md
§2.3 row 1).

Index layout: seg = (node_local * d + feature) * B + bin, with one extra
trash segment for rows whose node is already finalized (node_local < 0).
"""

import os

import jax
import jax.numpy as jnp

# "flat" (default): one segment_sum over n*d flattened (node,feature,bin) ids.
# "per_feature": d segment_sums over n with (node,bin) ids — smaller key
# space per sort, no [n, d] id materialization; A/B-able on hardware without
# code changes.
HIST_IMPL = os.environ.get("GRAFT_HIST_IMPL", "flat")


def level_histogram(bins, grad, hess, node_local, num_nodes, num_bins, axis_name=None):
    """Build (G, H) histograms for one tree level.

    Args:
      bins: i32 [n, d] bin indices (missing bin included in num_bins).
      grad, hess: f32 [n].
      node_local: i32 [n]; position of the row's node within this level,
        or negative when the row no longer participates.
      num_nodes: static int — number of nodes at this level (2**level).
      num_bins: static int — histogram width per feature (max_bin + 1).
      axis_name: mesh axis to psum over, or None on a single device.

    Returns:
      (G, H): f32 [num_nodes, d, num_bins].
    """
    n, d = bins.shape
    active = node_local >= 0
    # inactive rows land in the trailing trash segment
    safe_node = jnp.where(active, node_local, num_nodes)

    if HIST_IMPL == "per_feature":
        seg_base = safe_node * num_bins            # [n]
        trash = num_nodes * num_bins
        num_segments = trash + 1
        Gs, Hs = [], []
        for f in range(d):
            seg_f = jnp.where(active, seg_base + bins[:, f], trash)
            Gs.append(jax.ops.segment_sum(grad, seg_f, num_segments=num_segments)[:-1])
            Hs.append(jax.ops.segment_sum(hess, seg_f, num_segments=num_segments)[:-1])
        G = jnp.stack(Gs, axis=1).reshape(num_nodes, num_bins, d).transpose(0, 2, 1)
        H = jnp.stack(Hs, axis=1).reshape(num_nodes, num_bins, d).transpose(0, 2, 1)
        if axis_name is not None:
            G = jax.lax.psum(G, axis_name)
            H = jax.lax.psum(H, axis_name)
        return G, H

    seg = (safe_node[:, None] * d + jnp.arange(d, dtype=jnp.int32)[None, :]) * num_bins + bins
    seg = jnp.where(active[:, None], seg, num_nodes * d * num_bins)
    num_segments = num_nodes * d * num_bins + 1

    flat_seg = seg.reshape(-1)
    # two 1-D passes: the fused [n*d, 2] segment_sum variant compiles
    # pathologically on the TPU toolchain (multi-minute hang), so G and H go
    # through separate scatter-adds
    g_flat = jnp.broadcast_to(grad[:, None], (n, d)).reshape(-1)
    h_flat = jnp.broadcast_to(hess[:, None], (n, d)).reshape(-1)
    G = jax.ops.segment_sum(g_flat, flat_seg, num_segments=num_segments)
    H = jax.ops.segment_sum(h_flat, flat_seg, num_segments=num_segments)
    G = G[:-1].reshape(num_nodes, d, num_bins)
    H = H[:-1].reshape(num_nodes, d, num_bins)
    if axis_name is not None:
        G = jax.lax.psum(G, axis_name)
        H = jax.lax.psum(H, axis_name)
    return G, H
