"""Per-node gradient histograms on TPU.

The hot op of the whole framework: for each tree level, accumulate
(grad, hess) into a (node x feature x bin) tensor. This replaces libxgboost's
OpenMP hist builder + Rabit allreduce (reference hot loop at
algorithm_mode/train.py:367-376 -> C++), followed by an optional
``lax.psum`` over the data-parallel mesh axis, which is the entire
multi-host story (SURVEY.md §2.3 row 1).

Four interchangeable implementations (``GRAFT_HIST_IMPL``), A/B-able on
hardware without code changes:

* ``flat`` (default): one ``jax.ops.segment_sum`` over n*d flattened
  (node, feature, bin) ids. XLA lowers it to a sorted scatter-add —
  correct everywhere, fast on CPU, scatter-bound on TPU.
* ``per_feature``: d segment_sums over n with (node, bin) ids — smaller key
  space per sort, no [n, d] id materialization.
* ``matmul``: one-hot matmul formulation for the MXU — histograms become
  [2W, chunk] @ [chunk, B] dots (grad/hess stacked along the node axis),
  scanned over row chunks. No scatter at all; bandwidth-bound on the
  materialized bin one-hots.
* ``pallas``: the matmul formulation as a Pallas TPU kernel — per-block bin
  one-hots live only in VMEM (never HBM), accumulator resident in VMEM
  across the row-block grid. Compute-bound; bf16x2 split-precision operands
  (hi/lo decomposition of f32 grads) keep MXU rate with ~f16-mantissa
  accuracy, accumulated in f32.
"""

import collections
import functools
import os

import jax
import jax.numpy as jnp

from ..utils.envconfig import env_int

# Session-build-time snapshot of every histogram/scan/routing tuning knob.
# Trace-safety contract (graftlint trace-env-read, docs/static-analysis.md):
# the jitted round path must not read env — the training session resolves
# one HistKnobs via resolve_hist_knobs() when it builds the round closure
# (the PR-4 GRAFT_HIST_COMM pattern) and threads it through the builders.
# The per-knob env fallbacks below remain the documented API for DIRECT
# callers only (unit tests, bench probes A/B-ing a single kernel).
HistKnobs = collections.namedtuple(
    "HistKnobs",
    [
        "impl",          # GRAFT_HIST_IMPL (backend-aware default)
        "totals_impl",   # GRAFT_TOTALS_IMPL (backend-aware default)
        "route_impl",    # GRAFT_ROUTE_IMPL (ops/tree_build.row_bin_lookup)
        "matmul_chunk",  # GRAFT_HIST_CHUNK
        "pallas_block",  # GRAFT_HIST_BLOCK
        "precision",     # GRAFT_HIST_MM_PREC
        "align",         # GRAFT_HIST_ALIGN
        "vnodes",        # GRAFT_HIST_VNODES
        "vnode_vmem",    # GRAFT_VNODE_VMEM
        "subtract",      # GRAFT_HIST_SUBTRACT
        "subtract_mem",  # GRAFT_SUBTRACT_MEM
        "comm_overlap",  # GRAFT_HIST_OVERLAP
    ],
)


def resolve_hist_knobs():
    """Resolve every histogram-path knob from env ONCE, host-side.

    Call at session build time (models/booster.py), never from code that
    can run under trace: the snapshot is what keeps every shard — and
    every re-trace — seeing identical knob values for the session's life.
    """
    return HistKnobs(
        impl=_impl(),
        totals_impl=_totals_impl(),
        route_impl=os.environ.get("GRAFT_ROUTE_IMPL", "gather"),
        matmul_chunk=_matmul_chunk(),
        pallas_block=_pallas_block(),
        precision=_matmul_precision(),
        align=os.environ.get("GRAFT_HIST_ALIGN", "1") == "1",
        vnodes=os.environ.get("GRAFT_HIST_VNODES", "1") == "1",
        vnode_vmem=env_int("GRAFT_VNODE_VMEM", 4 * 1024 * 1024, minimum=0),
        subtract=os.environ.get("GRAFT_HIST_SUBTRACT", "1") == "1",
        subtract_mem=env_int("GRAFT_SUBTRACT_MEM", 512 * 1024 * 1024, minimum=0),
        comm_overlap=_comm_overlap(),
    )


def _impl():
    """Backend-aware default: the pallas one-hot matmul kernel is the
    measured TPU winner (BASELINE.md round-2 probes: pallas 3.15 r/s vs
    flat 0.265 on the bench config); the flat segment-sum wins on CPU.
    GRAFT_HIST_IMPL overrides either way."""
    # graftlint: disable=trace-env-read — direct-caller fallback only;
    # sessions snapshot this via resolve_hist_knobs() at build time
    v = os.environ.get("GRAFT_HIST_IMPL")
    if v:
        return v
    return "pallas" if jax.default_backend() == "tpu" else "flat"


def _totals_impl():
    """Backend-aware GRAFT_TOTALS_IMPL default (see node_totals)."""
    # graftlint: disable=trace-env-read — direct-caller fallback only;
    # sessions snapshot this via resolve_hist_knobs() at build time
    impl = os.environ.get("GRAFT_TOTALS_IMPL")
    if not impl:
        impl = "onehot" if jax.default_backend() == "tpu" else "segment"
    return impl


def _matmul_chunk():
    # graftlint: disable=trace-env-read — direct-caller fallback only;
    # sessions snapshot this via resolve_hist_knobs() at build time
    return env_int("GRAFT_HIST_CHUNK", 65536, minimum=1)


def _balanced_chunks(n, chunk_rows=None):
    """(chunk, steps) for scanning n rows in ~GRAFT_HIST_CHUNK-row chunks.

    Balanced: caps padding waste at steps-1 rows instead of a nearly full
    chunk when n slightly exceeds a multiple of the configured size.
    Requires n >= 1.
    """
    if chunk_rows is None:
        chunk_rows = _matmul_chunk()
    steps_wanted = -(-n // min(chunk_rows, n))
    chunk = -(-n // steps_wanted)
    return chunk, -(-n // chunk)


def _pallas_block():
    # graftlint: disable=trace-env-read — direct-caller fallback only;
    # sessions snapshot this via resolve_hist_knobs() at build time
    return env_int("GRAFT_HIST_BLOCK", 512, minimum=1)


def _matmul_precision():
    """f32 | bf16x2 | bf16 for matmul/pallas operand precision."""
    # graftlint: disable=trace-env-read — direct-caller fallback only;
    # sessions snapshot this via resolve_hist_knobs() at build time
    return os.environ.get("GRAFT_HIST_MM_PREC", "bf16x2")


def _comm_overlap():
    """GRAFT_HIST_OVERLAP: pipeline the per-level histogram collectives.

    When enabled (default), a tree level's node axis is split into two
    independent collective -> split-scan batches (overlap_node_batches), so
    the collective for the second node batch is in flight while the first
    batch's gain scan runs — XLA's latency-hiding scheduler can overlap
    the wire time with compute. Values are bit-identical either way: each
    node's histogram is reduced whole by exactly one collective in the
    same shard order. ``0`` restores the single fused per-level collective
    (A/B lever; also the fallback if a backend's scheduler serializes the
    split collectives poorly).
    """
    # graftlint: disable=trace-env-read — direct-caller fallback only;
    # sessions snapshot this via resolve_hist_knobs() at build time
    return os.environ.get("GRAFT_HIST_OVERLAP", "1") == "1"


def overlap_node_batches(num_nodes, enabled):
    """Node-axis batching schedule for the pipelined level collective.

    Returns the list of contiguous node slices whose histograms are
    reduced (and gain-scanned) as independent collective -> scan chains.
    With overlap disabled, or fewer than 2 nodes, the whole level is one
    batch — the exact dataflow of the unpipelined path.
    """
    if not enabled or num_nodes < 2:
        return [slice(0, num_nodes)]
    half = num_nodes // 2
    return [slice(0, half), slice(half, num_nodes)]


def apply_hist_collective(G, H, axis_name, comm, axis_size):
    """Reduce (G, H) level histograms across the data axis.

    The collective tail of :func:`level_histogram`, split out so the
    builders can issue it per node batch (overlap_node_batches): ``psum``
    allreduces the full payload, ``reduce_scatter`` psum_scatters along the
    feature dim (scatter_histograms). No-op when ``axis_name`` is None.
    Reducing a node-axis slice is bit-identical to reducing the whole
    level: both collectives sum the same per-node payloads in the same
    shard order.
    """
    if axis_name is None:
        return G, H
    if comm == "reduce_scatter":
        return scatter_histograms(G, H, axis_name, axis_size)
    return jax.lax.psum(G, axis_name), jax.lax.psum(H, axis_name)


def hist_comm_impl():
    """Cross-shard histogram collective for the data axis (GRAFT_HIST_COMM).

    * ``psum`` (default): allreduce the full [W, d, B] grad+hess histograms
      to every device; every device then runs the identical split scan.
    * ``reduce_scatter``: ``lax.psum_scatter`` along the data axis — each
      device receives the globally summed histograms for only its
      d/axis_size feature slice and scans just that slice; winners merge
      across shards afterwards (LightGBM's reduce-scatter histogram
      aggregation, Ke et al. 2017, transplanted onto the SPMD round).
      Roughly halves collective wire bytes (ring allreduce moves
      2(p-1)/p x payload, reduce-scatter (p-1)/p) and divides split-scan
      FLOPs by the axis size.
    """
    v = os.environ.get("GRAFT_HIST_COMM", "psum")
    if v not in ("psum", "reduce_scatter"):
        raise ValueError(
            "Unknown GRAFT_HIST_COMM=%r; expected psum|reduce_scatter" % v
        )
    return v


def padded_feature_width(d, axis_size):
    """Features padded up to a multiple of the data-axis size so the
    reduce-scatter slice boundary is static and every shard owns an equal
    contiguous column slice. The padded columns carry all-zero histograms
    and zero cut counts, so they can never win a split."""
    return -(-d // axis_size) * axis_size


def scatter_histograms(G, H, axis_name, axis_size):
    """psum_scatter (G, H) [W, d, B] along the feature dim of the data axis.

    Returns ([W, d_pad/axis_size, B], same) — the globally summed histograms
    for this shard's contiguous feature slice. Values are the same sums the
    full psum would produce for those columns (XLA reduces both collectives
    in rank order), so split decisions downstream stay bit-identical.
    ``d`` is whatever column width the caller histograms — the full matrix
    on a 1-D mesh, or a feature shard's d_local slice on a 2-D (data x
    feature) mesh, where the per-shard padding of d_local keeps the
    doubly-sharded slice boundary static.
    """
    d = G.shape[1]
    d_pad = padded_feature_width(d, axis_size)
    if d_pad != d:
        pad = [(0, 0), (0, d_pad - d), (0, 0)]
        G = jnp.pad(G, pad)
        H = jnp.pad(H, pad)
    G = jax.lax.psum_scatter(G, axis_name, scatter_dimension=1, tiled=True)
    H = jax.lax.psum_scatter(H, axis_name, scatter_dimension=1, tiled=True)
    return G, H


def _wire_ratio(comm, axis_size):
    """Per-device wire bytes per logical payload byte for a ring collective:
    allreduce = reduce-scatter + all-gather = 2(p-1)/p; reduce-scatter alone
    = (p-1)/p. The bytes-per-round formula in docs/DESIGN.md §Communication
    is this ratio times the payload size."""
    p = axis_size
    if p <= 1:
        return 0.0
    frac = (p - 1) / p
    return 2.0 * frac if comm == "psum" else frac


# data-axis collectives per winner-merge scan batch under reduce_scatter:
# broadcast_node_totals psums g and h (2), combine_splits_across_shards
# runs pmax(gain), pmin(tie-break candidate) and 3 selection psums
# (feature, bin, default_left) — 7 [W]-shaped collectives in total
MERGE_COLLECTIVES_PER_SCAN = 7


def round_comm_plan(
    grow_policy,
    max_depth,
    max_leaves,
    d,
    num_bins,
    axis_size,
    comm,
    subtract,
    trees_per_round=1,
):
    """Static per-round collective plan for the data axis.

    Returns ``(entries, bytes_per_round)`` where each entry is
    ``{"kind": "hist"|"totals"|"merge", "shape": local payload shape,
    "count": n, "bytes": wire bytes for all n collectives}``.
    ``bytes_per_round`` feeds the ``hist_comm_bytes_total`` counter; the
    entry list feeds the latency calibration (one timing per distinct
    shape). ``hist`` entries carry the G and H f32 histogram pair (wire
    bytes = payload x ring ratio, _wire_ratio); ``d`` is the width each
    data shard histograms — the feature-shard-LOCAL width on a 2-D mesh,
    which reduce_scatter pads and scatters to d/axis_size per device.
    Under reduce_scatter the plan also carries the ``merge`` entries of
    the winner merge (MERGE_COLLECTIVES_PER_SCAN [W]-shaped psum-class
    collectives per gain-scan: the node-totals broadcast plus the
    cross-shard split combine), so ``hist_comm_bytes_total`` and the
    latency calibration stay truthful for the scattered lowering — 1-D
    and the 2-D (data x feature) composition alike.
    """
    if axis_size <= 1:
        return [], 0
    d_eff = padded_feature_width(d, axis_size) if comm == "reduce_scatter" else d
    ratio = _wire_ratio(comm, axis_size)
    psum_ratio = _wire_ratio("psum", axis_size)
    hist_widths = []
    merge_widths = []   # winner-merge scan widths (reduce_scatter only)
    totals = []
    if grow_policy == "lossguide":
        hist_widths.append((1, 1))                       # root
        merge_widths.append((1, 1))
        if max_leaves > 1:
            w = 1 if subtract else 2
            hist_widths.append((w, max_leaves - 1))      # per split step
            merge_widths.append((2, max_leaves - 1))     # both fresh children
    else:
        hist_widths.append((1, 1))                       # level 0
        merge_widths.append((1, 1))
        for level in range(1, max_depth):
            hist_widths.append((2 ** (level - 1) if subtract else 2**level, 1))
            merge_widths.append((2**level, 1))           # full level scan
        totals.append((2**max_depth, 1))                 # last-level node totals
    entries = []
    total_bytes = 0.0
    for W, count in hist_widths:
        count *= trees_per_round
        payload = 2 * W * d_eff * num_bins * 4           # G + H, f32
        b = payload * ratio * count
        entries.append(
            {"kind": "hist", "shape": (W, d_eff, num_bins), "count": count,
             "bytes": b}
        )
        total_bytes += b
    for W, count in totals:
        count *= trees_per_round
        b = 2 * W * 4 * psum_ratio * count               # totals always psum
        entries.append(
            {"kind": "totals", "shape": (W,), "count": count, "bytes": b}
        )
        total_bytes += b
    if comm == "reduce_scatter":
        for W, count in merge_widths:
            count *= trees_per_round
            b = MERGE_COLLECTIVES_PER_SCAN * W * 4 * psum_ratio * count
            entries.append(
                {"kind": "merge", "shape": (W,), "count": count, "bytes": b}
            )
            total_bytes += b
    return entries, int(total_bytes)


def subtraction_enabled(cache_bytes, knobs=None):
    """Shared gate for sibling-subtraction paths (both growers): the
    GRAFT_HIST_SUBTRACT kill-switch plus a memory cap on the histogram cache
    the caller would have to keep alive (GRAFT_SUBTRACT_MEM, default 512MB).
    ``knobs``: the session's :class:`HistKnobs` (env fallback for direct
    callers)."""
    if knobs is not None:
        return knobs.subtract and cache_bytes <= knobs.subtract_mem
    # graftlint: disable=trace-env-read — direct-caller fallback only;
    # sessions snapshot these via resolve_hist_knobs() at build time
    if os.environ.get("GRAFT_HIST_SUBTRACT", "1") != "1":
        return False
    # graftlint: disable=trace-env-read — direct-caller fallback only
    cap = env_int("GRAFT_SUBTRACT_MEM", 512 * 1024 * 1024, minimum=0)
    return cache_bytes <= cap


def level_histogram(
    bins,
    grad,
    hess,
    node_local,
    num_nodes,
    num_bins,
    axis_name=None,
    comm="psum",
    axis_size=1,
    knobs=None,
):
    """Build (G, H) histograms for one tree level.

    Args:
      bins: i32 [n, d] bin indices (missing bin included in num_bins).
      grad, hess: f32 [n].
      node_local: i32 [n]; position of the row's node within this level,
        or negative when the row no longer participates.
      num_nodes: static int — number of nodes at this level (2**level).
      num_bins: static int — histogram width per feature (max_bin + 1).
      axis_name: mesh axis to psum over, or None on a single device.
      comm: cross-shard lowering (hist_comm_impl): "psum" allreduces the
        full histograms; "reduce_scatter" psum_scatters them along the
        feature dim so each shard gets only its d/axis_size column slice.
      axis_size: static size of ``axis_name`` (required for reduce_scatter).
      knobs: the session's :class:`HistKnobs` snapshot. None falls back to
        per-knob env reads — direct unit-test/bench callers only; traced
        production code must thread the session snapshot (trace-safety).

    Returns:
      (G, H): f32 [num_nodes, d, num_bins] for psum / no axis;
      f32 [num_nodes, padded_d/axis_size, num_bins] for reduce_scatter.
    """
    impl = knobs.impl if knobs is not None else _impl()
    if impl == "per_feature":
        G, H = _hist_per_feature(bins, grad, hess, node_local, num_nodes, num_bins)
    elif impl == "matmul":
        G, H = _hist_matmul(bins, grad, hess, node_local, num_nodes, num_bins,
                            knobs=knobs)
    elif impl == "pallas":
        G, H = _hist_pallas(bins, grad, hess, node_local, num_nodes, num_bins,
                            knobs=knobs)
    elif impl == "flat":
        G, H = _hist_flat(bins, grad, hess, node_local, num_nodes, num_bins)
    else:
        raise ValueError(
            "Unknown GRAFT_HIST_IMPL=%r; expected flat|per_feature|matmul|pallas"
            % impl
        )
    return apply_hist_collective(G, H, axis_name, comm, axis_size)


def node_totals(grad, hess, node_local, num_nodes, axis_name=None, knobs=None):
    """Per-node (sum g, sum h) without the full histogram.

    The last tree level only needs leaf weights -> node totals; skipping the
    [W, d, B] histogram there removes the widest (most expensive) level from
    every tree build.

    Three lowerings via ``GRAFT_TOTALS_IMPL``: ``segment`` uses segment_sum
    (a sorted scatter-add on TPU — sorts all n rows by node id; fast on
    CPU); ``onehot`` scans row chunks and contracts a node one-hot on the
    MXU, avoiding the sort entirely (same trick as the matmul histograms);
    ``pallas`` is the VMEM-resident VPU reduction. Default is backend-aware
    like ``_impl``: scatter lowerings are the measured pathology on TPU
    (flat-vs-pallas histograms: 12x), so TPU defaults to ``onehot`` and
    everything else to ``segment`` — the env var overrides either way and
    the bench probe battery A/Bs all three. ``knobs``: the session's
    :class:`HistKnobs` (env fallback for direct callers).
    """
    impl = knobs.totals_impl if knobs is not None else _totals_impl()
    if impl == "onehot":
        g_tot, h_tot = _totals_onehot(grad, hess, node_local, num_nodes,
                                      knobs=knobs)
    elif impl == "pallas":
        g_tot, h_tot = _totals_pallas(grad, hess, node_local, num_nodes,
                                      knobs=knobs)
    elif impl != "segment":
        raise ValueError(
            "Unknown GRAFT_TOTALS_IMPL=%r; expected segment|onehot|pallas" % impl
        )
    else:
        active = node_local >= 0
        safe = jnp.where(active, node_local, num_nodes)
        g_tot = jax.ops.segment_sum(
            jnp.where(active, grad, 0.0), safe, num_segments=num_nodes + 1
        )[:num_nodes]
        h_tot = jax.ops.segment_sum(
            jnp.where(active, hess, 0.0), safe, num_segments=num_nodes + 1
        )[:num_nodes]
    if axis_name is not None:
        g_tot = jax.lax.psum(g_tot, axis_name)
        h_tot = jax.lax.psum(h_tot, axis_name)
    return g_tot, h_tot


def _totals_onehot(grad, hess, node_local, num_nodes, knobs=None):
    """[2, c] @ node-one-hot[c, W] per row chunk, f32 accumulated — no sort,
    no scatter; the one-hot never leaves registers/VMEM after fusion."""
    n = grad.shape[0]
    W = num_nodes
    if n == 0:
        z = jnp.zeros(W, jnp.float32)
        return z, z
    active = node_local >= 0
    g = jnp.where(active, grad, 0.0)
    h = jnp.where(active, hess, 0.0)
    node = jnp.where(active, node_local, W)  # dead slot -> one-hot 0

    chunk, steps = _balanced_chunks(
        n, knobs.matmul_chunk if knobs is not None else None
    )
    n_pad = steps * chunk
    if n_pad != n:
        pad = [(0, n_pad - n)]
        g = jnp.pad(g, pad)
        h = jnp.pad(h, pad)
        node = jnp.pad(node, pad, constant_values=W)

    iota_w = jnp.arange(W, dtype=jnp.int32)

    def body(carry, i):
        sl = i * chunk
        node_c = jax.lax.dynamic_slice(node, (sl,), (chunk,))
        g_c = jax.lax.dynamic_slice(g, (sl,), (chunk,))
        h_c = jax.lax.dynamic_slice(h, (sl,), (chunk,))
        oh = (node_c[:, None] == iota_w[None, :]).astype(jnp.float32)  # [c, W]
        gh = jnp.stack([g_c, h_c])  # [2, c]
        P = jax.lax.dot_general(
            gh, oh, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return carry + P, None

    init = jnp.zeros((2, W), jnp.float32)
    if steps == 1:
        GH, _ = body(init, jnp.int32(0))
    else:
        GH, _ = jax.lax.scan(body, init, jnp.arange(steps, dtype=jnp.int32))
    return GH[0], GH[1]


@functools.lru_cache(maxsize=None)
def _totals_pallas_fn(n, W, block, interpret):
    """Pallas node-totals: per block, one-hot-scale (g|h) into [blk, 2W] and
    row-sum into a VMEM [1, 2W] accumulator — pure VPU reduction, no sort
    (segment_sum) and no matmul (the [2, c] @ [c, W] onehot dot pads M=2 to
    a 128 tile). The last tree level runs this over every row."""
    import jax.experimental.pallas as pl

    try:
        from jax.experimental.pallas import tpu as pltpu

        vmem = pltpu.VMEM
    except ImportError:  # pragma: no cover
        vmem = None

    def kernel(gh_ref, node_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        node = node_ref[:, 0]
        onehot = (node[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (block, W), 1)).astype(jnp.float32)
        g = gh_ref[:, 0]
        h = gh_ref[:, 1]
        A = jnp.concatenate([onehot * g[:, None], onehot * h[:, None]], axis=1)
        out_ref[:] += jnp.sum(A, axis=0, keepdims=True)

    steps = n // block
    in_space = dict(memory_space=vmem) if vmem is not None and not interpret else {}
    return pl.pallas_call(
        kernel,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((block, 2), lambda i: (i, 0), **in_space),
            pl.BlockSpec((block, 1), lambda i: (i, 0), **in_space),
        ],
        out_specs=pl.BlockSpec((1, 2 * W), lambda i: (0, 0), **in_space),
        out_shape=jax.ShapeDtypeStruct((1, 2 * W), jnp.float32),
        interpret=interpret,
    )


def _totals_pallas(grad, hess, node_local, num_nodes, knobs=None):
    n = grad.shape[0]
    W = num_nodes
    if n == 0:
        z = jnp.zeros(W, jnp.float32)
        return z, z
    block = knobs.pallas_block if knobs is not None else _pallas_block()
    interpret = jax.default_backend() != "tpu"
    active = node_local >= 0
    g = jnp.where(active, grad, 0.0)
    h = jnp.where(active, hess, 0.0)
    node = jnp.where(active, node_local, jnp.int32(W))
    n_pad = -(-n // block) * block
    if n_pad != n:
        pad = [(0, n_pad - n)]
        g = jnp.pad(g, pad)
        h = jnp.pad(h, pad)
        node = jnp.pad(node, pad, constant_values=W)
    gh = jnp.stack([g, h], axis=1)
    out = _totals_pallas_fn(n_pad, W, block, interpret)(
        gh, node[:, None].astype(jnp.int32)
    )[0]
    return out[:W], out[W:]


# --------------------------------------------------------------------- flat


def _hist_flat(bins, grad, hess, node_local, num_nodes, num_bins):
    n, d = bins.shape
    active = node_local >= 0
    safe_node = jnp.where(active, node_local, num_nodes)
    seg = (safe_node[:, None] * d + jnp.arange(d, dtype=jnp.int32)[None, :]) * num_bins + bins
    seg = jnp.where(active[:, None], seg, num_nodes * d * num_bins)
    num_segments = num_nodes * d * num_bins + 1

    flat_seg = seg.reshape(-1)
    # two 1-D passes: the fused [n*d, 2] segment_sum variant compiles
    # pathologically on the TPU toolchain (multi-minute hang), so G and H go
    # through separate scatter-adds
    g_flat = jnp.broadcast_to(grad[:, None], (n, d)).reshape(-1)
    h_flat = jnp.broadcast_to(hess[:, None], (n, d)).reshape(-1)
    G = jax.ops.segment_sum(g_flat, flat_seg, num_segments=num_segments)
    H = jax.ops.segment_sum(h_flat, flat_seg, num_segments=num_segments)
    G = G[:-1].reshape(num_nodes, d, num_bins)
    H = H[:-1].reshape(num_nodes, d, num_bins)
    return G, H


# -------------------------------------------------------------- per_feature


def _hist_per_feature(bins, grad, hess, node_local, num_nodes, num_bins):
    n, d = bins.shape
    active = node_local >= 0
    safe_node = jnp.where(active, node_local, num_nodes)
    seg_base = safe_node * num_bins            # [n]
    trash = num_nodes * num_bins
    num_segments = trash + 1
    Gs, Hs = [], []
    for f in range(d):
        seg_f = jnp.where(active, seg_base + bins[:, f], trash)
        Gs.append(jax.ops.segment_sum(grad, seg_f, num_segments=num_segments)[:-1])
        Hs.append(jax.ops.segment_sum(hess, seg_f, num_segments=num_segments)[:-1])
    G = jnp.stack(Gs, axis=1).reshape(num_nodes, num_bins, d).transpose(0, 2, 1)
    H = jnp.stack(Hs, axis=1).reshape(num_nodes, num_bins, d).transpose(0, 2, 1)
    return G, H


# ------------------------------------------------------------------- matmul


def _split_bf16(x):
    """f32 -> (hi, lo) bf16 pair with hi + lo ~= x to ~16 mantissa bits."""
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _mxu_split_missing(B, knobs=None):
    """When B = k*128 + 1 (the usual max_bin=256 -> 257 with the missing bin
    last), the one-hot dot's N dimension pads to the next lane multiple
    (257 -> 384 on the MXU, +50% wasted FLOPs). Splitting the missing column
    out — one [2W, d] dot over the (bins == B-1) mask — keeps the per-feature
    dots at an exact lane multiple. GRAFT_HIST_ALIGN=0 disables."""
    if knobs is not None:
        align = knobs.align
    else:
        # graftlint: disable=trace-env-read — direct-caller fallback only;
        # sessions snapshot this via resolve_hist_knobs() at build time
        align = os.environ.get("GRAFT_HIST_ALIGN", "1") == "1"
    if not align:
        return False
    return B > 128 and (B - 1) % 128 == 0


def _dot_prec(A, Ob32, prec):
    """dot_general(A^T, Ob) with GRAFT_HIST_MM_PREC operand handling,
    f32 accumulation. A [c, M] f32; Ob32 [c, N] f32 -> [M, N] f32."""
    if prec == "f32":
        return jax.lax.dot_general(
            A, Ob32, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
    if prec == "bf16":
        return jax.lax.dot_general(
            A.astype(jnp.bfloat16),
            Ob32.astype(jnp.bfloat16),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    Ob = Ob32.astype(jnp.bfloat16)
    hi, lo = _split_bf16(A)
    return jax.lax.dot_general(
        hi, Ob, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) + jax.lax.dot_general(
        lo, Ob, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _hist_matmul(bins, grad, hess, node_local, num_nodes, num_bins, knobs=None):
    """One-hot matmul histogram, scanned over row chunks.

    Per chunk: A[c, 2W] = node-one-hot * (grad | hess); per feature,
    P[2W, B] = A^T @ bin-one-hot[c, B]; accumulate into [2W, d, B] f32.
    The MXU does the binning — no scatter anywhere. Virtual-node packing
    (see _vnode_factor) fills the M tile at shallow levels exactly as in
    the pallas kernel.
    """
    n, d = bins.shape
    W = num_nodes
    B = num_bins
    prec = knobs.precision if knobs is not None else _matmul_precision()
    if n == 0:
        z = jnp.zeros((W, d, B), jnp.float32)
        return z, z

    # chunk rows needn't divide v here (sub-group = row index mod v), so
    # pass a block any power-of-two v divides — NOT 1, which would force
    # the divisibility loop to grind v down to 1 and disable the packing
    v = _vnode_factor(W, 128, d, B, knobs=knobs)
    Wv = W * v
    active = node_local >= 0
    g = jnp.where(active, grad, 0.0)
    h = jnp.where(active, hess, 0.0)
    node = jnp.where(active, node_local, Wv)  # dead slot, one-hot -> 0
    if v > 1:
        s = (jnp.arange(n, dtype=jnp.int32) % v) * W
        node = jnp.where(node >= Wv, Wv, node + s)

    chunk, steps = _balanced_chunks(
        n, knobs.matmul_chunk if knobs is not None else None
    )
    n_pad = steps * chunk
    if n_pad != n:
        pad = [(0, n_pad - n)]
        g = jnp.pad(g, pad)
        h = jnp.pad(h, pad)
        node = jnp.pad(node, pad, constant_values=Wv)
        bins = jnp.pad(bins, pad + [(0, 0)])

    split_missing = _mxu_split_missing(B, knobs=knobs)
    Bm = B - 1 if split_missing else B
    iota_w = jnp.arange(Wv, dtype=jnp.int32)
    iota_b = jnp.arange(Bm, dtype=jnp.int32)

    def body(carry, i):
        GH = carry
        sl = i * chunk
        node_c = jax.lax.dynamic_slice(node, (sl,), (chunk,))
        g_c = jax.lax.dynamic_slice(g, (sl,), (chunk,))
        h_c = jax.lax.dynamic_slice(h, (sl,), (chunk,))
        bins_c = jax.lax.dynamic_slice(bins, (sl, 0), (chunk, d))
        onehot_w = (node_c[:, None] == iota_w[None, :]).astype(jnp.float32)
        A = jnp.concatenate(
            [onehot_w * g_c[:, None], onehot_w * h_c[:, None]], axis=1
        )  # [c, 2*Wv]
        per_f = []
        for f in range(d):
            Ob32 = (bins_c[:, f][:, None] == iota_b[None, :]).astype(jnp.float32)
            per_f.append(_dot_prec(A, Ob32, prec))
        delta = jnp.stack(per_f, axis=1)  # [2*Wv, d, Bm]
        if split_missing:
            miss = (bins_c == (B - 1)).astype(jnp.float32)  # [c, d]
            Pm = _dot_prec(A, miss, prec)  # [2*Wv, d]
            delta = jnp.concatenate([delta, Pm[:, :, None]], axis=2)
        GH = GH + delta
        return GH, None

    init = jnp.zeros((2 * Wv, d, B), jnp.float32)
    if steps == 1:
        GH, _ = body(init, jnp.int32(0))
    else:
        GH, _ = jax.lax.scan(body, init, jnp.arange(steps, dtype=jnp.int32))
    if v > 1:
        G = GH[:Wv].reshape(v, W, d, B).sum(axis=0)
        H = GH[Wv:].reshape(v, W, d, B).sum(axis=0)
        return G, H
    return GH[:W], GH[W:]


# ------------------------------------------------------------------- pallas


def _vnode_factor(W, block, d, B, knobs=None):
    """Virtual-node packing factor: the MXU processes M in 128-row tiles, so
    a [blk, 2W] @ [blk, B] dot with 2W < 128 pads M and wastes (128/2W)x the
    FLOPs — the histogram cost of a SHALLOW level would match the deepest
    level's. Packing v = 128//(2W) row sub-groups as disjoint virtual node
    ranges fills the tile with real work; the v partial histograms sum after
    the grid. Exact (pure reassociation of the sum). GRAFT_HIST_VNODES=0
    disables for A/B.

    The VMEM accumulator grows to [2*W*v, d, B] f32, so v is also capped by
    GRAFT_VNODE_VMEM (default 4MB) — shallow levels of WIDE matrices must
    not allocate more VMEM than the deepest level the kernel already
    handles."""
    if knobs is not None:
        if not knobs.vnodes:
            return 1
        budget = knobs.vnode_vmem
    else:
        # graftlint: disable=trace-env-read — direct-caller fallback only;
        # sessions snapshot these via resolve_hist_knobs() at build time
        if os.environ.get("GRAFT_HIST_VNODES", "1") != "1":
            return 1
        # graftlint: disable=trace-env-read — direct-caller fallback only
        budget = env_int("GRAFT_VNODE_VMEM", 4 * 1024 * 1024, minimum=0)
    v = max(1, 128 // (2 * W))
    v = min(v, max(1, budget // (2 * W * d * B * 4)))
    while block % v or v & (v - 1):  # equal sub-groups; power of two
        v -= 1
    return max(1, v)


@functools.lru_cache(maxsize=None)
def _pallas_hist_fn(n, d, W, B, block, prec, interpret, split_missing, v):
    """Compiled pallas histogram: (bins int [n,d] — any integer storage
    dtype, widened per block in VMEM, so u8/u16 bins move half the HBM
    bytes — gh f32 [n,2], node i32 [n,1]) -> [2*W*v, d, B] f32 with the g
    histograms in rows [:W*v] and h in [W*v:], v sub-group copies each
    (see _vnode_factor; the caller reduces them). Grid over row blocks;
    VMEM-resident accumulator. split_missing: see _mxu_split_missing
    (part of the cache key because the kernel body changes with it)."""
    import jax.experimental.pallas as pl

    try:
        from jax.experimental.pallas import tpu as pltpu

        vmem = pltpu.VMEM
    except ImportError:  # pragma: no cover
        pltpu = None
        vmem = None

    Bm = B - 1 if split_missing else B
    Wv = W * v

    def kernel(bins_ref, gh_ref, node_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        node = node_ref[:, 0]                          # [blk]
        if v > 1:
            # row i -> virtual node range (i % v); dead rows (node == W)
            # must stay out of EVERY range, not collide with range (s+1)
            s = jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)[:, 0] % v
            node = jnp.where(node >= W, Wv, node + s * W)
        onehot_w = (node[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (block, Wv), 1)).astype(jnp.float32)
        g = gh_ref[:, 0]
        h = gh_ref[:, 1]
        A = jnp.concatenate(
            [onehot_w * g[:, None], onehot_w * h[:, None]], axis=1
        )  # [blk, 2*Wv]
        if prec == "bf16x2":
            A_hi, A_lo = _split_bf16(A)
        elif prec == "bf16":
            A_hi = A.astype(jnp.bfloat16)
            A_lo = None
        else:
            A_hi, A_lo = A, None
        bw = bins_ref[:].astype(jnp.int32)             # widen in VMEM
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (block, Bm), 1)
        for f in range(d):
            ob = (bw[:, f][:, None] == iota_b)
            ob = ob.astype(A_hi.dtype)
            P = jax.lax.dot_general(
                A_hi, ob, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if A_lo is not None:
                P = P + jax.lax.dot_general(
                    A_lo, ob.astype(A_lo.dtype), (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            out_ref[:, f, :Bm] += P
        if split_missing:
            miss = (bw == (B - 1)).astype(A_hi.dtype)  # [blk, d]
            Pm = jax.lax.dot_general(
                A_hi, miss, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if A_lo is not None:
                Pm = Pm + jax.lax.dot_general(
                    A_lo, miss.astype(A_lo.dtype), (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            out_ref[:, :, Bm:Bm + 1] += Pm[:, :, None]

    steps = n // block
    if vmem is not None and not interpret:
        in_space = dict(memory_space=vmem)
    else:
        in_space = {}

    return pl.pallas_call(
        kernel,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0), **in_space),
            pl.BlockSpec((block, 2), lambda i: (i, 0), **in_space),
            pl.BlockSpec((block, 1), lambda i: (i, 0), **in_space),
        ],
        out_specs=pl.BlockSpec((2 * Wv, d, B), lambda i: (0, 0, 0), **in_space),
        out_shape=jax.ShapeDtypeStruct((2 * Wv, d, B), jnp.float32),
        interpret=interpret,
    )


def _hist_pallas(bins, grad, hess, node_local, num_nodes, num_bins, knobs=None):
    n, d = bins.shape
    W = num_nodes
    B = num_bins
    if n == 0:
        # grid would be (0,): the step-0 out_ref init never runs and the
        # kernel would return an uninitialized buffer
        zeros = jnp.zeros((W, d, B), jnp.float32)
        return zeros, zeros
    block = knobs.pallas_block if knobs is not None else _pallas_block()
    prec = knobs.precision if knobs is not None else _matmul_precision()
    interpret = jax.default_backend() != "tpu"

    active = node_local >= 0
    g = jnp.where(active, grad, 0.0)
    h = jnp.where(active, hess, 0.0)
    node = jnp.where(active, node_local, jnp.int32(W))

    n_pad = -(-n // block) * block
    if n_pad != n:
        pad = [(0, n_pad - n)]
        g = jnp.pad(g, pad)
        h = jnp.pad(h, pad)
        node = jnp.pad(node, pad, constant_values=W)
        bins = jnp.pad(bins, pad + [(0, 0)])

    gh = jnp.stack([g, h], axis=1)                     # [n, 2]
    v = _vnode_factor(W, block, d, B, knobs=knobs)
    fn = _pallas_hist_fn(
        n_pad, d, W, B, block, prec, interpret, _mxu_split_missing(B, knobs=knobs), v
    )
    GH = fn(bins, gh, node[:, None].astype(jnp.int32))
    if v > 1:
        Wv = W * v
        G = GH[:Wv].reshape(v, W, d, B).sum(axis=0)
        H = GH[Wv:].reshape(v, W, d, B).sum(axis=0)
        return G, H
    return GH[:W], GH[W:]
