"""Compiled forest inference kernel.

The serving-side replacement for libxgboost's C++ predictor (reference hot
loop: serve_utils.py:244-250 ``booster.predict``). The whole forest is laid
out as stacked per-tree node arrays in HBM; traversal is ``depth`` rounds of
vectorized gather/compare over [rows x trees] — no per-tree Python, one XLA
program, jit-cached per (num_rows bucket, forest version).

Works on explicit child indices (not the padded full-binary layout) so
imported xgboost-JSON models of any shape run through the same kernel.
Missing values (NaN) follow ``default_left``.
"""

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _leaf_nodes_impl(
    xp, feature, threshold, default_left, left, right, is_leaf, x, depth,
    cat_split=None, cat_mask=None,
):
    """The ONE traversal implementation, parameterized by array namespace
    (``xp`` = jnp for the jitted device kernels, np for the host small-payload
    path) so the routing rules cannot diverge between them.

    Rules (xgboost semantics): NaN-missing follows ``default_left``;
    numerical nodes go right when ``v >= threshold``; categorical nodes
    (cat_split/cat_mask given; xgboost common::Decision) go right when the
    int category is in the node's bitmask, while an invalid category
    (negative float / out-of-range) goes LEFT unconditionally — negativity
    is checked on the FLOAT value: -0.5 truncates to int 0 but is still
    invalid. Leaves self-loop via left/right == own index.
    """
    n = x.shape[0]
    T = feature.shape[0]
    node = xp.zeros((n, T), xp.int32)
    t_idx = xp.broadcast_to(xp.arange(T)[None, :], (n, T))
    if cat_mask is not None:
        max_cat = cat_mask.shape[2] * 32

    for _ in range(depth):
        feat = feature[t_idx, node]            # [n, T]
        thr = threshold[t_idx, node]
        v = xp.take_along_axis(x, feat.reshape(n, -1), axis=1).reshape(n, T)
        miss = xp.isnan(v)
        dfl = default_left[t_idx, node]
        go_right = xp.where(miss, ~dfl, v >= thr)
        if cat_mask is not None:
            # range checks on the FLOAT value: float->int32 of values >= 2^31
            # wraps on numpy but saturates on XLA:TPU, so an int-side
            # comparison would diverge between the host and device paths
            invalid = (v < 0) | (v >= max_cat)
            # clip the FLOAT before the int cast: inf / >=2^31 values would
            # otherwise warn on numpy (and saturate on XLA); the `invalid`
            # flag above already captured out-of-range on the float side
            cat = xp.clip(
                xp.nan_to_num(v, nan=-1.0), -1.0, float(max_cat)
            ).astype(xp.int32)
            safe_cat = xp.clip(cat, 0, max_cat - 1)
            word = cat_mask[t_idx, node, safe_cat >> 5]
            in_set = ((word >> (safe_cat & 31).astype(xp.uint32)) & 1) == 1
            go_right_cat = xp.where(miss, ~dfl, xp.where(invalid, False, in_set))
            go_right = xp.where(cat_split[t_idx, node], go_right_cat, go_right)
        nxt = xp.where(go_right, right[t_idx, node], left[t_idx, node])
        node = xp.where(is_leaf[t_idx, node], node, nxt)
    return node


@partial(jax.jit, static_argnames=("depth",))
def _forest_leaf_nodes(feature, threshold, default_left, left, right, is_leaf, x, depth):
    """x: f32 [n, d] (NaN = missing) -> leaf node index per (row, tree)."""
    return _leaf_nodes_impl(
        jnp, feature, threshold, default_left, left, right, is_leaf, x, depth
    )


@partial(jax.jit, static_argnames=("depth",))
def _forest_leaf_nodes_cat(
    feature, threshold, default_left, left, right, is_leaf,
    cat_split, cat_mask, x, depth,
):
    """Traversal with partition-based categorical nodes (BYO xgboost models)."""
    return _leaf_nodes_impl(
        jnp, feature, threshold, default_left, left, right, is_leaf, x, depth,
        cat_split=cat_split, cat_mask=cat_mask,
    )


def _stacked_args(stacked, *extra_keys):
    """Common [T, N] traversal arrays (+ extras) as device arrays."""
    keys = ("feature", "threshold", "default_left", "left", "right", "is_leaf")
    return tuple(jnp.asarray(stacked[k]) for k in keys + extra_keys)


def forest_leaf_nodes(stacked, x):
    """Dispatch: the plain numerical kernel, or the categorical-aware one
    when the stacked forest carries category bitmasks."""
    x = jnp.asarray(x, jnp.float32)
    if "cat_split" in stacked:
        return _forest_leaf_nodes_cat(
            *_stacked_args(stacked, "cat_split", "cat_mask"), x, stacked["depth"]
        )
    return _forest_leaf_nodes(*_stacked_args(stacked), x, stacked["depth"])


@partial(jax.jit, static_argnames=("depth",))
def _forest_margin(feature, threshold, default_left, left, right, is_leaf, leaf_value, x, depth):
    """x: f32 [n, d] (NaN = missing) -> per-tree-group margins [n].

    Tree arrays: [T, N] stacked; leaves self-loop via left/right == own index.
    """
    T = feature.shape[0]
    t_idx = jnp.arange(T)[None, :]
    node = _forest_leaf_nodes(
        feature, threshold, default_left, left, right, is_leaf, x, depth
    )
    return leaf_value[t_idx, node]             # [n, T]


@partial(jax.jit, static_argnames=("depth",))
def _forest_margin_cat(
    feature, threshold, default_left, left, right, is_leaf,
    cat_split, cat_mask, leaf_value, x, depth,
):
    T = feature.shape[0]
    t_idx = jnp.arange(T)[None, :]
    node = _forest_leaf_nodes_cat(
        feature, threshold, default_left, left, right, is_leaf,
        cat_split, cat_mask, x, depth,
    )
    return leaf_value[t_idx, node]             # [n, T]


def forest_leaf_margins(stacked, x):
    """Per-tree leaf contributions [n, T]; one cached XLA program either way
    (categorical-aware when the stacked forest carries category bitmasks)."""
    x = jnp.asarray(x, jnp.float32)
    if "cat_split" in stacked:
        return _forest_margin_cat(
            *_stacked_args(stacked, "cat_split", "cat_mask", "leaf_value"),
            x,
            stacked["depth"],
        )
    return _forest_margin(
        *_stacked_args(stacked, "leaf_value"), x, stacked["depth"]
    )


def forest_predict_margin(stacked, x, num_output_group=1, base_margin=0.0, tree_info=None):
    """Sum per-tree leaf outputs into per-group margins.

    stacked: dict of [T, N] numpy/jnp arrays + "depth" int.
    Returns [n] (single group) or [n, num_output_group].
    """
    leaf = forest_leaf_margins(stacked, x)
    if num_output_group == 1:
        return np.asarray(leaf.sum(axis=1)) + base_margin
    # group trees by class id (tree_info) — static host-side partition
    out = np.zeros((x.shape[0], num_output_group), np.float32)
    leaf_np = np.asarray(leaf)
    info = np.asarray(tree_info)
    for c in range(num_output_group):
        out[:, c] = leaf_np[:, info == c].sum(axis=1) + base_margin
    return out


# ------------------------------------------------------------- host predictor


def host_leaf_nodes(stacked, x):
    """Numpy twin of the XLA traversal for tiny serving payloads.

    A 1-row `/invocations` on TPU pays the full host->device->host dispatch
    (and, under a tunneled chip, a network round trip) for microseconds of
    compute; the reference's C++ predictor (serve_utils.py:244-250) has no
    such floor. Rows below ``Forest``'s host-path threshold therefore run
    ``_leaf_nodes_impl`` with xp=np — the same code the jitted kernels run,
    so the routing rules cannot diverge.
    """
    x = np.asarray(x, np.float32)
    keys = ("feature", "threshold", "default_left", "left", "right", "is_leaf")
    arrays = tuple(np.asarray(stacked[k]) for k in keys)
    cat = {}
    if "cat_split" in stacked:
        cat = {
            "cat_split": np.asarray(stacked["cat_split"]),
            "cat_mask": np.asarray(stacked["cat_mask"]),
        }
    return _leaf_nodes_impl(np, *arrays, x, int(stacked["depth"]), **cat)


def _host_leaf_values(stacked, x):
    """[n, T] per-tree leaf values on the host: the C++ traversal
    (native/fastdata.cpp::forest_leaf_values — the reference's libxgboost
    C++ predictor analog, ~2 us vs ~0.3 ms of numpy per-op overhead for a
    100-tree single-row request) with the numpy twin as fallback.
    GRAFT_HOST_PREDICT_IMPL=numpy forces the fallback for A/Bs."""
    x = np.asarray(x, np.float32)
    if os.environ.get("GRAFT_HOST_PREDICT_IMPL", "native") != "numpy":
        from ..data.native import forest_leaf_values_native

        leaf = forest_leaf_values_native(stacked, x)
        if leaf is not None:
            return leaf
    node = host_leaf_nodes(stacked, x)
    leaf_value = np.asarray(stacked["leaf_value"])
    T = leaf_value.shape[0]
    return leaf_value[np.arange(T)[None, :], node]       # [n, T]


def host_predict_margin(stacked, x, num_output_group=1, base_margin=0.0, tree_info=None):
    """Host forest margin for tiny payloads (same contract as
    ``forest_predict_margin``, no device dispatch, no padding needed)."""
    leaf = _host_leaf_values(stacked, x)
    if num_output_group == 1:
        return leaf.sum(axis=1) + base_margin
    out = np.zeros((x.shape[0], num_output_group), np.float32)
    info = np.asarray(tree_info)
    for c in range(num_output_group):
        out[:, c] = leaf[:, info == c].sum(axis=1) + base_margin
    return out
