"""Compiled forest inference kernel.

The serving-side replacement for libxgboost's C++ predictor (reference hot
loop: serve_utils.py:244-250 ``booster.predict``). The whole forest is laid
out as stacked per-tree node arrays in HBM; traversal is ``depth`` rounds of
vectorized gather/compare over [rows x trees] — no per-tree Python, one XLA
program, jit-cached per (num_rows bucket, forest version).

Works on explicit child indices (not the padded full-binary layout) so
imported xgboost-JSON models of any shape run through the same kernel.
Missing values (NaN) follow ``default_left``.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("depth",))
def _forest_leaf_nodes(feature, threshold, default_left, left, right, is_leaf, x, depth):
    """x: f32 [n, d] (NaN = missing) -> leaf node index per (row, tree)."""
    n = x.shape[0]
    T = feature.shape[0]
    node = jnp.zeros((n, T), jnp.int32)
    t_idx = jnp.arange(T)[None, :]

    for _ in range(depth):
        feat = feature[t_idx, node]            # [n, T]
        thr = threshold[t_idx, node]
        v = jnp.take_along_axis(x, feat.reshape(n, -1), axis=1).reshape(n, T)
        miss = jnp.isnan(v)
        go_right = jnp.where(miss, ~default_left[t_idx, node], v >= thr)
        nxt = jnp.where(go_right, right[t_idx, node], left[t_idx, node])
        node = jnp.where(is_leaf[t_idx, node], node, nxt)
    return node


@partial(jax.jit, static_argnames=("depth",))
def _forest_margin(feature, threshold, default_left, left, right, is_leaf, leaf_value, x, depth):
    """x: f32 [n, d] (NaN = missing) -> per-tree-group margins [n].

    Tree arrays: [T, N] stacked; leaves self-loop via left/right == own index.
    """
    T = feature.shape[0]
    t_idx = jnp.arange(T)[None, :]
    node = _forest_leaf_nodes(
        feature, threshold, default_left, left, right, is_leaf, x, depth
    )
    return leaf_value[t_idx, node]             # [n, T]


def forest_predict_margin(stacked, x, num_output_group=1, base_margin=0.0, tree_info=None):
    """Sum per-tree leaf outputs into per-group margins.

    stacked: dict of [T, N] numpy/jnp arrays + "depth" int.
    Returns [n] (single group) or [n, num_output_group].
    """
    leaf = _forest_margin(
        stacked["feature"],
        stacked["threshold"],
        stacked["default_left"],
        stacked["left"],
        stacked["right"],
        stacked["is_leaf"],
        stacked["leaf_value"],
        jnp.asarray(x, jnp.float32),
        stacked["depth"],
    )
    if num_output_group == 1:
        return np.asarray(leaf.sum(axis=1)) + base_margin
    # group trees by class id (tree_info) — static host-side partition
    out = np.zeros((x.shape[0], num_output_group), np.float32)
    leaf_np = np.asarray(leaf)
    info = np.asarray(tree_info)
    for c in range(num_output_group):
        out[:, c] = leaf_np[:, info == c].sum(axis=1) + base_margin
    return out
