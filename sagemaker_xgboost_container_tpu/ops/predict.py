"""Compiled forest inference kernel.

The serving-side replacement for libxgboost's C++ predictor (reference hot
loop: serve_utils.py:244-250 ``booster.predict``). The whole forest is laid
out as stacked per-tree node arrays in HBM; traversal is ``depth`` rounds of
vectorized gather/compare over [rows x trees] — no per-tree Python, one XLA
program, jit-cached per (num_rows bucket, forest version).

Works on explicit child indices (not the padded full-binary layout) so
imported xgboost-JSON models of any shape run through the same kernel.
Missing values (NaN) follow ``default_left``.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("depth",))
def _forest_leaf_nodes(feature, threshold, default_left, left, right, is_leaf, x, depth):
    """x: f32 [n, d] (NaN = missing) -> leaf node index per (row, tree)."""
    n = x.shape[0]
    T = feature.shape[0]
    node = jnp.zeros((n, T), jnp.int32)
    t_idx = jnp.arange(T)[None, :]

    for _ in range(depth):
        feat = feature[t_idx, node]            # [n, T]
        thr = threshold[t_idx, node]
        v = jnp.take_along_axis(x, feat.reshape(n, -1), axis=1).reshape(n, T)
        miss = jnp.isnan(v)
        go_right = jnp.where(miss, ~default_left[t_idx, node], v >= thr)
        nxt = jnp.where(go_right, right[t_idx, node], left[t_idx, node])
        node = jnp.where(is_leaf[t_idx, node], node, nxt)
    return node


@partial(jax.jit, static_argnames=("depth",))
def _forest_leaf_nodes_cat(
    feature, threshold, default_left, left, right, is_leaf,
    cat_split, cat_mask, x, depth,
):
    """Traversal with partition-based categorical nodes (BYO xgboost models).

    cat_split: bool [T, N] — node is categorical; cat_mask: u32 [T, N, W]
    bitmask of the categories routed RIGHT (xgboost common::Decision:
    in-set -> right; invalid/missing -> default direction). The numerical
    path is identical to _forest_leaf_nodes.
    """
    n = x.shape[0]
    T = feature.shape[0]
    W = cat_mask.shape[2]
    max_cat = W * 32
    node = jnp.zeros((n, T), jnp.int32)
    t_idx = jnp.broadcast_to(jnp.arange(T)[None, :], (n, T))

    for _ in range(depth):
        feat = feature[t_idx, node]            # [n, T]
        thr = threshold[t_idx, node]
        v = jnp.take_along_axis(x, feat.reshape(n, -1), axis=1).reshape(n, T)
        miss = jnp.isnan(v)
        dfl = default_left[t_idx, node]

        cat = jnp.nan_to_num(v, nan=-1.0).astype(jnp.int32)
        # xgboost common::Decision: MISSING follows the default direction,
        # but an invalid (negative / out-of-range) category goes LEFT
        # unconditionally. Negativity is checked on the FLOAT value:
        # -0.5 truncates to int 0 but is still an invalid category.
        invalid = (v < 0) | (cat >= max_cat)
        safe_cat = jnp.clip(cat, 0, max_cat - 1)
        word = cat_mask[t_idx, node, safe_cat >> 5]
        in_set = ((word >> (safe_cat & 31).astype(jnp.uint32)) & 1) == 1
        go_right_cat = jnp.where(
            miss, ~dfl, jnp.where(invalid, False, in_set)
        )

        go_right_num = jnp.where(miss, ~dfl, v >= thr)
        go_right = jnp.where(cat_split[t_idx, node], go_right_cat, go_right_num)
        nxt = jnp.where(go_right, right[t_idx, node], left[t_idx, node])
        node = jnp.where(is_leaf[t_idx, node], node, nxt)
    return node


def _stacked_args(stacked, *extra_keys):
    """Common [T, N] traversal arrays (+ extras) as device arrays."""
    keys = ("feature", "threshold", "default_left", "left", "right", "is_leaf")
    return tuple(jnp.asarray(stacked[k]) for k in keys + extra_keys)


def forest_leaf_nodes(stacked, x):
    """Dispatch: the plain numerical kernel, or the categorical-aware one
    when the stacked forest carries category bitmasks."""
    x = jnp.asarray(x, jnp.float32)
    if "cat_split" in stacked:
        return _forest_leaf_nodes_cat(
            *_stacked_args(stacked, "cat_split", "cat_mask"), x, stacked["depth"]
        )
    return _forest_leaf_nodes(*_stacked_args(stacked), x, stacked["depth"])


@partial(jax.jit, static_argnames=("depth",))
def _forest_margin(feature, threshold, default_left, left, right, is_leaf, leaf_value, x, depth):
    """x: f32 [n, d] (NaN = missing) -> per-tree-group margins [n].

    Tree arrays: [T, N] stacked; leaves self-loop via left/right == own index.
    """
    T = feature.shape[0]
    t_idx = jnp.arange(T)[None, :]
    node = _forest_leaf_nodes(
        feature, threshold, default_left, left, right, is_leaf, x, depth
    )
    return leaf_value[t_idx, node]             # [n, T]


@partial(jax.jit, static_argnames=("depth",))
def _forest_margin_cat(
    feature, threshold, default_left, left, right, is_leaf,
    cat_split, cat_mask, leaf_value, x, depth,
):
    T = feature.shape[0]
    t_idx = jnp.arange(T)[None, :]
    node = _forest_leaf_nodes_cat(
        feature, threshold, default_left, left, right, is_leaf,
        cat_split, cat_mask, x, depth,
    )
    return leaf_value[t_idx, node]             # [n, T]


def forest_leaf_margins(stacked, x):
    """Per-tree leaf contributions [n, T]; one cached XLA program either way
    (categorical-aware when the stacked forest carries category bitmasks)."""
    x = jnp.asarray(x, jnp.float32)
    if "cat_split" in stacked:
        return _forest_margin_cat(
            *_stacked_args(stacked, "cat_split", "cat_mask", "leaf_value"),
            x,
            stacked["depth"],
        )
    return _forest_margin(
        *_stacked_args(stacked, "leaf_value"), x, stacked["depth"]
    )


def forest_predict_margin(stacked, x, num_output_group=1, base_margin=0.0, tree_info=None):
    """Sum per-tree leaf outputs into per-group margins.

    stacked: dict of [T, N] numpy/jnp arrays + "depth" int.
    Returns [n] (single group) or [n, num_output_group].
    """
    leaf = forest_leaf_margins(stacked, x)
    if num_output_group == 1:
        return np.asarray(leaf.sum(axis=1)) + base_margin
    # group trees by class id (tree_info) — static host-side partition
    out = np.zeros((x.shape[0], num_output_group), np.float32)
    leaf_np = np.asarray(leaf)
    info = np.asarray(tree_info)
    for c in range(num_output_group):
        out[:, c] = leaf_np[:, info == c].sum(axis=1) + base_margin
    return out
