"""TPU-native gradient-boosting training & serving container.

A ground-up JAX/XLA re-design of the SageMaker XGBoost container: the same
train/serve contracts (SM_* env, channel/HP validation, HPO stdout metrics,
checkpoint/resume, selectable inference) over an XLA histogram tree builder
sharded across a TPU mesh instead of libxgboost + Rabit/NCCL.
"""

__version__ = "0.1.0"
