"""TPU-native gradient-boosting training & serving container.

A ground-up JAX/XLA re-design of the SageMaker XGBoost container: the same
train/serve contracts (SM_* env, channel/HP validation, HPO stdout metrics,
checkpoint/resume, selectable inference) over an XLA histogram tree builder
sharded across a TPU mesh instead of libxgboost + Rabit/NCCL.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("JAX_PLATFORMS") == "cpu":
    # Site plugins (e.g. a PJRT tunnel) may force jax_platforms after env
    # parsing; an explicit JAX_PLATFORMS=cpu from the user must win (tests,
    # virtual-mesh dry runs).
    import jax as _jax

    if _jax.config.jax_platforms != "cpu":
        _jax.config.update("jax_platforms", "cpu")
