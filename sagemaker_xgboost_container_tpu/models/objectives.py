"""Objective functions: pure-jnp gradient/hessian pairs + output transforms.

The TPU equivalent of libxgboost's C++ objective registry (reference trains
via ``xgb.train(cfg, ...)`` — algorithm_mode/train.py:367-376 — with the
objective resolved inside the C++ core). Every objective is three pure
functions over jnp arrays, so the whole round step stays inside one XLA
program:

* ``grad_hess(margin, label, weight)`` -> (g, h) per row (per class for multi)
* ``margin_to_prediction(margin)``      -> what ``predict()`` returns
* ``base_margin(base_score)``           -> initial margin from base_score

Gradient formulas follow the published XGBoost objective definitions
(elementwise; no data-dependent control flow — everything is jnp.where).
"""

import math

import jax.numpy as jnp
import numpy as np

from ..toolkit import exceptions as exc
from ..constants import (
    LOGISTIC_REGRESSION_LABEL_RANGE_ERROR,
    MULTI_CLASS_LABEL_RANGE_ERROR,
    POISSON_REGRESSION_ERROR,
    TWEEDIE_REGRESSION_ERROR,
)

_EPS = 1e-16
_HESS_EPS = 1e-6


def _sigmoid(x):
    return 1.0 / (1.0 + jnp.exp(-x))


class Objective:
    """Base: binary/regression single-output objective."""

    name = None
    num_output_group = 1
    default_metric = "rmse"
    # prediction transform applied at serve time
    prob_transform = False

    def __init__(self, params=None):
        self.params = params or {}
        self.scale_pos_weight = float(self.params.get("scale_pos_weight", 1.0))

    # -- training ------------------------------------------------------------
    def grad_hess(self, margin, label, weight):
        raise NotImplementedError

    # -- label sanity (host-side, before training) ---------------------------
    def validate_labels(self, labels):
        pass

    # -- transforms ----------------------------------------------------------
    def base_margin(self, base_score):
        return float(base_score)

    def margin_to_prediction(self, margin):
        return margin


class SquaredError(Objective):
    name = "reg:squarederror"

    def grad_hess(self, margin, label, weight):
        return (margin - label) * weight, jnp.ones_like(margin) * weight


class SquaredLogError(Objective):
    name = "reg:squaredlogerror"
    default_metric = "rmsle"

    def grad_hess(self, margin, label, weight):
        p = jnp.maximum(margin, -1 + 1e-6)
        z = jnp.log1p(p) - jnp.log1p(label)
        g = z / (p + 1.0)
        h = jnp.maximum((1.0 - z) / ((p + 1.0) ** 2), _HESS_EPS)
        return g * weight, h * weight


class PseudoHuber(Objective):
    name = "reg:pseudohubererror"
    default_metric = "mphe"

    def grad_hess(self, margin, label, weight):
        delta = float(self.params.get("huber_slope", 1.0))
        z = margin - label
        scale = jnp.sqrt(1.0 + (z / delta) ** 2)
        g = z / scale
        h = 1.0 / (scale**3)
        return g * weight, h * weight


class AbsoluteError(Objective):
    name = "reg:absoluteerror"
    default_metric = "mae"

    def grad_hess(self, margin, label, weight):
        g = jnp.sign(margin - label)
        h = jnp.ones_like(margin)
        return g * weight, h * weight


class LogisticRegression(Objective):
    """reg:logistic — logistic loss, label in [0,1], prediction is probability."""

    name = "reg:logistic"
    default_metric = "rmse"
    prob_transform = True

    def validate_labels(self, labels):
        if labels.size and ((labels < 0).any() or (labels > 1).any()):
            raise exc.UserError(LOGISTIC_REGRESSION_LABEL_RANGE_ERROR)

    def base_margin(self, base_score):
        base_score = float(base_score)
        if not 0.0 < base_score < 1.0:
            raise exc.UserError(
                "base_score must be in (0,1) for logistic loss"
            )
        return math.log(base_score / (1.0 - base_score))

    def grad_hess(self, margin, label, weight):
        p = _sigmoid(margin)
        w = jnp.where(label == 1.0, weight * self.scale_pos_weight, weight)
        g = (p - label) * w
        h = jnp.maximum(p * (1.0 - p), _EPS) * w
        return g, h

    def margin_to_prediction(self, margin):
        return 1.0 / (1.0 + np.exp(-margin))


class BinaryLogistic(LogisticRegression):
    name = "binary:logistic"
    default_metric = "logloss"


class BinaryLogitRaw(LogisticRegression):
    """binary:logitraw — logistic gradient, raw margin as prediction."""

    name = "binary:logitraw"
    default_metric = "logloss"
    prob_transform = False

    def margin_to_prediction(self, margin):
        return margin


class BinaryHinge(Objective):
    name = "binary:hinge"
    default_metric = "error"

    def validate_labels(self, labels):
        if labels.size and ((labels < 0).any() or (labels > 1).any()):
            raise exc.UserError(LOGISTIC_REGRESSION_LABEL_RANGE_ERROR)

    def grad_hess(self, margin, label, weight):
        y = 2.0 * label - 1.0
        in_margin = margin * y < 1.0
        g = jnp.where(in_margin, -y, 0.0) * weight
        h = jnp.where(in_margin, 1.0, _HESS_EPS) * weight
        return g, h

    def margin_to_prediction(self, margin):
        return (margin > 0).astype(np.float32)


class PoissonRegression(Objective):
    name = "count:poisson"
    default_metric = "poisson-nloglik"

    def validate_labels(self, labels):
        if labels.size and (labels < 0).any():
            raise exc.UserError(POISSON_REGRESSION_ERROR)

    def base_margin(self, base_score):
        return math.log(max(float(base_score), 1e-16))

    def grad_hess(self, margin, label, weight):
        p = jnp.exp(margin)
        g = (p - label) * weight
        h = p * weight
        return g, h

    def margin_to_prediction(self, margin):
        return np.exp(margin)


class GammaRegression(PoissonRegression):
    name = "reg:gamma"
    default_metric = "gamma-nloglik"

    def validate_labels(self, labels):
        if labels.size and (labels < 0).any():
            raise exc.UserError("label must be nonnegative for gamma regression")

    def grad_hess(self, margin, label, weight):
        ey = label * jnp.exp(-margin)
        g = (1.0 - ey) * weight
        h = jnp.maximum(ey, _HESS_EPS) * weight
        return g, h


class TweedieRegression(PoissonRegression):
    name = "reg:tweedie"

    def __init__(self, params=None):
        super().__init__(params)
        self.rho = float(self.params.get("tweedie_variance_power", 1.5))

    @property
    def default_metric(self):  # noqa: A003 - mirrors xgboost's dynamic default
        return "tweedie-nloglik@{}".format(self.rho)

    def validate_labels(self, labels):
        if labels.size and (labels < 0).any():
            raise exc.UserError(TWEEDIE_REGRESSION_ERROR)

    def grad_hess(self, margin, label, weight):
        rho = self.rho
        a = label * jnp.exp((1.0 - rho) * margin)
        b = jnp.exp((2.0 - rho) * margin)
        g = (-a + b) * weight
        h = jnp.maximum(-a * (1.0 - rho) + b * (2.0 - rho), _HESS_EPS) * weight
        return g, h


class SoftmaxMulti(Objective):
    """multi:softmax / multi:softprob — margin is [n, num_class]."""

    name = "multi:softmax"
    default_metric = "merror"

    def __init__(self, params=None):
        super().__init__(params)
        self.num_class = int(self.params.get("num_class", 0))
        if self.num_class < 2:
            raise exc.UserError(
                "Require input for parameter 'num_class' for multi-classification"
            )
        self.num_output_group = self.num_class

    def validate_labels(self, labels):
        if labels.size and ((labels < 0).any() or (labels >= self.num_class).any()):
            raise exc.UserError(MULTI_CLASS_LABEL_RANGE_ERROR)

    def base_margin(self, base_score):
        return 0.5

    def grad_hess(self, margin, label, weight):
        # margin [n, C]; label [n]; weight [n]
        p = jnp.exp(margin - jnp.max(margin, axis=1, keepdims=True))
        p = p / jnp.sum(p, axis=1, keepdims=True)
        onehot = (label[:, None] == jnp.arange(p.shape[1])[None, :]).astype(p.dtype)
        g = (p - onehot) * weight[:, None]
        h = jnp.maximum(2.0 * p * (1.0 - p), _EPS) * weight[:, None]
        return g, h

    def margin_to_prediction(self, margin):
        return np.argmax(margin, axis=1).astype(np.float32)


class SoftprobMulti(SoftmaxMulti):
    name = "multi:softprob"
    default_metric = "mlogloss"
    prob_transform = True

    def margin_to_prediction(self, margin):
        e = np.exp(margin - margin.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)


class SurvivalAft(Objective):
    """survival:aft — accelerated failure time with point labels.

    The SageMaker data contract carries a single label column, so the
    censoring interval degenerates to y_lower == y_upper == label
    (uncensored); the distribution/scale hyperparameters
    (aft_loss_distribution[_scale]) behave as in xgboost.
    """

    name = "survival:aft"
    default_metric = "aft-nloglik"

    def __init__(self, params=None):
        super().__init__(params)
        self.dist = str(self.params.get("aft_loss_distribution", "normal"))
        self.sigma = float(self.params.get("aft_loss_distribution_scale", 1.0))

    def validate_labels(self, labels):
        if labels.size and (labels <= 0).any():
            raise exc.UserError("survival:aft labels (event times) must be positive")

    def base_margin(self, base_score):
        return math.log(max(float(base_score), 1e-16))

    def grad_hess(self, margin, label, weight):
        s = self.sigma
        z = (jnp.log(jnp.maximum(label, 1e-12)) - margin) / s
        if self.dist == "normal":
            g = -z / s
            h = jnp.full_like(margin, 1.0 / (s * s))
        elif self.dist == "logistic":
            ez = jnp.exp(-jnp.abs(z))
            sig = jnp.where(z >= 0, 1.0 / (1.0 + ez), ez / (1.0 + ez))
            g = -(2.0 * sig - 1.0) / s
            h = jnp.maximum(2.0 * sig * (1.0 - sig) / (s * s), _HESS_EPS)
        else:  # extreme (Gumbel)
            w = jnp.exp(jnp.clip(z, -30.0, 30.0))
            g = (1.0 - w) / s
            h = jnp.maximum(w / (s * s), _HESS_EPS)
        return g * weight, h * weight

    def margin_to_prediction(self, margin):
        return np.exp(margin)


class SurvivalCox(Objective):
    """survival:cox — proportional-hazards partial likelihood.

    Labels follow xgboost's convention: positive = event time (uncensored),
    negative = |censoring time| (right-censored). Risk sets are evaluated via
    cumulative sums over a host-precomputed time ordering captured at first
    call (the label vector is static across rounds).
    """

    name = "survival:cox"
    default_metric = "cox-nloglik"

    def base_margin(self, base_score):
        return 0.0

    def grad_hess(self, margin, label, weight):
        abs_time = jnp.abs(label)
        is_event = (label > 0).astype(margin.dtype)
        # risk set of i: rows with abs_time >= abs_time_i. Sort descending by
        # time; cumulative sums give risk-set aggregates.
        order = jnp.argsort(-abs_time)
        inv = jnp.argsort(order)
        exp_m = jnp.exp(margin - jnp.max(margin)) * weight
        exp_sorted = exp_m[order]
        cum_risk = jnp.cumsum(exp_sorted)[inv]          # sum over risk set of i
        # accumulate, over events e with t_e <= t_i, of 1/risk(e) and 1/risk(e)^2
        ev_sorted = (is_event * weight)[order]
        inv_risk = ev_sorted[::-1] / cum_risk[order][::-1]
        inv_risk2 = ev_sorted[::-1] / (cum_risk[order][::-1] ** 2)
        cum_inv = jnp.cumsum(inv_risk)[::-1][inv]
        cum_inv2 = jnp.cumsum(inv_risk2)[::-1][inv]
        g = -is_event * weight + exp_m * cum_inv
        h = jnp.maximum(exp_m * cum_inv - (exp_m**2) * cum_inv2, _HESS_EPS)
        return g, h

    def margin_to_prediction(self, margin):
        return np.exp(margin)


class LambdaRankObjective(Objective):
    """rank:pairwise / rank:ndcg / rank:map — LambdaMART gradients.

    Gradients need the query-group layout, so the booster routes these through
    ``ops.ranking.lambdarank_grad_hess`` over a padded [groups, max_group]
    index built once per dataset. This class carries scheme metadata only.
    """

    name = "rank:pairwise"
    default_metric = "map"
    needs_groups = True

    def __init__(self, params=None):
        super().__init__(params)
        self.scheme = self.name.split(":")[1]

    def base_margin(self, base_score):
        return float(base_score)

    def grad_hess(self, margin, label, weight):
        raise exc.AlgorithmError(
            "ranking objectives need group info; the booster must route through "
            "ops.ranking.lambdarank_grad_hess"
        )


class RankNdcg(LambdaRankObjective):
    name = "rank:ndcg"
    default_metric = "ndcg"


class RankMap(LambdaRankObjective):
    name = "rank:map"
    default_metric = "map"


_REGISTRY = {
    cls.name: cls
    for cls in [
        SquaredError,
        SquaredLogError,
        PseudoHuber,
        AbsoluteError,
        LogisticRegression,
        BinaryLogistic,
        BinaryLogitRaw,
        BinaryHinge,
        PoissonRegression,
        GammaRegression,
        TweedieRegression,
        SoftmaxMulti,
        SoftprobMulti,
        SurvivalAft,
        SurvivalCox,
        LambdaRankObjective,
        RankNdcg,
        RankMap,
    ]
}
_REGISTRY["reg:linear"] = SquaredError  # deprecated alias


def create_objective(name, params=None):
    """Instantiate an objective by its xgboost name."""
    name = name or "reg:squarederror"
    cls = _REGISTRY.get(name)
    if cls is None:
        raise exc.UserError(
            "Objective '{}' is not supported yet. Supported: {}".format(
                name, ", ".join(sorted(_REGISTRY))
            )
        )
    return cls(params)


def default_base_score(name):
    """XGBoost's default base_score is 0.5 for every objective family."""
    return 0.5
