from .booster import TrainConfig, train  # noqa: F401
from .forest import Forest, Tree  # noqa: F401
from .objectives import create_objective  # noqa: F401
