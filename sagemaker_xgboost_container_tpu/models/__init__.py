from .booster import TrainConfig, train  # noqa: F401
from .forest import Forest, Tree  # noqa: F401
from .objectives import create_objective  # noqa: F401

# familiar alias for script-mode users porting xgboost code
Booster = Forest
