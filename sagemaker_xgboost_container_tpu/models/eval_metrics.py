"""Built-in evaluation metrics (the xgboost ``eval_metric`` set).

Host-side numpy implementations evaluated on *transformed* predictions
(probabilities for logistic, class probabilities for softprob), matching
xgboost's metric semantics. The stdout line they feed is the HPO scrape
contract (algorithm/metrics.py); sklearn-backed "custom" metrics live in
``metrics/custom_metrics.py`` mirroring the reference split
(custom_metrics.py vs native metrics).

Each metric: fn(preds, labels, weights) -> float. ``preds`` is what
``Objective.margin_to_prediction`` returns except for the multiclass margin
metrics, which receive the full [n, C] probability matrix.
"""

import numpy as np

from ..toolkit import exceptions as exc

_EPS = 1e-15


def _w(weights, labels):
    return np.ones_like(labels) if weights is None else weights


def rmse(preds, labels, weights=None):
    w = _w(weights, labels)
    return float(np.sqrt(np.sum(w * (preds - labels) ** 2) / np.sum(w)))


def mse(preds, labels, weights=None):
    w = _w(weights, labels)
    return float(np.sum(w * (preds - labels) ** 2) / np.sum(w))


def mae(preds, labels, weights=None):
    w = _w(weights, labels)
    return float(np.sum(w * np.abs(preds - labels)) / np.sum(w))


def mape(preds, labels, weights=None):
    w = _w(weights, labels)
    return float(np.sum(w * np.abs((labels - preds) / np.maximum(np.abs(labels), _EPS))) / np.sum(w))


def rmsle(preds, labels, weights=None):
    w = _w(weights, labels)
    return float(
        np.sqrt(np.sum(w * (np.log1p(np.maximum(preds, 0)) - np.log1p(labels)) ** 2) / np.sum(w))
    )


def mphe(preds, labels, weights=None, slope=1.0):
    w = _w(weights, labels)
    z = (preds - labels) / slope
    return float(np.sum(w * (slope**2) * (np.sqrt(1 + z * z) - 1)) / np.sum(w))


def logloss(preds, labels, weights=None):
    w = _w(weights, labels)
    # float64 before clipping: in float32, 1 - 1e-15 rounds to exactly 1.0
    # and saturated probabilities produce log(0) -> nan
    p = np.clip(np.asarray(preds, np.float64), _EPS, 1 - _EPS)
    return float(-np.sum(w * (labels * np.log(p) + (1 - labels) * np.log(1 - p))) / np.sum(w))


def error(preds, labels, weights=None, threshold=0.5):
    w = _w(weights, labels)
    pred_label = (preds > threshold).astype(np.float32)
    return float(np.sum(w * (pred_label != labels)) / np.sum(w))


def auc(preds, labels, weights=None):
    """Weighted ROC-AUC via the Mann-Whitney statistic with tie midranks.

    U = sum_pos w_i * rank_i - W_pos^2 / 2, where rank_i is the sample's
    midrank in cumulative-weight space (ties share their group's midpoint);
    AUC = U / (W_pos * W_neg).
    """
    w = _w(weights, labels)
    pos = labels > 0
    if not pos.any() or pos.all():
        raise exc.UserError(
            "Check failed: !auc_error AUC: the dataset only contains pos or neg samples"
        )
    order = np.argsort(preds, kind="stable")
    sp, sw, spos = preds[order], w[order], pos[order]
    _, inv = np.unique(sp, return_inverse=True)
    group_w = np.bincount(inv, weights=sw)
    group_end = np.cumsum(group_w)
    ranks = (group_end - group_w / 2.0)[inv]
    w_pos = float(np.sum(sw[spos]))
    w_neg = float(np.sum(sw[~spos]))
    u = float(np.sum(ranks[spos] * sw[spos])) - w_pos * w_pos / 2.0
    return float(np.clip(u / (w_pos * w_neg), 0.0, 1.0))


def aucpr(preds, labels, weights=None):
    from sklearn.metrics import average_precision_score

    return float(average_precision_score(labels, preds, sample_weight=weights))


def merror(prob_matrix, labels, weights=None):
    w = _w(weights, labels)
    pred_label = np.argmax(prob_matrix, axis=1)
    return float(np.sum(w * (pred_label != labels)) / np.sum(w))


def mlogloss(prob_matrix, labels, weights=None):
    w = _w(weights, labels)
    p = np.clip(
        np.asarray(prob_matrix, np.float64)[np.arange(len(labels)), labels.astype(int)],
        _EPS,
        1.0,
    )
    return float(-np.sum(w * np.log(p)) / np.sum(w))


def poisson_nloglik(preds, labels, weights=None):
    from scipy.special import gammaln

    w = _w(weights, labels)
    p = np.maximum(preds, _EPS)
    return float(np.sum(w * (p - labels * np.log(p) + gammaln(labels + 1))) / np.sum(w))


def gamma_nloglik(preds, labels, weights=None):
    w = _w(weights, labels)
    p = np.maximum(preds, _EPS)
    y = np.maximum(labels, _EPS)
    # xgboost uses deviance-based nloglik with psi = 1
    return float(np.sum(w * (np.log(p) + y / p)) / np.sum(w))


def gamma_deviance(preds, labels, weights=None):
    w = _w(weights, labels)
    p = np.maximum(preds, _EPS)
    y = np.maximum(labels, _EPS)
    return float(2.0 * np.sum(w * (np.log(p / y) + y / p - 1)) / np.sum(w))


def tweedie_nloglik(preds, labels, weights=None, rho=1.5):
    w = _w(weights, labels)
    p = np.maximum(preds, _EPS)
    a = labels * np.power(p, 1 - rho) / (1 - rho)
    b = np.power(p, 2 - rho) / (2 - rho)
    return float(np.sum(w * (-a + b)) / np.sum(w))


def aft_nloglik(preds, labels, weights=None, dist="normal", sigma=1.0):
    """AFT negative log-likelihood for uncensored (point-label) data.

    preds are event-time predictions (exp(margin)); z = (log y - log pred)/sigma.
    """
    w = _w(weights, labels)
    z = (np.log(np.maximum(labels, 1e-12)) - np.log(np.maximum(preds, 1e-12))) / sigma
    if dist == "logistic":
        nll = -(-z - 2.0 * np.log1p(np.exp(-z))) + np.log(sigma * np.maximum(labels, 1e-12))
    elif dist == "extreme":
        nll = -(z - np.exp(np.clip(z, -30, 30))) + np.log(sigma * np.maximum(labels, 1e-12))
    else:  # normal
        nll = 0.5 * z * z + np.log(
            sigma * np.maximum(labels, 1e-12) * np.sqrt(2 * np.pi)
        )
    return float(np.sum(w * nll) / np.sum(w))


def cox_nloglik(preds, labels, weights=None):
    """Negative Breslow partial log-likelihood; labels<0 = censored at |t|,
    preds are hazard ratios exp(margin)."""
    w = _w(weights, labels)
    abs_time = np.abs(labels)
    event = (labels > 0).astype(np.float64)
    order = np.argsort(-abs_time, kind="stable")
    hz = np.maximum(np.asarray(preds, np.float64), 1e-300)[order] * w[order]
    cum_risk = np.cumsum(hz)
    ev = (event * w)[order]
    # clamp hz inside the log: weight-0 rows (sample weights or multi-host
    # gather padding) have hz=0, and 0 * log(0) would NaN the whole metric
    # even though ev=0 makes their true contribution zero
    ll = np.sum(
        ev
        * (np.log(np.maximum(hz, 1e-300)) - np.log(np.maximum(cum_risk, 1e-300)))
    )
    n_events = max(ev.sum(), 1e-12)
    return float(-ll / n_events)


def interval_regression_accuracy(preds, labels, weights=None):
    from ..toolkit import exceptions as exc

    raise exc.UserError(
        "Metric 'interval-regression-accuracy' requires interval-censored labels "
        "(label_lower_bound/label_upper_bound), which the csv/libsvm data contract "
        "cannot express; use 'aft-nloglik' instead."
    )


def _dcg_at(scores_sorted_labels, k):
    gains = (2.0**scores_sorted_labels - 1.0) / np.log2(np.arange(2, len(scores_sorted_labels) + 2))
    if k:
        gains = gains[:k]
    return gains.sum()


def ndcg(preds, labels, weights=None, groups=None, k=None):
    """Mean NDCG over query groups (groups = group-size array)."""
    if groups is None:
        groups = np.asarray([len(labels)])
    out, start = [], 0
    for size in groups:
        size = int(size)
        sl = slice(start, start + size)
        start += size
        lab = labels[sl]
        order = np.argsort(-preds[sl], kind="stable")
        dcg = _dcg_at(lab[order], k)
        ideal = _dcg_at(np.sort(lab)[::-1], k)
        out.append(dcg / ideal if ideal > 0 else 1.0)
    return float(np.mean(out))


def map_metric(preds, labels, weights=None, groups=None, k=None):
    """Mean average precision over query groups (binary relevance)."""
    if groups is None:
        groups = np.asarray([len(labels)])
    out, start = [], 0
    for size in groups:
        size = int(size)
        sl = slice(start, start + size)
        start += size
        lab = (labels[sl] > 0).astype(np.float64)
        order = np.argsort(-preds[sl], kind="stable")
        rel = lab[order]
        if k:
            rel = rel[:k]
        hits = np.cumsum(rel)
        precisions = hits / np.arange(1, len(rel) + 1)
        denom = rel.sum()
        out.append(float((precisions * rel).sum() / denom) if denom > 0 else 1.0)
    return float(np.mean(out))


_SIMPLE = {
    "rmse": rmse,
    "mse": mse,
    "mae": mae,
    "mape": mape,
    "rmsle": rmsle,
    "mphe": mphe,
    "logloss": logloss,
    "error": error,
    "auc": auc,
    "aucpr": aucpr,
    "poisson-nloglik": poisson_nloglik,
    "gamma-nloglik": gamma_nloglik,
    "gamma-deviance": gamma_deviance,
    "tweedie-nloglik": tweedie_nloglik,
    "aft-nloglik": aft_nloglik,
    "cox-nloglik": cox_nloglik,
    "interval-regression-accuracy": interval_regression_accuracy,
}

_MULTI = {"merror": merror, "mlogloss": mlogloss}
_RANKING = {"ndcg": ndcg, "map": map_metric}


def is_native_metric(name):
    base = name.split("@")[0]
    return base in _SIMPLE or base in _MULTI or base in _RANKING


def evaluate(name, preds, labels, weights=None, groups=None, prob_matrix=None):
    """Dispatch one metric by its (possibly @-suffixed) name."""
    base, _, suffix = name.partition("@")
    if base in _MULTI:
        if prob_matrix is None:
            raise exc.AlgorithmError("metric {} needs the probability matrix".format(name))
        return _MULTI[base](prob_matrix, labels, weights)
    if base in _RANKING:
        k = int(float(suffix)) if suffix else None
        return _RANKING[base](preds, labels, weights, groups=groups, k=k)
    if base == "error" and suffix:
        return error(preds, labels, weights, threshold=float(suffix))
    if base == "tweedie-nloglik" and suffix:
        return tweedie_nloglik(preds, labels, weights, rho=float(suffix))
    if base in _SIMPLE:
        return _SIMPLE[base](preds, labels, weights)
    raise exc.UserError("Unknown eval metric: {}".format(name))
