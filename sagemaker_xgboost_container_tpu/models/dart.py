"""DART booster: dropout-regularized boosting rounds.

The reference validates booster=dart with sample_type/normalize_type/
rate_drop/one_drop/skip_drop (hyperparameter_validation.py:272-276) and
delegates to libxgboost's dart updater. Algorithm (Rashmi & Gilad-Bachrach,
mirrored from xgboost's dart semantics):

per round: sample a dropped subset D of existing trees (each kept tree with
prob rate_drop; if empty and one_drop, force one; with prob skip_drop no
dropout at all) -> compute gradients at margins *without* D -> fit the new
tree -> rescale: normalize_type=tree: new *= eta/(k+eta), dropped *= k/(k+eta);
forest: new *= eta/(1+eta), dropped *= 1/(1+eta).

Per-tree train-row contributions are cached on device so "margins without D"
is a subtraction, not a re-predict; dropped trees' cached contributions and
host-side leaf values are rescaled in place (dart mutates history).

Multi-class (num_class>1): the round builds one tree per class under the same
per-class vmap the gbtree path uses (booster.py one_round), sharing one rng so
feature-subset draws match across classes. The dropout unit is a whole
boosting round — all classes drop the same historical rounds (shared-seed
dropout) — so cached contributions are [n, num_class] and the round's
normalization rescales every class's tree for a dropped round. The reference
permits booster=dart with multi:softmax/softprob (its HP schema constrains
only sample_type/normalize_type, hyperparameter_validation.py:272-276, and
libxgboost's dart updater imposes no class restriction).
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.tree_build import build_tree
from ..toolkit import exceptions as exc
from .booster import _TrainingSession, _eval_metric_names
from .forest import compact_padded_tree

logger = logging.getLogger(__name__)


def train_dart(config, forest, dtrain, evals, feval, callbacks, num_boost_round, mesh=None):
    # multi-process: rows shard across hosts exactly like the tree booster;
    # the jitted builder runs on the global arrays (GSPMD combines), eval
    # lines combine across hosts, dropout draws ride the shared seed so all
    # hosts drop identical tree sets (reference parity: libxgboost's dart
    # trains under Rabit like any other updater). A multi-process run MUST
    # carry a cross-host data mesh — anything else would silently train a
    # divergent per-host model, so refuse loudly (checked BEFORE the
    # axis-name fallback below).
    is_multiproc = jax.process_count() > 1
    if is_multiproc and (
        mesh is None
        or "data" not in getattr(mesh, "axis_names", ())
        or int(mesh.shape["data"]) <= 1
    ):
        raise exc.UserError(
            "Multi-process booster=dart training requires a mesh with a "
            "'data' axis spanning the hosts."
        )
    if mesh is not None and "data" not in getattr(mesh, "axis_names", ()):
        mesh = None
    p = config.objective_params
    rate_drop = float(p.get("rate_drop", 0.0))
    skip_drop = float(p.get("skip_drop", 0.0))
    one_drop = int(p.get("one_drop", 0))
    sample_type = p.get("sample_type", "uniform")
    normalize_type = p.get("normalize_type", "tree")
    eta = config.eta

    for cb in callbacks:
        if getattr(cb, "save_best", False):
            raise exc.UserError(
                "early_stopping with save_best is not supported for booster=dart: "
                "dropout rescales historical trees, so truncating to the best "
                "iteration does not reproduce the best model."
            )
    if config.num_parallel_tree > 1:
        logger.warning(
            "booster=dart ignores num_parallel_tree=%d and builds one tree "
            "per class per round (libxgboost's dart samples dropout over "
            "individual trees; this engine's dropout unit is the round).",
            config.num_parallel_tree,
        )

    # With a mesh the session shards rows over the data axis; dart's own
    # jitted builder/grad ops run on those sharded arrays under XLA's
    # automatic SPMD partitioning (GSPMD inserts the histogram combines —
    # semantically the same global program, so trees match single-device)
    session = _TrainingSession(config, dtrain, list(evals), forest, mesh=mesh)
    metric_names = _eval_metric_names(config, session.objective)
    # class count follows the session's output-group count (the objective),
    # not raw num_class: a single-output objective with num_class set keeps
    # 1-D shapes everywhere, same as the gbtree path
    nclass = session.num_group

    # build trees with unit shrinkage; dart applies its own scaling
    jit_kwargs = {}
    if is_multiproc:
        # the small tree arrays must come back replicated so every host can
        # pull them (np.asarray on a non-addressable sharded output would
        # fail); row_out stays sharded with the rows
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ..ops.tree_build import _TREE_FIELDS

        tree_spec = {k: NamedSharding(mesh, P()) for k in _TREE_FIELDS}
        row_spec = P("data") if nclass == 1 else P("data", None)
        jit_kwargs["out_shardings"] = (tree_spec, NamedSharding(mesh, row_spec))

    hist_knobs = session.hist_knobs  # the session's host-side knob snapshot (trace-safety)

    def _build_one(bins, g, h, num_cuts, mask, rng):
        return build_tree(
            bins, g, h, num_cuts,
            max_depth=config.max_depth,
            num_bins=session.train_binned.num_bins,
            reg_lambda=config.reg_lambda,
            alpha=config.alpha,
            gamma=config.gamma,
            min_child_weight=config.min_child_weight,
            eta=1.0,
            max_delta_step=config.max_delta_step,
            feature_mask=mask,
            colsample_bylevel=config.colsample_bylevel,
            rng=rng,
            knobs=hist_knobs,
        )

    if nclass > 1:
        # same per-class vmap as the gbtree path; the shared rng makes
        # every class draw identical feature subsets
        def _build(bins, g, h, num_cuts, mask, rng):
            tree, row_out = jax.vmap(
                lambda gc, hc: _build_one(bins, gc, hc, num_cuts, mask, rng)
            )(g.T, h.T)
            return tree, row_out.T
    else:
        _build = _build_one
    # graftlint: disable=trace-uncached-jit — session-scope construction: one builder per train_dart call
    builder = jax.jit(_build, **jit_kwargs)
    # graftlint: disable=trace-uncached-jit — session-scope construction: one grad fn per train_dart call
    grad_fn = jax.jit(session.objective.grad_hess)

    tree_contribs = []   # device [n] ([n, C] multi-class) contributions, current scaling
    tree_weights = []    # current scale factor per dropout unit (host floats)
    unit_slices = []     # dropout unit -> (start, stop) into forest.trees
    rng = np.random.RandomState(config.seed)

    n_pad = session.bins.shape[0]  # global padded rows

    if forest.trees:
        # checkpoint resume: dropout must cover the checkpoint's trees too, so
        # rebuild their per-row contributions (one stacked-kernel pass;
        # categorical-aware for BYO xgboost checkpoints). The [n, T] matrix is
        # staged on device ONCE; per-unit contributions are device slices.
        from ..ops.predict import forest_leaf_margins

        stacked = forest._stack(slice(0, len(forest.trees)))
        leaf = forest_leaf_margins(stacked, dtrain.features)  # [n_local, T]
        if is_multiproc:
            # this host's rows -> its segment of the global [n_pad] layout
            from jax.sharding import PartitionSpec as P

            local_pad = n_pad // jax.process_count()
            leaf = np.asarray(leaf)
            if leaf.shape[0] != local_pad:
                leaf = np.pad(leaf, ((0, local_pad - leaf.shape[0]), (0, 0)))
            leaf = session._put(leaf, P("data", None))
        elif leaf.shape[0] != n_pad:  # mesh padding: align with session rows
            leaf = jnp.pad(leaf, ((0, n_pad - leaf.shape[0]), (0, 0)))
        if nclass > 1:
            # round-units: one [n, C] contribution per boosted round, columns
            # placed by the stored class ids — a dropped unit removes the
            # whole round across classes (shared-seed dropout)
            indptr = forest.iteration_indptr
            for i in range(len(indptr) - 1):
                s0, s1 = int(indptr[i]), int(indptr[i + 1])
                info = [int(c) for c in forest.tree_info[s0:s1]]
                if info == list(range(nclass)):
                    cols = leaf[:, s0:s1]
                else:  # BYO layouts (e.g. parallel trees): one-hot matmul
                    onehot = jax.nn.one_hot(jnp.asarray(info), nclass, dtype=leaf.dtype)
                    cols = leaf[:, s0:s1] @ onehot
                tree_contribs.append(cols)
                tree_weights.append(1.0)
                unit_slices.append((s0, s1))
        else:
            for i in range(leaf.shape[1]):
                tree_contribs.append(leaf[:, i])
                tree_weights.append(1.0)
                unit_slices.append((i, i + 1))

    evals_log = {}
    _rows_cache = {}  # round-invariant global labels/weights (cox gather)
    stop = False
    # full callback protocol, like the gbtree loop (booster.py): RoundTimer's
    # round-0 timestamp and phase recorder are armed in before_training
    for cb in callbacks:
        if hasattr(cb, "before_training"):
            forest = cb.before_training(forest) or forest
    for rnd in range(num_boost_round):
        if session.approx_resketch:
            # tree_method='approx': hessian-weighted candidate re-sketch per
            # round, same as the gbtree dispatch path (the session re-bins in
            # place; dropout bookkeeping is float-margin-space and unaffected).
            # Sketch weights come from the FULL-forest margins — the dropout
            # set isn't sampled yet; libxgboost sketches from the
            # dropout-adjusted gradients, a one-round-lag nuance at
            # rate_drop-sized magnitude.
            session._resketch_bins()
        # ---- sample dropout set -----------------------------------------
        dropped = []
        if tree_contribs and rng.uniform() >= skip_drop:
            if sample_type == "weighted" and sum(tree_weights) > 0:
                probs = np.asarray(tree_weights) / sum(tree_weights)
                draws = rng.uniform(size=len(tree_contribs)) < rate_drop * probs * len(probs)
            else:
                draws = rng.uniform(size=len(tree_contribs)) < rate_drop
            dropped = list(np.flatnonzero(draws))
            if not dropped and one_drop:
                dropped = [int(rng.randint(len(tree_contribs)))]

        drop_sum = None
        for i in dropped:
            drop_sum = tree_contribs[i] if drop_sum is None else drop_sum + tree_contribs[i]
        margins_used = session.margins - drop_sum if drop_sum is not None else session.margins

        g, h = grad_fn(margins_used, session.labels, session.weights)

        d = session.bins.shape[1]
        if config.colsample_bytree < 1.0:
            k = max(1, int(round(config.colsample_bytree * d)))
            mask = np.zeros(d, np.float32)
            mask[rng.choice(d, size=k, replace=False)] = 1.0
        else:
            mask = np.ones(d, np.float32)
        if config.subsample < 1.0:
            keep = (rng.uniform(size=session.bins.shape[0]) < config.subsample).astype(np.float32)
            kj = jnp.asarray(keep)
            if nclass > 1:
                kj = kj[:, None]
            g, h = g * kj, h * kj

        tree, row_out = builder(
            session.bins, g, h, session.num_cuts, jnp.asarray(mask),
            jax.random.PRNGKey(rng.randint(2**31)),
        )

        # ---- dart normalization ------------------------------------------
        k = len(dropped)
        if k == 0:
            new_scale, old_scale = eta, 1.0
        elif normalize_type == "forest":
            new_scale = eta / (1.0 + eta)
            old_scale = 1.0 / (1.0 + eta)
        else:  # "tree"
            new_scale = eta / (k + eta)
            old_scale = k / (k + eta)

        new_contrib = row_out * new_scale
        margins = margins_used + new_contrib
        for i in dropped:
            tree_contribs[i] = tree_contribs[i] * old_scale
            tree_weights[i] *= old_scale
            margins = margins + tree_contribs[i]
            # rescale the stored trees' leaves (dart mutates history); a
            # multi-class unit covers the round's whole per-class tree group
            s0, s1 = unit_slices[i]
            for t in forest.trees[s0:s1]:
                t.value *= old_scale
        forest._stacked_cache = None
        session.margins = margins
        tree_contribs.append(new_contrib)
        tree_weights.append(new_scale)

        tree_np = jax.tree_util.tree_map(np.asarray, tree)
        tree_np["leaf_value"] = tree_np["leaf_value"] * new_scale
        tree_np["base_weight"] = tree_np["base_weight"] * new_scale
        if nclass > 1:
            forest.append_round(
                [
                    compact_padded_tree(
                        jax.tree_util.tree_map(lambda a: a[c], tree_np),
                        session.cuts,
                    )
                    for c in range(nclass)
                ],
                list(range(nclass)),
            )
        else:
            forest.append_round([compact_padded_tree(tree_np, session.cuts)], [0])
        unit_slices.append((len(forest.trees) - nclass, len(forest.trees)))

        # ---- eval: dart predicts with the full (rescaled) forest ---------
        results = []
        if session.eval_sets:
            from .booster import evaluate_host_lines

            # train margins come from the session (maintained under dart's
            # rescaling); other sets re-predict with the mutated forest.
            # _to_host returns this host's local rows in multi-process runs
            # and evaluate_host_lines combines the lines across hosts.
            results = evaluate_host_lines(
                (
                    (
                        name,
                        dm,
                        session._to_host(session.margins, session.n)
                        if binned is session.train_binned
                        else forest.predict_margin(dm.features),
                    )
                    for name, dm, binned in session.eval_sets
                ),
                metric_names,
                feval,
                session.objective,
                session.num_group,
                config.objective_params,
                session.is_multiprocess,
                global_rows_cache=_rows_cache,
            )
        for data_name, metric_name, value in results:
            evals_log.setdefault(data_name, {}).setdefault(metric_name, []).append(value)

        for cb in callbacks:
            if hasattr(cb, "after_iteration") and cb.after_iteration(forest, rnd, evals_log):
                stop = True
        if stop:
            break

    for cb in callbacks:
        if hasattr(cb, "after_training"):
            forest = cb.after_training(forest) or forest
    return forest
