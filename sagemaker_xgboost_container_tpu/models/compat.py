"""Foreign model-format loaders: xgboost UBJSON, legacy binary, pickles.

The serving contract requires loading models produced by real xgboost
(reference serve_utils.py:171-197 loads pickle-or-native): customers bring
``xgboost-model`` files saved as

* xgboost JSON (handled by Forest.load_json directly),
* xgboost UBJSON (draft-12 UBJ encoding of the same document — the default
  ``save_model`` format since xgboost 2.x),
* the legacy binary format (pre-1.0 ``deprecated`` format: packed C structs),
* Python pickles of ``xgboost.core.Booster`` — unpickled via a stub module
  (no xgboost import in this image), whose ``handle`` buffer embeds either
  the legacy binary + a ``CONFIG-offset:`` JSON trailer, UBJ, or JSON.

All paths land in our Forest, so every model runs on the XLA predict kernel.
"""

import io
import json
import pickle
import struct
import sys
import types

import numpy as np

from ..toolkit import exceptions as exc
from .forest import Forest, Tree

PKL_FORMAT = "pkl_format"
XGB_FORMAT = "xgb_format"


# ---------------------------------------------------------------------------
# UBJSON (draft-12, the subset xgboost emits)
# ---------------------------------------------------------------------------

# UBJSON numbers are big-endian (draft-12 spec)
_UBJ_INT_TYPES = {
    b"i": ("b", 1),
    b"U": ("B", 1),
    b"I": (">h", 2),
    b"u": (">H", 2),
    b"l": (">i", 4),
    b"m": (">I", 4),
    b"L": (">q", 8),
    b"M": (">Q", 8),
}
_UBJ_FLOAT_TYPES = {b"d": (">f", 4), b"D": (">d", 8)}


class _UbjReader:
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def take(self, n):
        out = self.buf[self.pos : self.pos + n]
        if len(out) != n:
            raise ValueError("truncated UBJSON")
        self.pos += n
        return out

    def peek(self):
        return self.buf[self.pos : self.pos + 1]

    def read_marker(self):
        marker = self.take(1)
        while marker == b"N":  # no-op
            marker = self.take(1)
        return marker

    def read_int(self, marker=None):
        marker = marker or self.read_marker()
        spec = _UBJ_INT_TYPES.get(marker)
        if spec is None:
            raise ValueError("expected UBJ int, got {!r}".format(marker))
        fmt, size = spec
        return struct.unpack(fmt, self.take(size))[0]

    def read_string(self):
        return self.take(self.read_int()).decode("utf-8")

    def read_value(self, marker=None):
        marker = marker or self.read_marker()
        if marker in _UBJ_INT_TYPES:
            fmt, size = _UBJ_INT_TYPES[marker]
            return struct.unpack(fmt, self.take(size))[0]
        if marker in _UBJ_FLOAT_TYPES:
            fmt, size = _UBJ_FLOAT_TYPES[marker]
            return struct.unpack(fmt, self.take(size))[0]
        if marker == b"S":
            return self.read_string()
        if marker == b"C":
            return self.take(1).decode("latin-1")
        if marker == b"T":
            return True
        if marker == b"F":
            return False
        if marker == b"Z":
            return None
        if marker == b"[":
            return self._read_array()
        if marker == b"{":
            return self._read_object()
        raise ValueError("unsupported UBJ marker {!r}".format(marker))

    def _read_array(self):
        el_type = None
        count = None
        if self.peek() == b"$":
            self.take(1)
            el_type = self.take(1)
        if self.peek() == b"#":
            self.take(1)
            count = self.read_int()
        if el_type is not None and count is not None:
            if el_type in _UBJ_INT_TYPES or el_type in _UBJ_FLOAT_TYPES:
                fmt, size = (_UBJ_INT_TYPES.get(el_type) or _UBJ_FLOAT_TYPES[el_type])
                raw = self.take(size * count)
                dtype = {
                    b"i": "b", b"U": "B", b"I": ">i2", b"u": ">u2",
                    b"l": ">i4", b"m": ">u4", b"L": ">i8", b"M": ">u8",
                    b"d": ">f4", b"D": ">f8",
                }[el_type]
                return np.frombuffer(raw, dtype=np.dtype(dtype)).tolist()
            return [self.read_value(el_type) for _ in range(count)]
        out = []
        if count is not None:
            for _ in range(count):
                out.append(self.read_value())
            return out
        while self.peek() != b"]":
            out.append(self.read_value())
        self.take(1)
        return out

    def _read_object(self):
        count = None
        if self.peek() == b"$":
            raise ValueError("typed UBJ objects unsupported")
        if self.peek() == b"#":
            self.take(1)
            count = self.read_int()
        out = {}
        if count is not None:
            for _ in range(count):
                key = self.read_string()
                out[key] = self.read_value()
            return out
        while self.peek() != b"}":
            key = self.read_string()
            out[key] = self.read_value()
        self.take(1)
        return out


def decode_ubjson(buf):
    return _UbjReader(buf).read_value()


def _ubj_int(value):
    if -128 <= value <= 127:
        return b"i" + struct.pack("b", value)
    if 0 <= value <= 255:
        return b"U" + struct.pack("B", value)
    if -(2**15) <= value < 2**15:
        return b"I" + struct.pack(">h", value)
    if -(2**31) <= value < 2**31:
        return b"l" + struct.pack(">i", value)
    return b"L" + struct.pack(">q", value)


def _ubj_str_payload(s):
    raw = s.encode("utf-8")
    return _ubj_int(len(raw)) + raw


def encode_ubjson(obj):
    """Draft-12 UBJSON encoder for the subset the model document uses."""
    out = io.BytesIO()

    def write(o):
        if o is None:
            out.write(b"Z")
        elif o is True:
            out.write(b"T")
        elif o is False:
            out.write(b"F")
        elif isinstance(o, (int, np.integer)):
            out.write(_ubj_int(int(o)))
        elif isinstance(o, (float, np.floating)):
            out.write(b"D" + struct.pack(">d", float(o)))
        elif isinstance(o, str):
            out.write(b"S" + _ubj_str_payload(o))
        elif isinstance(o, dict):
            out.write(b"{")
            for key, value in o.items():
                out.write(_ubj_str_payload(str(key)))
                write(value)
            out.write(b"}")
        elif isinstance(o, (list, tuple, np.ndarray)):
            seq = list(o)
            if seq and all(isinstance(v, (float, np.floating)) for v in seq):
                out.write(b"[$D#" + _ubj_int(len(seq)))
                out.write(struct.pack(">{}d".format(len(seq)), *map(float, seq)))
            else:
                out.write(b"[")
                for v in seq:
                    write(v)
                out.write(b"]")
        else:
            raise TypeError("cannot UBJSON-encode {!r}".format(type(o)))

    write(obj)
    return out.getvalue()


# ---------------------------------------------------------------------------
# Legacy binary model format (xgboost "deprecated" serialization)
# ---------------------------------------------------------------------------


def _parse_legacy_binary(buf):
    """Packed-struct model reader. Layouts follow the published C structs:
    LearnerModelParam (128B), GBTreeModelParam (160B), per-tree TreeParam
    (148B) + Node(20B)*n + RTreeNodeStat(16B)*n.
    """
    r = io.BytesIO(buf)
    if buf[:4] == b"binf":
        r.read(4)
    base_score, num_feature, num_class, contain_extra_attrs, contain_eval_metrics = (
        struct.unpack("<fIiii", r.read(20))
    )
    r.read(116)  # major + minor + reserved[27] -> LearnerModelParam is 136 bytes
    (len_obj,) = struct.unpack("<Q", r.read(8))
    name_obj = r.read(len_obj).decode()
    (len_gbm,) = struct.unpack("<Q", r.read(8))
    name_gbm = r.read(len_gbm).decode()
    if name_gbm not in ("gbtree", "dart"):
        raise exc.UserError(
            "Legacy binary model with booster '{}' is not supported".format(name_gbm)
        )
    num_trees, _roots, _feat, _pad = struct.unpack("<iiii", r.read(16))
    (_pbuffer,) = struct.unpack("<q", r.read(8))
    num_output_group, size_leaf_vector = struct.unpack("<ii", r.read(8))
    r.read(128)  # reserved[32]

    forest = Forest(
        objective_name=name_obj,
        base_score=base_score,
        num_feature=int(num_feature),
        num_class=max(0, int(num_class)),
    )
    trees = []
    for _ in range(num_trees):
        _roots2, num_nodes, _deleted, _maxd, _nfeat, _slv = struct.unpack(
            "<iiiiii", r.read(24)
        )
        r.read(124)  # reserved[31]
        node_raw = np.frombuffer(r.read(20 * num_nodes), dtype=np.uint8).reshape(
            num_nodes, 20
        )
        parent = node_raw[:, 0:4].copy().view("<i4").ravel()
        cleft = node_raw[:, 4:8].copy().view("<i4").ravel()
        cright = node_raw[:, 8:12].copy().view("<i4").ravel()
        sindex = node_raw[:, 12:16].copy().view("<u4").ravel()
        info = node_raw[:, 16:20].copy().view("<f4").ravel()
        stat_raw = np.frombuffer(r.read(16 * num_nodes), dtype=np.uint8).reshape(
            num_nodes, 16
        )
        loss_chg = stat_raw[:, 0:4].copy().view("<f4").ravel()
        sum_hess = stat_raw[:, 4:8].copy().view("<f4").ravel()
        base_weight = stat_raw[:, 8:12].copy().view("<f4").ravel()

        is_leaf = cleft == -1
        feature = (sindex & 0x7FFFFFFF).astype(np.int32)
        default_left = (sindex >> 31).astype(bool)
        trees.append(
            Tree(
                feature=np.where(is_leaf, 0, feature),
                threshold=np.where(is_leaf, 0.0, info),
                default_left=default_left,
                left=cleft,
                right=cright,
                value=np.where(is_leaf, info, 0.0),
                base_weight=base_weight,
                gain=loss_chg,
                sum_hess=sum_hess,
                parent=np.where(parent < 0, 2147483647, parent & 0x7FFFFFFF),
            )
        )
    forest.trees = trees
    if num_output_group <= 0:
        # some writers leave GBTreeModelParam.num_output_group zero; fall back
        # to the learner's num_class
        num_output_group = max(1, num_class)
    groups = max(1, num_output_group)
    forest.tree_info = [i % groups for i in range(num_trees)]
    per_round = groups
    forest.iteration_indptr = list(range(0, num_trees + 1, per_round))
    if forest.iteration_indptr[-1] != num_trees:
        forest.iteration_indptr.append(num_trees)
    if contain_extra_attrs:
        try:
            (count,) = struct.unpack("<Q", r.read(8))
            for _ in range(count):
                (klen,) = struct.unpack("<Q", r.read(8))
                key = r.read(klen).decode()
                (vlen,) = struct.unpack("<Q", r.read(8))
                forest.attributes[key] = r.read(vlen).decode()
        except (struct.error, UnicodeDecodeError):
            pass
    return forest


# ---------------------------------------------------------------------------
# Pickle stub
# ---------------------------------------------------------------------------


class _StubBooster:
    """Unpickle target standing in for xgboost.core.Booster."""

    def __setstate__(self, state):
        self.__dict__.update(state)

    def __reduce__(self):  # defensive: never re-pickle the stub
        raise TypeError("stub booster cannot be pickled")


def _install_xgboost_stub():
    if "xgboost" in sys.modules:
        return
    xgb = types.ModuleType("xgboost")
    core = types.ModuleType("xgboost.core")
    sklearn_mod = types.ModuleType("xgboost.sklearn")
    core.Booster = _StubBooster
    xgb.Booster = _StubBooster
    for cls_name in ("XGBRegressor", "XGBClassifier", "XGBRanker", "XGBModel"):
        setattr(sklearn_mod, cls_name, type(cls_name, (_StubBooster,), {}))
    xgb.core = core
    xgb.sklearn = sklearn_mod
    sys.modules["xgboost"] = xgb
    sys.modules["xgboost.core"] = core
    sys.modules["xgboost.sklearn"] = sklearn_mod


def _model_from_dict(doc):
    """Dispatch a decoded model document by booster type."""
    name = doc.get("learner", {}).get("gradient_booster", {}).get("name", "gbtree")
    if name == "gblinear":
        from .gblinear import LinearModel

        return LinearModel.from_dict(doc)
    return Forest.from_dict(doc)


def _forest_from_raw(raw):
    """Dispatch a raw model buffer by magic."""
    raw = bytes(raw)
    if raw[:14] == b"CONFIG-offset:":
        (offset,) = struct.unpack("<Q", raw[14:22])
        body = raw[22:]
        forest = _parse_legacy_binary(body[:offset])
        try:
            config = json.loads(body[offset:].decode("utf-8", errors="ignore") or "{}")
            learner = config.get("learner", {})
            obj_name = learner.get("objective", {}).get("name")
            if obj_name:
                forest.objective_name = obj_name
        except ValueError:
            pass
        return forest
    head = raw.lstrip()[:1]
    if head == b"{" and raw[1:2] not in (b"L", b"l", b"i", b"U", b"I", b"#", b"$"):
        return _model_from_dict(json.loads(raw.decode("utf-8")))
    if raw[:1] == b"{":
        return _model_from_dict(decode_ubjson(raw))
    return _parse_legacy_binary(raw)


def _forest_from_pickle(path):
    _install_xgboost_stub()
    with open(path, "rb") as f:
        obj = pickle.load(f)
    state = getattr(obj, "__dict__", None)
    if not state or "handle" not in state:
        raise exc.UserError("Pickled object is not an xgboost Booster")
    forest = _forest_from_raw(state["handle"])
    if state.get("feature_names"):
        forest.feature_names = list(state["feature_names"])
    best_it = state.get("best_iteration")
    if best_it is not None and not isinstance(best_it, (dict, list)):
        try:
            forest.attributes.setdefault("best_iteration", str(int(best_it)))
        except (TypeError, ValueError):
            pass
    return forest


def load_model_any_format(path):
    """-> (Forest, source format tag). The reference's pickle-or-native probe
    order (serve_utils.py:180-190): try pickle first, then native."""
    try:
        return _forest_from_pickle(path), PKL_FORMAT
    except Exception:
        pass
    with open(path, "rb") as f:
        raw = f.read()
    try:
        return _forest_from_raw(raw), XGB_FORMAT
    except Exception as e:
        raise RuntimeError(
            "Model {} cannot be loaded as pickle, JSON, UBJSON, or legacy binary: {}".format(
                path, e
            )
        )
