"""The boosting engine: ``train()`` — the TPU replacement for ``xgb.train``.

Reference hot loop (algorithm_mode/train.py:367-376) calls into libxgboost;
here each boosting round is one jitted XLA program: objective grad/hess ->
level-wise tree build (ops/tree_build) -> margin updates for train and every
eval set — the only host work per round is pulling the tree's small node
arrays (O(2^max_depth)) for the Forest and the eval scalars for callbacks.

Distribution: when a mesh is supplied, rows are sharded over the "data" axis
with ``shard_map``; the single ``lax.psum`` inside the histogram op is the
entire cross-host protocol (replacing Rabit allreduce + tracker topology,
SURVEY.md §5). Trees come out bitwise identical on every shard, so the
"master saves the model" contract is trivially consistent.

Callback protocol mirrors xgboost's (before_training / after_iteration ->
bool stop / after_training) so the orchestration layer's checkpoint, early
stop, and monitor callbacks port naturally.
"""

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.binning import bin_matrix
from ..ops.tree_build import build_tree, max_nodes_for_depth, predict_binned
from ..toolkit import exceptions as exc
from . import eval_metrics
from . import objectives as objectives_mod
from .forest import Forest, compact_padded_tree

logger = logging.getLogger(__name__)


class TrainConfig:
    """Parsed + defaulted booster parameters (static across rounds)."""

    def __init__(self, params):
        p = dict(params or {})
        self.eta = float(p.get("eta", 0.3))
        self.max_depth = int(p.get("max_depth", 6) or 6)
        self.reg_lambda = float(p.get("lambda", 1.0))
        self.alpha = float(p.get("alpha", 0.0))
        self.gamma = float(p.get("gamma", 0.0))
        self.min_child_weight = float(p.get("min_child_weight", 1.0))
        self.max_delta_step = float(p.get("max_delta_step", 0.0))
        self.max_bin = int(p.get("max_bin", 256) or 256)
        self.subsample = float(p.get("subsample", 1.0))
        self.colsample_bytree = float(p.get("colsample_bytree", 1.0))
        self.colsample_bylevel = float(p.get("colsample_bylevel", 1.0))
        self.seed = int(p.get("seed", 0))
        self.objective = p.get("objective", "reg:squarederror")
        self.num_class = int(p.get("num_class", 0) or 0)
        self.base_score = float(p.get("base_score", 0.5))
        self.tree_method = p.get("tree_method", "auto")
        self.monotone_constraints = p.get("monotone_constraints")
        self.eval_metric = p.get("eval_metric")
        self.num_parallel_tree = int(p.get("num_parallel_tree", 1) or 1)
        self.objective_params = p
        if self.objective == "count:poisson" and "max_delta_step" not in p:
            self.max_delta_step = 0.7
        if self.tree_method == "gpu_hist":
            raise exc.UserError(
                "tree_method 'gpu_hist' is not available in the TPU container; use 'hist'."
            )


def _eval_metric_names(config, objective):
    metrics = config.eval_metric
    if metrics is None:
        metrics = [objective.default_metric]
    elif isinstance(metrics, str):
        metrics = [metrics]
    return list(metrics)


class _TrainingSession:
    """Device state for one training run (bins, margins, jitted round fns)."""

    def __init__(self, config, dtrain, evals, forest, mesh=None):
        self.config = config
        self.objective = forest.objective()
        self.num_group = self.objective.num_output_group
        self.mesh = mesh

        labels = dtrain.labels
        self.objective.validate_labels(labels)

        self.train_binned = bin_matrix(dtrain, config.max_bin)
        self.cuts = self.train_binned.cut_points
        self.num_cuts = jnp.asarray(
            np.array([len(c) for c in self.cuts], np.int32)
        )
        self.eval_sets = []
        for dm, name in evals:
            binned = (
                self.train_binned
                if dm is dtrain
                else bin_matrix(dm, config.max_bin, cut_points=self.cuts)
            )
            self.eval_sets.append((name, dm, binned))

        n = dtrain.num_row
        self.n = n
        self.bins = jnp.asarray(self.train_binned.bins)
        self.labels = jnp.asarray(labels)
        self.weights = jnp.asarray(dtrain.get_weight())
        self.groups = dtrain.groups
        base = self.objective.base_margin(forest.base_score)
        shape = (n,) if self.num_group == 1 else (n, self.num_group)
        if forest.trees:
            # resume: margins from the existing forest
            margin = forest.predict_margin(dtrain.features)
            self.margins = jnp.asarray(margin.reshape(shape))
        else:
            self.margins = jnp.full(shape, base, jnp.float32)
        self.eval_margins = []
        for name, dm, binned in self.eval_sets:
            eshape = (dm.num_row,) if self.num_group == 1 else (dm.num_row, self.num_group)
            if binned is self.train_binned:
                self.eval_margins.append(None)  # shares training margins
            elif forest.trees:
                self.eval_margins.append(
                    jnp.asarray(forest.predict_margin(dm.features).reshape(eshape))
                )
            else:
                self.eval_margins.append(jnp.full(eshape, base, jnp.float32))
        self.rng = jax.random.PRNGKey(config.seed)

        monotone = None
        if config.monotone_constraints:
            mono = np.zeros(dtrain.num_col, np.int32)
            vals = config.monotone_constraints
            mono[: len(vals)] = np.asarray(vals, np.int32)
            monotone = jnp.asarray(mono)
        self.monotone = monotone

        self._round_fn = self._make_round_fn()
        self._apply_fn = self._make_apply_fn()

    # ------------------------------------------------------------------ jit
    def _make_round_fn(self):
        cfg = self.config
        num_bins = self.train_binned.num_bins
        builder = partial(
            build_tree,
            max_depth=cfg.max_depth,
            num_bins=num_bins,
            reg_lambda=cfg.reg_lambda,
            alpha=cfg.alpha,
            gamma=cfg.gamma,
            min_child_weight=cfg.min_child_weight,
            eta=cfg.eta,
            max_delta_step=cfg.max_delta_step,
        )
        grad_hess = self.objective.grad_hess
        num_group = self.num_group
        subsample = cfg.subsample

        def one_round(bins, margins, labels, weights, num_cuts, rng, feature_mask, monotone):
            g, h = grad_hess(margins, labels, weights)
            if subsample < 1.0:
                keep = (
                    jax.random.uniform(rng, (bins.shape[0],)) < subsample
                ).astype(jnp.float32)
                if num_group == 1:
                    g, h = g * keep, h * keep
                else:
                    g, h = g * keep[:, None], h * keep[:, None]
            if num_group == 1:
                tree, row_out = builder(
                    bins, g, h, num_cuts, feature_mask=feature_mask, monotone=monotone
                )
                margins = margins + row_out
            else:
                tree, row_out = jax.vmap(
                    lambda gc, hc: builder(
                        bins, gc, hc, num_cuts, feature_mask=feature_mask, monotone=monotone
                    )
                )(g.T, h.T)
                margins = margins + row_out.T
            return tree, margins

        return jax.jit(one_round, donate_argnums=(1,))

    def _make_apply_fn(self):
        cfg = self.config
        num_bins = self.train_binned.num_bins
        num_group = self.num_group

        def apply_tree(tree, bins, margins):
            if num_group == 1:
                return margins + predict_binned(tree, bins, cfg.max_depth, num_bins)
            deltas = jax.vmap(
                lambda t: predict_binned(t, bins, cfg.max_depth, num_bins)
            )(tree)
            return margins + deltas.T

        return jax.jit(apply_tree, donate_argnums=(2,))

    # ---------------------------------------------------------------- round
    def run_round(self):
        self.rng, sub, colrng = jax.random.split(self.rng, 3)
        d = self.bins.shape[1]
        if self.config.colsample_bytree < 1.0:
            k = max(1, int(round(self.config.colsample_bytree * d)))
            chosen = jax.random.permutation(colrng, d)[:k]
            feature_mask = jnp.zeros(d, jnp.float32).at[chosen].set(1.0)
        else:
            feature_mask = None
        tree, self.margins = self._round_fn(
            self.bins,
            self.margins,
            self.labels,
            self.weights,
            self.num_cuts,
            sub,
            feature_mask,
            self.monotone,
        )
        for i, (name, dm, binned) in enumerate(self.eval_sets):
            if self.eval_margins[i] is not None:
                self.eval_margins[i] = self._apply_fn(
                    tree, jnp.asarray(binned.bins), self.eval_margins[i]
                )
        return jax.tree_util.tree_map(np.asarray, tree)

    # ----------------------------------------------------------------- eval
    def margins_for(self, index):
        m = self.eval_margins[index]
        return np.asarray(self.margins if m is None else m)

    def evaluate(self, metric_names, feval=None):
        """Returns list of (data_name, metric_name, value) per eval set."""
        results = []
        for i, (name, dm, binned) in enumerate(self.eval_sets):
            margin = self.margins_for(i)
            preds = self.objective.margin_to_prediction(margin)
            prob_matrix = None
            if self.num_group > 1:
                e = np.exp(margin - margin.max(axis=1, keepdims=True))
                prob_matrix = e / e.sum(axis=1, keepdims=True)
            for metric in metric_names:
                value = eval_metrics.evaluate(
                    metric,
                    preds if preds.ndim == 1 else preds,
                    dm.labels,
                    dm.weights,
                    groups=dm.groups,
                    prob_matrix=prob_matrix,
                )
                results.append((name, metric, value))
            if feval is not None:
                for metric_name, value in feval(preds, dm, margin=margin):
                    results.append((name, metric_name, value))
        return results


def train(
    params,
    dtrain,
    num_boost_round=10,
    evals=(),
    feval=None,
    callbacks=None,
    xgb_model=None,
    verbose_eval=True,
    mesh=None,
):
    """Train a Forest. API mirrors ``xgb.train`` for the orchestration layer.

    xgb_model: a Forest or a model-file path to continue training from
    (checkpoint resume — reference checkpointing.py:45-55).
    """
    config = TrainConfig(params)
    callbacks = list(callbacks or [])

    if xgb_model is None:
        forest = Forest(
            objective_name=config.objective,
            objective_params={
                k: v
                for k, v in config.objective_params.items()
                if k
                in (
                    "scale_pos_weight",
                    "tweedie_variance_power",
                    "huber_slope",
                    "max_delta_step",
                    "num_class",
                )
            },
            base_score=config.base_score,
            num_feature=dtrain.num_col,
            num_class=config.num_class,
            feature_names=dtrain.feature_names,
        )
    elif isinstance(xgb_model, Forest):
        forest = xgb_model
    else:
        forest = Forest.load_model(xgb_model)
    if forest.num_feature < dtrain.num_col and forest.trees:
        raise exc.UserError("feature_names mismatch between checkpoint and data")
    forest.num_feature = max(forest.num_feature, dtrain.num_col)

    session = _TrainingSession(config, dtrain, list(evals), forest, mesh=mesh)
    metric_names = _eval_metric_names(config, session.objective)

    for cb in callbacks:
        if hasattr(cb, "before_training"):
            forest = cb.before_training(forest) or forest

    evals_log = {}
    start_round = forest.num_boosted_rounds
    stop = False
    for rnd in range(start_round, start_round + num_boost_round):
        tree_np = session.run_round()
        if session.num_group == 1:
            trees = [compact_padded_tree(tree_np, session.cuts)]
            info = [0]
        else:
            trees = [
                compact_padded_tree(
                    {k: v[c] for k, v in tree_np.items()}, session.cuts
                )
                for c in range(session.num_group)
            ]
            info = list(range(session.num_group))
        forest.append_round(trees, info)

        results = session.evaluate(metric_names, feval=feval) if session.eval_sets else []
        for data_name, metric_name, value in results:
            evals_log.setdefault(data_name, {}).setdefault(metric_name, []).append(value)

        for cb in callbacks:
            if hasattr(cb, "after_iteration") and cb.after_iteration(
                forest, rnd, evals_log
            ):
                stop = True
        if stop:
            break

    for cb in callbacks:
        if hasattr(cb, "after_training"):
            forest = cb.after_training(forest) or forest
    return forest
