"""The boosting engine: ``train()`` — the TPU replacement for ``xgb.train``.

Reference hot loop (algorithm_mode/train.py:367-376) calls into libxgboost;
here each boosting round is one jitted XLA program: objective grad/hess ->
level-wise tree build (ops/tree_build) -> margin updates for train and every
eval set — the only host work per round is pulling the tree's small node
arrays (O(2^max_depth)) for the Forest and the eval scalars for callbacks.

Distribution: with a mesh, every round runs under ``shard_map`` with rows
sharded over the "data" axis; the single ``lax.psum`` inside the histogram op
is the entire cross-host protocol (replacing Rabit allreduce + tracker
topology — SURVEY.md §5). Trees come out bitwise identical on every shard, so
the "master saves the model" contract is trivially consistent. Rows are
zero-weight padded to a multiple of the shard count.

Ranking objectives route through ops/ranking's LambdaMART gradients over a
padded [groups, max_group] layout.

Callback protocol mirrors xgboost's (before_training / after_iteration ->
bool stop / after_training) so the orchestration layer's checkpoint, early
stop, and monitor callbacks port naturally.
"""

import functools
import logging
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..data.binning import BinnedMatrix, bin_matrix
from ..ops.histogram import (
    hist_comm_impl,
    padded_feature_width,
    resolve_hist_knobs,
    round_comm_plan,
)
from ..ops.ranking import build_group_layout, lambdarank_grad_hess
from ..ops.tree_build import (
    build_tree,
    pack_tree,
    predict_binned,
    tree_from_packed,
    unpack_tree,
)
from ..toolkit import exceptions as exc
from ..utils.faults import fault_point
from . import eval_metrics
from . import objectives as objectives_mod
from .forest import Forest, compact_padded_tree

try:
    from jax import shard_map

    _SHARD_MAP_REP_KW = {"check_vma": False}
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

    _SHARD_MAP_REP_KW = {"check_rep": False}  # pre-0.6 kwarg name

logger = logging.getLogger(__name__)

# objective hyperparameters carried into the saved model / objective
# construction (shared by train() and the fold-parallel CV path)
OBJECTIVE_PARAM_KEYS = (
    "scale_pos_weight",
    "tweedie_variance_power",
    "huber_slope",
    "max_delta_step",
    "num_class",
    "aft_loss_distribution",
    "aft_loss_distribution_scale",
)


class TrainConfig:
    """Parsed + defaulted booster parameters (static across rounds)."""

    def __init__(self, params):
        p = dict(params or {})
        self.eta = float(p.get("eta", 0.3))
        max_depth = p.get("max_depth", 6)
        self.max_depth = int(max_depth) if max_depth is not None else 6
        self.grow_policy = p.get("grow_policy", "depthwise")
        self.max_leaves = int(p.get("max_leaves", 0) or 0)
        if self.grow_policy == "lossguide" and self.max_leaves <= 0:
            # xgboost's 0 means unlimited; static shapes need a bound
            raise exc.UserError(
                "grow_policy='lossguide' requires max_leaves >= 2 in the TPU "
                "container (static-shape tree builder)."
            )
        if self.max_depth == 0 and self.grow_policy != "lossguide":
            raise exc.UserError(
                "max_depth=0 (unlimited depth) is not supported by the TPU static-shape "
                "tree builder with grow_policy='depthwise'; set max_depth >= 1 or use "
                "grow_policy='lossguide' with max_leaves."
            )
        self.reg_lambda = float(p.get("lambda", 1.0))
        self.alpha = float(p.get("alpha", 0.0))
        self.gamma = float(p.get("gamma", 0.0))
        self.min_child_weight = float(p.get("min_child_weight", 1.0))
        self.max_delta_step = float(p.get("max_delta_step", 0.0))
        self.exact_binning = p.get("tree_method") == "exact"
        self.exact_bin_cap = None
        if self.exact_binning:
            # True exact-greedy parity: hist with cuts at EVERY adjacent
            # distinct-value midpoint is the same candidate-split set and the
            # same midpoint thresholds as libxgboost's exact enumeration
            # (reference schema hyperparameter_validation.py:22-24), but
            # static-shape. max_bin is sized by the data at binning time
            # (bin_matrix(max_bin=None)), bounded by the cap below; xgboost
            # likewise ignores max_bin for exact.
            self.max_bin = None
            self.exact_bin_cap = int(os.environ.get("GRAFT_EXACT_BIN_CAP", 8192))
        elif p.get("max_bin") is not None:
            self.max_bin = int(p["max_bin"])
        elif p.get("sketch_eps"):
            # approx-method users control sketch granularity via sketch_eps;
            # bins ~ 1/eps is xgboost's own guidance for the hist equivalent
            self.max_bin = int(min(max(1.0 / float(p["sketch_eps"]), 2), 1024))
        else:
            self.max_bin = 256
        if p.get("tree_method") == "approx":
            # r5 (VERDICT r4 #8): approx now matches libxgboost's candidate
            # refresh — a hessian-weighted re-sketch before every dispatch
            # (_TrainingSession._resketch_bins). GRAFT_APPROX_RESKETCH=0
            # restores the single global sketch (hist semantics) for A/Bs.
            logger.info(
                "tree_method='approx': TPU hist engine at max_bin=%d "
                "(~1/sketch_eps) with per-dispatch hessian-weighted "
                "re-sketch (disable via GRAFT_APPROX_RESKETCH=0).",
                self.max_bin,
            )
        self.subsample = float(p.get("subsample", 1.0))
        self.colsample_bytree = float(p.get("colsample_bytree", 1.0))
        self.colsample_bylevel = float(p.get("colsample_bylevel", 1.0))
        self.colsample_bynode = float(p.get("colsample_bynode", 1.0))
        self.seed = int(p.get("seed", 0))
        self.objective = p.get("objective", "reg:squarederror")
        self.num_class = int(p.get("num_class", 0) or 0)
        self.base_score = float(p.get("base_score", 0.5))
        self.tree_method = p.get("tree_method", "auto")
        self.monotone_constraints = p.get("monotone_constraints")
        self.interaction_constraints = p.get("interaction_constraints")
        self.eval_metric = p.get("eval_metric")
        self.num_parallel_tree = int(p.get("num_parallel_tree", 1) or 1)
        self.booster = p.get("booster", "gbtree")
        # internal: build K trees per device dispatch (with eval sets the
        # per-round metrics ride back as device-computed stats inside the
        # scan; falls back to 1 when a metric can't — see _TrainingSession)
        self.rounds_per_dispatch = int(p.get("_rounds_per_dispatch", 1) or 1)
        self.objective_params = p
        if self.objective == "count:poisson" and "max_delta_step" not in p:
            self.max_delta_step = 0.7
        if self.tree_method == "gpu_hist":
            raise exc.UserError(
                "tree_method 'gpu_hist' is not available in the TPU container; use 'hist'."
            )
        self.predict_depth = (
            (self.max_depth if self.max_depth > 0 else self.max_leaves - 1)
            if self.grow_policy == "lossguide"
            else self.max_depth
        )
        self.process_type = p.get("process_type", "default")
        if self.process_type not in ("default", "update"):
            raise exc.UserError(
                "process_type must be 'default' or 'update', got {!r}".format(
                    self.process_type
                )
            )


def _eval_metric_names(config, objective):
    metrics = config.eval_metric
    if metrics is None:
        metrics = [objective.default_metric]
    elif isinstance(metrics, str):
        metrics = [metrics]
    return list(metrics)


def _predict_margin_rows(forest, dm, block_rows=1 << 16):
    """``forest.predict_margin`` over a data/eval matrix's rows.

    DataMatrix inputs predict from their float features as always. Pre-binned
    inputs (chunked streaming ingest — the float channel was never
    materialized) predict from bounded blocks of *representative* values
    (``BinnedMatrix.rep_block``): every committed threshold is a cut value of
    the same cut set, so leaf routing — and therefore the margins — is
    bit-identical to predicting from the original floats, at O(block) peak
    memory instead of O(dataset).
    """
    if not isinstance(dm, BinnedMatrix):
        return np.asarray(forest.predict_margin(dm.features), np.float32)
    if dm.num_row == 0:
        return np.zeros((0,), np.float32)
    parts = [
        np.asarray(
            forest.predict_margin(dm.rep_block(s, min(s + block_rows, dm.num_row))),
            np.float32,
        )
        for s in range(0, dm.num_row, block_rows)
    ]
    return np.concatenate(parts, axis=0)


def _merged_distributed_cuts(dtrain, max_bin, weights=None):
    """Allgather per-host cut candidates and deterministically merge them.

    Every process computes shard-local quantile cuts, gathers all hosts'
    candidates, and re-selects <= max_bin - 1 evenly spaced thresholds from
    the sorted union. Deterministic: identical inputs on every host yield
    identical cuts everywhere.

    weights: sketch weights overriding dtrain.weights (the approx
    re-sketch passes current hessians).
    """
    from jax.experimental import multihost_utils

    from ..data.binning import compute_cut_points

    if weights is None:
        weights = dtrain.weights
    local_cuts = compute_cut_points(dtrain.features, weights, max_bin)
    width = max_bin - 1
    d = dtrain.num_col
    mat = np.full((d, width), np.nan, np.float32)
    counts = np.zeros(d, np.int32)
    for f, c in enumerate(local_cuts):
        mat[f, : len(c)] = c
        counts[f] = len(c)
    all_mats = np.asarray(multihost_utils.process_allgather(mat))       # [P, d, W]
    all_counts = np.asarray(multihost_utils.process_allgather(counts))  # [P, d]
    merged = []
    for f in range(d):
        cands = np.concatenate(
            [all_mats[p, f, : all_counts[p, f]] for p in range(all_mats.shape[0])]
        )
        cands = np.unique(cands[np.isfinite(cands)])
        if len(cands) > width:
            picks = np.linspace(0, len(cands) - 1, width).round().astype(int)
            cands = cands[np.unique(picks)]
        merged.append(cands.astype(np.float32))
    return merged


def _apply_packed_tree(packed, bins, margins, num_group, num_parallel, depth,
                       num_bins, route_impl=None):
    """margins += the packed tree's (or tree stack's) outputs on ``bins``.

    Runs under trace (the round fn and the session apply fn), so the
    routing knob must arrive as ``route_impl`` — the session's
    ``hist_knobs.route_impl`` snapshot, never a trace-time env read.
    """
    tree = tree_from_packed(packed)

    def one(t):
        return predict_binned(t, bins, depth, num_bins, route_impl=route_impl)

    if num_group == 1:
        if num_parallel > 1:
            delta = jax.vmap(one)(tree).sum(axis=0)
        else:
            delta = one(tree)
        return margins + delta
    if num_parallel > 1:
        # packed [P, C, ...]: sum the bagged parallel trees per class
        deltas = jax.vmap(jax.vmap(one))(tree).sum(axis=0)
    else:
        deltas = jax.vmap(one)(tree)
    return margins + deltas.T


@functools.lru_cache(maxsize=None)
def _calibrated_comm_ms(mesh, hist_comm, plan_key):
    """Standalone timing of one round's data-axis collectives (ms).

    lru_cached module factory: one calibration per (mesh, lowering, plan
    shapes) per PROCESS, not per session — a CV fold rebuild or an elastic
    reform that lands on an identical plan skips the compile + timing
    dispatches entirely (jax Meshes hash by device assignment + axis
    names, so a genuinely different topology still re-calibrates).

    Each DISTINCT payload shape in ``plan_key`` (tuples of
    ``(kind, shape, count)`` from ``round_comm_plan``) is timed as a
    standalone jitted collective on zeros (min of 3 reps after a warmup)
    and the per-round estimate is the count-weighted sum. An
    isolated-latency estimate: real rounds overlap collectives with
    compute (GRAFT_HIST_OVERLAP pipelines them on purpose), so this is an
    upper bound on the comm share. Raises on failure — lru_cache does NOT
    memoize raising calls, so a transient failure (device momentarily
    busy) is retried by the next session rebuild instead of pinning the
    gauge to a cached 0.0 for the rest of the process; the caller
    (_calibrate_hist_comm_ms) catches and degrades to 0.0 for ITS session.
    """
    import time

    def psum_fn(x):
        return jax.lax.psum(x, "data")

    def scatter_fn(x):
        return jax.lax.psum_scatter(
            x, "data", scatter_dimension=1, tiled=True
        )

    from ..ops.histogram import MERGE_COLLECTIVES_PER_SCAN

    total_s = 0.0
    timed = {}
    for kind, shape, count in plan_key:
        key = (kind, shape)
        if key not in timed:
            if kind == "hist" and hist_comm == "reduce_scatter":
                fn, out_spec = scatter_fn, P(None, "data", None)
            else:
                # totals and winner-merge entries are psum-class [W]
                # collectives under both lowerings
                fn, out_spec = psum_fn, P()
            # graftlint: disable=trace-uncached-jit — calibration-scope: lru_cached module factory, one standalone collective timing per distinct (mesh, plan shape, impl) per process, off the round path
            mapped = jax.jit(
                shard_map(
                    fn,
                    mesh=mesh,
                    in_specs=(P(),),
                    out_specs=out_spec,
                    **_SHARD_MAP_REP_KW,
                )
            )
            x = jnp.zeros(shape, jnp.float32)
            jax.block_until_ready(mapped(x))  # compile + warm
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                jax.block_until_ready(mapped(x))
                best = min(best, time.perf_counter() - t0)
            timed[key] = best
        # one timing covers one tensor: hist/totals move G and H (2 per
        # count); a winner-merge scan issues MERGE_COLLECTIVES_PER_SCAN
        # [W]-shaped collectives per count
        per_count = MERGE_COLLECTIVES_PER_SCAN if kind == "merge" else 2
        total_s += timed[key] * per_count * count
    return total_s * 1000.0


_approx_k_forcing_warned = False


def _warn_approx_k_forcing_once(requested):
    """Warn (once per process) that the approx re-sketch forces K -> 1.

    libxgboost's approx refreshes split candidates every ITERATION; a
    K-round dispatch would re-sketch only once per K rounds — a silent
    semantic weakening (ADVICE r5). GRAFT_APPROX_RESKETCH=0 restores
    batched dispatches (single global sketch, hist semantics) —
    docs/MIGRATION.md. Every CV fold / elastic generation rebuilds the
    session, so the log is deduplicated here rather than spamming one
    line per rebuild.
    """
    global _approx_k_forcing_warned
    if _approx_k_forcing_warned:
        return
    _approx_k_forcing_warned = True
    logger.warning(
        "tree_method='approx' re-sketches candidates before every "
        "boosting iteration; forcing _rounds_per_dispatch=%d -> 1 "
        "(set GRAFT_APPROX_RESKETCH=0 to keep batched dispatches "
        "with a single global sketch).",
        requested,
    )


def _pad_rows(array, target_rows, fill):
    n = array.shape[0]
    if n == target_rows:
        return array
    pad_shape = (target_rows - n,) + array.shape[1:]
    return np.concatenate([array, np.full(pad_shape, fill, array.dtype)], axis=0)


class _TrainingSession:
    """Device state for one training run (bins, margins, jitted round fns)."""

    def __init__(
        self,
        config,
        dtrain,
        evals,
        forest,
        mesh=None,
        metric_names=None,
        has_feval=False,
        hist_knobs=None,
    ):
        # persistent XLA compile cache (GRAFT_COMPILE_CACHE_DIR): armed
        # before anything in this session can trigger a compile, resolved
        # once per process like every other session knob
        from ..utils.compile_cache import maybe_enable_compile_cache

        maybe_enable_compile_cache()
        self.config = config
        self.objective = forest.objective()
        self.num_group = self.objective.num_output_group
        self.mesh = mesh
        self.n_shards = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
        # optional second mesh axis: column sharding for wide data
        self.has_feature_axis = mesh is not None and "feature" in mesh.axis_names
        self.n_feature_shards = (
            int(mesh.shape["feature"]) if self.has_feature_axis else 1
        )
        self.n_data_shards = (
            int(mesh.shape["data"]) if mesh is not None else 1
        )
        # data-axis histogram collective (GRAFT_HIST_COMM): resolved ONCE per
        # session — the round program is traced against it, so flipping the
        # env mid-job cannot desynchronize shards; a new train() call (new
        # session, new round-fn closure, hence its own jit cache entry)
        # picks up the new value.
        self.hist_comm = hist_comm_impl() if mesh is not None else "psum"
        # every other histogram/scan/routing knob, snapshotted host-side for
        # the same reason (trace-safety: graftlint trace-env-read forbids
        # env reads in the traced build path) and threaded into the builders.
        # Callers may inject a snapshot: an elastic membership reform rebuilds
        # the session on a smaller mesh but MUST train under the same knobs
        # as the generation it resumes (no mid-job env drift).
        self.hist_knobs = hist_knobs if hist_knobs is not None else resolve_hist_knobs()
        # reduce_scatter composes with a 'feature' mesh axis: each feature
        # shard's local histograms psum_scatter along the DATA axis, every
        # device gain-scans only its doubly-sharded d_local/n_data_shards
        # column block, and winners merge hierarchically (data-axis
        # sub-slice merge, then the feature-axis merge) — bit-identical to
        # the psum lowering on the same mesh (ops/tree_build.build_tree).
        # multi-host: every process holds its own row shard; device arrays are
        # assembled into global arrays over the whole mesh
        self.is_multiprocess = mesh is not None and jax.process_count() > 1
        if self.is_multiprocess and self.has_feature_axis:
            # every process must own whole rows (all columns of its row
            # shard) so host-local arrays assemble into the global 2-D
            # layout; the feature axis therefore has to live within a host
            local_feat = int(mesh.local_mesh.shape["feature"])
            if local_feat != self.n_feature_shards:
                raise exc.UserError(
                    "The 'feature' mesh axis must not span processes: build "
                    "the mesh with the data axis across hosts and the "
                    "feature axis over each host's local devices."
                )
        if self.is_multiprocess:
            # local rows pad to a multiple of the *local* data shards; the
            # global array is the concatenation over processes
            self.pad_unit = max(1, int(mesh.local_mesh.shape["data"]))
        else:
            self.pad_unit = self.n_data_shards

        labels = dtrain.labels
        self.objective.validate_labels(labels)

        self.is_ranking = getattr(self.objective, "needs_groups", False)
        # survival:cox multi-host watchlists are exact: the partial
        # likelihood does not decompose across hosts, so cox-nloglik rides
        # a dedicated global-rows path — all_gather over the data axis on
        # device (device_metrics needs_global_rows) or process_allgather on
        # the host evaluate() path — the same way the Cox gradients gather
        # global risk sets (r3 parity debt, VERDICT #4).
        # ranking layouts: single device keeps the [G, M] global layout;
        # on a mesh, rows are re-partitioned BY GROUP (groups never straddle
        # shards, so intra-group pairwise gradients stay shard-exact — the
        # reference's Rabit ranking path keeps worker groups whole the same
        # way, hyperparameter_validation.py:283-309 trains them under Rabit)
        self.row_index = None
        self.rank_perm = None          # device-order position -> original row
        self.rank_pos = None           # original (local) row -> device position
        self._rank_index_np = None     # [local_shards, G_max, M]
        if self.is_ranking:
            # ranking composes with a feature axis: the group-partitioned
            # row layout permutes ROWS only, so bins shard P("data",
            # "feature") as usual, rank_index replicates over the feature
            # axis, and the builder's cross-shard split combine + owner/psum
            # routing (ops/tree_build, ops/lossguide) do the column work
            # (r3 parity debt, VERDICT #4)
            if dtrain.groups is None:
                # xgboost convention: absent group info = one group per dataset
                groups = np.asarray([dtrain.num_row], np.int64)
            else:
                groups = np.asarray(dtrain.groups, np.int64)
            if mesh is None:
                self.row_index = jnp.asarray(build_group_layout(groups))
            else:
                from ..ops.ranking import build_sharded_group_layout

                # DATA shards only: with a feature axis, local_devices also
                # counts column shards, which hold the same rows
                local_shards = (
                    max(1, int(mesh.local_mesh.shape["data"]))
                    if self.is_multiprocess
                    else self.n_data_shards
                )
                perm, ri, rps = build_sharded_group_layout(groups, local_shards)
                if self.is_multiprocess:
                    # all hosts must agree on padded shapes
                    from jax.experimental import multihost_utils

                    maxima = np.asarray(
                        multihost_utils.process_allgather(
                            np.asarray([rps, ri.shape[1], ri.shape[2]], np.int64)
                        )
                    ).max(axis=0)
                    perm, ri, rps = build_sharded_group_layout(
                        groups,
                        local_shards,
                        rows_per_shard=int(maxima[0]),
                        max_groups_per_shard=int(maxima[1]),
                        max_group_size=int(maxima[2]),
                    )
                self.rank_perm = perm
                self._rank_index_np = ri
                pos = np.full(dtrain.num_row, -1, np.int64)
                m = perm >= 0
                pos[perm[m]] = np.nonzero(m)[0]
                self.rank_pos = pos

        pre_binned = isinstance(dtrain, BinnedMatrix)
        shared_cuts = None
        if self.is_multiprocess:
            if config.max_bin is None:
                # libxgboost's exact updater is likewise single-machine only
                raise exc.UserError(
                    "tree_method='exact' does not support distributed "
                    "training (it doesn't in XGBoost either); use "
                    "tree_method='hist'."
                )
            # every host must bin with identical thresholds or the psum'd
            # histograms are meaningless: merge the per-host quantile sketches
            # (allgather candidate cuts, union, re-select) — the TPU analog of
            # xgboost's allreduced weighted quantile sketch. Pre-binned input
            # (chunked streaming ingest) already agreed its cuts cross-rank
            # through the ingest sketch allgather, so it skips this.
            if not pre_binned:
                shared_cuts = _merged_distributed_cuts(dtrain, config.max_bin)

        if pre_binned:
            # chunked streaming ingest: the sketch+bin stage already ran at
            # ingest time (with rank-agreed cuts); trust the matrix, but
            # fail loudly on a config/ingest max_bin drift — a silently
            # re-interpreted bin width would corrupt every histogram
            if config.max_bin is None or int(config.max_bin) != dtrain.max_bin:
                raise exc.UserError(
                    "Pre-binned training data was ingested with max_bin={} "
                    "but the training config resolves max_bin={}; re-ingest "
                    "or align the hyperparameters.".format(
                        dtrain.max_bin, config.max_bin
                    )
                )
            self.train_binned = dtrain
        else:
            self.train_binned = bin_matrix(
                dtrain,
                config.max_bin,
                cut_points=shared_cuts,
                exact_cap=config.exact_bin_cap,
            )
        self.cuts = self.train_binned.cut_points
        self.eval_sets = []
        for dm, name in evals:
            if dm is dtrain:
                binned = self.train_binned
            elif isinstance(dm, BinnedMatrix):
                # pre-binned eval set: must carry the training channel's
                # bin edges (streaming ingest bins validation with the
                # train cuts) or its bin indices mean different thresholds
                if dm.max_bin != self.train_binned.max_bin or not (
                    dm.cut_points is self.cuts
                    or (
                        len(dm.cut_points) == len(self.cuts)
                        and all(
                            np.array_equal(a, b)
                            for a, b in zip(dm.cut_points, self.cuts)
                        )
                    )
                ):
                    raise exc.AlgorithmError(
                        "pre-binned eval set {!r} was binned with different "
                        "cut points than the training data".format(name)
                    )
                binned = dm
            else:
                binned = bin_matrix(dm, config.max_bin, cut_points=self.cuts)
            self.eval_sets.append((name, dm, binned))

        def _agreed_pad(num_row):
            """Local padded row count, agreed across processes. Hosts may
            hold UNEVEN row counts (ShardedByS3Key): every process must pad
            to the same local size or any global row gather (cox risk sets /
            cox-nloglik metric) hits a cross-host collective size mismatch
            (gloo: "402 vs 400") — equal device shards also keep the mesh
            layout uniform. Applies to the train rows AND every eval set;
            ranking agrees via its own maxima allgather above."""
            pad = -(-num_row // self.pad_unit) * self.pad_unit
            if not self.is_multiprocess:
                return pad
            from jax.experimental import multihost_utils

            return int(
                np.asarray(
                    multihost_utils.process_allgather(np.asarray([pad], np.int64))
                ).max()
            )

        self.n = dtrain.num_row
        if self.rank_perm is not None:
            n_pad = len(self.rank_perm)   # local_shards * rows_per_shard
        else:
            n_pad = _agreed_pad(self.n)

        def _layout_rows(arr, fill):
            """Original-order rows -> device layout (tail padding, or the
            group-partitioned permutation for distributed ranking)."""
            if self.rank_perm is None:
                return _pad_rows(arr, n_pad, fill)
            out = np.full((n_pad,) + arr.shape[1:], fill, arr.dtype)
            m = self.rank_perm >= 0
            out[m] = arr[self.rank_perm[m]]
            return out

        # column padding: features pad to a multiple of the feature shards
        # with always-missing columns (zero cuts -> never split on)
        d_real = self.train_binned.num_col
        d_pad = padded_feature_width(d_real, self.n_feature_shards)
        self.d_pad = d_pad

        def _put(local_np, spec):
            """Local host array -> placed device array (global across procs)."""
            if self.mesh is None:
                return jnp.asarray(local_np)
            from jax.sharding import NamedSharding

            sharding = NamedSharding(self.mesh, spec)
            if self.is_multiprocess:
                return jax.make_array_from_process_local_data(sharding, local_np)
            return jax.device_put(local_np, sharding)

        self.bins_spec = (
            P("data", "feature") if self.has_feature_axis else P("data", None)
        )
        self.feat_spec = P("feature") if self.has_feature_axis else P()
        margin_spec = P("data") if self.num_group == 1 else P("data", None)

        self._put = _put
        self._layout_rows = _layout_rows
        self._d_real = d_real
        self._stage_train_bins(
            self.train_binned.bins, self.cuts, self.train_binned.max_bin
        )
        # approx re-sketch state (see _resketch_bins)
        self._dtrain = dtrain
        self._grad_fn = None
        self._feats_dev = None       # device-staged float features (sketch)
        self._eval_feats_dev = {}    # eval-set index -> device features
        self.approx_resketch = (
            config.tree_method == "approx"
            and os.environ.get("GRAFT_APPROX_RESKETCH", "1") != "0"
        )
        if self.approx_resketch and pre_binned:
            # the per-round re-sketch needs the float channel resident —
            # exactly what chunked ingest exists to avoid. The ingest gating
            # refuses approx up front; this is the defense for direct API
            # callers handing a BinnedMatrix to an approx config.
            logger.warning(
                "tree_method='approx' with pre-binned input keeps the "
                "ingest-time sketch (no per-iteration re-binning)."
            )
            self.approx_resketch = False
        if self.approx_resketch and self.rank_perm is not None:
            logger.warning(
                "tree_method='approx' with distributed ranking keeps the "
                "initial sketch (the group-partitioned row layout does not "
                "support per-iteration re-binning)."
            )
            self.approx_resketch = False
        self.labels = _put(_layout_rows(labels, 0.0), P("data"))
        self.weights = _put(_layout_rows(dtrain.get_weight(), 0.0), P("data"))
        self.groups = dtrain.groups
        if self._rank_index_np is not None:
            self.rank_index_dev = _put(self._rank_index_np, P("data", None, None))
        elif self.row_index is not None:
            self.rank_index_dev = self.row_index
        else:
            self.rank_index_dev = jnp.zeros((1, 1), jnp.int32)  # inert dummy

        base = self.objective.base_margin(forest.base_score)
        shape = (n_pad,) if self.num_group == 1 else (n_pad, self.num_group)
        if forest.trees:
            margin = _predict_margin_rows(forest, dtrain).reshape(
                (self.n,) if self.num_group == 1 else (self.n, self.num_group)
            )
            self.margins = _put(
                _layout_rows(margin.astype(np.float32), base), margin_spec
            )
        else:
            self.margins = _put(np.full(shape, base, np.float32), margin_spec)

        # eval-set device state: bins cached once, margins incremental;
        # labels/weights kept on device for batched device-side metrics
        self.eval_bins = []
        self.eval_margins = []
        self.eval_labels = []
        self.eval_weights = []
        self._eval_pads = []  # per eval set: padded row count (None = shared)
        for name, dm, binned in self.eval_sets:
            if binned is self.train_binned:
                self.eval_bins.append(None)     # shares training margins
                self.eval_margins.append(None)
                self.eval_labels.append(self.labels)
                self.eval_weights.append(self.weights)
                self._eval_pads.append(None)
                continue
            m_pad = _agreed_pad(dm.num_row)
            self._eval_pads.append(m_pad)
            self.eval_bins.append(
                _put(_pad_rows(binned.bins, m_pad, binned.max_bin), P("data", None))
            )
            self.eval_labels.append(_put(_pad_rows(dm.labels, m_pad, 0.0), P("data")))
            self.eval_weights.append(
                _put(_pad_rows(dm.get_weight(), m_pad, 0.0), P("data"))
            )
            eshape = (m_pad,) if self.num_group == 1 else (m_pad, self.num_group)
            if forest.trees:
                em = _predict_margin_rows(forest, dm).reshape(
                    (dm.num_row,) if self.num_group == 1 else (dm.num_row, self.num_group)
                )
                self.eval_margins.append(
                    _put(_pad_rows(em.astype(np.float32), m_pad, base), margin_spec)
                )
            else:
                self.eval_margins.append(_put(np.full(eshape, base, np.float32), margin_spec))

        self.rng = jax.random.PRNGKey(config.seed)

        self.rounds_per_dispatch = max(1, config.rounds_per_dispatch)
        if self.approx_resketch and self.rounds_per_dispatch > 1:
            _warn_approx_k_forcing_once(self.rounds_per_dispatch)
            self.rounds_per_dispatch = 1
        self.device_metric_fns = None
        # Device metrics decompose into psum-able partial stats
        # (device_metrics.py), so they work on any mesh: K-round batching
        # psums per-round stat vectors over the "data" axis inside the
        # jitted scan, and multi-process runs get globally exact metric
        # lines (reference semantics: metrics allreduced under the
        # communicator, distributed.py:219). They activate when batching is
        # requested (K > 1) or when multi-process exactness needs them.
        want_device_metrics = (
            self.eval_sets
            and metric_names
            and not has_feval
            and not self.is_ranking
            and (self.rounds_per_dispatch > 1 or self.is_multiprocess)
        )
        if want_device_metrics:
            from .device_metrics import all_supported

            self.device_metric_fns = all_supported(
                metric_names,
                self.objective.name,
                self.num_group,
                config.objective_params,
            )
            if self.device_metric_fns is not None:
                self.device_metric_names = list(metric_names)
        # Metrics outside device_metrics.all_supported (feval, ranking
        # metrics, non-decomposable scalars) no longer force K -> 1: the
        # fused dispatch keeps K, the scan carries every eval set's margins
        # on device, and the HOST evaluates once per dispatch — metric
        # lines land every K rounds at the batch-end round index instead of
        # every round (the documented host-fallback cadence, docs/DESIGN.md
        # §Round pipeline; callbacks skip stale rounds).
        self.host_eval_batched = (
            self.rounds_per_dispatch > 1
            and bool(self.eval_sets)
            and self.device_metric_fns is None
        )
        if self.host_eval_batched:
            logger.info(
                "_rounds_per_dispatch=%d with eval metrics that cannot ride "
                "back from the device: keeping the fused dispatch; host "
                "metrics are computed once per dispatch (every %d rounds).",
                self.rounds_per_dispatch, self.rounds_per_dispatch,
            )
        # the lax.scan round path carries eval margins + metric stats on
        # device; used for K > 1 and for exact multi-process evaluation
        self.use_scan_rounds = self.rounds_per_dispatch > 1 or (
            self.device_metric_fns is not None and self.is_multiprocess
        )
        from ..telemetry import REGISTRY

        REGISTRY.gauge(
            "dispatch_fused_rounds",
            "Boosting rounds fused into one device dispatch per round "
            "program (the lax.scan length K of the fused round pipeline)",
        ).set(self.rounds_per_dispatch)

        monotone = np.zeros(self.d_pad, np.int32)
        if config.monotone_constraints:
            vals = np.asarray(config.monotone_constraints, np.int32)
            monotone[: len(vals)] = vals
        self.monotone = jnp.asarray(monotone)
        self.has_monotone = bool(config.monotone_constraints)

        # static per-round collective footprint (telemetry): the data-axis
        # histogram collectives' shapes + wire bytes, derived from the same
        # level/step structure the builders trace (docs/DESIGN.md
        # §Communication has the formula)
        self.hist_comm_plan, self.hist_comm_bytes_per_round = self._comm_plan()
        self._hist_comm_ms = None  # lazily calibrated at the first dispatch
        self._set_comm_round_fields()

        # device-sync attribution sampling (SM_TRACE_DEVICE_SYNC = N):
        # every Nth dispatch is split by a block_until_ready fence into a
        # `host_dispatch` span (python + XLA dispatch until the async call
        # returns) and a `device_sync` span (waiting on device compute) —
        # the host/device split the flat round record can't see. Resolved
        # ONCE here, host-side, like the hist knobs: the traced round path
        # never reads env. 0 (default) means no fences, no spans.
        from ..telemetry.tracing import DEVICE_SYNC_ENV
        from ..utils.envconfig import env_int

        self._device_sync_every = env_int(DEVICE_SYNC_ENV, 0, minimum=0)
        self._dispatch_index = 0

        # model-quality plane (SM_MODEL_TELEMETRY): resolved ONCE here,
        # host-side, like the hist knobs — unset traces exactly the pre-PR
        # round program (no stats outputs at all); set adds read-only
        # reductions of g/h/margins, so committed trees are bit-identical
        # either way. The drift baseline is one bincount per feature over
        # the already-binned matrix, captured now and stamped into the
        # model manifest at save time.
        from ..telemetry import model as model_telemetry

        self.learning_stats = model_telemetry.enabled()
        self.last_learning_stats = []
        if self.learning_stats:
            model_telemetry.capture_drift_baseline(self.train_binned)

        self._round_fn = self._make_round_fn()
        self._apply_fn = self._make_apply_fn()
        self._introspect_compiled_cost()

    # ------------------------------------------------------------------ jit
    def _grad_hess_fn(self):
        if not self.is_ranking:
            return None
        scheme = self.objective.scheme

        def ranking_grads(margins, labels, weights, rank_index):
            if rank_index.ndim == 3:
                # per-shard [1, G_max, M] slice under shard_map
                rank_index = rank_index.reshape(rank_index.shape[1:])
            return lambdarank_grad_hess(
                margins, labels, weights, rank_index, scheme=scheme
            )

        return ranking_grads

    def _make_round_fn(self):
        cfg = self.config
        num_bins = self.train_binned.num_bins
        axis_name = "data" if self.mesh is not None else None
        feature_axis = "feature" if self.has_feature_axis else None
        interaction_sets = None
        if cfg.interaction_constraints:
            d_cols = self.train_binned.num_col
            # width = padded GLOBAL columns: with a feature axis the split
            # ids crossing shards are global, and per-shard masks slice out
            # their own column segment (tree_build._local_cols)
            sets_np = np.zeros((len(cfg.interaction_constraints), self.d_pad), bool)
            for s, members in enumerate(cfg.interaction_constraints):
                for f in members:
                    if 0 <= int(f) < d_cols:
                        sets_np[s, int(f)] = True
            interaction_sets = jnp.asarray(sets_np)

        # With num_parallel_tree=K, all K trees of a round fit the *same*
        # gradients (a bagged forest step), so their summed corrections are
        # averaged via eta/K — otherwise the round overshoots by K.
        effective_eta = cfg.eta / cfg.num_parallel_tree
        common = dict(
            num_bins=num_bins,
            reg_lambda=cfg.reg_lambda,
            alpha=cfg.alpha,
            gamma=cfg.gamma,
            min_child_weight=cfg.min_child_weight,
            eta=effective_eta,
            max_delta_step=cfg.max_delta_step,
            colsample_bylevel=cfg.colsample_bylevel,
            colsample_bynode=cfg.colsample_bynode,
            axis_name=axis_name,
            interaction_sets=interaction_sets,
            feature_axis_name=feature_axis,
            n_feature_shards=self.n_feature_shards,
            d_global=self.train_binned.num_col,
            hist_comm=self.hist_comm,
            n_data_shards=self.n_data_shards,
            knobs=self.hist_knobs,
        )
        if cfg.grow_policy == "lossguide":
            from ..ops.lossguide import build_tree_lossguide

            builder = partial(
                build_tree_lossguide,
                max_leaves=cfg.max_leaves,
                max_depth=cfg.max_depth,
                **common,
            )
        else:
            builder = partial(build_tree, max_depth=cfg.max_depth, **common)
        ranking_grads = self._grad_hess_fn()
        grad_hess = self.objective.grad_hess
        if self.objective.name == "survival:cox" and axis_name is not None:
            # Cox risk sets span the WHOLE dataset (cumulative sums over the
            # global time ordering), so shard-local gradients would be
            # silently wrong. Exact distributed form: all_gather the margin/
            # label/weight shards over the data axis inside the jitted round,
            # compute global gradients (replicated — padding rows carry
            # weight 0 and drop out), and slice this shard's row segment.
            # This is exact where the reference's per-worker Cox is not.
            base_grad_hess = grad_hess

            def cox_mesh_grad_hess(m, y, w):
                M = jax.lax.all_gather(m, axis_name, tiled=True)
                Y = jax.lax.all_gather(y, axis_name, tiled=True)
                Wt = jax.lax.all_gather(w, axis_name, tiled=True)
                G, H = base_grad_hess(M, Y, Wt)
                k = jax.lax.axis_index(axis_name)
                c = m.shape[0]
                return (
                    jax.lax.dynamic_slice(G, (k * c,), (c,)),
                    jax.lax.dynamic_slice(H, (k * c,), (c,)),
                )

            grad_hess = cox_mesh_grad_hess
        num_group = self.num_group
        subsample = cfg.subsample
        num_parallel = cfg.num_parallel_tree
        use_monotone = self.has_monotone
        collect_stats = self.learning_stats

        def _learning_stats(g, h, margins_new):
            # read-only reductions of the round's gradients/hessians and
            # post-update margins; telemetry/model.DEVICE_STAT_FIELDS owns
            # the layout. Sums/counts psum and extrema pmin/pmax over the
            # data axis, so the vector is globally exact and replicated
            # (matching its P() out_spec); nothing here feeds back into the
            # tree build, keeping committed trees bit-identical.
            gv = g.reshape(-1)
            hv = h.reshape(-1)
            mv = margins_new.reshape(-1)
            g_fin = jnp.isfinite(gv)
            h_fin = jnp.isfinite(hv)
            vec = jnp.stack(
                [
                    jnp.sum(jnp.where(g_fin, gv, 0.0)),
                    jnp.min(jnp.where(g_fin, gv, jnp.inf)),
                    jnp.max(jnp.where(g_fin, gv, -jnp.inf)),
                    jnp.sum(jnp.where(h_fin, hv, 0.0)),
                    jnp.min(jnp.where(h_fin, hv, jnp.inf)),
                    jnp.max(jnp.where(h_fin, hv, -jnp.inf)),
                    jnp.sum((~g_fin).astype(jnp.float32)),
                    jnp.sum((~jnp.isfinite(mv)).astype(jnp.float32)),
                ]
            ).astype(jnp.float32)
            if axis_name is not None:
                sums = jax.lax.psum(vec, axis_name)
                mins = jax.lax.pmin(vec, axis_name)
                maxs = jax.lax.pmax(vec, axis_name)
                vec = jnp.stack(
                    [
                        sums[0], mins[1], maxs[2],
                        sums[3], mins[4], maxs[5],
                        sums[6], sums[7],
                    ]
                )
            return vec

        def one_round(
            bins, margins, labels, weights, num_cuts, rng, feature_mask, monotone,
            rank_index,
        ):
            mono = monotone if use_monotone else None
            # Two rng streams: the replicated one drives feature-subset draws
            # inside build_tree (colsample_bylevel/bynode), which MUST be
            # identical on every shard so all shards pick the same splits;
            # the shard-folded one drives row subsampling, which must be
            # decorrelated per shard (each shard owns different rows).
            if axis_name is not None:
                shard_rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
            else:
                shard_rng = rng
            if ranking_grads is not None:
                g, h = ranking_grads(margins, labels, weights, rank_index)
            else:
                g, h = grad_hess(margins, labels, weights)

            def sampled(rng_k, gc, hc):
                if subsample >= 1.0:
                    return gc, hc
                keep = (
                    jax.random.uniform(rng_k, (bins.shape[0],)) < subsample
                ).astype(jnp.float32)
                if gc.ndim == 1:
                    return gc * keep, hc * keep
                return gc * keep[:, None], hc * keep[:, None]

            trees = []
            if num_group == 1:
                total_out = jnp.zeros_like(margins)
                for k in range(num_parallel):
                    rng_k = jax.random.fold_in(rng, k)
                    gk, hk = sampled(jax.random.fold_in(shard_rng, k), g, h)
                    tree, row_out = builder(
                        bins, gk, hk, num_cuts,
                        feature_mask=feature_mask, monotone=mono, rng=rng_k,
                    )
                    trees.append(tree)
                    total_out = total_out + row_out
                margins = margins + total_out
            else:
                # multi-class: vmap the builder over the class axis; with
                # num_parallel_tree=P the class-vmap runs P times on P row
                # subsamples (a bagged forest step per class — same layout
                # as xgboost: P trees per class per round, eta/P averaging)
                total_out = jnp.zeros_like(margins)
                for k in range(num_parallel):
                    rng_k = jax.random.fold_in(rng, k)
                    gk, hk = sampled(jax.random.fold_in(shard_rng, k), g, h)
                    tree, row_out = jax.vmap(
                        lambda gc, hc: builder(
                            bins, gc, hc, num_cuts,
                            feature_mask=feature_mask, monotone=mono, rng=rng_k,
                        )
                    )(gk.T, hk.T)
                    trees.append(tree)
                    total_out = total_out + row_out.T
                margins = margins + total_out
            stacked = jax.tree_util.tree_map(
                lambda *leaves: jnp.stack(leaves), *trees
            ) if num_parallel > 1 else trees[0]
            # pack inside the program: the host pulls ONE array per dispatch
            if not collect_stats:
                return pack_tree(stacked), margins
            return pack_tree(stacked), margins, _learning_stats(g, h, margins)

        K = self.rounds_per_dispatch
        colsample = cfg.colsample_bytree
        d = self.train_binned.num_col

        metric_fns = self.device_metric_fns
        shared_flags = [b is None for b in self.eval_bins]
        predict_depth = cfg.predict_depth
        n_data_shards = self.n_data_shards

        def multi_round(
            bins, margins, labels, weights, num_cuts, rng, feature_mask, monotone,
            rank_index, eval_m, eval_blw,
        ):
            # eval_blw: ((bins, labels, weights), ...) for the non-shared
            # eval sets — passed as sharded args (closures would stay global
            # under shard_map and mismatch the per-shard margins)
            # lax.scan so the round body is compiled ONCE regardless of K
            k_features = max(1, int(round(colsample * d)))
            d_pad = self.d_pad
            n_fs = self.n_feature_shards

            def body(carry, j):
                margins_c, extra = carry
                rng_j = jax.random.fold_in(rng, j)
                if colsample < 1.0:
                    # same exactly-k-without-replacement draw as the host
                    # path, over GLOBAL columns; with a feature axis each
                    # shard slices its own column segment of the one mask
                    chosen = jax.random.permutation(
                        jax.random.fold_in(rng_j, 777), d
                    )[:k_features]
                    gmask = jnp.zeros(d_pad, jnp.float32).at[chosen].set(1.0)
                    if feature_axis is not None:
                        d_local = d_pad // n_fs
                        fs = jax.lax.axis_index(feature_axis)
                        mask = jax.lax.dynamic_slice(
                            gmask, (fs * d_local,), (d_local,)
                        )
                    else:
                        mask = gmask
                else:
                    mask = feature_mask
                round_out = one_round(
                    bins, margins_c, labels, weights, num_cuts, rng_j, mask,
                    monotone, rank_index,
                )
                if collect_stats:
                    packed, margins_c, lstats = round_out
                else:
                    packed, margins_c = round_out
                    lstats = None
                # every non-shared eval set's margins ride the scan carry:
                # the committed tree applies on device each round whether or
                # not metrics are device-computable, so the host-fallback
                # cadence (evaluate once per dispatch) reads fresh margins
                # without a single extra dispatch, and the carried buffers
                # stay donated round over round (donate_argnums below).
                new_extra = []
                per_set = []
                ei = 0
                for si, shared in enumerate(shared_flags):
                    if shared:
                        m_e, y_e, w_e = margins_c, labels, weights
                    else:
                        b_e, y_e, w_e = eval_blw[ei]
                        m_e = _apply_packed_tree(
                            packed, b_e, extra[ei],
                            num_group, num_parallel, predict_depth, num_bins,
                            route_impl=self.hist_knobs.route_impl,
                        )
                        new_extra.append(m_e)
                        ei += 1
                    if not metric_fns:
                        continue
                    # shard-local partial stats -> psum over the data
                    # axis -> finalize: metric scalars are globally
                    # exact and identical on every shard/host. The
                    # non-decomposable exception (cox-nloglik) gathers
                    # the global rows first — its replicated stats are
                    # pre-divided by the axis size so the shared psum
                    # restores the global value.
                    def _stats_for(fn, m_s, y_s, w_s):
                        if fn.needs_global_rows and axis_name is not None:
                            m_g = jax.lax.all_gather(m_s, axis_name, tiled=True)
                            y_g = jax.lax.all_gather(y_s, axis_name, tiled=True)
                            w_g = jax.lax.all_gather(w_s, axis_name, tiled=True)
                            return fn.partial(m_g, y_g, w_g) / n_data_shards
                        return fn.partial(m_s, y_s, w_s)

                    stats = jnp.concatenate(
                        [_stats_for(fn, m_e, y_e, w_e) for fn in metric_fns]
                    )
                    if axis_name is not None:
                        stats = jax.lax.psum(stats, axis_name)
                    scalars_set = []
                    off = 0
                    for fn in metric_fns:
                        scalars_set.append(fn.finalize(stats[off : off + fn.size]))
                        off += fn.size
                    per_set.append(jnp.stack(scalars_set))
                extra = tuple(new_extra)
                if metric_fns:
                    scalars = jnp.stack(per_set)          # [n_sets, n_metrics]
                else:
                    # non-empty dummy: zero-sized scan outputs are a
                    # lowering hazard on some backends
                    scalars = jnp.zeros((1, 1), jnp.float32)
                outs = (packed, scalars, lstats) if collect_stats else (packed, scalars)
                return (margins_c, extra), outs

            (margins, eval_m), outs = jax.lax.scan(
                body, (margins, eval_m), jnp.arange(K)
            )
            if collect_stats:
                packed_all, metrics_all, stats_all = outs
                return packed_all, metrics_all, margins, eval_m, stats_all
            packed_all, metrics_all = outs
            return packed_all, metrics_all, margins, eval_m

        use_scan = self.use_scan_rounds
        fn = multi_round if use_scan else one_round
        if self.mesh is None:
            if not use_scan:
                # graftlint: disable=trace-uncached-jit — session-scope construction: built once per training session, not per call (one session = one round closure = its own jit cache)
                return jax.jit(fn, donate_argnums=(1,))
            # graftlint: disable=trace-uncached-jit — session-scope construction: built once per training session, not per call (one session = one round closure = its own jit cache)
            return jax.jit(fn, donate_argnums=(1, 9))

        margin_spec = P("data") if num_group == 1 else P("data", None)
        rank_spec = (
            P("data", None, None) if self._rank_index_np is not None else P()
        )
        base_specs = (
            self.bins_spec,    # bins
            margin_spec,       # margins
            P("data"),         # labels
            P("data"),         # weights
            self.feat_spec,    # num_cuts
            P(),               # rng
            self.feat_spec,    # feature_mask
            self.feat_spec,    # monotone
            rank_spec,         # rank_index
        )
        stats_specs = (P(),) if collect_stats else ()
        if not use_scan:
            in_specs = base_specs
            out_specs = (P(), margin_spec) + stats_specs
            donate = (1,)
        else:
            eval_specs = tuple(
                margin_spec for m in self.eval_margins if m is not None
            )
            eval_blw_specs = tuple(
                (P("data", None), P("data"), P("data"))
                for b in self.eval_bins
                if b is not None
            )
            in_specs = base_specs + (eval_specs, eval_blw_specs)
            out_specs = (P(), P(), margin_spec, eval_specs) + stats_specs
            donate = (1, 9)
        mapped = shard_map(
            fn,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            **_SHARD_MAP_REP_KW,
        )
        # graftlint: disable=trace-uncached-jit — session-scope construction: built once per training session, not per call (one session = one round closure = its own jit cache)
        return jax.jit(mapped, donate_argnums=donate)

    def _make_apply_fn(self):
        cfg = self.config
        num_bins = self.train_binned.num_bins
        num_group = self.num_group
        num_parallel = cfg.num_parallel_tree

        route_impl = self.hist_knobs.route_impl

        def apply_tree(packed, bins, margins):
            return _apply_packed_tree(
                packed, bins, margins, num_group, num_parallel,
                cfg.predict_depth, num_bins, route_impl=route_impl,
            )

        if self.mesh is None:
            # graftlint: disable=trace-uncached-jit — session-scope construction: _make_apply_fn runs once per session
            return jax.jit(apply_tree, donate_argnums=(2,))
        margin_spec = P("data") if num_group == 1 else P("data", None)
        mapped = shard_map(
            apply_tree,
            mesh=self.mesh,
            in_specs=(P(), P("data", None), margin_spec),
            out_specs=margin_spec,
            **_SHARD_MAP_REP_KW,
        )
        # graftlint: disable=trace-uncached-jit — session-scope construction: _make_apply_fn runs once per session
        return jax.jit(mapped, donate_argnums=(2,))

    # ----------------------------------------------------------- comm stats
    def _comm_plan(self):
        """(entries, wire bytes/round) of the data-axis histogram
        collectives — ops.histogram.round_comm_plan fed with this session's
        static build structure (grow policy, subtraction gating, trees per
        round)."""
        cfg = self.config
        if self.mesh is None or self.n_data_shards <= 1:
            return [], 0
        # columns each data shard histograms: the whole width, unless a
        # feature axis splits them — under the 2-D reduce_scatter lowering
        # round_comm_plan further pads/scatters this local width to
        # d_local/n_data_shards per device and adds the winner-merge
        # entries of the hierarchical two-axis merge
        d_local = self.d_pad // self.n_feature_shards
        num_bins = self.train_binned.num_bins
        # the builders gate subtraction on the FULL feature width under both
        # comm lowerings (bit-identity contract) — mirror that here so the
        # plan matches what actually traces
        if cfg.grow_policy == "lossguide":
            from ..ops.lossguide import _subtraction_enabled

            subtract = _subtraction_enabled(
                cfg.max_leaves, d_local, num_bins, knobs=self.hist_knobs
            )
        else:
            from ..ops.tree_build import _subtraction_enabled

            subtract = _subtraction_enabled(
                cfg.max_depth, d_local, num_bins, knobs=self.hist_knobs
            )
        return round_comm_plan(
            cfg.grow_policy,
            cfg.max_depth,
            cfg.max_leaves,
            d_local,
            num_bins,
            self.n_data_shards,
            self.hist_comm,
            subtract,
            trees_per_round=cfg.num_parallel_tree * max(self.num_group, 1),
        )

    def _set_comm_round_fields(self):
        """Clear the comm keys from the per-round record at session start so
        no session inherits a previous one's collectives (dart reuses this
        session for staging but dispatches its own GSPMD loop; single-device
        sessions have no collectives at all). The real values are published
        by the first ``_note_comm_dispatch`` — i.e. only by sessions that
        actually run the comm-lowered round program."""
        from ..telemetry import set_round_fields

        set_round_fields(hist_comm=None, hist_comm_bytes=None, hist_comm_ms=None)

    def _calibrate_hist_comm_ms(self):
        """Isolated latency of one round's data-axis collectives, in ms.

        Delegates to the module-level lru_cached factory keyed by
        (mesh, lowering, plan shapes): a session rebuilt on the same mesh
        with the same static plan — every sequential CV fold, an elastic
        generation that kept its topology, a dart staging rebuild — reuses
        the measured number instead of re-paying the standalone collective
        compile + timing dispatches on its first round. Returns 0.0 when
        calibration is disabled (GRAFT_HIST_COMM_CALIBRATE=0) or fails.
        """
        if not self.hist_comm_plan:
            return 0.0
        if os.environ.get("GRAFT_HIST_COMM_CALIBRATE", "1") != "1":
            return 0.0
        plan_key = tuple(
            (entry["kind"], entry["shape"], entry["count"])
            for entry in self.hist_comm_plan
        )
        try:
            return _calibrated_comm_ms(self.mesh, self.hist_comm, plan_key)
        except Exception as e:  # calibration must never break training
            # degrade THIS session to 0.0 only: a raising call is not
            # memoized by lru_cache, so the next session rebuild retries
            # instead of serving a cached failure forever
            logger.warning("hist comm calibration failed: %s", e)
            return 0.0

    def _note_comm_dispatch(self, k_rounds):
        """Fold one dispatch (k_rounds boosting rounds) into the comm
        telemetry: hist_comm_bytes_total counter + (lazily) the calibrated
        hist_comm_ms gauge and round-record field."""
        if not self.hist_comm_plan:
            return
        from ..telemetry import REGISTRY, set_round_fields

        labels = {"impl": self.hist_comm}
        set_round_fields(
            hist_comm=self.hist_comm,
            hist_comm_bytes=self.hist_comm_bytes_per_round,
        )
        if self._hist_comm_ms is None:
            self._hist_comm_ms = self._calibrate_hist_comm_ms()
            if self._hist_comm_ms:
                REGISTRY.gauge(
                    "hist_comm_ms",
                    "Calibrated isolated latency of one round's data-axis "
                    "histogram collectives (upper bound: real rounds may "
                    "overlap them with compute)",
                    labels,
                ).set(round(self._hist_comm_ms, 3))
                set_round_fields(hist_comm_ms=round(self._hist_comm_ms, 3))
        REGISTRY.counter(
            "hist_comm_bytes_total",
            "Estimated cross-shard wire bytes moved by histogram "
            "collectives (ring formula, docs/DESIGN.md Communication)",
            labels,
        ).inc(self.hist_comm_bytes_per_round * k_rounds)
        # trace the dispatch as a span under the open round span; the span
        # duration is the calibrated isolated collective latency (0 until
        # calibration lands) — an estimate, flagged as such in the attrs
        from ..telemetry import tracing

        if tracing.enabled():
            tracing.record_span(
                "collective.dispatch",
                duration_s=(self._hist_comm_ms or 0.0) * k_rounds / 1000.0,
                attributes={
                    "impl": self.hist_comm,
                    "bytes": self.hist_comm_bytes_per_round * k_rounds,
                    "rounds": k_rounds,
                    "calibrated": bool(self._hist_comm_ms),
                },
            )

    # ------------------------------------------------------------- resketch
    def _stage_train_bins(self, raw_bins, cuts, max_bin):
        """Stage [n_local, d_real] bin indices + per-feature cuts as the
        session's padded, placed device arrays (cuts/num_cuts/bins). Shared
        by __init__ and the approx re-sketch so the two paths can never
        disagree on padding conventions."""
        cuts = list(cuts)
        if self.d_pad != self._d_real:
            cuts += [
                np.zeros(0, np.float32)
                for _ in range(self.d_pad - self._d_real)
            ]
        bins_np = self._layout_rows(np.asarray(raw_bins), max_bin)
        if self.d_pad != self._d_real:
            bins_np = np.concatenate(
                [
                    bins_np,
                    np.full(
                        (bins_np.shape[0], self.d_pad - self._d_real),
                        max_bin,
                        bins_np.dtype,
                    ),
                ],
                axis=1,
            )
        self.cuts = cuts
        self.num_cuts = self._put(
            np.array([len(c) for c in cuts], np.int32), self.feat_spec
        )
        self.bins = self._put(bins_np, self.bins_spec)

    def _resketch_bins(self):
        """Per-dispatch candidate re-sketch for tree_method='approx'.

        libxgboost's approx re-selects split candidates every iteration via
        a hessian-weighted quantile sketch (its GlobalApproxUpdater; the
        reference delegates to it through the tree_method HP,
        hyperparameter_validation.py:22-24). Here: pull current hessians,
        recompute cuts (allgather-merged across hosts in multi-process
        runs), re-bin train + cached eval sets, and refresh cuts/num_cuts —
        all shapes/dtypes static, so the jitted round program is reused with
        new array CONTENTS. Committed trees are unaffected: each round's
        trees were already compacted to float thresholds under the cuts
        active when they were built. Runs before EVERY dispatch (including
        the first: libxgboost hessian-weights the iteration-0 sketch too —
        from the base margin, or real margins on checkpoint resume)."""
        from ..data.binning import (
            _sketch_impl, apply_cut_points, compute_cut_points,
        )

        if self._grad_fn is None:
            # graftlint: disable=trace-uncached-jit — memoized on self._grad_fn: constructed once per session
            self._grad_fn = jax.jit(self.objective.grad_hess)
        _g, h = self._grad_fn(self.margins, self.labels, self.weights)
        if h.ndim == 2:  # multi-class: sketch weight = summed class hessians
            h = h.sum(axis=1)
        max_bin = self.train_binned.max_bin
        device_sketch = not self.is_multiprocess and _sketch_impl() == "device"
        if not device_sketch:
            h_host = np.asarray(self._to_host(h, self.n), np.float32)
        if self.is_multiprocess:
            cuts = _merged_distributed_cuts(self._dtrain, max_bin, weights=h_host)
            feats = self._dtrain.features
        elif device_sketch:
            # TPU path: float features staged on device ONCE — re-uploading
            # [n, d] floats every dispatch would pay n*d*4 bytes of
            # host->HBM per round; hessians never leave the device at all.
            # Trade: the staged floats stay resident (n*d*4 bytes of HBM)
            # alongside the round program for the whole job —
            # GRAFT_SKETCH_IMPL=host trades them back for per-round uploads
            # if an approx job is HBM-bound.
            if self._feats_dev is None:
                self._feats_dev = jnp.asarray(self._dtrain.features, jnp.float32)
            feats = self._feats_dev
            cuts = compute_cut_points(feats, h[: self.n], max_bin)
        else:
            feats = self._dtrain.features
            cuts = compute_cut_points(feats, h_host, max_bin)
        self._stage_train_bins(
            apply_cut_points(feats, cuts, max_bin), cuts, max_bin
        )
        # cached eval bins were built with the old cuts; the incremental
        # eval-margin apply reads bin indices, so they must re-bin too
        for i, (name, dm, binned) in enumerate(self.eval_sets):
            if self.eval_bins[i] is None:
                continue
            efeats = dm.features
            if device_sketch:
                if i not in self._eval_feats_dev:
                    self._eval_feats_dev[i] = jnp.asarray(efeats, jnp.float32)
                efeats = self._eval_feats_dev[i]
            eb = np.asarray(apply_cut_points(efeats, cuts, max_bin))
            self.eval_bins[i] = self._put(
                _pad_rows(eb, self._eval_pads[i], max_bin), P("data", None)
            )

    # ------------------------------------------------------- device window
    def _introspect_compiled_cost(self):
        """AOT-lower the fused round dispatch and feed its XLA
        ``cost_analysis``/``memory_analysis`` into the device-window plane
        (``training.compiled`` record + flops/HBM gauges). Gated on
        ``SM_DEVICE_TELEMETRY`` because the AOT compile is real work (the
        jit path's own compile is served from the persistent cache when
        ``GRAFT_COMPILE_CACHE_DIR`` is armed); lowering never *executes*,
        so donated buffers are not consumed. Diagnostics only — any
        failure is one warning, never a failed session."""
        from ..telemetry import device as device_telemetry

        if not device_telemetry.enabled():
            return
        try:
            d_pad = self.bins.shape[1]
            mask_np = np.ones(d_pad, np.float32)
            if self.has_feature_axis:
                feature_mask = self._put(mask_np, self.feat_spec)
            else:
                feature_mask = jnp.asarray(mask_np)
            # self.rng is key-shaped and is NOT consumed here — lowering
            # only reads avals, so the training stream stays bit-identical
            args = (
                self.bins,
                self.margins,
                self.labels,
                self.weights,
                self.num_cuts,
                self.rng,
                feature_mask,
                self.monotone,
                self.rank_index_dev,
            )
            if self.use_scan_rounds:
                eval_m = tuple(m for m in self.eval_margins if m is not None)
                eval_blw = tuple(
                    (self.eval_bins[i], self.eval_labels[i], self.eval_weights[i])
                    for i in range(len(self.eval_bins))
                    if self.eval_bins[i] is not None
                )
                lowered = self._round_fn.lower(*args, eval_m, eval_blw)
            else:
                lowered = self._round_fn.lower(*args)
            cost = device_telemetry.cost_from_compiled(lowered.compile())
            mesh_shape = dict(self.mesh.shape) if self.mesh is not None else None
            device_telemetry.note_compiled(
                cost,
                mesh_shape=mesh_shape,
                rounds_per_dispatch=self.rounds_per_dispatch,
                backend=jax.default_backend(),
            )
        except Exception as e:
            logger.warning(
                "compiled-cost introspection failed (%s); training continues "
                "without the training.compiled record",
                e,
            )

    def _abort_device_oom(self, exc):
        """A round dispatch died with the allocator exhausted: dump the HBM
        forensics (top live buffers, allocator stats, compiled memory
        analysis, last watermark), then take the shared watchdog abort path
        (checkpoint flush + flight recorder + ``training.abort``) with
        exit 86 so the platform log names the OOM instead of a raw XLA
        traceback."""
        from ..constants import EXIT_DEVICE_OOM
        from ..telemetry import device as device_telemetry
        from ..training import watchdog

        path = device_telemetry.dump_oom_forensics(exc)
        watchdog.request_abort(
            "device_oom",
            EXIT_DEVICE_OOM,
            error=str(exc)[:400],
            forensics=path or "",
        )

    # ---------------------------------------------------------------- round
    def _maybe_fenced_dispatch(self, dispatch):
        """Run one round dispatch, attribution-fenced on every Nth call
        (SM_TRACE_DEVICE_SYNC): the async XLA dispatch is timed as a
        `host_dispatch` span and the wait on its outputs as `device_sync`.
        The fence serializes host/device overlap, which is why it is
        sampled, never always-on. Unsampled calls run ``dispatch`` as-is."""
        sampled = (
            self._device_sync_every > 0
            and self._dispatch_index % self._device_sync_every == 0
        )
        self._dispatch_index += 1
        if not sampled:
            return dispatch()
        from ..telemetry import active_recorder, compile_stats, span

        pre_compile = compile_stats()["seconds"]
        with span("host_dispatch"):
            out = dispatch()
        # an XLA compile that completed inside THIS dispatch is wall time
        # the host_dispatch span already contains; RoundTimer reports it
        # under the round's `compile` key, so remove exactly the measured
        # overlap from the phase accumulator (and only then — a compile on
        # an unfenced dispatch must not erode the sampled host time)
        overlap = compile_stats()["seconds"] - pre_compile
        if overlap > 0:
            recorder = active_recorder()
            if recorder is not None:
                recorder.add("host_dispatch", -overlap)
        with span("device_sync"):
            # dispatch callables return every output they put in flight
            # (round program + any separate eval-apply programs), so
            # blocking the returned pytree fences the whole device step
            jax.block_until_ready(out)
        return out

    def run_rounds(self):
        """One device dispatch -> (list of host tree dicts, metrics or None).

        metrics: [K, n_metrics] numpy when device metrics are active (batched
        mode); None when evaluation happens host-side (K=1).

        An allocator exhaustion anywhere in the dispatch (the async XLA
        error materializes at the blocking transfer) is terminal for the
        process — no retry can succeed against a full HBM — so it routes
        through the OOM forensics dump + watchdog abort (exit 86) instead
        of unwinding as a raw traceback. Every other exception propagates
        unchanged."""
        try:
            return self._run_rounds_inner()
        except Exception as e:
            from ..telemetry import device as device_telemetry

            if device_telemetry.is_oom_error(e):
                self._abort_device_oom(e)
            raise

    def _stash_learning_stats(self, stats_dev):
        """One small host transfer per dispatch: the per-round learning
        stats vectors, decoded into dicts the train loop folds (with the
        committed-tree stats) into ``telemetry/model.note_learning`` and
        the numeric-health guard. ``[]`` when the plane is unarmed."""
        if stats_dev is None:
            self.last_learning_stats = []
            return
        from ..telemetry import model as model_telemetry

        rows = np.asarray(stats_dev)
        if rows.ndim == 1:
            rows = rows[None, :]
        self.last_learning_stats = [
            model_telemetry.decode_device_stats(rows[j])
            for j in range(rows.shape[0])
        ]

    def _run_rounds_inner(self):
        if self.approx_resketch:
            self._resketch_bins()
        if fault_point("train.gradient_poison", dispatch=self._dispatch_index):
            # numeric-poison drill: corrupt the live margins so the next
            # round's gradients genuinely go NaN through the real device
            # pipeline (the learning-telemetry guard must catch it there)
            self.margins = self.margins * jnp.float32(np.nan)
        self.rng, sub, colrng = jax.random.split(self.rng, 3)
        d_pad = self.bins.shape[1]
        if self.config.colsample_bytree < 1.0:
            # draw k of the REAL columns (padded always-missing columns are
            # never legal splits, but counting them would shrink k)
            d_real = self.train_binned.num_col
            k = max(1, int(round(self.config.colsample_bytree * d_real)))
            chosen = np.asarray(jax.random.permutation(colrng, d_real)[:k])
            mask_np = np.zeros(d_pad, np.float32)
            mask_np[chosen] = 1.0
        else:
            mask_np = np.ones(d_pad, np.float32)
        if self.has_feature_axis:
            # the global mask is column-sharded over the feature axis; place
            # it properly (required in multi-process runs)
            feature_mask = self._put(mask_np, self.feat_spec)
        else:
            feature_mask = jnp.asarray(mask_np)
        args = (
            self.bins,
            self.margins,
            self.labels,
            self.weights,
            self.num_cuts,
            sub,
            feature_mask,
            self.monotone,
            self.rank_index_dev,
        )
        if not self.use_scan_rounds:

            def _dispatch_single():
                if self.learning_stats:
                    packed, self.margins, lstats = self._round_fn(*args)
                else:
                    packed, self.margins = self._round_fn(*args)
                    lstats = None
                for i in range(len(self.eval_sets)):
                    if self.eval_margins[i] is not None:
                        self.eval_margins[i] = self._apply_fn(
                            packed, self.eval_bins[i], self.eval_margins[i]
                        )
                # return EVERY freshly dispatched output — the eval-margin
                # applies are separate jitted programs, and the attribution
                # fence must cover them too or their device time would leak
                # into build_eval / the next round's host_dispatch
                return packed, lstats, [m for m in self.eval_margins if m is not None]

            packed, lstats, _fenced_evals = self._maybe_fenced_dispatch(_dispatch_single)
            self._note_comm_dispatch(1)
            self._stash_learning_stats(lstats)
            return [unpack_tree(np.asarray(packed))], None
        eval_m = tuple(m for m in self.eval_margins if m is not None)
        eval_blw = tuple(
            (self.eval_bins[i], self.eval_labels[i], self.eval_weights[i])
            for i in range(len(self.eval_bins))
            if self.eval_bins[i] is not None
        )
        out = self._maybe_fenced_dispatch(
            lambda: self._round_fn(*args, eval_m, eval_blw)
        )
        if self.learning_stats:
            packed, metrics, self.margins, eval_m_out, lstats = out
        else:
            packed, metrics, self.margins, eval_m_out = out
            lstats = None
        ei = 0
        for i in range(len(self.eval_margins)):
            if self.eval_margins[i] is not None:
                self.eval_margins[i] = eval_m_out[ei]
                ei += 1
        packed_np = np.asarray(packed)  # ONE transfer for K rounds
        self._note_comm_dispatch(packed_np.shape[0])
        self._stash_learning_stats(lstats)
        metrics_np = np.asarray(metrics) if self.device_metric_fns else None
        return (
            [unpack_tree(packed_np[j]) for j in range(packed_np.shape[0])],
            metrics_np,
        )

    # ----------------------------------------------------------------- eval
    def _to_host(self, arr, n_real):
        """Device margins -> host numpy. In multi-process mode this returns
        the *local* shard's rows; ``evaluate`` then combines per-host values
        into one global number (see its docstring)."""
        if self.is_multiprocess:
            shards = sorted(arr.addressable_shards, key=lambda s: s.index[0].start or 0)
            local = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
            return local if n_real is None else local[:n_real]
        full = np.asarray(arr)
        return full if n_real is None else full[:n_real]

    def margins_for(self, index):
        dm = self.eval_sets[index][1]
        m = self.eval_margins[index]
        if m is None:
            if self.rank_pos is not None:
                # distributed-ranking layout: padding is interleaved per
                # shard; map device positions back to original row order
                full = self._to_host(self.margins, None)
                return full[self.rank_pos]
            return self._to_host(self.margins, self.n)
        return self._to_host(m, dm.num_row)

    def evaluate(self, metric_names, feval=None, forest=None):
        """Returns list of (data_name, metric_name, value) per eval set.

        In multi-process runs each host computes on its local shard and the
        values combine as a weight-sum-weighted mean across hosts, so every
        host reports identical numbers (the path for metrics that cannot
        decompose into device partials — ndcg/map/feval; decomposable ones
        ride the exact device psum path instead). This mirrors distributed
        xgboost, where python-side custom metrics are computed per worker
        and averaged rather than allreduced elementwise.

        forest: evaluate from the COMMITTED forest's margins instead of the
        session's device margins. Used by the host-fallback cadence when
        the final dispatch over-built (num_boost_round not a multiple of K,
        or an early stop mid-batch): the device margins then include
        discarded trees, so the last metric line — the one HPO reads —
        must come from the forest that was actually kept. Cost note: this
        re-predicts each eval set (train watchlist included) with the
        whole-forest predictor, once per job at the final round — exactness
        of the final line is deliberately bought with one extra predict
        pass; sizing num_boost_round to a multiple of K avoids it entirely.
        """
        if not hasattr(self, "_global_rows_cache"):
            self._global_rows_cache = {}
        if forest is not None:
            def _committed_margin(dm):
                m = _predict_margin_rows(forest, dm)
                return m.reshape(
                    (dm.num_row,)
                    if self.num_group == 1
                    else (dm.num_row, self.num_group)
                )

            entries = (
                (name, dm, _committed_margin(dm))
                for name, dm, _binned in self.eval_sets
            )
        else:
            entries = (
                (name, dm, self.margins_for(i))
                for i, (name, dm, _binned) in enumerate(self.eval_sets)
            )
        return evaluate_host_lines(
            entries,
            metric_names,
            feval,
            self.objective,
            self.num_group,
            self.config.objective_params,
            self.is_multiprocess,
            global_rows_cache=self._global_rows_cache,
        )


def evaluate_host_lines(
    entries,
    metric_names,
    feval,
    objective,
    num_group,
    objective_params,
    is_multiprocess,
    global_rows_cache=None,
):
    """Host-side metric lines for ``entries`` of (name, dm, margin).

    Single-process: plain host evaluation. Multi-process, per metric:
    decomposable metrics combine EXACTLY from per-host partial stats
    (device_metrics); the cox-nloglik exception gathers the global rows
    (labels/weights cached round-invariant in ``global_rows_cache``, keyed
    by entry position); everything else (ndcg/map/feval) combines as a
    weight-sum-weighted mean — all hosts return identical lines. Shared by
    the tree booster's evaluate(), gblinear, and dart."""
    from .device_metrics import make_device_metric

    results = []       # (name, metric, local_value or None placeholder)
    pairs = []         # per entry: summable stats vector
    finalizers = []    # per entry: fn(summed stats) -> global value

    def append_weighted_mean(value, wsum):
        pairs.append(np.asarray([value * wsum, wsum], np.float64))
        finalizers.append(lambda s: float(s[0] / max(s[1], 1e-12)))

    for i, (name, dm, margin) in enumerate(entries):
        preds = None
        prob_matrix = None
        w = dm.get_weight()
        wsum = float(np.sum(w)) if w is not None else float(dm.num_row)
        for metric in metric_names:
            dmf = (
                make_device_metric(metric, objective.name, num_group, objective_params)
                if is_multiprocess
                else None
            )
            if dmf is not None and dmf.needs_global_rows:
                # non-decomposable (cox-nloglik): gather every host's rows
                # (padded to the max local length, weight 0) and evaluate on
                # the global arrays — exact and identical on every host, the
                # host-side mirror of the device all_gather path. Labels/
                # weights (and the agreed max length) are round-invariant:
                # gathered once per eval set and cached; only the margins
                # travel per round.
                from jax.experimental import multihost_utils

                n_loc = int(dm.num_row)

                def _padded(a, n_max):
                    out = np.zeros(n_max, np.float32)
                    out[:n_loc] = np.asarray(a, np.float32)[:n_loc]
                    return out

                cache = global_rows_cache if global_rows_cache is not None else {}
                if i not in cache:
                    w_arr = (
                        np.asarray(w, np.float32)
                        if w is not None
                        else np.ones(n_loc, np.float32)
                    )
                    n_max = int(
                        np.asarray(
                            multihost_utils.process_allgather(
                                np.asarray([n_loc], np.int64)
                            )
                        ).max()
                    )
                    yw = np.asarray(
                        multihost_utils.process_allgather(
                            np.stack(
                                [_padded(dm.labels, n_max), _padded(w_arr, n_max)]
                            )
                        ),
                        np.float64,
                    )  # [P, 2, n_max]
                    cache[i] = (n_max, yw[:, 0].ravel(), yw[:, 1].ravel())
                n_max, y_g, w_g = cache[i]
                m_g = np.asarray(
                    multihost_utils.process_allgather(_padded(margin, n_max)),
                    np.float64,
                ).ravel()
                value = eval_metrics.evaluate(
                    metric, objective.margin_to_prediction(m_g), y_g, w_g
                )
                results.append((name, metric, value))
                # identical on every host: combines to mean(value)
                append_weighted_mean(value, 1.0)
                continue
            if dmf is not None:
                # decomposable: combine exactly from per-host partial
                # stats; skip the (discarded) host-local evaluation
                w_arr = (
                    np.asarray(w, np.float32)
                    if w is not None
                    else np.ones(dm.num_row, np.float32)
                )
                stats = np.asarray(
                    dmf.partial(
                        jnp.asarray(margin),
                        jnp.asarray(dm.labels),
                        jnp.asarray(w_arr),
                    ),
                    np.float64,
                )
                results.append((name, metric, None))
                pairs.append(stats)
                finalizers.append(
                    lambda s, f=dmf: float(
                        f.finalize(jnp.asarray(s, dtype=jnp.float32))
                    )
                )
                continue
            if preds is None:
                preds = objective.margin_to_prediction(margin)
                if num_group > 1:
                    prob_matrix = objectives_mod.SoftprobMulti.margin_to_prediction(
                        objective, margin
                    )
            value = eval_metrics.evaluate(
                metric,
                preds,
                dm.labels,
                dm.weights,
                groups=dm.groups,
                prob_matrix=prob_matrix,
            )
            results.append((name, metric, value))
            if is_multiprocess:
                # non-decomposable (ndcg/map): weight-sum-weighted mean
                append_weighted_mean(value, wsum)
        if feval is not None:
            # xgboost >= 1.2 convention: feval receives the raw margin
            for metric_name, value in feval(margin, dm):
                results.append((name, metric_name, value))
                if is_multiprocess:
                    append_weighted_mean(value, wsum)
    if not is_multiprocess or not results:
        return results
    return combine_host_metric_entries(results, pairs, finalizers)


def combine_host_metric_entries(results, pairs, finalizers):
    """Cross-host combine of per-entry metric stats -> identical lines.

    ``results``: [(name, metric, local_value_or_None)] in a deterministic
    order identical on every host; ``pairs[j]``: the entry's summable stats
    vector; ``finalizers[j]``: fn(summed stats) -> float. Device partial
    stats are f32 (x64 is not enabled); the allgather rides the device too,
    so transport is f32 — the cross-host SUM happens host-side in f64 to
    avoid accumulating f32 rounding over many hosts. Shared by the tree
    booster's evaluate() and the gblinear eval loop."""
    from jax.experimental import multihost_utils

    gathered = np.asarray(
        multihost_utils.process_allgather(
            np.stack(pairs, axis=0).astype(np.float32)
        ),
        np.float64,
    )  # [P, n_entries, stat_size]
    summed = gathered.sum(axis=0)
    return [
        (name, metric, finalizers[j](summed[j]))
        for j, (name, metric, _v) in enumerate(results)
    ]


def _abort_numeric_poison(round_index):
    """The numeric-health guard tripped: a NaN/Inf count in the round's
    learning stats went nonzero. Dump the learning forensics (the last-K
    stats history, naming the first poisoned round), then take the shared
    watchdog abort path (checkpoint flush + flight recorder +
    ``training.abort``) with exit 87 — the stats counters are globally
    psum'd, so every rank sees the same poisoned round and aborts on it,
    long before the consensus digest cadence would reach exit 81."""
    from ..constants import EXIT_NUMERIC_POISON
    from ..telemetry import model as model_telemetry
    from ..training import watchdog

    path = model_telemetry.dump_learning_forensics(
        "numeric_poison", first_bad_round=round_index
    )
    watchdog.request_abort(
        "numeric_poison",
        EXIT_NUMERIC_POISON,
        round=int(round_index),
        forensics=path or "",
    )


def train(
    params,
    dtrain,
    num_boost_round=10,
    evals=(),
    feval=None,
    callbacks=None,
    xgb_model=None,
    verbose_eval=True,
    mesh=None,
    hist_knobs=None,
):
    """Train a Forest. API mirrors ``xgb.train`` for the orchestration layer.

    xgb_model: a Forest or a model-file path to continue training from
    (checkpoint resume — reference checkpointing.py:45-55).
    mesh: optional jax Mesh with a "data" axis for multi-chip data parallelism.
    hist_knobs: optional pre-resolved histogram-knob snapshot (ops/histogram
    HistKnobs); an elastic membership reform passes the original session's
    snapshot so the rebuilt (smaller-mesh) session trains under identical
    kernel choices.
    """
    from ..utils.compile_cache import maybe_enable_compile_cache

    # armed here too so every booster path (gblinear, dart, update) gets
    # the persistent compile cache, not just _TrainingSession builders
    maybe_enable_compile_cache()
    config = TrainConfig(params)
    callbacks = list(callbacks or [])

    if isinstance(dtrain, BinnedMatrix) and (
        config.booster != "gbtree" or config.process_type != "default"
    ):
        # gblinear fits raw floats and update/refresh recomputes leaf stats
        # from them — representative values would silently change the model.
        # (The streaming-ingest gating refuses these configs up front; this
        # guards direct API callers.)
        raise exc.UserError(
            "Pre-binned training input (chunked ingest) requires "
            "booster='gbtree' with process_type='default'; got booster={!r} "
            "process_type={!r}. Use SM_INGEST_MODE=whole.".format(
                config.booster, config.process_type
            )
        )

    if config.process_type == "update" and config.booster == "gblinear":
        # checked before the gblinear branch returns: otherwise a refresh
        # request is silently reinterpreted as "boost more rounds"
        raise exc.UserError(
            "process_type 'update' can only be used with updater 'refresh' and "
            "'prune' (tree boosters); booster=gblinear does not support it."
        )

    if config.booster == "gblinear":
        from .gblinear import LinearModel, train_linear

        initial = None
        if xgb_model is not None:
            if isinstance(xgb_model, LinearModel):
                initial = xgb_model
            else:
                from .compat import load_model_any_format

                initial, _fmt = load_model_any_format(xgb_model)
                if not isinstance(initial, LinearModel):
                    raise exc.UserError(
                        "Checkpoint {} is not a gblinear model".format(xgb_model)
                    )
        return train_linear(
            config,
            dtrain,
            num_boost_round,
            evals=evals,
            feval=feval,
            callbacks=callbacks,
            initial_model=initial,
            mesh=mesh,
        )

    if xgb_model is None:
        forest = Forest(
            objective_name=config.objective,
            objective_params={
                k: v
                for k, v in config.objective_params.items()
                if k in OBJECTIVE_PARAM_KEYS
            },
            base_score=config.base_score,
            num_feature=dtrain.num_col,
            num_class=config.num_class,
            feature_names=dtrain.feature_names,
        )
    elif isinstance(xgb_model, Forest):
        forest = xgb_model
    else:
        forest = Forest.load_model(xgb_model)
    if forest.num_feature < dtrain.num_col and forest.trees:
        raise exc.UserError("feature_names mismatch between checkpoint and data")
    forest.num_feature = max(forest.num_feature, dtrain.num_col)

    if config.process_type == "update":
        from .update import train_update

        return train_update(
            config, forest, dtrain, list(evals), feval, callbacks, num_boost_round,
            mesh=mesh,
        )

    if config.booster == "dart":
        from .dart import train_dart

        return train_dart(
            config, forest, dtrain, list(evals), feval, callbacks, num_boost_round,
            mesh=mesh,
        )

    metric_names = _eval_metric_names(config, forest.objective())
    session = _TrainingSession(
        config,
        dtrain,
        list(evals),
        forest,
        mesh=mesh,
        metric_names=metric_names,
        has_feval=feval is not None,
        hist_knobs=hist_knobs,
    )

    for cb in callbacks:
        if hasattr(cb, "before_training"):
            forest = cb.before_training(forest) or forest

    def _trees_for_round(arrs):
        if session.num_group > 1 and config.num_parallel_tree > 1:
            # stacked [P, C, ...]: commit class-major (class 0's P trees,
            # then class 1's, ...) matching xgboost's per-group layout
            return (
                [
                    compact_padded_tree(
                        {k: v[t, c] for k, v in arrs.items()}, session.cuts
                    )
                    for c in range(session.num_group)
                    for t in range(config.num_parallel_tree)
                ],
                [
                    c
                    for c in range(session.num_group)
                    for _ in range(config.num_parallel_tree)
                ],
            )
        if session.num_group > 1:
            return (
                [
                    compact_padded_tree({k: v[c] for k, v in arrs.items()}, session.cuts)
                    for c in range(session.num_group)
                ],
                list(range(session.num_group)),
            )
        if config.num_parallel_tree > 1:
            return (
                [
                    compact_padded_tree({k: v[t] for k, v in arrs.items()}, session.cuts)
                    for t in range(config.num_parallel_tree)
                ],
                [0] * config.num_parallel_tree,
            )
        return [compact_padded_tree(arrs, session.cuts)], [0]

    evals_log = {}
    start_round = forest.num_boosted_rounds
    end_round = start_round + num_boost_round
    rnd = start_round
    stop = False
    while rnd < end_round and not stop:
        trees_batch, batch_metrics = session.run_rounds()
        for j, tree_np in enumerate(trees_batch):
            if rnd >= end_round:
                break  # trees past the requested count are discarded
            trees, info = _trees_for_round(tree_np)
            forest.append_round(trees, info)

            if j < len(session.last_learning_stats):
                # model-quality plane: device reductions + committed-tree
                # stats -> one training.learning record, then the numeric-
                # health guard (NaN/Inf counters nonzero -> forensics dump
                # + exit 87 on every rank, naming this round)
                from ..telemetry import model as model_telemetry

                stats = dict(session.last_learning_stats[j])
                stats.update(model_telemetry.tree_stats(trees))
                model_telemetry.note_learning(rnd, stats)
                if model_telemetry.first_poisoned_round([stats], rnd) is not None:
                    _abort_numeric_poison(rnd)

            if batch_metrics is not None:
                # device-computed per-round metrics: [K, n_sets, n_metrics]
                results = [
                    (name, metric_name, float(batch_metrics[j, si, i]))
                    for si, (name, _dm, _b) in enumerate(session.eval_sets)
                    for i, metric_name in enumerate(session.device_metric_names)
                ]
            elif not session.eval_sets:
                results = []
            elif not session.host_eval_batched:
                results = session.evaluate(metric_names, feval=feval)
            elif j == len(trees_batch) - 1:
                # host-fallback cadence: the fused K-round dispatch finished
                # and the device margins cover exactly the committed trees —
                # one host evaluation per dispatch, attributed to the
                # batch-end round.
                results = session.evaluate(metric_names, feval=feval)
            elif rnd == end_round - 1:
                # final round lands mid-batch (num_boost_round % K != 0):
                # the device margins include the over-built, discarded trees
                # — evaluate the committed forest so the last metric line
                # (the one HPO reads) is exact.
                results = session.evaluate(metric_names, feval=feval, forest=forest)
            else:
                results = []  # stale round inside the fused batch
            for data_name, metric_name, value in results:
                evals_log.setdefault(data_name, {}).setdefault(metric_name, []).append(value)

            for cb in callbacks:
                if hasattr(cb, "after_iteration") and cb.after_iteration(
                    forest, rnd, evals_log
                ):
                    stop = True
            rnd += 1
            if stop:
                break

    for cb in callbacks:
        if hasattr(cb, "after_training"):
            forest = cb.after_training(forest) or forest
    return forest
