"""Forest: the trained model — host representation + xgboost JSON codec.

The model artifact stays **xgboost-compatible** (SURVEY.md §7 layer 3): we
serialize to the public xgboost JSON schema so (a) the serving contract keeps
the ``xgboost-model`` file name/format (reference xgb_constants.py:96), and
(b) models trained elsewhere with real xgboost load into our XLA predictor.

Host side each tree is compact arrays (left/right children, split feature,
float threshold, default_left, values); for inference the forest stacks into
padded [T, N] device arrays consumed by ops.predict. Trees coming out of the
trainer arrive in the padded full-binary layout with *bin* splits and are
compacted here, converting bins to float thresholds via the binning cuts
(bin(v) <= b  <=>  v < cuts[b] by construction — data/binning.py).
"""

import json

import numpy as np

from ..ops.predict import forest_predict_margin, host_predict_margin
from ..toolkit import exceptions as exc
from . import objectives as objectives_mod


def predict_bucket(n):
    """Power-of-two row bucket the device predict path pads to — the single
    source of truth shared by predict_margin and the serving warmup (which
    pre-compiles exactly these buckets)."""
    return max(8, 1 << (int(n - 1).bit_length())) if n else 8


def _host_predict_rows():
    """Row-count cutover below which prediction runs the numpy host path
    instead of the compiled device kernel (0 disables). Default 32: at that
    size host traversal is still ~100us while a device dispatch is >=1ms on
    a tunneled TPU (bench_serve.py measures both sides of the cutover)."""
    from ..utils.envconfig import env_int

    return env_int("GRAFT_HOST_PREDICT_ROWS", 32)


class Tree:
    """One decision tree, compact arrays, xgboost node ordering (root = 0).

    ``categories``: optional dict {node_id: int array} for partition-based
    categorical splits (xgboost ``enable_categorical``). Stored categories
    are the set that routes to the RIGHT child (xgboost
    common::Decision semantics: category in set -> not default-left branch
    decision -> right); invalid/missing categories follow ``default_left``.
    Our trainer never produces these — they exist for BYO xgboost models
    loaded for serving (reference serve_utils.py:171-197 loads any customer
    model through libxgboost, which handles categorical nodes natively).
    """

    def __init__(self, feature, threshold, default_left, left, right, value,
                 base_weight=None, gain=None, sum_hess=None, parent=None,
                 categories=None):
        self.feature = np.asarray(feature, np.int32)
        self.threshold = np.asarray(threshold, np.float32)
        self.default_left = np.asarray(default_left, np.bool_)
        self.left = np.asarray(left, np.int32)
        self.right = np.asarray(right, np.int32)
        self.value = np.asarray(value, np.float32)  # leaf value at leaves
        n = len(self.feature)
        self.base_weight = np.asarray(
            base_weight if base_weight is not None else np.zeros(n), np.float32
        )
        self.gain = np.asarray(gain if gain is not None else np.zeros(n), np.float32)
        self.sum_hess = np.asarray(sum_hess if sum_hess is not None else np.zeros(n), np.float32)
        self.parent = np.asarray(
            parent if parent is not None else _parents_from_children(self.left, self.right),
            np.int32,
        )
        self.categories = {
            int(k): np.asarray(v, np.int64) for k, v in (categories or {}).items()
        }

    @property
    def num_nodes(self):
        return len(self.feature)

    @property
    def has_categorical(self):
        return bool(self.categories)

    def max_category(self):
        return max(
            (int(v.max()) for v in self.categories.values() if len(v)), default=-1
        )

    @property
    def is_leaf(self):
        return self.left < 0

    def depth(self):
        """Max root->leaf depth (host-side, for kernel iteration count)."""
        depth = 0
        frontier = [(0, 0)]
        while frontier:
            node, d = frontier.pop()
            depth = max(depth, d)
            if self.left[node] >= 0:
                frontier.append((int(self.left[node]), d + 1))
                frontier.append((int(self.right[node]), d + 1))
        return depth


def _parents_from_children(left, right):
    parent = np.full(len(left), 2147483647, np.int32)  # xgboost root parent marker
    for i, (l, r) in enumerate(zip(left, right)):
        if l >= 0:
            parent[l] = i
            parent[r] = i
    return parent


def compact_padded_tree(padded, cut_points):
    """Trainer's padded arrays (numpy) -> compact Tree.

    Keeps only reachable nodes (BFS from root through explicit child indices);
    split bin indices become float thresholds via the feature's cut array.
    """
    is_leaf = np.asarray(padded["is_leaf"])
    feature = np.asarray(padded["feature"])
    bin_idx = np.asarray(padded["bin"])
    default_left = np.asarray(padded["default_left"])
    leaf_value = np.asarray(padded["leaf_value"])
    base_weight = np.asarray(padded["base_weight"])
    gain = np.asarray(padded["gain"])
    sum_hess = np.asarray(padded["sum_hess"])
    if "left" in padded:
        child_left = np.asarray(padded["left"])
        child_right = np.asarray(padded["right"])
    else:  # legacy full-binary layout
        ids = np.arange(len(is_leaf), dtype=np.int32)
        child_left, child_right = 2 * ids + 1, 2 * ids + 2

    # BFS in padded numbering, assigning compact ids in visit order
    order = [0]
    compact_id = {0: 0}
    for node in order:
        if not is_leaf[node]:
            for child in (int(child_left[node]), int(child_right[node])):
                compact_id[child] = len(order)
                order.append(child)

    k = len(order)
    out = {
        "feature": np.zeros(k, np.int32),
        "threshold": np.zeros(k, np.float32),
        "default_left": np.zeros(k, np.bool_),
        "left": np.full(k, -1, np.int32),
        "right": np.full(k, -1, np.int32),
        "value": np.zeros(k, np.float32),
        "base_weight": np.zeros(k, np.float32),
        "gain": np.zeros(k, np.float32),
        "sum_hess": np.zeros(k, np.float32),
    }
    for node in order:
        cid = compact_id[node]
        out["base_weight"][cid] = base_weight[node]
        out["sum_hess"][cid] = sum_hess[node]
        if is_leaf[node]:
            out["value"][cid] = leaf_value[node]
        else:
            f = int(feature[node])
            out["feature"][cid] = f
            out["threshold"][cid] = cut_points[f][int(bin_idx[node])]
            out["default_left"][cid] = default_left[node]
            out["left"][cid] = compact_id[int(child_left[node])]
            out["right"][cid] = compact_id[int(child_right[node])]
            out["gain"][cid] = gain[node]
    return Tree(**out)


def _parse_base_score(value):
    """xgboost >= 2.x may store base_score as a vector literal '[5E-1]'."""
    if isinstance(value, str):
        value = value.strip()
        if value.startswith("["):
            value = value.strip("[]").split(",")[0]
    return float(value)


class Forest:
    """The model: trees + objective metadata + prediction entry points."""

    def __init__(self, objective_name="reg:squarederror", objective_params=None,
                 base_score=0.5, num_feature=0, num_class=0, feature_names=None):
        self.trees = []
        self.tree_info = []  # class id per tree (0 for single-output)
        self.iteration_indptr = [0]
        self.objective_name = objective_name
        self.objective_params = dict(objective_params or {})
        self.base_score = float(base_score)
        self.num_feature = int(num_feature)
        self.num_class = int(num_class)  # 0 = not multiclass (xgboost convention)
        self.feature_names = feature_names
        self.attributes = {}
        self._stacked_cache = None

    # ------------------------------------------------------------------ meta
    @property
    def num_output_group(self):
        return max(1, self.num_class)

    @property
    def num_boosted_rounds(self):
        return len(self.iteration_indptr) - 1

    def objective(self):
        params = dict(self.objective_params)
        if self.num_class:
            params.setdefault("num_class", self.num_class)
        return objectives_mod.create_objective(self.objective_name, params)

    # ------------------------------------------------------------- mutation
    def append_round(self, trees, tree_info):
        """Add one boosting round's trees (list[Tree], list[int] class ids)."""
        self.trees.extend(trees)
        self.tree_info.extend(int(c) for c in tree_info)
        self.iteration_indptr.append(len(self.trees))
        self._stacked_cache = None

    # ------------------------------------------------------------ prediction
    def _stack(self, tree_slice):
        # memoized per (start, stop): serving calls predict per request and a
        # rebuild of the padded [T, N] arrays (a Python loop over every tree)
        # costs ~5ms on a 100-tree forest — dominating small-payload latency
        key = (tree_slice.start, tree_slice.stop)
        if self._stacked_cache is None:
            self._stacked_cache = {}
        if key in self._stacked_cache:
            return self._stacked_cache[key]
        stacked = self._stack_uncached(tree_slice)
        self._stacked_cache[key] = stacked
        return stacked

    def _stack_uncached(self, tree_slice):
        trees = self.trees[tree_slice]
        if not trees:
            return None
        N = max(t.num_nodes for t in trees)
        T = len(trees)

        def pad(getter, dtype, fill=0):
            out = np.full((T, N), fill, dtype)
            for i, t in enumerate(trees):
                out[i, : t.num_nodes] = getter(t)
            return out

        self_idx = np.arange(N, dtype=np.int32)[None, :].repeat(T, axis=0)
        left = pad(lambda t: t.left, np.int32, -1)
        right = pad(lambda t: t.right, np.int32, -1)
        is_leaf = left < 0
        left = np.where(is_leaf, self_idx, left)
        right = np.where(is_leaf, self_idx, right)
        stacked = {
            "feature": pad(lambda t: t.feature, np.int32),
            "threshold": pad(lambda t: t.threshold, np.float32),
            "default_left": pad(lambda t: t.default_left, np.bool_),
            "left": left,
            "right": right,
            "is_leaf": is_leaf,
            "leaf_value": pad(lambda t: t.value, np.float32),
            "depth": max(t.depth() for t in trees),
        }
        max_cat = max((t.max_category() for t in trees), default=-1)
        if max_cat >= 0:
            # bitmask of right-branch categories per node: [T, N, W] u32
            W = (max_cat >> 5) + 1
            cat_split = np.zeros((T, N), np.bool_)
            cat_mask = np.zeros((T, N, W), np.uint32)
            for i, t in enumerate(trees):
                for node, cats in t.categories.items():
                    cat_split[i, node] = True
                    for c in cats:
                        cat_mask[i, node, c >> 5] |= np.uint32(1) << np.uint32(c & 31)
            stacked["cat_split"] = cat_split
            stacked["cat_mask"] = cat_mask
        return stacked

    def predict_margin(self, features, iteration_range=None):
        """features: np [n, d] float32 with NaN missing -> margins."""
        obj = self.objective()
        base = obj.base_margin(self.base_score)
        if iteration_range is None:
            lo, hi = 0, self.num_boosted_rounds
        else:
            lo, hi = iteration_range
            hi = hi or self.num_boosted_rounds
        tree_lo, tree_hi = self.iteration_indptr[lo], self.iteration_indptr[hi]
        if features.shape[1] < self.num_feature:
            raise exc.UserError(
                "feature_names mismatch: model expects {} features, data has {}".format(
                    self.num_feature, features.shape[1]
                )
            )
        stacked = self._stack(slice(tree_lo, tree_hi))
        n = features.shape[0]
        if stacked is None:
            if self.num_output_group == 1:
                return np.full(n, base, np.float32)
            return np.full((n, self.num_output_group), base, np.float32)
        if 0 < n <= _host_predict_rows():
            # tiny payloads skip the device entirely: the per-dispatch floor
            # (host<->device transfer; a network round trip on tunneled TPUs)
            # dwarfs microseconds of traversal. Threshold: GRAFT_HOST_PREDICT_ROWS.
            return host_predict_margin(
                stacked,
                np.ascontiguousarray(features, np.float32),
                num_output_group=self.num_output_group,
                base_margin=base,
                tree_info=self.tree_info[tree_lo:tree_hi],
            )
        # bucket the row count to a power of two so serving payloads of
        # varying size share jit-compiled kernels instead of recompiling
        n_pad = predict_bucket(n)
        if n_pad != n:
            features = np.concatenate(
                [features, np.zeros((n_pad - n, features.shape[1]), np.float32)], axis=0
            )
        out = forest_predict_margin(
            stacked,
            features,
            num_output_group=self.num_output_group,
            base_margin=base,
            tree_info=self.tree_info[tree_lo:tree_hi],
        )
        return out[:n]

    def predict(self, features, output_margin=False, iteration_range=None, pred_leaf=False):
        if pred_leaf:
            return self.predict_leaf(features, iteration_range=iteration_range)
        margin = self.predict_margin(features, iteration_range=iteration_range)
        if output_margin:
            return margin
        return self.objective().margin_to_prediction(margin)

    def predict_leaf(self, features, iteration_range=None):
        """Leaf index per (row, tree) — xgboost ``predict(pred_leaf=True)``."""
        from ..ops.predict import forest_leaf_nodes

        if iteration_range is None:
            lo, hi = 0, self.num_boosted_rounds
        else:
            lo, hi = iteration_range
            hi = hi or self.num_boosted_rounds
        stacked = self._stack(
            slice(self.iteration_indptr[lo], self.iteration_indptr[hi])
        )
        features = np.asarray(features, np.float32)
        if stacked is None:
            return np.zeros((features.shape[0], 0), np.int32)
        return np.asarray(forest_leaf_nodes(stacked, features))

    # ------------------------------------------------------------ attributes
    def attr(self, key):
        """xgboost Booster.attr: stored attribute or None."""
        return self.attributes.get(key)

    def set_attr(self, **kwargs):
        """xgboost Booster.set_attr: set (or delete with None) attributes."""
        for key, value in kwargs.items():
            if value is None:
                self.attributes.pop(key, None)
            else:
                self.attributes[key] = str(value)

    # ------------------------------------------------------------ importance
    def get_score(self, importance_type="weight"):
        """Feature importances (xgboost Booster.get_score semantics).

        weight: split counts; [total_]gain / [total_]cover: summed loss change
        / summed hessian at splits, averaged for the non-total variants. Keys
        are feature names when known, else ``f<index>``.
        """
        valid = ("weight", "gain", "cover", "total_gain", "total_cover")
        if importance_type not in valid:
            raise exc.UserError(
                "importance_type must be one of {}".format(", ".join(valid))
            )
        counts = {}
        gains = {}
        covers = {}
        for tree in self.trees:
            split_mask = ~tree.is_leaf
            for f, g, c in zip(
                tree.feature[split_mask], tree.gain[split_mask], tree.sum_hess[split_mask]
            ):
                f = int(f)
                counts[f] = counts.get(f, 0) + 1
                gains[f] = gains.get(f, 0.0) + float(g)
                covers[f] = covers.get(f, 0.0) + float(c)

        def name(f):
            if self.feature_names and f < len(self.feature_names):
                return self.feature_names[f]
            return "f{}".format(f)

        if importance_type == "weight":
            return {name(f): v for f, v in counts.items()}
        if importance_type == "total_gain":
            return {name(f): v for f, v in gains.items()}
        if importance_type == "total_cover":
            return {name(f): v for f, v in covers.items()}
        if importance_type == "gain":
            return {name(f): gains[f] / counts[f] for f in counts}
        return {name(f): covers[f] / counts[f] for f in counts}

    def get_fscore(self):
        return self.get_score("weight")

    def get_dump(self, with_stats=False):
        """Text dump of every tree (xgboost ``Booster.get_dump`` format)."""

        def name(f):
            if self.feature_names and f < len(self.feature_names):
                return self.feature_names[f]
            return "f{}".format(f)

        dumps = []
        for tree in self.trees:
            lines = {}

            def walk(node, depth):
                indent = "\t" * depth
                if tree.is_leaf[node]:
                    line = "{}{}:leaf={:.9g}".format(indent, node, float(tree.value[node]))
                    if with_stats:
                        line += ",cover={:.9g}".format(float(tree.sum_hess[node]))
                else:
                    left, right = int(tree.left[node]), int(tree.right[node])
                    missing = left if tree.default_left[node] else right
                    if node in tree.categories:
                        # xgboost categorical dump: the right-branch set,
                        # with yes/no swapped (in-set routes right)
                        cond = "{}:{{{}}}".format(
                            name(int(tree.feature[node])),
                            ",".join(str(int(c)) for c in tree.categories[node]),
                        )
                        line = "{}{}:[{}] yes={},no={},missing={}".format(
                            indent, node, cond, right, left, missing
                        )
                    else:
                        line = "{}{}:[{}<{:.9g}] yes={},no={},missing={}".format(
                            indent,
                            node,
                            name(int(tree.feature[node])),
                            float(tree.threshold[node]),
                            left,
                            right,
                            missing,
                        )
                    if with_stats:
                        line += ",gain={:.9g},cover={:.9g}".format(
                            float(tree.gain[node]), float(tree.sum_hess[node])
                        )
                lines[node] = line
                if not tree.is_leaf[node]:
                    walk(int(tree.left[node]), depth + 1)
                    walk(int(tree.right[node]), depth + 1)

            walk(0, 0)
            dumps.append("\n".join(lines[k] for k in sorted(lines)) + "\n")
        return dumps

    # ----------------------------------------------------------------- json
    _OBJECTIVE_PARAM_BLOCKS = {
        "reg:squarederror": ("reg_loss_param", {"scale_pos_weight": "1"}),
        "reg:squaredlogerror": ("reg_loss_param", {"scale_pos_weight": "1"}),
        "reg:logistic": ("reg_loss_param", {"scale_pos_weight": "1"}),
        "binary:logistic": ("reg_loss_param", {"scale_pos_weight": "1"}),
        "binary:logitraw": ("reg_loss_param", {"scale_pos_weight": "1"}),
        "count:poisson": ("poisson_regression_param", {"max_delta_step": "0.7"}),
        "reg:tweedie": ("tweedie_regression_param", {"tweedie_variance_power": "1.5"}),
        "reg:pseudohubererror": ("pseudo_huber_param", {"huber_slope": "1"}),
        "multi:softmax": ("softmax_multiclass_param", {"num_class": "0"}),
        "multi:softprob": ("softmax_multiclass_param", {"num_class": "0"}),
        "rank:pairwise": ("lambdarank_param", {}),
        "rank:ndcg": ("lambdarank_param", {}),
        "rank:map": ("lambdarank_param", {}),
    }

    def _tree_to_json(self, tree, tree_id):
        is_leaf = tree.is_leaf
        # xgboost: split_conditions holds the threshold for splits, the leaf
        # value for leaves; split_indices is 0 at leaves.
        split_conditions = np.where(is_leaf, tree.value, tree.threshold)
        cats, cat_nodes, cat_segs, cat_sizes = [], [], [], []
        split_type = [0] * tree.num_nodes
        for node in sorted(tree.categories):
            node_cats = tree.categories[node]
            cat_nodes.append(int(node))
            cat_segs.append(len(cats))
            cat_sizes.append(len(node_cats))
            cats.extend(int(c) for c in node_cats)
            split_type[node] = 1
        return {
            "base_weights": [float(v) for v in tree.base_weight],
            "categories": cats,
            "categories_nodes": cat_nodes,
            "categories_segments": cat_segs,
            "categories_sizes": cat_sizes,
            "default_left": [int(b) for b in tree.default_left],
            "id": tree_id,
            "left_children": [int(v) for v in tree.left],
            "right_children": [int(v) for v in tree.right],
            "loss_changes": [float(v) for v in tree.gain],
            "parents": [int(v) for v in tree.parent],
            "split_conditions": [float(v) for v in split_conditions],
            "split_indices": [int(v) for v in tree.feature],
            "split_type": split_type,
            "sum_hessian": [float(v) for v in tree.sum_hess],
            "tree_param": {
                "num_deleted": "0",
                "num_feature": str(self.num_feature),
                "num_nodes": str(tree.num_nodes),
                "size_leaf_vector": "1",
            },
        }

    @staticmethod
    def _tree_from_json(blob):
        categories = None
        if blob.get("categories_nodes"):
            # xgboost stores all categorical nodes' right-branch category
            # sets in one flat list with per-node segments
            flat = np.asarray(blob.get("categories", []), np.int64)
            nodes = blob["categories_nodes"]
            segs = blob.get("categories_segments", [])
            sizes = blob.get("categories_sizes", [])
            categories = {
                int(node): flat[int(segs[j]) : int(segs[j]) + int(sizes[j])]
                for j, node in enumerate(nodes)
            }
        left = np.asarray(blob["left_children"], np.int32)
        is_leaf = left < 0
        cond = np.asarray(blob["split_conditions"], np.float32)
        return Tree(
            feature=blob["split_indices"],
            threshold=np.where(is_leaf, 0.0, cond),
            default_left=np.asarray(blob["default_left"], bool),
            left=left,
            right=blob["right_children"],
            value=np.where(is_leaf, cond, 0.0),
            base_weight=blob.get("base_weights"),
            gain=blob.get("loss_changes"),
            sum_hess=blob.get("sum_hessian"),
            parent=blob.get("parents"),
            categories=categories,
        )

    def save_json(self):
        block_name, defaults = self._OBJECTIVE_PARAM_BLOCKS.get(
            self.objective_name, ("reg_loss_param", {"scale_pos_weight": "1"})
        )
        block = dict(defaults)
        for key in list(block):
            if key in self.objective_params:
                block[key] = str(self.objective_params[key])
        if "num_class" in block:
            block["num_class"] = str(self.num_class)
        doc = {
            "version": [3, 0, 0],
            "learner": {
                "attributes": self.attributes,
                "feature_names": self.feature_names or [],
                "feature_types": [],
                "gradient_booster": {
                    "model": {
                        "gbtree_model_param": {
                            "num_trees": str(len(self.trees)),
                            "num_parallel_tree": "1",
                        },
                        "iteration_indptr": list(self.iteration_indptr),
                        "tree_info": list(self.tree_info),
                        "trees": [
                            self._tree_to_json(t, i) for i, t in enumerate(self.trees)
                        ],
                    },
                    "name": "gbtree",
                },
                "learner_model_param": {
                    "base_score": repr(self.base_score),
                    "boost_from_average": "1",
                    "num_class": str(self.num_class),
                    "num_feature": str(self.num_feature),
                    "num_target": "1",
                },
                "objective": {"name": self.objective_name, block_name: block},
            },
        }
        return json.dumps(doc)

    @classmethod
    def load_json(cls, text):
        try:
            doc = json.loads(text)
        except (ValueError, TypeError) as e:
            raise exc.UserError("Not a valid xgboost JSON model", caused_by=e)
        return cls.from_dict(doc)

    @classmethod
    def from_dict(cls, doc):
        try:
            learner = doc["learner"]
            gb = learner["gradient_booster"]
            weight_drop = None
            if gb.get("name") == "dart" or "gbtree" in gb:
                # dart nests the tree model under "gbtree" and carries
                # per-tree dropout scale factors in "weight_drop"
                weight_drop = gb.get("weight_drop")
                gb = gb["gbtree"]
            model = gb["model"]
            lmp = learner["learner_model_param"]
            objective = learner["objective"]
        except (KeyError, ValueError, TypeError) as e:
            raise exc.UserError("Not a valid xgboost JSON model", caused_by=e)
        params = {}
        for block in objective.values():
            if isinstance(block, dict):
                params.update(block)
        forest = cls(
            objective_name=objective["name"],
            objective_params=params,
            base_score=_parse_base_score(lmp.get("base_score", 0.5)),
            num_feature=int(lmp.get("num_feature", 0)),
            num_class=int(lmp.get("num_class", 0)),
            feature_names=learner.get("feature_names") or None,
        )
        forest.attributes = learner.get("attributes", {})
        forest.trees = [cls._tree_from_json(t) for t in model["trees"]]
        if weight_drop:
            for tree, scale in zip(forest.trees, weight_drop):
                tree.value = tree.value * np.float32(scale)
        forest.tree_info = [int(v) for v in model.get("tree_info", [0] * len(forest.trees))]
        indptr = model.get("iteration_indptr")
        if indptr:
            forest.iteration_indptr = [int(v) for v in indptr]
        else:
            per_round = max(1, forest.num_output_group)
            forest.iteration_indptr = list(
                range(0, len(forest.trees) + 1, per_round)
            )
        return forest

    def save_model(self, path, model_format=None):
        """Write the model; format by explicit arg or .ubj extension
        (mirrors xgboost's extension-driven choice), JSON otherwise."""
        if model_format is None:
            model_format = "ubj" if str(path).endswith(".ubj") else "json"
        if model_format == "ubj":
            import json as json_mod

            from .compat import encode_ubjson

            with open(path, "wb") as f:
                f.write(encode_ubjson(json_mod.loads(self.save_json())))
            return
        with open(path, "w") as f:
            f.write(self.save_json())

    @classmethod
    def load_model(cls, path):
        with open(path, "rb") as f:
            raw = f.read()
        return cls.load_json(raw.decode("utf-8"))
