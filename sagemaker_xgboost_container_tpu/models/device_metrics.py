"""Device-side (jnp) eval metrics as psum-able partial statistics.

Every metric decomposes into a fixed-size statistics vector that combines
across data shards by plain summation (``lax.psum`` over the "data" mesh
axis) plus a cheap ``finalize`` that turns combined stats into the scalar.
This is what lets boosting rounds batch K-at-a-time (`_rounds_per_dispatch`)
*on a mesh* and makes multi-host metric lines globally exact: the reference
allreduces metrics inside xgb.train under the communicator
(reference distributed.py:219), so every host prints the same value — here
the psum of (numerator, denominator) pairs inside the jitted round does the
same job.

Weighted formulations throughout: padding rows carry weight 0, so they drop
out of every metric automatically.

AUC is the one metric that does not decompose exactly: following xgboost's
own distributed semantics, each shard computes its local weighted
Mann-Whitney AUC and shards combine as a weighted average with weight
(local positive weight x local negative weight). Single-shard runs are
exact.
"""

import jax.numpy as jnp

_EPS = 1e-15


class DeviceMetric:
    """A decomposable metric: ``partial`` -> psum-able f32 [size] -> ``finalize``.

    ``needs_global_rows`` marks the one exception (cox-nloglik): its partial
    is NOT shard-decomposable — the caller must all_gather the row shards
    over the data axis, call ``partial`` on the replicated global arrays,
    and divide by the axis size so the shared downstream psum restores the
    global value (mirroring the booster's Cox gradient path, which gathers
    global risk sets the same way)."""

    def __init__(self, name, size, partial, finalize, needs_global_rows=False):
        self.name = name
        self.size = size
        self.partial = partial
        self.finalize = finalize
        self.needs_global_rows = needs_global_rows

    def __call__(self, margins, labels, weights):
        return self.finalize(self.partial(margins, labels, weights))


def _sigmoid(m):
    return 1.0 / (1.0 + jnp.exp(-m))


def _softmax(m):
    e = jnp.exp(m - jnp.max(m, axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def _prob_transform(objective_name, margins):
    if objective_name in ("reg:logistic", "binary:logistic"):
        return _sigmoid(margins)
    if objective_name in ("count:poisson", "reg:gamma", "reg:tweedie", "survival:aft", "survival:cox"):
        return jnp.exp(margins)
    return margins


def _weighted_mean_metric(name, objective_name, term_fn, post=None):
    """Metric = post(sum(w * term) / sum(w)); stats vector [num, den]."""

    def partial(m, y, w):
        p = _prob_transform(objective_name, m)
        return jnp.stack([jnp.sum(term_fn(p, y, w) * w), jnp.sum(w)])

    def finalize(stats):
        mean = stats[0] / jnp.maximum(stats[1], _EPS)
        return post(mean) if post is not None else mean

    return DeviceMetric(name, 2, partial, finalize)


def make_device_metric(name, objective_name, num_group=1, params=None):
    """-> DeviceMetric, or None if unsupported on device."""
    params = params or {}
    base, _, suffix = name.partition("@")

    if num_group > 1:
        if base == "merror":
            def term(m, y, w):
                pred = jnp.argmax(m, axis=1)
                return (pred != y.astype(jnp.int32)).astype(jnp.float32)

            def partial(m, y, w):
                return jnp.stack([jnp.sum(term(m, y, w) * w), jnp.sum(w)])

            return DeviceMetric(name, 2, partial, lambda s: s[0] / jnp.maximum(s[1], _EPS))
        if base == "mlogloss":
            def partial(m, y, w):
                p = _softmax(m)
                picked = jnp.take_along_axis(
                    p, y.astype(jnp.int32)[:, None], axis=1
                )[:, 0]
                v = -jnp.log(jnp.clip(picked, _EPS, 1.0))
                return jnp.stack([jnp.sum(v * w), jnp.sum(w)])

            return DeviceMetric(name, 2, partial, lambda s: s[0] / jnp.maximum(s[1], _EPS))
        return None

    wm = lambda term_fn, post=None: _weighted_mean_metric(  # noqa: E731
        name, objective_name, term_fn, post
    )

    if base == "rmse":
        return wm(lambda p, y, w: (p - y) ** 2, post=jnp.sqrt)
    if base == "mse":
        return wm(lambda p, y, w: (p - y) ** 2)
    if base == "mae":
        return wm(lambda p, y, w: jnp.abs(p - y))
    if base == "mape":
        return wm(lambda p, y, w: jnp.abs((y - p) / jnp.maximum(jnp.abs(y), _EPS)))
    if base == "rmsle":
        return wm(
            lambda p, y, w: (jnp.log1p(jnp.maximum(p, 0.0)) - jnp.log1p(y)) ** 2,
            post=jnp.sqrt,
        )
    if base == "logloss":
        def term(p, y, w):
            # f32-safe: clip with an epsilon representable in float32
            eps32 = 1e-7
            p = jnp.clip(p, eps32, 1 - eps32)
            return -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))

        return wm(term)
    if base == "error":
        threshold = float(suffix) if suffix else 0.5
        return wm(
            lambda p, y, w: ((p > threshold).astype(jnp.float32) != y).astype(
                jnp.float32
            )
        )
    if base == "auc":
        def partial(m, y, w):
            # weighted Mann-Whitney with tie midranks in cumulative-weight
            # space (same formulation as eval_metrics.auc, static shapes:
            # tie groups via neighbor-inequality cumsum + segment reductions)
            p = _prob_transform(objective_name, m)
            n = p.shape[0]
            order = jnp.argsort(p)
            sp, sw = p[order], w[order]
            spos = (y[order] > 0).astype(jnp.float32) * sw
            sneg = (1.0 - (y[order] > 0).astype(jnp.float32)) * sw
            new_group = jnp.concatenate(
                [jnp.ones(1, jnp.int32), (sp[1:] != sp[:-1]).astype(jnp.int32)]
            )
            gid = jnp.cumsum(new_group) - 1
            import jax as _jax

            group_w = _jax.ops.segment_sum(sw, gid, num_segments=n)
            cumw = jnp.cumsum(sw)
            group_end = _jax.ops.segment_max(cumw, gid, num_segments=n)
            midrank = group_end - group_w / 2.0
            ranks = midrank[gid]
            w_pos = jnp.sum(spos)
            w_neg = jnp.sum(sneg)
            u = jnp.sum(ranks * spos) - w_pos * w_pos / 2.0
            pairw = w_pos * w_neg
            auc = jnp.clip(u / jnp.maximum(pairw, _EPS), 0.0, 1.0)
            # shards combine as a pair-weighted average (xgboost's
            # distributed-AUC semantics); exact when single-shard
            return jnp.stack([auc * pairw, pairw])

        return DeviceMetric(name, 2, partial, lambda s: s[0] / jnp.maximum(s[1], _EPS))
    if base == "poisson-nloglik":
        def term(p, y, w):
            from jax.scipy.special import gammaln

            p = jnp.maximum(p, _EPS)
            return p - y * jnp.log(p) + gammaln(y + 1.0)

        return wm(term)
    if base == "gamma-nloglik":
        def term(p, y, w):
            p = jnp.maximum(p, _EPS)
            return jnp.log(p) + y / p

        return wm(term)
    if base == "gamma-deviance":
        def term(p, y, w):
            p = jnp.maximum(p, _EPS)
            yy = jnp.maximum(y, _EPS)
            return jnp.log(p / yy) + yy / p - 1.0

        return wm(term, post=lambda x: 2.0 * x)
    if base == "cox-nloglik":
        def partial(m, y, w):
            # negative Breslow partial log-likelihood (device form of
            # eval_metrics.cox_nloglik): labels < 0 = censored at |t|,
            # hazard ratio = exp(margin); risk sets are cumulative sums
            # over the descending-time ordering. Padding rows (weight 0)
            # contribute nothing to either the risk sets or the events.
            p = jnp.exp(m)
            abs_t = jnp.abs(y)
            event = (y > 0).astype(jnp.float32)
            order = jnp.argsort(-abs_t)  # stable, matches the host metric
            hz = jnp.maximum(p, 1e-30)[order] * w[order]
            cum = jnp.cumsum(hz)
            ev = (event * w)[order]
            ll = jnp.sum(
                ev
                * (jnp.log(jnp.maximum(hz, 1e-30)) - jnp.log(jnp.maximum(cum, 1e-30)))
            )
            return jnp.stack([-ll, jnp.sum(ev)])

        return DeviceMetric(
            name,
            2,
            partial,
            lambda s: s[0] / jnp.maximum(s[1], 1e-12),
            needs_global_rows=True,
        )
    if base == "tweedie-nloglik":
        rho = float(suffix) if suffix else float(params.get("tweedie_variance_power", 1.5))

        def term(p, y, w):
            p = jnp.maximum(p, _EPS)
            a = y * jnp.power(p, 1 - rho) / (1 - rho)
            b = jnp.power(p, 2 - rho) / (2 - rho)
            return -a + b

        return wm(term)
    return None


def all_supported(names, objective_name, num_group, params=None):
    fns = [make_device_metric(n, objective_name, num_group, params) for n in names]
    if any(f is None for f in fns):
        return None
    return fns
