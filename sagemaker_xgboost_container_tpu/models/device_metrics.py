"""Device-side (jnp) eval metrics for batched-round dispatches.

When every watched metric is computable on device and all eval sets share the
training margins (the default SageMaker watchlist is just "train"), boosting
rounds batch K-at-a-time (`_rounds_per_dispatch`) and the per-round metric
scalars come back as one [K, n_metrics] array — preserving the per-round HPO
stdout contract without per-round host round-trips.

Weighted formulations throughout: padding rows carry weight 0, so they drop
out of every metric automatically.
"""

import jax.numpy as jnp

_EPS = 1e-15


def _sigmoid(m):
    return 1.0 / (1.0 + jnp.exp(-m))


def _softmax(m):
    e = jnp.exp(m - jnp.max(m, axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def _weighted_mean(values, w):
    return jnp.sum(values * w) / jnp.maximum(jnp.sum(w), _EPS)


def _prob_transform(objective_name, margins):
    if objective_name in ("reg:logistic", "binary:logistic"):
        return _sigmoid(margins)
    if objective_name in ("count:poisson", "reg:gamma", "reg:tweedie", "survival:aft", "survival:cox"):
        return jnp.exp(margins)
    return margins


def make_device_metric(name, objective_name, num_group=1, params=None):
    """-> fn(margins, labels, weights) -> scalar, or None if unsupported."""
    params = params or {}
    base, _, suffix = name.partition("@")

    if num_group > 1:
        if base == "merror":
            def merror(m, y, w):
                pred = jnp.argmax(m, axis=1)
                return _weighted_mean((pred != y.astype(jnp.int32)).astype(jnp.float32), w)

            return merror
        if base == "mlogloss":
            def mlogloss(m, y, w):
                p = _softmax(m)
                picked = jnp.take_along_axis(
                    p, y.astype(jnp.int32)[:, None], axis=1
                )[:, 0]
                return _weighted_mean(-jnp.log(jnp.clip(picked, _EPS, 1.0)), w)

            return mlogloss
        return None

    def with_pred(fn):
        def wrapped(m, y, w):
            return fn(_prob_transform(objective_name, m), y, w)

        return wrapped

    if base == "rmse":
        return with_pred(lambda p, y, w: jnp.sqrt(_weighted_mean((p - y) ** 2, w)))
    if base == "mse":
        return with_pred(lambda p, y, w: _weighted_mean((p - y) ** 2, w))
    if base == "mae":
        return with_pred(lambda p, y, w: _weighted_mean(jnp.abs(p - y), w))
    if base == "mape":
        return with_pred(
            lambda p, y, w: _weighted_mean(
                jnp.abs((y - p) / jnp.maximum(jnp.abs(y), _EPS)), w
            )
        )
    if base == "rmsle":
        return with_pred(
            lambda p, y, w: jnp.sqrt(
                _weighted_mean((jnp.log1p(jnp.maximum(p, 0.0)) - jnp.log1p(y)) ** 2, w)
            )
        )
    if base == "logloss":
        def logloss(p, y, w):
            # f32-safe: clip with an epsilon representable in float32
            eps32 = 1e-7
            p = jnp.clip(p, eps32, 1 - eps32)
            return _weighted_mean(-(y * jnp.log(p) + (1 - y) * jnp.log(1 - p)), w)

        return with_pred(logloss)
    if base == "error":
        threshold = float(suffix) if suffix else 0.5

        def error(p, y, w):
            return _weighted_mean(((p > threshold).astype(jnp.float32) != y).astype(jnp.float32), w)

        return with_pred(error)
    if base == "auc":
        def auc(p, y, w):
            # weighted Mann-Whitney with tie midranks in cumulative-weight
            # space (same formulation as eval_metrics.auc, static shapes:
            # tie groups via neighbor-inequality cumsum + segment reductions)
            n = p.shape[0]
            order = jnp.argsort(p)
            sp, sw = p[order], w[order]
            spos = (y[order] > 0).astype(jnp.float32) * sw
            sneg = (1.0 - (y[order] > 0).astype(jnp.float32)) * sw
            new_group = jnp.concatenate(
                [jnp.ones(1, jnp.int32), (sp[1:] != sp[:-1]).astype(jnp.int32)]
            )
            gid = jnp.cumsum(new_group) - 1
            import jax as _jax

            group_w = _jax.ops.segment_sum(sw, gid, num_segments=n)
            cumw = jnp.cumsum(sw)
            group_end = _jax.ops.segment_max(cumw, gid, num_segments=n)
            midrank = group_end - group_w / 2.0
            ranks = midrank[gid]
            w_pos = jnp.sum(spos)
            w_neg = jnp.sum(sneg)
            u = jnp.sum(ranks * spos) - w_pos * w_pos / 2.0
            return jnp.clip(u / jnp.maximum(w_pos * w_neg, _EPS), 0.0, 1.0)

        return with_pred(auc)
    if base == "poisson-nloglik":
        def poisson(p, y, w):
            from jax.scipy.special import gammaln

            p = jnp.maximum(p, _EPS)
            return _weighted_mean(p - y * jnp.log(p) + gammaln(y + 1.0), w)

        return with_pred(poisson)
    if base == "gamma-nloglik":
        def gamma_nll(p, y, w):
            p = jnp.maximum(p, _EPS)
            return _weighted_mean(jnp.log(p) + y / p, w)

        return with_pred(gamma_nll)
    if base == "gamma-deviance":
        def gamma_dev(p, y, w):
            p = jnp.maximum(p, _EPS)
            y = jnp.maximum(y, _EPS)
            return 2.0 * _weighted_mean(jnp.log(p / y) + y / p - 1.0, w)

        return with_pred(gamma_dev)
    if base == "tweedie-nloglik":
        rho = float(suffix) if suffix else float(params.get("tweedie_variance_power", 1.5))

        def tweedie(p, y, w):
            p = jnp.maximum(p, _EPS)
            a = y * jnp.power(p, 1 - rho) / (1 - rho)
            b = jnp.power(p, 2 - rho) / (2 - rho)
            return _weighted_mean(-a + b, w)

        return with_pred(tweedie)
    return None


def all_supported(names, objective_name, num_group, params=None):
    fns = [make_device_metric(n, objective_name, num_group, params) for n in names]
    if any(f is None for f in fns):
        return None
    return fns
