"""process_type=update: refresh / prune an existing model on (new) data.

The reference validates process_type=update with updater in {refresh, prune}
(hyperparameter_validation.py:56-58) and delegates to libxgboost's
TreeRefresher/TreePruner. Semantics mirrored here:

* iteration i processes the loaded model's iteration-i trees (no new trees);
  gradients are computed at the margins of the trees processed so far, like
  normal boosting (num_boost_round caps at the model's round count);
* ``refresh``: re-route the training rows through each tree, rebuild every
  node's (sum_g, sum_h), store sum_hess, recompute internal-node gain
  (0.5*(score_L + score_R - score_parent), xgboost's stored loss_chg
  convention) and — when refresh_leaf (default 1) — replace leaf values with
  eta * optimal weight from the fresh stats;
* ``prune``: bottom-up collapse of internal nodes whose both children are
  leaves and whose gain < gamma; the collapsed node becomes a leaf valued
  eta * its base weight. Stats are ALWAYS recomputed from the update data
  first (leaf values only touched when refresh was requested): stored gains
  follow different conventions per model source (this builder stores
  0.5*delta - gamma_train, ops/split.py:72-77; imported xgboost models
  store raw loss_chg), so comparing them directly against the update job's
  gamma would double-count gamma or over-prune — one recomputed convention
  makes prune consistent for every model source.

Runs host-side except row routing (the compiled forest kernel): update jobs
are one pass over num_round trees, not a boosting loop — throughput is
bounded by routing, which stays on device.
"""

import numpy as np

from ..ops.predict import forest_leaf_nodes
from ..toolkit import exceptions as exc


def _node_depth_order(tree):
    """Node indices deepest-first (children before parents).

    Root's parent is xgboost's 2147483647 marker (forest.py
    _parents_from_children); any out-of-range parent means "no parent".
    Child indices exceed their parent's in our layouts, so the forward pass
    sees parents before children.
    """
    n = tree.num_nodes
    depth = np.zeros(n, np.int32)
    for node in range(n):
        p = tree.parent[node]
        if 0 <= p < n and p != node:
            depth[node] = depth[p] + 1
    return np.argsort(-depth, kind="stable"), depth


def _score(g, h, reg_lambda, alpha):
    t = np.sign(g) * np.maximum(np.abs(g) - alpha, 0.0)
    return (t * t) / (h + reg_lambda)


def _refresh_tree(tree, leaf_of_row, g, h, config, refresh_leaf, combine=None):
    """Rebuild node stats from rows routed to each leaf; returns the tree's
    per-row contribution after any leaf-value update. ``combine`` (multi-
    host) sums the per-leaf stats across processes — the refresh analog of
    libxgboost TreeRefresher's rabit allreduce of node stats."""
    n_nodes = tree.num_nodes
    G = np.zeros(n_nodes, np.float64)
    H = np.zeros(n_nodes, np.float64)
    np.add.at(G, leaf_of_row, g)
    np.add.at(H, leaf_of_row, h)
    if combine is not None:
        GH = combine(np.stack([G, H]))
        G, H = GH[0], GH[1]
    order, _depth = _node_depth_order(tree)
    for node in order:  # children accumulate into parents (deepest first)
        p = tree.parent[node]
        if 0 <= p < n_nodes and p != node:
            G[p] += G[node]
            H[p] += H[node]

    lam, alpha = config.reg_lambda, config.alpha
    weight = -np.sign(G) * np.maximum(np.abs(G) - alpha, 0.0) / (H + lam)
    if config.max_delta_step > 0:
        weight = np.clip(weight, -config.max_delta_step, config.max_delta_step)

    tree.sum_hess = H.astype(np.float32)
    tree.base_weight = weight.astype(np.float32)
    is_leaf = tree.is_leaf
    internal = ~is_leaf
    l, r = tree.left, tree.right
    gain = np.zeros(n_nodes, np.float32)
    gain[internal] = 0.5 * (
        _score(G[l[internal]], H[l[internal]], lam, alpha)
        + _score(G[r[internal]], H[r[internal]], lam, alpha)
        - _score(G[internal], H[internal], lam, alpha)
    )
    tree.gain = gain
    if refresh_leaf:
        tree.value = np.where(
            is_leaf, (config.eta * weight).astype(np.float32), tree.value
        )


def _prune_tree(tree, gamma, eta):
    """Bottom-up: collapse internal nodes with two leaf children and
    gain < gamma into leaves valued eta * base_weight."""
    order, _depth = _node_depth_order(tree)
    is_leaf = tree.is_leaf.copy()
    pruned = 0
    for node in order:
        if is_leaf[node]:
            continue
        l, r = tree.left[node], tree.right[node]
        if is_leaf[l] and is_leaf[r] and tree.gain[node] < gamma:
            is_leaf[node] = True
            tree.left[node] = -1
            tree.right[node] = -1
            tree.value[node] = eta * tree.base_weight[node]
            pruned += 1
    return pruned


def train_update(config, forest, dtrain, evals, feval, callbacks, num_boost_round, mesh=None):
    """Apply refresh/prune updaters to ``forest`` over ``dtrain``."""
    updaters = [
        u.strip()
        for u in str(config.objective_params.get("updater", "refresh")).split(",")
        if u.strip()
    ]
    bad = [u for u in updaters if u not in ("refresh", "prune")]
    if bad:
        raise exc.UserError(
            "process_type 'update' can only be used with updater 'refresh' and 'prune'"
        )
    refresh_leaf = int(config.objective_params.get("refresh_leaf", 1) or 0)
    if not forest.trees:
        raise exc.UserError(
            "process_type='update' needs an existing model to update "
            "(provide a checkpoint / base_model)."
        )
    import jax

    # multi-host: each host routes its own row shard; per-node (sum_g,
    # sum_h) combine across hosts before the refresh/prune math, so every
    # host applies identical updates (reference parity: libxgboost's
    # TreeRefresher allreduces node stats under Rabit — with replicated
    # channels rows count once per host there too). Requires the cross-host
    # data mesh as the sharding signal; a multi-process run without one
    # would silently refresh divergent per-host models, so refuse loudly.
    # Transport is f32 (x64 is off), summation host-side in f64 — same
    # policy as the metric combine.
    combine = None
    if jax.process_count() > 1:
        if (
            mesh is None
            or "data" not in getattr(mesh, "axis_names", ())
            or int(mesh.shape["data"]) <= 1
        ):
            raise exc.UserError(
                "Multi-process process_type='update' requires a mesh with a "
                "'data' axis spanning the hosts."
            )
        from jax.experimental import multihost_utils

        def combine(stats):
            return np.asarray(
                multihost_utils.process_allgather(stats.astype(np.float32)),
                np.float64,
            ).sum(axis=0)

    objective = forest.objective()
    objective.validate_labels(dtrain.labels)
    G_out = forest.num_output_group
    n = dtrain.num_row
    x = np.asarray(dtrain.features, np.float32)
    labels = np.asarray(dtrain.labels, np.float32)
    weights = np.asarray(dtrain.get_weight(), np.float32)
    base = objective.base_margin(forest.base_score)
    margins = (
        np.full(n, base, np.float32)
        if G_out == 1
        else np.full((n, G_out), base, np.float32)
    )

    rounds = min(num_boost_round, forest.num_boosted_rounds)
    from .booster import _eval_metric_names

    metric_names = _eval_metric_names(config, objective)
    evals_log = {}
    _rows_cache = {}  # round-invariant global labels/weights (cox gather)
    stop = False
    # full callback protocol, like the gbtree loop (booster.py): RoundTimer's
    # round-0 timestamp and phase recorder are armed in before_training
    for cb in callbacks:
        if hasattr(cb, "before_training"):
            forest = cb.before_training(forest) or forest
    for rnd in range(rounds):
        g, h = objective.grad_hess(margins, labels, weights)
        g = np.asarray(g, np.float64)
        h = np.asarray(h, np.float64)
        t0, t1 = forest.iteration_indptr[rnd], forest.iteration_indptr[rnd + 1]
        stacked = forest._stack(slice(t0, t1))
        leaf_nodes = np.asarray(forest_leaf_nodes(stacked, x))  # [n, T_iter]
        for j, t in enumerate(range(t0, t1)):
            tree = forest.trees[t]
            cls = forest.tree_info[t]
            g_c = g if g.ndim == 1 else g[:, cls]
            h_c = h if h.ndim == 1 else h[:, cls]
            # stats always recomputed (one gain convention for prune);
            # leaf values only replaced when refresh was requested
            _refresh_tree(
                tree, leaf_nodes[:, j], g_c, h_c, config,
                refresh_leaf and "refresh" in updaters,
                combine=combine,
            )
            if "prune" in updaters:
                _prune_tree(tree, config.gamma, config.eta)
        forest._stacked_cache = None
        # margins advance with the UPDATED trees (leaf re-lookup: pruning
        # may have collapsed the routing)
        stacked = forest._stack(slice(t0, t1))
        leaf_nodes = np.asarray(forest_leaf_nodes(stacked, x))
        for j, t in enumerate(range(t0, t1)):
            contrib = forest.trees[t].value[leaf_nodes[:, j]]
            if G_out == 1:
                margins += contrib
            else:
                margins[:, forest.tree_info[t]] += contrib

        from .booster import evaluate_host_lines

        results = evaluate_host_lines(
            (
                (
                    name,
                    dm,
                    forest.predict_margin(
                        np.asarray(dm.features, np.float32),
                        iteration_range=(0, rnd + 1),
                    ),
                )
                for dm, name in evals
            ),
            metric_names,
            feval,
            objective,
            G_out,
            config.objective_params,
            combine is not None,
            global_rows_cache=_rows_cache,
        )
        for data_name, metric_name, value in results:
            evals_log.setdefault(data_name, {}).setdefault(metric_name, []).append(value)
        for cb in callbacks:
            if hasattr(cb, "after_iteration") and cb.after_iteration(
                forest, rnd, evals_log
            ):
                stop = True
        if stop:
            break
    for cb in callbacks:
        if hasattr(cb, "after_training"):
            forest = cb.after_training(forest) or forest
    return forest
