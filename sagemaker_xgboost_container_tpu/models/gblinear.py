"""gblinear booster: elastic-net linear model trained by parallel coordinate
descent ("shotgun") — one jitted update per boosting round.

The reference validates booster=gblinear with updaters shotgun/coord_descent
(hyperparameter_validation.py:45-55) and delegates to libxgboost's linear
updater. Here each round updates every coordinate simultaneously from the
current gradients (shotgun-style; exact for orthogonal features, converges
with the eta shrinkage otherwise) — a dense [n, d] matvec pair per round that
maps straight onto the MXU, plus the same objective/metric/callback machinery
as the tree path.

Model format: xgboost gblinear JSON (weights laid out feature-major with the
per-group bias at the end), loadable by real xgboost and by our predictor.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..toolkit import exceptions as exc
from . import objectives as objectives_mod


class LinearModel:
    """Host-side gblinear model: weights [d, G] + bias [G]."""

    def __init__(self, weights, bias, objective_name, base_score, num_feature, num_class=0,
                 objective_params=None):
        self.weights = np.asarray(weights, np.float32)
        self.bias = np.asarray(bias, np.float32)
        self.objective_name = objective_name
        self.objective_params = dict(objective_params or {})
        self.base_score = float(base_score)
        self.num_feature = int(num_feature)
        self.num_class = int(num_class)
        self.attributes = {}
        self.rounds = 0

    @property
    def num_output_group(self):
        return max(1, self.num_class)

    @property
    def num_boosted_rounds(self):
        return self.rounds

    def objective(self):
        params = dict(self.objective_params)
        if self.num_class:
            params.setdefault("num_class", self.num_class)
        return objectives_mod.create_objective(self.objective_name, params)

    def predict_margin(self, features, iteration_range=None):
        obj = self.objective()
        base = obj.base_margin(self.base_score)
        x = np.nan_to_num(np.asarray(features, np.float32), nan=0.0)
        if x.shape[1] < self.num_feature:
            x = np.pad(x, ((0, 0), (0, self.num_feature - x.shape[1])))
        elif x.shape[1] > self.num_feature:
            x = x[:, : self.num_feature]
        margin = x @ self.weights + self.bias[None, :] + base
        if self.num_output_group == 1:
            return margin[:, 0]
        return margin

    def predict(self, features, output_margin=False, iteration_range=None):
        margin = self.predict_margin(features)
        if output_margin:
            return margin
        return self.objective().margin_to_prediction(margin)

    # ------------------------------------------------------------------ json
    def save_json(self):
        import json

        G = self.num_output_group
        flat = []
        for f in range(self.num_feature):
            flat.extend(float(self.weights[f, g]) for g in range(G))
        flat.extend(float(b) for b in self.bias)
        attributes = dict(self.attributes)
        attributes.setdefault("num_boosted_rounds", str(self.rounds))
        doc = {
            "version": [3, 0, 0],
            "learner": {
                "attributes": attributes,
                "feature_names": [],
                "feature_types": [],
                "gradient_booster": {
                    "model": {"param": {}, "weights": flat},
                    "name": "gblinear",
                },
                "learner_model_param": {
                    "base_score": repr(self.base_score),
                    "num_class": str(self.num_class),
                    "num_feature": str(self.num_feature),
                    "num_target": "1",
                },
                "objective": {"name": self.objective_name},
            },
        }
        return json.dumps(doc)

    def save_model(self, path):
        with open(path, "w") as f:
            f.write(self.save_json())

    @classmethod
    def from_dict(cls, doc):
        learner = doc["learner"]
        lmp = learner["learner_model_param"]
        num_feature = int(lmp.get("num_feature", 0))
        num_class = int(lmp.get("num_class", 0))
        G = max(1, num_class)
        flat = np.asarray(learner["gradient_booster"]["model"]["weights"], np.float32)
        weights = flat[: num_feature * G].reshape(num_feature, G)
        bias = flat[num_feature * G : num_feature * G + G]
        from .forest import _parse_base_score

        model = cls(
            weights,
            bias,
            objective_name=learner["objective"]["name"],
            base_score=_parse_base_score(lmp.get("base_score", 0.5)),
            num_feature=num_feature,
            num_class=num_class,
        )
        model.attributes = dict(learner.get("attributes", {}))
        try:
            model.rounds = int(model.attributes.pop("num_boosted_rounds", 0))
        except (TypeError, ValueError):
            model.rounds = 0
        return model


def train_linear(
    config, dtrain, num_boost_round, evals=(), feval=None, callbacks=None,
    initial_model=None, mesh=None,
):
    """Train a gblinear model; mirrors booster.train's loop contract.

    initial_model: a LinearModel to continue from (checkpoint resume).
    mesh: optional Mesh with a "data" axis — rows shard across devices and
    the per-coordinate sufficient statistics (x_j·g, x_j²·h, bias sums)
    psum across the axis, so every device runs identical weight updates
    (the reference trains gblinear under Rabit the same way: allreduced
    gradient sums in libxgboost's linear updater)."""
    from .booster import _eval_metric_names

    callbacks = list(callbacks or [])
    objective = objectives_mod.create_objective(config.objective, config.objective_params)
    objective.validate_labels(dtrain.labels)
    G = objective.num_output_group

    n, d = dtrain.num_row, dtrain.num_col
    x_host = np.nan_to_num(dtrain.features, nan=0.0)  # linear path: missing = 0

    # multi-process: each host holds its own row shard; arrays assemble into
    # global arrays over the whole mesh (the same contract as the tree
    # booster — reference parity: libxgboost's linear updater allreduces its
    # gradient sums under Rabit exactly like hist does). Anything other
    # than a cross-host data mesh would silently train divergent per-host
    # models — refuse loudly.
    is_multiproc = jax.process_count() > 1
    if is_multiproc and (
        mesh is None
        or "data" not in getattr(mesh, "axis_names", ())
        or int(mesh.shape["data"]) <= 1
    ):
        raise exc.UserError(
            "Multi-process booster=gblinear training requires a mesh with a "
            "'data' axis spanning the hosts."
        )

    n_shards = 1
    axis = None
    if mesh is not None and "data" in getattr(mesh, "axis_names", ()):
        n_shards = int(mesh.shape["data"])
        if n_shards > 1:
            axis = "data"
    grad_hess = objective.grad_hess
    if axis is not None and config.objective == "survival:cox":
        # Cox risk sets span the whole dataset; inside shard_map the plain
        # grad_hess would see only shard-local rows and silently compute
        # wrong risk sets. Same recipe as the tree path's cox-on-mesh
        # (booster.py cox_mesh_grad_hess): all_gather the global rows,
        # compute replicated global gradients (padding rows carry weight 0
        # and drop out of every cumsum), slice this shard's segment. Exact
        # where the reference's per-worker Cox approximation is not.
        base_grad_hess = grad_hess

        def cox_mesh_grad_hess(m, y, wt):
            M = jax.lax.all_gather(m, axis, tiled=True)
            Y = jax.lax.all_gather(y, axis, tiled=True)
            Wt = jax.lax.all_gather(wt, axis, tiled=True)
            Gg, Hh = base_grad_hess(M, Y, Wt)
            k = jax.lax.axis_index(axis)
            c = m.shape[0]
            return (
                jax.lax.dynamic_slice(Gg, (k * c,), (c,)),
                jax.lax.dynamic_slice(Hh, (k * c,), (c,)),
            )

        grad_hess = cox_mesh_grad_hess

    from .booster import _pad_rows

    # pad divisor: LOCAL data shards in a multi-process run (each host lays
    # out only its own rows); whole-mesh data shards otherwise
    pad_unit = (
        max(1, int(mesh.local_mesh.shape["data"])) if is_multiproc else n_shards
    )
    n_pad = -(-n // pad_unit) * pad_unit
    if is_multiproc:
        # hosts may hold UNEVEN row counts: agree on one local padded size
        # so the assembled global array has uniform device shards
        from jax.experimental import multihost_utils

        n_pad = int(
            np.asarray(
                multihost_utils.process_allgather(np.asarray([n_pad], np.int64))
            ).max()
        )
    if n_pad != n:
        # zero-weight padding rows: contribute nothing to any psum'd stat
        x_host = _pad_rows(x_host, n_pad, 0.0)
    xT_host = np.ascontiguousarray(x_host.T)
    labels_np = _pad_rows(np.asarray(dtrain.labels, np.float32), n_pad, 0.0)
    weights_np = _pad_rows(np.asarray(dtrain.get_weight(), np.float32), n_pad, 0.0)
    if axis is not None:
        # place each array in its shard_map layout ONCE; jnp.asarray would
        # commit them to the default device and every round's dispatch would
        # re-scatter ~3x the dataset
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        def put(arr, spec):
            sharding = NamedSharding(mesh, spec)
            if is_multiproc:
                return jax.make_array_from_process_local_data(
                    sharding, np.asarray(arr)
                )
            return jax.device_put(jnp.asarray(arr), sharding)

        x = put(x_host, P("data", None))
        xT = put(xT_host, P(None, "data"))
        xT_sq = put(xT_host**2, P(None, "data"))
        lab_spec = P("data") if labels_np.ndim == 1 else P("data", None)
        labels = put(labels_np, lab_spec)
        weights_row = put(weights_np, P("data"))
    else:
        x = jnp.asarray(x_host)
        xT = jnp.asarray(xT_host)
        xT_sq = xT**2
        labels = jnp.asarray(labels_np)
        weights_row = jnp.asarray(weights_np)
    del x_host, xT_host
    n = n_pad
    base = objective.base_margin(config.base_score)

    lambda_ = config.reg_lambda
    alpha = config.alpha
    eta = config.eta
    lambda_bias = float(config.objective_params.get("lambda_bias", 0.0))

    if initial_model is not None:
        w = jnp.asarray(initial_model.weights.reshape(d, G))
        b = jnp.asarray(initial_model.bias.reshape(G))
        start_round = initial_model.num_boosted_rounds
    else:
        w = jnp.zeros((d, G), jnp.float32)
        b = jnp.zeros(G, jnp.float32)
        start_round = 0

    def _round_body(x_s, xT_s, xT_sq_s, labels_s, weights_s, wc, bc):
        """Sequential coordinate descent (xgboost's coord_descent updater):
        grad/hess computed once per round, then per-coordinate updates with
        the per-row gradient adjusted incrementally (g += h * x_j * delta) —
        stable under correlated features where simultaneous shotgun updates
        diverge. The coordinate sweep is a lax.scan over features, fully
        on-device. Row-dim inputs may be a data-axis shard: every sum over
        rows psums so all shards compute identical updates."""
        n_s = x_s.shape[0]
        m = x_s @ wc + bc[None, :] + base
        margins = m[:, 0] if G == 1 else m
        g, h = grad_hess(margins, labels_s, weights_s)
        g2 = g.reshape(n_s, G) if G > 1 else g[:, None]
        h2 = h.reshape(n_s, G) if G > 1 else h[:, None]

        def allsum(v):
            return jax.lax.psum(v, axis) if axis is not None else v

        def step(g_cur, inputs):
            x_j, x2_j, w_j = inputs          # [n_s], [n_s], [G]
            gw = allsum(x_j @ g_cur) + lambda_ * w_j    # [G]
            hw = allsum(x2_j @ h2) + lambda_            # [G]
            raw = w_j - gw / hw
            new_w = jnp.sign(raw) * jnp.maximum(jnp.abs(raw) - alpha / hw, 0.0)
            delta = eta * (new_w - w_j)
            g_cur = g_cur + h2 * x_j[:, None] * delta[None, :]
            return g_cur, w_j + delta

        g2, new_w = jax.lax.scan(step, g2, (xT_s, xT_sq_s, wc))
        gb = allsum(g2.sum(axis=0)) + lambda_bias * bc
        hb = allsum(h2.sum(axis=0)) + lambda_bias
        bc = bc - eta * gb / jnp.maximum(hb, 1e-6)
        return new_w, bc

    if axis is not None:
        from jax.sharding import PartitionSpec as P

        try:
            from jax import shard_map

            rep_kw = {"check_vma": False}
        except ImportError:  # pragma: no cover - older jax
            from jax.experimental.shard_map import shard_map

            rep_kw = {"check_rep": False}  # pre-0.6 kwarg name

        lab_spec = P("data") if labels.ndim == 1 else P("data", None)
        # graftlint: disable=trace-uncached-jit — session-scope construction: one linear round program per train call
        one_round_sharded = jax.jit(
            shard_map(
                _round_body,
                mesh=mesh,
                in_specs=(
                    P("data", None), P(None, "data"), P(None, "data"),
                    lab_spec, P("data"), P(None, None), P(None),
                ),
                out_specs=(P(None, None), P(None)),
                **rep_kw,
            )
        )

        def one_round(wc, bc):
            return one_round_sharded(x, xT, xT_sq, labels, weights_row, wc, bc)

    else:

        @jax.jit
        def one_round(wc, bc):
            return _round_body(x, xT, xT_sq, labels, weights_row, wc, bc)

    model = LinearModel(
        np.zeros((d, G)), np.zeros(G),
        objective_name=config.objective,
        base_score=config.base_score,
        num_feature=d,
        num_class=config.num_class,
        objective_params={
            k: v for k, v in config.objective_params.items()
            if k in ("scale_pos_weight", "num_class", "lambda_bias")
        },
    )
    metric_names = _eval_metric_names(config, objective)

    _rows_cache = {}

    def _eval_round():
        """One round's metric lines: host evaluation with the shared
        cross-host combine (identical lines on every host — same semantics
        as the tree booster's evaluate())."""
        from .booster import evaluate_host_lines

        results = evaluate_host_lines(
            ((name, dm, model.predict_margin(dm.features)) for dm, name in evals),
            metric_names,
            feval,
            objective,
            G,
            config.objective_params,
            is_multiproc,
            global_rows_cache=_rows_cache,
        )
        for name, metric, value in results:
            evals_log.setdefault(name, {}).setdefault(metric, []).append(value)

    model.rounds = start_round
    evals_log = {}
    stop = False
    # full callback protocol, like the gbtree loop (booster.py): RoundTimer's
    # round-0 timestamp and phase recorder are armed in before_training
    for cb in callbacks:
        if hasattr(cb, "before_training"):
            model = cb.before_training(model) or model
    for rnd in range(start_round, start_round + num_boost_round):
        w, b = one_round(w, b)
        model.weights = np.asarray(w)
        model.bias = np.asarray(b)
        model.rounds = rnd + 1
        _eval_round()
        for cb in callbacks:
            if hasattr(cb, "after_iteration") and cb.after_iteration(model, rnd, evals_log):
                stop = True
        if stop:
            break
    for cb in callbacks:
        if hasattr(cb, "after_training"):
            model = cb.after_training(model) or model
    return model
