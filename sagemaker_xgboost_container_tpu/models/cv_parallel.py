"""Fold-parallel k-fold CV: train all folds simultaneously across devices.

The reference trains k-fold CV strictly sequentially (k x r full boosting
runs, algorithm_mode/train.py:378-459) because libxgboost owns one process.
On TPU the folds are embarrassingly parallel and tiny relative to a chip:
every fold is the SAME dataset with a different row-weight mask (held-out
rows carry weight 0 and drop out of histograms and metrics identically to
xgboost's row slicing), so one ``vmap`` over the fold axis trains all folds
in a single XLA program, and sharding that axis over a ``Mesh`` spreads
folds across devices with zero collectives (SURVEY.md §2.3 row 5's
"opportunity" column).

Scope: single-process, gbtree, depthwise growth, single output group,
num_parallel_tree=1, device-decomposable metrics. The orchestration layer
falls back to the sequential path otherwise.

Binning note: quantile cut points are computed ONCE over the full
train+validation matrix (feature values + weights only — no labels, so no
label leakage), where the sequential path re-sketches each fold's training
slice. This is standard unsupervised preprocessing, but it means the two
paths can produce slightly different trees/metric lines for skewed
features; ``GRAFT_PARALLEL_CV=0`` forces the sequential behavior.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.binning import bin_matrix
from ..ops.histogram import resolve_hist_knobs
from ..ops.tree_build import build_tree, pack_tree, unpack_tree
from .device_metrics import all_supported
from .forest import Forest, compact_padded_tree

logger = logging.getLogger(__name__)


def parallel_cv_supported(config, metric_names, has_feval):
    """Static eligibility for the fold-parallel path."""
    if has_feval or not metric_names:
        return False
    if config.booster != "gbtree" or config.grow_policy != "depthwise":
        return False
    if config.num_class > 1 or config.num_parallel_tree > 1:
        return False
    if config.objective.startswith("rank:") or config.objective == "survival:cox":
        return False
    if config.rounds_per_dispatch < 1:
        return False
    fns = all_supported(
        metric_names, config.objective, 1, config.objective_params
    )
    return fns is not None


def train_cv_parallel(
    config, dmatrix, splits, num_boost_round, metric_names, forest_factory
):
    """Train len(splits) folds in parallel. Returns (forests, evals_results).

    splits: [(train_idx, val_idx)] over dmatrix rows; evals_results matches
    the sequential recorder shape: per fold {"train": {m: [v...]},
    "validation": {m: [v...]}}.
    """
    K = len(splits)
    devices = jax.devices()
    F = min(len(devices), K)
    K_pad = -(-K // F) * F

    binned = bin_matrix(dmatrix, config.max_bin, exact_cap=config.exact_bin_cap)
    n, d = binned.bins.shape
    num_bins = binned.num_bins
    labels = np.asarray(dmatrix.labels, np.float32)
    base_w = np.asarray(dmatrix.get_weight(), np.float32)

    train_w = np.zeros((K_pad, n), np.float32)
    val_w = np.zeros((K_pad, n), np.float32)
    for k, (tr_idx, va_idx) in enumerate(splits):
        train_w[k, tr_idx] = base_w[tr_idx]
        val_w[k, va_idx] = base_w[va_idx]

    proto = forest_factory()
    objective = proto.objective()
    base = objective.base_margin(proto.base_score)
    metric_fns = all_supported(
        metric_names, config.objective, 1, config.objective_params
    )

    mesh = Mesh(np.array(devices[:F]), axis_names=("fold",))
    fold_sharding = NamedSharding(mesh, P("fold"))
    repl = NamedSharding(mesh, P())

    bins_dev = jax.device_put(binned.bins, repl)  # u8/u16 stays narrow on device
    labels_dev = jax.device_put(labels, repl)
    num_cuts_dev = jax.device_put(
        np.array([len(c) for c in binned.cut_points], np.int32), repl
    )
    train_w_dev = jax.device_put(train_w, fold_sharding)
    val_w_dev = jax.device_put(val_w, fold_sharding)
    margins_dev = jax.device_put(
        np.full((K_pad, n), base, np.float32), fold_sharding
    )

    grad_hess = objective.grad_hess
    cfg = config
    monotone = None
    if cfg.monotone_constraints:
        vals = np.asarray(cfg.monotone_constraints, np.int32)
        mono_np = np.zeros(d, np.int32)
        mono_np[: len(vals)] = vals
        monotone = jnp.asarray(mono_np)
    interaction_sets = None
    if cfg.interaction_constraints:
        sets_np = np.zeros((len(cfg.interaction_constraints), d), bool)
        for s, members in enumerate(cfg.interaction_constraints):
            for f in members:
                if 0 <= int(f) < d:
                    sets_np[s, int(f)] = True
        interaction_sets = jnp.asarray(sets_np)

    k_rounds = max(1, cfg.rounds_per_dispatch)
    from ..telemetry import REGISTRY

    REGISTRY.gauge(
        "dispatch_fused_rounds",
        "Boosting rounds fused into one device dispatch per round "
        "program (the lax.scan length K of the fused round pipeline)",
    ).set(k_rounds)

    # knob snapshot for the traced build (trace-safety: no env reads under
    # trace) — resolved here, host-side, once per CV dispatch program
    hist_knobs = resolve_hist_knobs()

    def fold_round(bins, margins_k, tw_k, vw_k, rng_k):
        g, h = grad_hess(margins_k, labels_dev, tw_k)
        if cfg.subsample < 1.0:
            keep = (
                jax.random.uniform(jax.random.fold_in(rng_k, 13), (n,))
                < cfg.subsample
            ).astype(jnp.float32)
            g, h = g * keep, h * keep
        if cfg.colsample_bytree < 1.0:
            kf = max(1, int(round(cfg.colsample_bytree * d)))
            chosen = jax.random.permutation(jax.random.fold_in(rng_k, 777), d)[:kf]
            fmask = jnp.zeros(d, jnp.float32).at[chosen].set(1.0)
        else:
            fmask = jnp.ones(d, jnp.float32)
        tree, row_out = build_tree(
            bins, g, h, num_cuts_dev,
            max_depth=cfg.max_depth,
            num_bins=num_bins,
            reg_lambda=cfg.reg_lambda,
            alpha=cfg.alpha,
            gamma=cfg.gamma,
            min_child_weight=cfg.min_child_weight,
            eta=cfg.eta,
            max_delta_step=cfg.max_delta_step,
            feature_mask=fmask,
            monotone=monotone,
            rng=rng_k,
            colsample_bylevel=cfg.colsample_bylevel,
            colsample_bynode=cfg.colsample_bynode,
            interaction_sets=interaction_sets,
            knobs=hist_knobs,
        )
        margins_k = margins_k + row_out
        stats = []
        for fn in metric_fns:
            stats.append(fn.finalize(fn.partial(margins_k, labels_dev, tw_k)))
            stats.append(fn.finalize(fn.partial(margins_k, labels_dev, vw_k)))
        return pack_tree(tree), margins_k, jnp.stack(stats)

    def dispatch(margins, rng):
        def body(carry, j):
            m = carry
            rng_j = jax.random.fold_in(rng, j)
            per_fold = jax.vmap(
                lambda mk, tw, vw, i: fold_round(
                    bins_dev, mk, tw, vw, jax.random.fold_in(rng_j, i)
                )
            )(m, train_w_dev, val_w_dev, jnp.arange(K_pad))
            packed, m, stats = per_fold
            return m, (packed, stats)

        margins, (packed_all, stats_all) = jax.lax.scan(
            body, margins, jnp.arange(k_rounds)
        )
        return margins, packed_all, stats_all

    # graftlint: disable=trace-uncached-jit — session-scope construction: one CV dispatch program per train call
    dispatch_jit = jax.jit(dispatch, donate_argnums=(0,))

    rng = jax.random.PRNGKey(cfg.seed)
    forests = [forest_factory() for _ in range(K)]
    evals_results = [
        {"train": {m: [] for m in metric_names},
         "validation": {m: [] for m in metric_names}}
        for _ in range(K)
    ]
    cuts = binned.cut_points
    rnd = 0
    while rnd < num_boost_round:
        rng, sub = jax.random.split(rng)
        margins_dev, packed_all, stats_all = dispatch_jit(margins_dev, sub)
        packed_np = np.asarray(packed_all)     # [R, K_pad, ...]
        stats_np = np.asarray(stats_all)       # [R, K_pad, 2*n_metrics]
        for j in range(packed_np.shape[0]):
            if rnd >= num_boost_round:
                break
            for k in range(K):
                tree_np = unpack_tree(packed_np[j, k])
                forests[k].append_round(
                    [compact_padded_tree(tree_np, cuts)], [0]
                )
                for i, m in enumerate(metric_names):
                    evals_results[k]["train"][m].append(float(stats_np[j, k, 2 * i]))
                    evals_results[k]["validation"][m].append(
                        float(stats_np[j, k, 2 * i + 1])
                    )
            rnd += 1
    return forests, evals_results
