"""The ``train`` entrypoint: algorithm mode vs user-script mode dispatch.

Reference: training.py:29-103. Algorithm mode reads the SageMaker filesystem
contract (SM_* env vars pointing at JSON config files + channel dirs) and
calls ``sagemaker_train``. Script mode executes the customer's entry point
(from the ``sagemaker_submit_directory``/code channel) as a subprocess with
the full SM environment, like the sagemaker-containers runner did.
"""

import json
import logging
import os
import subprocess
import sys
import tarfile

from .. import constants
from ..toolkit import exceptions as exc
from .algorithm_train import sagemaker_train

logger = logging.getLogger(__name__)

FAILURE_FILE = "/opt/ml/output/failure"
SM_INPUT_ROOT = "/opt/ml/input"


def _read_json(path, default=None):
    if path and os.path.exists(path):
        with open(path, "r") as f:
            return json.load(f)
    return default if default is not None else {}


def derive_sm_env(input_root=SM_INPUT_ROOT):
    """Fill missing SM_* env vars from the mounted /opt/ml tree.

    A BYO SageMaker container receives only the filesystem contract —
    /opt/ml/input/config/{hyperparameters,inputdataconfig,resourceconfig}.json
    plus /opt/ml/input/data/<channel>/ mounts; the SM_* env variables are an
    invention of the sagemaker-containers toolkit the reference embeds
    (training.py:76-98 reads framework.training_env()). Same derivation
    here, so ``docker run -v …:/opt/ml <image> train`` works bare.
    Explicitly-set env always wins (tests/local runs override freely).
    """
    cfg = os.path.join(input_root, "config")
    defaults = {
        constants.SM_INPUT_TRAINING_CONFIG_FILE: os.path.join(
            cfg, "hyperparameters.json"
        ),
        constants.SM_INPUT_DATA_CONFIG_FILE: os.path.join(
            cfg, "inputdataconfig.json"
        ),
        constants.SM_CHECKPOINT_CONFIG_FILE: os.path.join(
            cfg, "checkpointconfig.json"
        ),
        constants.SM_MODEL_DIR: "/opt/ml/model",
        constants.SM_OUTPUT_DATA_DIR: "/opt/ml/output/data",
    }
    for key, path in defaults.items():
        os.environ.setdefault(key, path)
    data_root = os.path.join(input_root, "data")
    if os.path.isdir(data_root):
        for channel in sorted(os.listdir(data_root)):
            channel_dir = os.path.join(data_root, channel)
            if os.path.isdir(channel_dir):
                os.environ.setdefault(
                    "SM_CHANNEL_{}".format(channel.upper()), channel_dir
                )
    resource = _read_json(os.path.join(cfg, "resourceconfig.json"))
    if resource:
        os.environ.setdefault(
            constants.SM_HOSTS, json.dumps(resource.get("hosts", ["algo-1"]))
        )
        os.environ.setdefault(
            constants.SM_CURRENT_HOST, resource.get("current_host", "algo-1")
        )
    else:
        os.environ.setdefault(constants.SM_HOSTS, '["algo-1"]')
        os.environ.setdefault(constants.SM_CURRENT_HOST, "algo-1")


def run_algorithm_mode():
    """Parse the SM env contract and run algorithm-mode training."""
    train_config = _read_json(os.getenv(constants.SM_INPUT_TRAINING_CONFIG_FILE))
    data_config = _read_json(os.getenv(constants.SM_INPUT_DATA_CONFIG_FILE))
    checkpoint_config = _read_json(os.getenv(constants.SM_CHECKPOINT_CONFIG_FILE))

    train_path = os.environ.get(constants.SM_CHANNEL_TRAIN)
    if not train_path:
        raise exc.UserError(
            "No training data: the 'train' channel is required (mount it at "
            "/opt/ml/input/data/train or set SM_CHANNEL_TRAIN)."
        )
    val_path = os.environ.get(constants.SM_CHANNEL_VALIDATION)
    sm_hosts = json.loads(os.environ[constants.SM_HOSTS])
    sm_current_host = os.environ[constants.SM_CURRENT_HOST]
    model_dir = os.getenv(constants.SM_MODEL_DIR)

    sagemaker_train(
        train_config=train_config,
        data_config=data_config,
        train_path=train_path,
        val_path=val_path,
        model_dir=model_dir,
        sm_hosts=sm_hosts,
        sm_current_host=sm_current_host,
        checkpoint_config=checkpoint_config,
    )


def _stage_user_module(hyperparameters, code_dir="/opt/ml/code"):
    """Unpack sagemaker_submit_directory (tar.gz or dir) into code_dir."""
    submit_dir = hyperparameters.get("sagemaker_submit_directory")
    if not submit_dir:
        return None
    os.makedirs(code_dir, exist_ok=True)
    if os.path.isdir(submit_dir):
        return submit_dir
    if submit_dir.endswith(".tar.gz") and os.path.exists(submit_dir):
        with tarfile.open(submit_dir) as tar:
            tar.extractall(code_dir)
        return code_dir
    raise exc.UserError(
        "sagemaker_submit_directory {} not found locally; S3 download is the "
        "platform's responsibility".format(submit_dir)
    )


def run_script_mode():
    """Execute the user-supplied training script as a subprocess."""
    train_config = _read_json(os.getenv(constants.SM_INPUT_TRAINING_CONFIG_FILE))
    program = train_config.get("sagemaker_program") or os.environ.get("SAGEMAKER_PROGRAM")
    code_dir = _stage_user_module(train_config) or os.environ.get(
        "SAGEMAKER_SUBMIT_DIRECTORY", "/opt/ml/code"
    )
    script = os.path.join(code_dir, program)
    if not os.path.exists(script):
        raise exc.UserError("User entry point {} does not exist".format(script))
    from ..utils.requirements import install_requirements_if_present

    install_requirements_if_present(code_dir)

    # expose hyperparameters the way sagemaker-containers did
    env = dict(os.environ)
    hps = {
        k: v for k, v in train_config.items() if not k.startswith("sagemaker_")
    }
    env["SM_HPS"] = json.dumps(hps)
    env.setdefault("SM_MODEL_DIR", os.getenv(constants.SM_MODEL_DIR, "/opt/ml/model"))
    args = [sys.executable, script]
    for key, value in sorted(hps.items()):
        args.extend(["--{}".format(key), str(value)])
    logger.info("Invoking user training script: %s", " ".join(args))
    result = subprocess.run(args, env=env, cwd=code_dir)
    if result.returncode != 0:
        raise exc.UserError(
            "User script exited with non-zero status {}".format(result.returncode)
        )


def train():
    train_config = _read_json(os.getenv(constants.SM_INPUT_TRAINING_CONFIG_FILE))
    if train_config.get("sagemaker_program") or os.environ.get("SAGEMAKER_PROGRAM"):
        logger.info("Invoking user training script.")
        run_script_mode()
    else:
        logger.info("Running XGBoost Sagemaker in algorithm mode")
        run_algorithm_mode()


def _write_failure_file(message):
    try:
        os.makedirs(os.path.dirname(FAILURE_FILE), exist_ok=True)
        with open(FAILURE_FILE, "w") as f:
            f.write(message)
    except OSError:
        pass


def main():
    from ..utils.logging_config import setup_main_logger

    setup_main_logger(__name__)  # honors SAGEMAKER_CONTAINER_LOG_LEVEL
    try:
        derive_sm_env()
        train()
    except exc.BaseToolkitError as e:
        logger.exception("Training failed")
        _write_failure_file(e.public_failure_message())
        sys.exit(1)
    except Exception as e:  # unclassified: our bug
        logger.exception("Training failed")
        _write_failure_file(exc.convert_to_algorithm_error(e).public_failure_message())
        sys.exit(1)
    sys.exit(0)


if __name__ == "__main__":
    main()
