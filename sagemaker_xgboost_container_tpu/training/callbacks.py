"""Training callbacks: evaluation monitor (the HPO stdout contract), early
stopping, checkpoint assembly, SIGTERM model save.

Reference behaviors mirrored from callback.py:42-123 + the xgboost callbacks
it delegates to. The EvaluationMonitor line format is load-bearing: SageMaker
HPO scrapes ``.*\\[[0-9]+\\].*#011validation-<metric>:(\\S+)`` from stdout
(the tab renders as #011 in CloudWatch), so the monitor prints
``[<iter>]<TAB><data>-<metric>:<value:.5f>...`` exactly.
"""

import logging
import os
import signal

from ..constants import MODEL_NAME, XGB_MAXIMIZE_METRICS
from ..telemetry import span
from . import checkpointing, train_utils

logger = logging.getLogger(__name__)


class _TimedCallback:
    """Delegate that records the inner callback's after_iteration wall time
    as a named phase (feeding the per-round ``phases_ms`` breakdown emitted
    by RoundTimer and the ``training_phase_seconds`` histogram). Transparent
    for the booster protocol AND for attribute introspection: train loops
    duck-type callbacks (e.g. dart's ``getattr(cb, "save_best", False)``
    rejection guard), so unknown attributes forward to ``inner``."""

    def __init__(self, inner, phase):
        self.inner = inner
        self.phase = phase

    def __getattr__(self, name):
        # only reached for attributes not defined on the wrapper itself;
        # guard 'inner' against recursion when called pre-__init__ (copy etc.)
        if name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    def before_training(self, model):
        if hasattr(self.inner, "before_training"):
            return self.inner.before_training(model)
        return model

    def after_iteration(self, model, epoch, evals_log):
        if not hasattr(self.inner, "after_iteration"):
            return False
        with span(self.phase):
            return self.inner.after_iteration(model, epoch, evals_log)

    def after_training(self, model):
        if hasattr(self.inner, "after_training"):
            return self.inner.after_training(model)
        return model


class EvaluationMonitor:
    """Print one stdout line per round in xgboost's format.

    Under the fused-dispatch host-fallback cadence (metric lines land once
    per K-round dispatch — models/booster.py) rounds between dispatches add
    no fresh entries; printing the stale previous values against a new
    round index would misreport, so those rounds print nothing.

    Under ``SM_MODEL_TELEMETRY`` each printed entry is additionally emitted
    as a machine-readable ``training.eval`` record and folded into the live
    learning curve (telemetry/model.py); the stdout line itself is the
    SageMaker HPO contract and stays byte-identical either way.
    """

    def __init__(self):
        self._entries_seen = 0
        from ..telemetry import model as model_telemetry

        self._model_telemetry = model_telemetry.enabled() and model_telemetry

    def after_iteration(self, model, epoch, evals_log):
        parts = []
        total = 0
        fresh = []
        for data_name, metrics in evals_log.items():
            for metric_name, values in metrics.items():
                total += len(values)
                parts.append("{}-{}:{:.5f}".format(data_name, metric_name, values[-1]))
                fresh.append((data_name, metric_name, values[-1]))
        if parts and total != self._entries_seen:
            self._entries_seen = total
            print("[{}]\t{}".format(epoch, "\t".join(parts)), flush=True)
            if self._model_telemetry:
                from ..telemetry import emit_metric

                for data_name, metric_name, value in fresh:
                    emit_metric(
                        "training.eval",
                        round=int(epoch),
                        dataset=data_name,
                        name=metric_name,
                        value=float(value),
                    )
                    self._model_telemetry.note_eval(
                        epoch, data_name, metric_name, value
                    )
        return False


class EarlyStopping:
    """Stop after ``rounds`` non-improving rounds on (data_name, metric_name).

    With save_best, the forest is truncated to the best iteration after
    training (xgboost EarlyStopping(save_best=True) semantics).
    """

    def __init__(self, rounds, data_name, metric_name, maximize, save_best=False):
        self.rounds = rounds
        self.data_name = data_name
        self.metric_name = metric_name
        self.maximize = maximize
        self.save_best = save_best
        self.best_score = None
        self.best_iteration = 0
        self.stagnation = 0
        self._entries_seen = 0

    def _improved(self, score):
        if self.best_score is None:
            return True
        return score > self.best_score if self.maximize else score < self.best_score

    def after_iteration(self, model, epoch, evals_log):
        series = evals_log.get(self.data_name, {}).get(self.metric_name)
        if not series:
            return False
        if len(series) == self._entries_seen:
            # no fresh metric this round: the fused-dispatch host-fallback
            # cadence evaluates once per K rounds — a stale repeat carries
            # no evidence, so no stop decision is made here
            return False
        self._entries_seen = len(series)
        score = series[-1]
        if self._improved(score):
            self.best_score = score
            self.best_iteration = epoch
            self.stagnation = 0
            return False
        # patience is measured in boosting ROUNDS since the best iteration,
        # not in fresh metric entries: under the once-per-K-rounds cadence
        # counting entries would silently multiply early_stopping_rounds by
        # K. Equivalent to the entry count when every round has an entry.
        self.stagnation = epoch - self.best_iteration
        return self.stagnation >= self.rounds

    def after_training(self, model):
        model.attributes["best_iteration"] = str(self.best_iteration)
        if self.best_score is not None:
            model.attributes["best_score"] = str(self.best_score)
        if self.save_best and not hasattr(model, "iteration_indptr"):
            from ..toolkit import exceptions as exc

            raise exc.UserError(
                "early_stopping with save_best is not supported for booster=gblinear; "
                "the linear model cannot be truncated to a past iteration."
            )
        if self.save_best:
            # truncate to the best round (iteration indices are absolute)
            end_tree = model.iteration_indptr[self.best_iteration + 1]
            model.trees = model.trees[:end_tree]
            model.tree_info = model.tree_info[:end_tree]
            model.iteration_indptr = model.iteration_indptr[: self.best_iteration + 2]
            model._stacked_cache = None
        return model


def add_sigterm_handler(model_dir, is_master):
    """On SIGTERM: master cleans stale files from model_dir, all exit 0."""

    def _cleanup_and_exit(signo, frame):
        if is_master:
            train_utils.cleanup_dir(model_dir, MODEL_NAME)
        os._exit(0)

    signal.signal(signal.SIGTERM, _cleanup_and_exit)


def get_callbacks(
    model_dir,
    checkpoint_dir,
    early_stopping_data_name,
    early_stopping_metric,
    early_stopping_rounds,
    save_model_on_termination,
    is_master,
    fold=None,
    num_round=None,
    num_rows=None,
    train_cfg=None,
):
    """-> (xgb_model path or None, start iteration, callback list).

    Assembly order mirrors reference callback.py:63-123: monitor, checkpoint
    saver (master only), intermediate-model + SIGTERM, early stopping.

    ``train_cfg`` (when given) feeds the integrity layer: its config
    fingerprint is stamped into every checkpoint manifest and validated
    against the resume candidate's manifest (warn, or refuse under
    ``SM_RESUME_STRICT=true``).
    """
    from ..utils import integrity

    if checkpoint_dir and fold is not None:
        checkpoint_dir = os.path.join(checkpoint_dir, "model-{}".format(fold))

    fingerprint = (
        integrity.config_fingerprint(train_cfg) if train_cfg is not None else None
    )

    from . import elastic

    xgb_model, iteration = checkpointing.load_checkpoint(checkpoint_dir)
    if xgb_model is not None:
        if fingerprint is not None:
            # the live membership log downgrades a recorded world-size
            # transition (elastic shrink) from config skew to a clean resume
            integrity.validate_resume(
                xgb_model, fingerprint, membership_log=elastic.membership_log()
            )
        logger.info("Checkpoint loaded from %s", xgb_model)
        logger.info("Resuming from iteration %s", iteration)

    callbacks = [_TimedCallback(EvaluationMonitor(), "eval_monitor")]

    # consensus guard (SM_CONSENSUS_EVERY): every rank digests its committed
    # trees and allgathers the digests every N rounds — a diverged rank takes
    # the whole job down with exit 81 instead of training a forked ensemble
    # to completion (digest work is host-side, off the jitted round path).
    # MUST precede the checkpoint saver: on the detection round the abort
    # fires before the round's checkpoint write, so a possibly-forked forest
    # never reaches disk with a self-consistent manifest — restart resumes
    # from the last round that PASSED consensus.
    from .consensus import maybe_consensus_guard

    guard = maybe_consensus_guard()
    if guard is not None:
        callbacks.append(_TimedCallback(guard, "consensus"))

    if checkpoint_dir and is_master:
        callbacks.append(
            _TimedCallback(
                checkpointing.SaveCheckpointCallBack(
                    checkpoint_dir,
                    start_iteration=iteration,
                    num_round=num_round,
                    fingerprint=fingerprint,
                    membership_provider=elastic.membership_log,
                ),
                "checkpoint",
            )
        )

    if save_model_on_termination == "true" and is_master:
        model_name = "{}-{}".format(MODEL_NAME, fold) if fold is not None else MODEL_NAME
        callbacks.append(
            _TimedCallback(
                checkpointing.SaveIntermediateModelCallBack(
                    model_dir, model_name, is_master
                ),
                "intermediate_save",
            )
        )
        add_sigterm_handler(model_dir, is_master)

    # elastic membership (SM_ELASTIC): the shrink-to-continue drain point.
    # AFTER the checkpoint/intermediate savers — the round that just
    # finished (and passed consensus, ordered above the saver) lands on
    # disk before the loop unwinds for the reform — and BEFORE early
    # stopping so a reform round can't double-count as stagnation.
    elastic_cb = elastic.maybe_elastic_callback()
    if elastic_cb is not None:
        callbacks.append(_TimedCallback(elastic_cb, "elastic"))

    if early_stopping_data_name and early_stopping_metric and early_stopping_rounds:
        callbacks.append(
            _TimedCallback(
                EarlyStopping(
                    rounds=early_stopping_rounds,
                    data_name=early_stopping_data_name,
                    metric_name=early_stopping_metric,
                    maximize=early_stopping_metric in XGB_MAXIMIZE_METRICS,
                    save_best=is_master,
                ),
                "early_stopping",
            )
        )

    # round watchdog (SM_ROUND_DEADLINE_S): every rank supervises its own
    # round progress — a dead peer stalls ALL ranks' collectives, so each
    # flushes and exits on its own rather than waiting on a coordinator
    from .watchdog import maybe_round_watchdog

    watchdog = maybe_round_watchdog()
    if watchdog is not None:
        callbacks.append(watchdog)

    # LAST: each round's record must drain the phases the callbacks above
    # recorded for that same round. Per-round log lines stay opt-in
    # (SM_ROUND_TIMING); the structured record is the telemetry contract.
    from .profiling import RoundTimer

    round_timing = os.environ.get("SM_ROUND_TIMING", "").lower() in ("1", "true")
    callbacks.append(
        RoundTimer(
            num_rows=num_rows, log_every=10 if round_timing else 0, fold=fold
        )
    )

    return xgb_model, iteration, callbacks
