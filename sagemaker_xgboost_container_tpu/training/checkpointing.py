"""Spot-safe checkpointing: save/resume + retention with SageMaker markers.

Contract parity with the reference (checkpointing.py:139-453):

* checkpoints are full serialized models named ``xgboost-checkpoint.<iter>``
  in the checkpoint dir; resume picks the highest iteration and training
  continues with ``num_round - iteration`` remaining rounds,
* writes are atomic (tempfile + rename),
* a daemon thread deletes all but the ``max_to_keep`` newest, deferring any
  file SageMaker is mid-upload (``.sagemaker-uploading`` marker present and
  ``.sagemaker-uploaded`` absent),
* ``SaveIntermediateModel`` overwrites ``<model_dir>/<model_name>`` every
  round so SIGTERM (spot interruption / HPO early stop) always leaves a
  fresh model behind.
"""

import json
import logging
import os
import queue
import re
import tempfile
import threading
import time
import weakref

from ..telemetry.tracing import trace_span
from ..utils import integrity
from ..utils.faults import fault_point
from ..utils.retry import retry_transient

TEMP_FILE_SUFFIX = ".sagemaker-ignore"
FILE_LOCK_SUFFIX = ".sagemaker-uploading"
FILE_SAFE_SUFFIX = ".sagemaker-uploaded"
MANIFEST_SUFFIX = integrity.MANIFEST_SUFFIX

CHECKPOINT_FILENAME = "xgboost-checkpoint"

logger = logging.getLogger(__name__)

# live SaveCheckpointCallBack instances, for the abort path's final flush
# (training/watchdog.request_abort) — weak so a completed training run's
# callback doesn't linger here
_active_savers = weakref.WeakSet()


def _note_verify_fail(reason):
    from ..telemetry import REGISTRY

    REGISTRY.counter(
        "checkpoint_verify_fail_total",
        "Resume candidates rejected by digest or parse validation",
        {"reason": reason},
    ).inc()


def _checkpoint_usable(path):
    """Cheap integrity check for a checkpoint file.

    With a manifest sidecar (every checkpoint since the integrity layer),
    the sha256 digest is the verdict: a match proves the exact saved bytes
    and SHORT-CIRCUITS the full JSON parse (digesting streams the file
    once; parsing a multi-GB model JSON allocates its whole object tree), a
    mismatch rejects the candidate — stronger than the parse, which accepts
    any bit flip that stays inside JSON syntax.

    Manifest-less checkpoints (older runs) keep the parse fallback:
    checkpoints are full serialized models (forest/gblinear both emit JSON;
    the ``.ubj`` branch only triggers on an explicit suffix, which the
    extension-less ``xgboost-checkpoint.<iter>`` names never carry). A file
    killed mid-write — crash between temp-create and rename shouldn't leave
    one, but an interrupted upload-restore or disk-full truncation can — is
    empty or cuts off mid-JSON; both fail the parse.
    """
    try:
        if os.path.getsize(path) == 0:
            return False
    except OSError:
        return False
    manifest = integrity.read_manifest(path)
    if manifest is not None:
        try:
            integrity.verify_file_against_manifest(path, manifest)
            return True
        except integrity.IntegrityError as e:
            logger.warning("checkpoint digest verification failed: %s", e)
            _note_verify_fail("digest")
            return False
        except OSError:
            _note_verify_fail("io")
            return False
    try:
        with open(path, "rb") as f:
            json.loads(f.read().decode("utf-8"))
        return True
    except OSError:
        _note_verify_fail("io")
        return False
    except (ValueError, UnicodeDecodeError):
        _note_verify_fail("parse")
        return False


def load_checkpoint(checkpoint_dir):
    """-> (model path or None, next iteration number).

    Picks the highest-iteration checkpoint that actually *verifies* — the
    manifest digest where a sidecar exists, the JSON parse otherwise
    (``_checkpoint_usable``). A corrupt/partial/bit-flipped file is skipped
    with a warning and the next-highest takes over, so one bad file can't
    turn a resumable job into a from-scratch retrain or a crash loop. Also
    sweeps orphaned ``.sagemaker-ignore`` temp files left by a crash
    mid-``_atomic_save`` and orphaned ``.manifest`` sidecars whose
    checkpoint is gone (retention deleted it, or the pair was half-restored).
    """
    if not checkpoint_dir or not os.path.exists(checkpoint_dir):
        return None, 0
    pattern = re.compile(r"^{}\.([0-9]+)$".format(re.escape(CHECKPOINT_FILENAME)))
    found = []
    names = set(os.listdir(checkpoint_dir))
    for name in sorted(names):
        if name.endswith(TEMP_FILE_SUFFIX):
            try:
                os.remove(os.path.join(checkpoint_dir, name))
                logger.info("removed orphaned checkpoint temp file %s", name)
            except OSError:
                logger.debug("could not remove orphaned temp file %s", name)
            continue
        if name.endswith(MANIFEST_SUFFIX):
            if name[: -len(MANIFEST_SUFFIX)] not in names:
                try:
                    os.remove(os.path.join(checkpoint_dir, name))
                    logger.info("removed orphaned checkpoint manifest %s", name)
                except OSError:
                    logger.debug("could not remove orphaned manifest %s", name)
            continue
        m = pattern.match(name)
        if m:
            found.append((int(m.group(1)), name))
    for iteration, name in sorted(found, reverse=True):
        path = os.path.join(checkpoint_dir, name)
        if _checkpoint_usable(path):
            return path, iteration + 1
        logger.warning(
            "checkpoint %s is corrupt or partial; falling back to the "
            "next-highest iteration", name
        )
    return None, 0


def _atomic_save(
    model, directory, final_name, iteration=None, fingerprint=None, membership_log=None
):
    """tempfile + rename, with bounded transient-IO retries. Each attempt
    uses a fresh temp file and cleans up its own debris on failure, so a
    retried save can't leak ``.sagemaker-ignore`` orphans.

    With ``iteration``/``fingerprint`` (checkpoint saves), a manifest
    sidecar (``<final_name>.manifest``: sha256 + byte count + iteration +
    config fingerprint) is written after the model with the same
    atomic-retried semantics. The digest is taken from the temp file BEFORE
    the rename — it describes the exact bytes that became the checkpoint,
    not a re-read that could race a concurrent restore. Order matters:
    model first, manifest second, so a crash in between leaves a
    manifest-less checkpoint (degrades to the parse fallback) rather than a
    manifest describing a file that doesn't exist.

    Without them (the per-round intermediate model overwrite), NO manifest
    is written — a SIGTERM can land between the two renames on any round,
    and a sidecar describing the previous round's bytes would make serving
    reject the perfectly fresh model the spot-interruption contract just
    saved. Instead any stale sidecar for the name (e.g. the final-model
    manifest of a previous completed run in the same model_dir) is removed,
    keeping the invariant: a manifest, when present, describes the current
    bytes.
    """
    digest_box = {}
    want_manifest = iteration is not None or fingerprint is not None

    def _attempt():
        fault_point("checkpoint.save", path=final_name)
        with tempfile.NamedTemporaryFile(
            dir=directory, suffix=TEMP_FILE_SUFFIX, delete=False, mode="w"
        ) as tf:
            tmp = tf.name
        try:
            model.save_model(tmp)
            if want_manifest:
                digest_box["sha256"] = integrity.file_digest(tmp)
                digest_box["bytes"] = os.path.getsize(tmp)
                # re-saving an existing name (resume re-writes a rejected
                # iteration): drop the old sidecar BEFORE the rename, so a
                # crash in the rename->manifest window leaves new bytes
                # manifest-less (parse fallback) rather than new bytes +
                # a stale manifest that would verify-fail a good checkpoint
                try:
                    os.remove(
                        os.path.join(directory, final_name + MANIFEST_SUFFIX)
                    )
                except OSError:
                    pass
            os.rename(tmp, os.path.join(directory, final_name))
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    # tracer spans (SM_TRACE): the save and its manifest nest under the
    # callback's `checkpoint` phase span inside the open round span, so a
    # slow storage volume shows up as a fat checkpoint.save in the timeline
    with trace_span("checkpoint.save", attributes={"file": final_name}):
        retry_transient(_attempt, site="checkpoint.save")
    if not want_manifest:
        try:
            os.remove(os.path.join(directory, final_name + MANIFEST_SUFFIX))
        except OSError:
            pass
        return
    manifest = integrity.build_manifest(
        os.path.join(directory, final_name),
        iteration=iteration,
        fingerprint=fingerprint,
        digest=digest_box["sha256"],
        size=digest_box["bytes"],
        membership_log=membership_log,
    )
    _atomic_write_manifest(directory, final_name + MANIFEST_SUFFIX, manifest)


def _atomic_write_manifest(directory, manifest_name, manifest):
    """Write the manifest sidecar: tempfile + rename under ``retry_transient``
    with per-attempt temp cleanup — the same durability contract as the
    model write it describes (a manifest that can be torn by a crash would
    reject the healthy checkpoint it sits next to)."""

    def _attempt():
        fault_point("checkpoint.manifest", path=manifest_name)
        with tempfile.NamedTemporaryFile(
            dir=directory, suffix=TEMP_FILE_SUFFIX, delete=False, mode="w"
        ) as tf:
            tmp = tf.name
        integrity.dump_manifest_atomic(
            os.path.join(directory, manifest_name), manifest, tmp
        )

    with trace_span("checkpoint.manifest", attributes={"file": manifest_name}):
        retry_transient(_attempt, site="checkpoint.manifest")


def active_checkpoint_dirs():
    """Checkpoint dirs of live savers. The abort path writes its
    flight-recorder dump here when no explicit trace dir is configured:
    the checkpoint channel is uploaded/preserved by the platform, so the
    post-mortem survives the container."""
    return [s.checkpoint_dir for s in list(_active_savers) if s.checkpoint_dir]


def flush_checkpoints(timeout=10.0):
    """Abort-path flush: drain every live checkpoint deleter queue so the
    newest checkpoint files are settled on disk before the process exits
    (the per-round saves themselves are synchronous — the last completed
    round is already durable; this stops the background machinery cleanly).
    The join is bounded: when the deleter itself is wedged on the hung
    storage that triggered the abort, the exit must still happen.
    """
    for saver in list(_active_savers):
        try:
            saver.stop(timeout=timeout)
        except Exception:
            logger.exception("checkpoint flush failed for %r", saver)


class SaveCheckpointCallBack:
    """Save a checkpoint each round; background-delete stale ones."""

    SENTINEL = None

    def __init__(
        self,
        checkpoint_dir,
        start_iteration=0,
        max_to_keep=5,
        num_round=None,
        fingerprint=None,
        membership_provider=None,
    ):
        self.checkpoint_dir = checkpoint_dir
        self.max_to_keep = max_to_keep
        self.start_iteration = start_iteration
        self.num_round = num_round
        # config fingerprint stamped into every manifest sidecar; the resume
        # validator (utils/integrity.validate_resume) compares it on restart
        self.fingerprint = fingerprint
        # elastic membership: a zero-arg callable returning the current
        # transition log — called per save (not captured once) so a shrink
        # mid-generation lands in the very next sidecar
        self.membership_provider = membership_provider
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.previous_checkpoints = {
            os.path.join(checkpoint_dir, f) for f in os.listdir(checkpoint_dir)
        }
        self.delete_queue = queue.Queue()
        self._start_deleter()
        _active_savers.add(self)

    def format_path(self, iteration):
        return os.path.join(
            self.checkpoint_dir, "{}.{}".format(CHECKPOINT_FILENAME, iteration)
        )

    def after_iteration(self, model, epoch, evals_log):
        _atomic_save(
            model,
            self.checkpoint_dir,
            "{}.{}".format(CHECKPOINT_FILENAME, epoch),
            iteration=epoch,
            fingerprint=self.fingerprint,
            membership_log=(
                self.membership_provider() if self.membership_provider else None
            ),
        )
        self.delete_queue.put(epoch - self.max_to_keep)
        # /status carries the last durably-saved checkpoint: the resume
        # point an operator would restart from if they killed the job now
        from ..telemetry import fleet

        fleet.note_status(
            last_checkpoint={"path": self.format_path(epoch), "round": epoch}
        )
        if self.num_round is not None and epoch + 1 >= self.num_round:
            self.stop()
        return False

    def after_training(self, model):
        self.stop()
        return model

    # ------------------------------------------------------------- deleter
    def _start_deleter(self):
        def _is_uploading(path):
            return os.path.isfile(path + FILE_LOCK_SUFFIX) and not os.path.isfile(
                path + FILE_SAFE_SUFFIX
            )

        def _remove(path):
            try:
                try:
                    os.remove(path)
                except OSError:
                    # checkpoint survived the delete (EACCES, upload-lock
                    # race): its sidecar must survive too — stripping the
                    # manifest from a live checkpoint would downgrade a later
                    # resume to the parse fallback, losing bit-rot detection.
                    # load_checkpoint sweeps the sidecar once the checkpoint
                    # is truly gone.
                    logger.debug("Failed to delete %s", path)
                else:
                    # the sidecar lives and dies with its checkpoint:
                    # retention must never leak one (a stale manifest next to
                    # a later re-used name would reject a good file)
                    try:
                        os.remove(path + MANIFEST_SUFFIX)
                    except OSError:
                        pass
            finally:
                self.delete_queue.task_done()

        def _drain():
            for iteration in iter(self.delete_queue.get, self.SENTINEL):
                path = self.format_path(iteration)
                if not os.path.isfile(path) or path in self.previous_checkpoints:
                    self.delete_queue.task_done()
                    continue
                if _is_uploading(path):
                    # SageMaker still uploading: requeue and revisit later
                    # (sleep so a lone stuck item doesn't busy-spin a core)
                    time.sleep(0.5)
                    self.delete_queue.put(iteration)
                    continue
                _remove(path)
            self.delete_queue.task_done()
            # training over: second pass removes stragglers regardless of locks
            self.delete_queue.put(self.SENTINEL)
            for iteration in iter(self.delete_queue.get, self.SENTINEL):
                _remove(self.format_path(iteration))
            self.delete_queue.task_done()

        self.thread = threading.Thread(target=_drain, daemon=True)
        self.thread.start()

    def stop(self, timeout=None):
        """Drain and join the deleter. ``timeout`` bounds the join for the
        abort path — a deleter wedged on hung storage must not keep the
        process from its exit (normal end-of-training keeps the full
        blocking drain)."""
        if self.thread.is_alive():
            self.delete_queue.put(self.SENTINEL)
            self.thread.join(timeout)


class SaveIntermediateModelCallBack:
    """Overwrite ``model_dir/model_name`` after every round (master only)."""

    def __init__(self, intermediate_model_dir, model_name, is_master):
        self.intermediate_model_dir = intermediate_model_dir
        self.model_name = model_name
        self.is_master = is_master
        os.makedirs(intermediate_model_dir, exist_ok=True)

    def after_iteration(self, model, epoch, evals_log):
        if self.is_master:
            _atomic_save(model, self.intermediate_model_dir, self.model_name)
        return False
