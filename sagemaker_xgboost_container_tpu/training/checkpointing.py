"""Spot-safe checkpointing: save/resume + retention with SageMaker markers.

Contract parity with the reference (checkpointing.py:139-453):

* checkpoints are full serialized models named ``xgboost-checkpoint.<iter>``
  in the checkpoint dir; resume picks the highest iteration and training
  continues with ``num_round - iteration`` remaining rounds,
* writes are atomic (tempfile + rename),
* a daemon thread deletes all but the ``max_to_keep`` newest, deferring any
  file SageMaker is mid-upload (``.sagemaker-uploading`` marker present and
  ``.sagemaker-uploaded`` absent),
* ``SaveIntermediateModel`` overwrites ``<model_dir>/<model_name>`` every
  round so SIGTERM (spot interruption / HPO early stop) always leaves a
  fresh model behind.
"""

import logging
import os
import queue
import re
import tempfile
import threading
import time

TEMP_FILE_SUFFIX = ".sagemaker-ignore"
FILE_LOCK_SUFFIX = ".sagemaker-uploading"
FILE_SAFE_SUFFIX = ".sagemaker-uploaded"

CHECKPOINT_FILENAME = "xgboost-checkpoint"

logger = logging.getLogger(__name__)


def load_checkpoint(checkpoint_dir):
    """-> (model path or None, next iteration number)."""
    if not checkpoint_dir or not os.path.exists(checkpoint_dir):
        return None, 0
    pattern = re.compile(r"^{}\.([0-9]+)$".format(re.escape(CHECKPOINT_FILENAME)))
    found = []
    for name in os.listdir(checkpoint_dir):
        m = pattern.match(name)
        if m:
            found.append((int(m.group(1)), name))
    if not found:
        return None, 0
    iteration, name = max(found)
    return os.path.join(checkpoint_dir, name), iteration + 1


def _atomic_save(model, directory, final_name):
    with tempfile.NamedTemporaryFile(
        dir=directory, suffix=TEMP_FILE_SUFFIX, delete=False, mode="w"
    ) as tf:
        tmp = tf.name
    model.save_model(tmp)
    os.rename(tmp, os.path.join(directory, final_name))


class SaveCheckpointCallBack:
    """Save a checkpoint each round; background-delete stale ones."""

    SENTINEL = None

    def __init__(self, checkpoint_dir, start_iteration=0, max_to_keep=5, num_round=None):
        self.checkpoint_dir = checkpoint_dir
        self.max_to_keep = max_to_keep
        self.start_iteration = start_iteration
        self.num_round = num_round
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.previous_checkpoints = {
            os.path.join(checkpoint_dir, f) for f in os.listdir(checkpoint_dir)
        }
        self.delete_queue = queue.Queue()
        self._start_deleter()

    def format_path(self, iteration):
        return os.path.join(
            self.checkpoint_dir, "{}.{}".format(CHECKPOINT_FILENAME, iteration)
        )

    def after_iteration(self, model, epoch, evals_log):
        _atomic_save(
            model, self.checkpoint_dir, "{}.{}".format(CHECKPOINT_FILENAME, epoch)
        )
        self.delete_queue.put(epoch - self.max_to_keep)
        if self.num_round is not None and epoch + 1 >= self.num_round:
            self.stop()
        return False

    def after_training(self, model):
        self.stop()
        return model

    # ------------------------------------------------------------- deleter
    def _start_deleter(self):
        def _is_uploading(path):
            return os.path.isfile(path + FILE_LOCK_SUFFIX) and not os.path.isfile(
                path + FILE_SAFE_SUFFIX
            )

        def _remove(path):
            try:
                os.remove(path)
            except OSError:
                logger.debug("Failed to delete %s", path)
            finally:
                self.delete_queue.task_done()

        def _drain():
            for iteration in iter(self.delete_queue.get, self.SENTINEL):
                path = self.format_path(iteration)
                if not os.path.isfile(path) or path in self.previous_checkpoints:
                    self.delete_queue.task_done()
                    continue
                if _is_uploading(path):
                    # SageMaker still uploading: requeue and revisit later
                    # (sleep so a lone stuck item doesn't busy-spin a core)
                    time.sleep(0.5)
                    self.delete_queue.put(iteration)
                    continue
                _remove(path)
            self.delete_queue.task_done()
            # training over: second pass removes stragglers regardless of locks
            self.delete_queue.put(self.SENTINEL)
            for iteration in iter(self.delete_queue.get, self.SENTINEL):
                _remove(self.format_path(iteration))
            self.delete_queue.task_done()

        self.thread = threading.Thread(target=_drain, daemon=True)
        self.thread.start()

    def stop(self):
        if self.thread.is_alive():
            self.delete_queue.put(self.SENTINEL)
            self.thread.join()


class SaveIntermediateModelCallBack:
    """Overwrite ``model_dir/model_name`` after every round (master only)."""

    def __init__(self, intermediate_model_dir, model_name, is_master):
        self.intermediate_model_dir = intermediate_model_dir
        self.model_name = model_name
        self.is_master = is_master
        os.makedirs(intermediate_model_dir, exist_ok=True)

    def after_iteration(self, model, epoch, evals_log):
        if self.is_master:
            _atomic_save(model, self.intermediate_model_dir, self.model_name)
        return False
