"""Cross-rank consensus guard: prove the mesh agrees on the committed trees.

The distributed contract says every rank commits bit-identical trees (the
histogram psum/reduce_scatter lowerings are proven equivalent at test time)
— but nothing *enforced* it at runtime. A diverged rank (flaky HBM bit
flips, a non-deterministic collective on a misbehaving fabric, version skew
after a partial restart) silently trains a forked ensemble: rank 0 saves
its fork, every serving host later loads whichever fork it's handed, and
no log line ever says so.

The :class:`ConsensusGuard` closes that hole. Every ``SM_CONSENSUS_EVERY``
committed rounds, each rank digests its forest's packed-tree bytes
(``utils.integrity.forest_digest`` — the host mirror of the u32-view
identity the bit-identity tests assert on, computed OFF the jitted round
path) and allgathers the hex digests over the cluster framing
(``parallel/distributed.Cluster.synchronize`` on a dedicated port). Any
disagreement:

* emits one ``training.divergence`` record carrying every rank's digest
  (the runbook artifact: the odd digest out names the bad rank),
* counts ``consensus_divergence_total``,
* takes the whole job down with ``EXIT_CONSENSUS_DIVERGENCE`` (81) through
  PR 3's abort machinery — rank 0 broadcasts an abort frame (carrying the
  exit code) to every peer before aborting itself; every other rank saw
  the same allgathered digests and aborts locally. Restart resumes from
  the last digest-verified checkpoint instead of training the fork to
  completion.

Env-gated and inert by default: ``SM_CONSENSUS_EVERY`` unset/0 means no
guard object, no sockets, no digest work. The ``consensus.check`` fault
point lets chaos drills perturb one rank's digest deterministically (the
injectable stand-in for a real memory fault).
"""

import logging

from ..constants import EXIT_CONSENSUS_DIVERGENCE
from ..telemetry import REGISTRY
from ..telemetry.emit import emit_metric
from ..utils.envconfig import env_float, env_int, env_port
from ..utils.faults import fault_point
from ..utils.integrity import forest_digest

logger = logging.getLogger(__name__)

CONSENSUS_EVERY_ENV = "SM_CONSENSUS_EVERY"
CONSENSUS_PORT_ENV = "SM_CONSENSUS_PORT"
CONSENSUS_TIMEOUT_ENV = "SM_CONSENSUS_TIMEOUT_S"

# NOT the rendezvous (9099), heartbeat (9199), or abort (9299) ports: the
# digest allgather must never collide with an in-flight conversation there
DEFAULT_CONSENSUS_PORT = 9399

# membership registered by algorithm_train._pre_exec over the RE-FORMED
# cluster (hosts without data already exited); None until a multi-host job
# registers — single-host jobs never do, and the guard degrades to a local
# digest (trivially consistent, but the fault point stays drillable)
_hosts = None
_current_host = None


def consensus_every():
    return env_int(CONSENSUS_EVERY_ENV, 0, minimum=0)


def consensus_port():
    return env_port(CONSENSUS_PORT_ENV, DEFAULT_CONSENSUS_PORT)


def consensus_timeout_s():
    return env_float(CONSENSUS_TIMEOUT_ENV, 60.0, minimum=0.1, maximum=3600.0)


def register_cluster(hosts, current_host):
    """Record the participating host list for guards built later
    (algorithm_train._pre_exec calls this on every participant)."""
    global _hosts, _current_host
    _hosts = sorted(hosts)
    _current_host = current_host


def _reset_for_tests():
    global _hosts, _current_host
    _hosts = None
    _current_host = None


def cluster_exchange(hosts, current_host, port=None, timeout=None, master_addr=None):
    """-> exchange fn (digest, round) -> rank-ordered digest list.

    One ``Cluster.synchronize`` allgather per consensus check on the
    dedicated consensus port — the same framed-JSON protocol (and the same
    trickle-proof deadlines) as the startup rendezvous, so a wedged peer
    degrades to a logged exchange failure, never a hang. ``master_addr``
    overrides DNS resolution of the master host (loopback drills).
    """
    from ..parallel.distributed import Cluster

    def _exchange(digest, rnd):
        cluster = Cluster(hosts, current_host, port=consensus_port() if port is None else port)
        if master_addr is not None:
            cluster.master_host = master_addr
        # world rides along so a rank whose membership drifted (missed an
        # elastic shrink, resumed at a stale world size) is caught as a
        # membership pathology, not misread as tree divergence
        return cluster.synchronize(
            {"digest": digest, "round": rnd, "world": len(hosts)},
            timeout=consensus_timeout_s() if timeout is None else timeout,
        )

    return _exchange


class ConsensusGuard:
    """Booster-protocol callback: digest + allgather every N rounds.

    ``exchange`` / ``abort_fn`` are injectable for tests and the dryrun
    drill; production wiring (``maybe_consensus_guard``) uses the cluster
    allgather and ``watchdog.coordinate_abort``/``request_abort``.
    """

    def __init__(
        self,
        every,
        hosts=None,
        current_host=None,
        port=None,
        timeout=None,
        master_addr=None,
        exchange=None,
        abort_fn=None,
    ):
        self.every = max(1, int(every))
        self.hosts = sorted(hosts) if hosts else None
        self.current_host = current_host
        self.rank = self.hosts.index(current_host) if self.hosts else 0
        self.world_size = len(self.hosts) if self.hosts else 1
        if exchange is not None:
            self.exchange = exchange
        elif self.world_size > 1:
            self.exchange = cluster_exchange(
                self.hosts, current_host, port=port, timeout=timeout,
                master_addr=master_addr,
            )
        else:
            self.exchange = lambda digest, rnd: [digest]
        self.abort_fn = abort_fn or self._default_abort
        self.checks = 0
        self.divergences = 0

    # ----------------------------------------------------- callback protocol
    def after_iteration(self, model, epoch, evals_log):
        if (epoch + 1) % self.every != 0:
            return False
        # tracer span (SM_TRACE): the digest + allgather as one tree node
        # under the round span — a consensus check stalled on a slow peer
        # is visible in the timeline (and in the flight recorder, since an
        # exit-81 abort leaves this span in_flight)
        from ..telemetry.tracing import trace_span

        with trace_span(
            "consensus.check", attributes={"round": epoch, "rank": self.rank}
        ):
            return self._check(model, epoch)

    def _check(self, model, epoch):
        digest = forest_digest(model)
        try:
            fault_point("consensus.check", round=epoch, rank=self.rank)
        except (OSError, ConnectionError) as e:
            # injected divergence: the drillable stand-in for a real memory
            # fault — this rank claims a perturbed digest
            logger.error(
                "consensus.check fault injected on rank %d: perturbing this "
                "rank's digest (%s)", self.rank, e
            )
            digest = "f" * 8 + digest[8:]
        self.checks += 1
        REGISTRY.counter(
            "consensus_checks_total",
            "Cross-rank committed-tree digest checks performed",
        ).inc()
        try:
            replies = self.exchange(digest, epoch)
        except Exception as e:
            # an unreachable peer here is the abort plane's / watchdog's
            # failure domain, not a divergence verdict — log and keep
            # training rather than abort on a transport blip
            logger.warning(
                "consensus digest exchange failed at round %d (%s); skipping "
                "this check", epoch, e
            )
            return False
        # the cluster exchange returns the full payload dicts so the round
        # can be validated; injected exchanges (tests, the dryrun drill) may
        # return bare digest lists
        if replies and isinstance(replies[0], dict):
            worlds = {int(r.get("world", self.world_size)) for r in replies}
            if worlds != {self.world_size}:
                # membership drift: a rank answering with a different world
                # size missed (or hasn't finished) an elastic membership
                # transition — its forest legitimately differs, so a digest
                # verdict would abort a healthy cluster. Skip; the drifted
                # rank either re-forms (its exchange keeps failing on the
                # wrong host list) or the abort plane takes it down.
                logger.warning(
                    "consensus exchange at round %d mixed world sizes %s "
                    "(this rank: %d); skipping this check as membership "
                    "drift, not divergence", epoch, sorted(worlds), self.world_size,
                )
                return False
            rounds = {int(r.get("round", epoch)) for r in replies}
            if rounds != {epoch}:
                # a check-index misalignment (one rank skipped a timed-out
                # exchange and this allgather mixed two check rounds) is a
                # transport pathology, NOT a divergence verdict: forests
                # from different rounds necessarily differ, and aborting on
                # that would take down a healthy cluster
                logger.warning(
                    "consensus exchange at round %d mixed check rounds %s; "
                    "skipping this check (ranks re-align at the next one)",
                    epoch, sorted(rounds),
                )
                return False
            digests = [r["digest"] for r in replies]
        else:
            digests = list(replies)
        if len(set(digests)) <= 1:
            return False
        self.divergences += 1
        REGISTRY.counter(
            "consensus_divergence_total",
            "Consensus checks that found ranks with diverged committed trees",
        ).inc()
        per_rank = {str(r): d for r, d in enumerate(digests)}
        emit_metric(
            "training.divergence",
            round=epoch,
            rank=self.rank,
            world_size=self.world_size,
            digests=per_rank,
        )
        logger.error(
            "CONSENSUS DIVERGENCE at round %d: committed trees disagree "
            "across ranks (%s) — aborting all ranks with exit code %d",
            epoch,
            ", ".join("rank {}={}".format(r, d[:12]) for r, d in sorted(per_rank.items())),
            EXIT_CONSENSUS_DIVERGENCE,
        )
        self.abort_fn(
            "consensus_divergence",
            EXIT_CONSENSUS_DIVERGENCE,
            round=epoch,
            digests=per_rank,
        )
        return False

    # ------------------------------------------------------------- internals
    def _default_abort(self, reason, exit_code, **fields):
        from . import watchdog

        if self.hosts and self.rank == 0:
            # rank 0 broadcasts the exit code to peers first — every rank
            # saw the same allgathered digests, but a peer that failed its
            # exchange mid-flight still gets taken down
            watchdog.coordinate_abort(
                self.hosts, self.current_host, reason, exit_code=exit_code, **fields
            )
        else:
            watchdog.request_abort(reason, exit_code, **fields)


def maybe_consensus_guard():
    """-> a ConsensusGuard when ``SM_CONSENSUS_EVERY`` > 0, else None.

    Uses the membership ``register_cluster`` recorded (multi-host) or runs
    single-rank (the digest work and fault point still execute, so the
    knob's overhead is measurable anywhere).
    """
    every = consensus_every()
    if every <= 0:
        return None
    return ConsensusGuard(every, hosts=_hosts, current_host=_current_host)
