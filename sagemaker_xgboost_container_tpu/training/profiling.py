"""First-class training profiling (SURVEY.md §5: the reference's only tracing
was wall-clock tracker logs; smdebug was installed but disabled).

Three light-weight hooks:

* ``RoundTimer`` — per-round wall time + throughput. Always feeds the
  telemetry layer: every round emits one structured JSON stdout record
  (``training.round``) carrying the round latency and a per-phase breakdown
  (the span recorder drains into it), and observes the
  ``training_round_seconds`` registry histogram. Human-readable per-round
  log lines stay opt-in via ``log_every`` (SM_ROUND_TIMING); the end-of-run
  summary reports mean, p50, and p95.
* ``xla_trace`` — context manager around training that writes a JAX profiler
  trace (TensorBoard-viewable) when ``SM_PROFILER_TRACE_DIR`` is set.
* the span API (``telemetry.span``) — algorithm_train wraps data ingest,
  the boosting loop, and model save in named phases.
"""

import contextlib
import logging
import os
import time

from ..telemetry import (
    REGISTRY,
    ROUND_STATE,
    emit_metric,
    get_round_fields,
    pop_recorder,
    push_recorder,
)
from ..telemetry import percentile  # noqa: F401  (canonical home: telemetry.registry)
from ..utils.faults import fault_point

logger = logging.getLogger(__name__)

TRACE_DIR_ENV = "SM_PROFILER_TRACE_DIR"

ROUND_HISTOGRAM = "training_round_seconds"


class RoundTimer:
    """Per-round timing callback; rides the standard booster protocol.

    ``emit_structured`` controls the per-round ``training.round`` stdout
    record (default on; SM_STRUCTURED_METRICS=false silences it globally).
    ``log_every=0`` disables the human-readable per-round log lines while
    keeping the structured emission and the end-of-run summary.
    ``fold`` tags every record in k-fold CV runs (each fold trains its own
    callback stack, so per-epoch records from different folds must stay
    distinguishable for the CloudWatch regexes).
    """

    def __init__(self, num_rows=None, log_every=10, emit_structured=True, fold=None):
        self.num_rows = num_rows
        self.log_every = log_every
        self.emit_structured = emit_structured
        self.fold = fold
        self._last = None
        self._times = []
        self._recorder = None

    def before_training(self, model):
        self._last = time.perf_counter()
        # collect span phases (checkpoint saves, eval monitor, ...) per round;
        # popped in after_training. Thread-local, so parallel fold loops on
        # other threads never cross-talk.
        self._recorder = push_recorder()
        return model

    def after_iteration(self, model, epoch, evals_log):
        # chaos hook: the one per-round fault point every training run owns
        # (RoundTimer is always in the stack) — lets drills stall a round
        # (watchdog tests) or deliver SIGTERM mid-training deterministically
        fault_point("training.round_end", round=epoch)
        now = time.perf_counter()
        if self._last is not None:
            elapsed = now - self._last
            self._times.append(elapsed)
            REGISTRY.histogram(
                ROUND_HISTOGRAM, help="Boosting round wall time"
            ).observe(elapsed)
            # feed the cluster heartbeat's round state (telemetry/cluster.py):
            # a deque append under a lock — negligible, so always on
            ROUND_STATE.note_round(epoch, elapsed)
            phases = self._recorder.drain() if self._recorder is not None else {}
            if self.emit_structured:
                # callback work is measured by its spans; the remainder of the
                # round is device compute: binning (first round), tree build,
                # eval. One record per round — the CloudWatch-regex contract.
                overhead = sum(phases.values())
                phases_ms = {
                    k: round(v * 1000, 3) for k, v in sorted(phases.items())
                }
                phases_ms["build_eval"] = round(
                    max(elapsed - overhead, 0.0) * 1000, 3
                )
                fields = {
                    "round": epoch,
                    "round_ms": round(elapsed * 1000, 3),
                    "phases_ms": phases_ms,
                }
                # session-owned extras (hist_comm lowering + per-round
                # collective bytes/ms on a mesh — see booster.py)
                fields.update(get_round_fields())
                if self.fold is not None:
                    fields["fold"] = self.fold
                if self.num_rows and elapsed > 0:
                    fields["rows_per_sec"] = round(self.num_rows / elapsed, 1)
                emit_metric("training.round", **fields)
            if self.log_every and (epoch + 1) % self.log_every == 0:
                recent = self._times[-self.log_every :]
                mean = sum(recent) / len(recent)
                msg = "round {}: {:.1f} ms/round".format(epoch, mean * 1000)
                if self.num_rows and mean > 0:
                    msg += " ({:.2f}M rows/sec)".format(
                        self.num_rows / mean / 1e6
                    )
                logger.info(msg)
        self._last = now
        return False

    def after_training(self, model):
        if self._recorder is not None:
            pop_recorder(self._recorder)
            self._recorder = None
        if self._times:
            total = sum(self._times)
            p50 = percentile(self._times, 0.5)
            p95 = percentile(self._times, 0.95)
            # guard: a ~0 total (trivial data, coarse clocks) must not divide
            rate = len(self._times) / total if total > 0 else float("inf")
            logger.info(
                "trained %d rounds in %.2fs (%.2f rounds/sec, "
                "p50 %.1f ms, p95 %.1f ms)",
                len(self._times),
                total,
                rate,
                p50 * 1000,
                p95 * 1000,
            )
            if self.emit_structured:
                fields = {
                    "rounds": len(self._times),
                    "total_s": round(total, 3),
                    "p50_ms": round(p50 * 1000, 3),
                    "p95_ms": round(p95 * 1000, 3),
                }
                if self.fold is not None:
                    fields["fold"] = self.fold
                emit_metric("training.summary", **fields)
        return model


@contextlib.contextmanager
def xla_trace():
    """Capture a JAX profiler trace when SM_PROFILER_TRACE_DIR is set."""
    trace_dir = os.environ.get(TRACE_DIR_ENV)
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("Wrote XLA profiler trace to %s", trace_dir)
