"""First-class training profiling (SURVEY.md §5: the reference's only tracing
was wall-clock tracker logs; smdebug was installed but disabled).

Two light-weight hooks:

* ``RoundTimer`` — per-round wall time + throughput, logged every
  ``log_every`` rounds and summarized at end of training.
* ``xla_trace`` — context manager around training that writes a JAX profiler
  trace (TensorBoard-viewable) when ``SM_PROFILER_TRACE_DIR`` is set.
"""

import contextlib
import logging
import os
import time

logger = logging.getLogger(__name__)

TRACE_DIR_ENV = "SM_PROFILER_TRACE_DIR"


class RoundTimer:
    def __init__(self, num_rows=None, log_every=10):
        self.num_rows = num_rows
        self.log_every = log_every
        self._last = None
        self._times = []

    def before_training(self, model):
        self._last = time.perf_counter()
        return model

    def after_iteration(self, model, epoch, evals_log):
        now = time.perf_counter()
        if self._last is not None:
            self._times.append(now - self._last)
            if self.log_every and (epoch + 1) % self.log_every == 0:
                recent = self._times[-self.log_every :]
                mean = sum(recent) / len(recent)
                msg = "round {}: {:.1f} ms/round".format(epoch, mean * 1000)
                if self.num_rows:
                    msg += " ({:.2f}M rows/sec)".format(
                        self.num_rows / mean / 1e6
                    )
                logger.info(msg)
        self._last = now
        return False

    def after_training(self, model):
        if self._times:
            total = sum(self._times)
            logger.info(
                "trained %d rounds in %.2fs (%.2f rounds/sec)",
                len(self._times),
                total,
                len(self._times) / total,
            )
        return model


@contextlib.contextmanager
def xla_trace():
    """Capture a JAX profiler trace when SM_PROFILER_TRACE_DIR is set."""
    trace_dir = os.environ.get(TRACE_DIR_ENV)
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("Wrote XLA profiler trace to %s", trace_dir)
