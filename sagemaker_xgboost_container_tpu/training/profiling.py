"""First-class training profiling (SURVEY.md §5: the reference's only tracing
was wall-clock tracker logs; smdebug was installed but disabled).

Three light-weight hooks:

* ``RoundTimer`` — per-round wall time + throughput. Always feeds the
  telemetry layer: every round emits one structured JSON stdout record
  (``training.round``) carrying the round latency and a per-phase breakdown
  (the span recorder drains into it), and observes the
  ``training_round_seconds`` registry histogram. Human-readable per-round
  log lines stay opt-in via ``log_every`` (SM_ROUND_TIMING); the end-of-run
  summary reports mean, p50, and p95.
* ``xla_trace`` — context manager around training that writes a JAX profiler
  trace (TensorBoard-viewable) when ``SM_PROFILER_TRACE_DIR`` is set.
* the span API (``telemetry.span``) — algorithm_train wraps data ingest,
  the boosting loop, and model save in named phases.
"""

import contextlib
import logging
import os
import time

from ..telemetry import (
    REGISTRY,
    ROUND_STATE,
    compile_stats,
    emit_metric,
    get_round_fields,
    pop_recorder,
    push_recorder,
    tracing,
)
from ..telemetry import percentile  # noqa: F401  (canonical home: telemetry.registry)
from ..telemetry import device as device_telemetry
from ..utils.envconfig import env_int
from ..utils.faults import fault_point

logger = logging.getLogger(__name__)

TRACE_DIR_ENV = "SM_PROFILER_TRACE_DIR"

#: emit a rolling ``training.attribution`` record every N rounds (0 = only
#: the final one at after_training) — a week-long job surfaces attribution
#: mid-flight instead of only at the end, and /status reads the same data
ATTRIBUTION_EVERY_ENV = "SM_ATTRIBUTION_EVERY"

ROUND_HISTOGRAM = "training_round_seconds"


class RoundTimer:
    """Per-round timing callback; rides the standard booster protocol.

    ``emit_structured`` controls the per-round ``training.round`` stdout
    record (default on; SM_STRUCTURED_METRICS=false silences it globally).
    ``log_every=0`` disables the human-readable per-round log lines while
    keeping the structured emission and the end-of-run summary.
    ``fold`` tags every record in k-fold CV runs (each fold trains its own
    callback stack, so per-epoch records from different folds must stay
    distinguishable for the CloudWatch regexes).
    """

    def __init__(self, num_rows=None, log_every=10, emit_structured=True, fold=None):
        self.num_rows = num_rows
        self.log_every = log_every
        self.emit_structured = emit_structured
        self.fold = fold
        self._attr_every = env_int(ATTRIBUTION_EVERY_ENV, 0, minimum=0)
        # HBM watermark cadence (SM_DEVICE_TELEMETRY + SM_HBM_SAMPLE_EVERY):
        # 0 when the device plane is unarmed — resolved once here so the
        # per-round path never reads env
        self._hbm_every = device_telemetry.sample_cadence()
        self._last = None
        self._times = []
        self._recorder = None
        self._round_span = None
        self._compile_base = None
        self._compile_total_s = 0.0
        self._phase_totals = {}

    def before_training(self, model):
        self._last = time.perf_counter()
        # collect span phases (checkpoint saves, eval monitor, ...) per round;
        # popped in after_training. Thread-local, so parallel fold loops on
        # other threads never cross-talk.
        self._recorder = push_recorder()
        # per-round compile accounting: XLA compiles completed during a
        # round (the jax.monitoring listener feeds compile_stats) become a
        # `compile` phase key instead of silently inflating build_eval
        self._compile_base = compile_stats()["seconds"]
        self._compile_total_s = 0.0
        self._phase_totals = {}
        if tracing.enabled():
            # per-round ROOT span: stays open for the whole round, so the
            # phase spans (checkpoint, consensus, eval_monitor, ...) and the
            # booster's dispatch/collective/compile spans nest under it
            self._round_span = tracing.start_span("round")
        return model

    def after_iteration(self, model, epoch, evals_log):
        # chaos hook: the one per-round fault point every training run owns
        # (RoundTimer is always in the stack) — lets drills stall a round
        # (watchdog tests) or deliver SIGTERM mid-training deterministically
        fault_point("training.round_end", round=epoch)
        now = time.perf_counter()
        if self._last is not None:
            elapsed = now - self._last
            self._times.append(elapsed)
            REGISTRY.histogram(
                ROUND_HISTOGRAM, help="Boosting round wall time"
            ).observe(elapsed)
            # feed the cluster heartbeat's round state (telemetry/cluster.py):
            # a deque append under a lock — negligible, so always on
            ROUND_STATE.note_round(epoch, elapsed)
            if self._hbm_every and epoch % self._hbm_every == 0:
                # per-round HBM watermark (shares the cached device-memory
                # walk with the heartbeat plane; ships to rank 0 with the
                # next span frame)
                device_telemetry.sample_watermark(epoch)
            phases = self._recorder.drain() if self._recorder is not None else {}
            compile_now = compile_stats()["seconds"]
            compile_delta = (
                max(compile_now - self._compile_base, 0.0)
                if self._compile_base is not None
                else 0.0
            )
            self._compile_base = compile_now
            self._compile_total_s += compile_delta
            # NOTE: a compile that completes inside a fenced dispatch is
            # already subtracted from the host_dispatch phase at the source
            # (booster._maybe_fenced_dispatch measures the exact overlap),
            # so compile + host_dispatch + build_eval sum without double
            # counting; values only clamp here against float noise
            for name, seconds in phases.items():
                self._phase_totals[name] = (
                    self._phase_totals.get(name, 0.0) + seconds
                )
            if self.emit_structured:
                # callback work is measured by its spans; XLA compiles that
                # completed this round get their own key; the remainder is
                # device compute: binning (first round), tree build, eval.
                # One record per round — the CloudWatch-regex contract.
                overhead = sum(phases.values())
                phases_ms = {
                    k: round(max(v, 0.0) * 1000, 3)
                    for k, v in sorted(phases.items())
                }
                if compile_delta > 0:
                    phases_ms["compile"] = round(compile_delta * 1000, 3)
                phases_ms["build_eval"] = round(
                    max(elapsed - overhead - compile_delta, 0.0) * 1000, 3
                )
                fields = {
                    "round": epoch,
                    "round_ms": round(elapsed * 1000, 3),
                    "phases_ms": phases_ms,
                }
                # session-owned extras (hist_comm lowering + per-round
                # collective bytes/ms on a mesh — see booster.py)
                fields.update(get_round_fields())
                if self.fold is not None:
                    fields["fold"] = self.fold
                if self.num_rows and elapsed > 0:
                    fields["rows_per_sec"] = round(self.num_rows / elapsed, 1)
                emit_metric("training.round", **fields)
            if (
                self.emit_structured
                and self._attr_every
                and (epoch + 1) % self._attr_every == 0
            ):
                self._emit_attribution(
                    sum(self._times), rolling=True, round_index=epoch
                )
            if self.log_every and (epoch + 1) % self.log_every == 0:
                recent = self._times[-self.log_every :]
                mean = sum(recent) / len(recent)
                msg = "round {}: {:.1f} ms/round".format(epoch, mean * 1000)
                if self.num_rows and mean > 0:
                    msg += " ({:.2f}M rows/sec)".format(
                        self.num_rows / mean / 1e6
                    )
                logger.info(msg)
        if self._round_span is not None:
            # RoundTimer is last in the callback stack, so every phase span
            # of round `epoch` has already closed under this span; rotate
            tracing.finish_span(self._round_span, round=epoch)
            self._round_span = tracing.start_span("round")
        self._last = now
        return False

    def after_training(self, model):
        if self._round_span is not None:
            # the span opened after the last round covers post-training
            # callback work (final checkpoint flush, early-stopping trim)
            tracing.finish_span(self._round_span, tail=True)
            self._round_span = None
        if self._recorder is not None:
            pop_recorder(self._recorder)
            self._recorder = None
        if self._times:
            total = sum(self._times)
            p50 = percentile(self._times, 0.5)
            p95 = percentile(self._times, 0.95)
            # guard: a ~0 total (trivial data, coarse clocks) must not divide
            rate = len(self._times) / total if total > 0 else float("inf")
            logger.info(
                "trained %d rounds in %.2fs (%.2f rounds/sec, "
                "p50 %.1f ms, p95 %.1f ms)",
                len(self._times),
                total,
                rate,
                p50 * 1000,
                p95 * 1000,
            )
            if self.emit_structured:
                fields = {
                    "rounds": len(self._times),
                    "total_s": round(total, 3),
                    "p50_ms": round(p50 * 1000, 3),
                    "p95_ms": round(p95 * 1000, 3),
                }
                if self.fold is not None:
                    fields["fold"] = self.fold
                emit_metric("training.summary", **fields)
                self._emit_attribution(total)
                # roofline record (device plane): the measured device window
                # against the compiled cost — one record per training run
                device_ms, source = self._device_window_ms(total)
                extra = {"fold": self.fold} if self.fold is not None else None
                device_telemetry.maybe_roofline(
                    device_ms, len(self._times), source, emit=True, extra=extra
                )
        return model

    def _device_window_ms(self, total_s):
        """-> (device-window ms, source): the fenced ``device_sync`` span
        totals when SM_TRACE_DEVICE_SYNC was armed, else the residual of
        the round totals minus every instrumented host phase and compile —
        the same remainder the round records call ``build_eval``."""
        device_s = self._phase_totals.get("device_sync", 0.0)
        if device_s > 0:
            return device_s * 1000.0, "device_sync"
        residual = max(
            total_s - sum(self._phase_totals.values()) - self._compile_total_s,
            0.0,
        )
        return residual * 1000.0, "residual"

    def _emit_attribution(self, total_s, rolling=False, round_index=None):
        """One ``training.attribution`` record: where the run's wall time
        went — XLA compile (the jax.monitoring listener), host dispatch /
        device compute (the SM_TRACE_DEVICE_SYNC sampling spans), and the
        calibrated histogram collectives. Fields are 0.0 when the matching
        instrumentation wasn't armed, so the record shape is stable.

        ``rolling=True`` marks the SM_ATTRIBUTION_EVERY mid-job emissions
        (cumulative since the start of training — same shape, plus the
        round index) so CloudWatch regexes can tell them from the final
        after_training record."""
        comm_per_round = get_round_fields().get("hist_comm_ms") or 0.0
        fields = attribution_fields(
            total_ms=total_s * 1000.0,
            compile_ms=self._compile_total_s * 1000.0,
            host_ms=max(self._phase_totals.get("host_dispatch", 0.0), 0.0)
            * 1000.0,
            device_ms=self._phase_totals.get("device_sync", 0.0) * 1000.0,
            collective_ms=float(comm_per_round) * len(self._times),
        )
        fields["rounds"] = len(self._times)
        # mirror the roofline verdict (device plane; None when unarmed or
        # nothing introspected) so attribution says WHY the device share is
        # what it is, not just how big it is
        device_ms, source = self._device_window_ms(total_s)
        roofline = device_telemetry.maybe_roofline(
            device_ms, len(self._times), source
        )
        if roofline is not None:
            fields["roofline"] = {
                "binding": roofline["binding"],
                "achieved_flops_per_sec": roofline["achieved_flops_per_sec"],
                "achieved_bytes_per_sec": roofline["achieved_bytes_per_sec"],
                "operational_intensity": roofline["operational_intensity"],
            }
        if rolling:
            fields["rolling"] = True
        if round_index is not None:
            fields["round"] = round_index
        if self.fold is not None:
            fields["fold"] = self.fold
        emit_metric("training.attribution", **fields)
        # publish the same shape to the rank-0 /status endpoint (inert — a
        # dict update — when the fleet plane never starts)
        from ..telemetry import fleet

        fleet.note_attribution(fields)


def attribution_fields(total_ms, compile_ms, host_ms, device_ms, collective_ms):
    """The shared compile/host/device/collective attribution shape — stable
    keys for CloudWatch regexes, used by both the ``training.attribution``
    record and bench.py's ``attribution`` section. Percentages are shares of
    ``total_ms`` (0.0 when the window is empty)."""

    def pct(ms):
        return round(ms / total_ms * 100.0, 1) if total_ms > 0 else 0.0

    return {
        "total_ms": round(total_ms, 3),
        "compile_ms": round(compile_ms, 3),
        "host_ms": round(host_ms, 3),
        "device_ms": round(device_ms, 3),
        "collective_ms": round(collective_ms, 3),
        "compile_pct": pct(compile_ms),
        "host_pct": pct(host_ms),
        "device_pct": pct(device_ms),
        "collective_pct": pct(collective_ms),
    }


@contextlib.contextmanager
def xla_trace():
    """Capture a JAX profiler trace when SM_PROFILER_TRACE_DIR is set.

    Hardened: the trace is diagnostics, never a correctness dependency — the
    directory is created when missing, and a profiler that refuses to start
    (already-active session, unwritable volume) or to stop logs a warning
    and lets training proceed/finish. A successful capture emits one
    ``training.trace`` record carrying the output path, so the artifact is
    discoverable from the job log alone.
    """
    trace_dir = os.environ.get(TRACE_DIR_ENV)
    if not trace_dir:
        yield
        return
    import jax

    started = False
    try:
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        started = True
    except Exception as e:
        logger.warning(
            "could not start XLA profiler trace in %s (%s); training "
            "continues untraced",
            trace_dir,
            e,
        )
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                logger.warning(
                    "XLA profiler stop_trace failed (%s); trace in %s may "
                    "be incomplete",
                    e,
                    trace_dir,
                )
            else:
                logger.info("Wrote XLA profiler trace to %s", trace_dir)
                emit_metric("training.trace", trace_dir=trace_dir)
