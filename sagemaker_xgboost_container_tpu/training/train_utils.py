"""Metric-list plumbing between HPO config, native metrics, and feval.

Reference: algorithm_mode/train_utils.py:25-112. The union of the HPO tuning
metric and user eval_metric is sorted for cross-host determinism, then split
into natively-computed metrics vs sklearn feval metrics.
"""

import os

from ..metrics.custom_metrics import configure_feval, get_custom_metrics

HPO_SEPARATOR = ":"


class MetricNameComponents:
    """Decodes ``validation:auc[:freq]`` tuning-objective names."""

    def __init__(self, data_segment, metric_name, emission_frequency=None):
        self.data_segment = data_segment
        self.metric_name = metric_name
        self.emission_frequency = emission_frequency

    @classmethod
    def decode(cls, tuning_objective_metric):
        return cls(*tuning_objective_metric.split(HPO_SEPARATOR))


def get_union_metrics(metric_a, metric_b):
    """Sorted union (order must agree across hosts)."""
    if metric_a is None and metric_b is None:
        return None
    if metric_a is None:
        return metric_b
    if metric_b is None:
        return metric_a
    return sorted(set(metric_a) | set(metric_b))


def get_eval_metrics_and_feval(tuning_objective_metric_param, eval_metric):
    """-> (native metric list, configured feval or None, tuning metric list)."""
    tuning_objective_metric = None
    configured_feval = None
    cleaned_eval_metrics = None

    if tuning_objective_metric_param is not None:
        components = MetricNameComponents.decode(tuning_objective_metric_param)
        tuning_objective_metric = components.metric_name.split(",")

    union = get_union_metrics(tuning_objective_metric, eval_metric)
    if union is not None:
        feval_metrics = get_custom_metrics(union)
        if feval_metrics:
            configured_feval = configure_feval(feval_metrics)
            cleaned_eval_metrics = [m for m in union if m not in set(feval_metrics)]
        else:
            cleaned_eval_metrics = union

    return cleaned_eval_metrics, configured_feval, tuning_objective_metric


def cleanup_dir(directory, file_prefix):
    """Remove files in ``directory`` that don't start with ``file_prefix``."""
    for name in os.listdir(directory):
        path = os.path.join(directory, name)
        if os.path.isfile(path) and not name.startswith(file_prefix):
            try:
                os.remove(path)
            except OSError:
                pass
