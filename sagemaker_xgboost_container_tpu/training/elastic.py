"""Elastic shrink-to-continue: survivor re-rendezvous and resharded resume.

The supervision stack (PR 3/5/7) turned a dead host from a silent deadlock
into a *clean, attributable* job failure: stale-heartbeat detection, a
coordinated abort broadcast, exit 80, restart at the original world size.
But a host that is truly gone never comes back — the restarted job waits at
the rendezvous for a peer that no longer exists, and a multi-hour
north-star run dies with it. This module composes the existing ingredients
into actual fault *tolerance*:

1. **Detection -> shrink decision.** When rank 0's heartbeat aggregator
   declares a host stale (``telemetry/cluster.py``), the decision hook
   (``training/watchdog.handle_stale_host``) consults this module: with
   ``SM_ELASTIC=1`` and the floors satisfied (``SM_ELASTIC_MIN_HOSTS``
   survivors remaining, fewer than ``SM_ELASTIC_MAX_SHRINKS`` shrinks so
   far) rank 0 *proposes a survivor set* instead of plain exit 80. The
   legacy coordinated abort is untouched when the gate is closed.
2. **Shrink fan-out.** The proposal rides the existing abort channel — one
   frame per survivor carrying ``verb: "shrink"``, the survivor host list,
   and a monotonically increasing *generation* — so no new listener socket
   or port is introduced and the abort plane's idempotence (duplicate-frame
   suppression, first-wins dispatch) covers racing detections for free.
3. **Re-rendezvous.** Every survivor finishes its in-flight round (the
   :class:`ElasticMembershipCallback` raises :class:`ReformRequested` at
   the round boundary — that IS the drain), tears down the heartbeat/abort
   planes, and re-runs the bounded rendezvous handshake over the survivor
   list (``parallel/distributed.reform_cluster``: retried, deadline-bounded,
   fault point ``rendezvous.reform``). A reform that cannot complete aborts
   every survivor with the distinct ``EXIT_REFORM_FAILED`` (82) and a
   flight-recorder dump — restart then resumes at the *old* membership.
4. **Resharded resume.** The caller's ``train_once`` (train_job) reloads
   the last digest-verified checkpoint and rebuilds the booster session on
   the new, smaller mesh — rows rebin/repartition over the shrunken data
   axis as a consequence of the rebuilt session, under the SAME
   ``hist_knobs`` snapshot as the original session (no mid-job env drift).
   ``utils/integrity.validate_resume`` accepts the ``world_size``
   fingerprint drift because this module *records the transition*: an
   append-only ``membership_log`` (old/new size, epoch, reason, surviving
   ranks, generation) stamped into every subsequent checkpoint manifest,
   which later resumes — and operators — validate against.

Everything is env-gated and inert by default: ``SM_ELASTIC`` unset means no
callback in the stack, no state, and byte-identical legacy behavior (the
same kill still produces the coordinated exit 80).
"""

import logging
import threading

from ..constants import EXIT_CLUSTER_ABORT, EXIT_REFORM_FAILED
from ..telemetry import REGISTRY
from ..telemetry.emit import emit_metric
from ..utils.envconfig import env_bool, env_float, env_int

logger = logging.getLogger(__name__)

ELASTIC_ENV = "SM_ELASTIC"
ELASTIC_MIN_HOSTS_ENV = "SM_ELASTIC_MIN_HOSTS"
ELASTIC_MAX_SHRINKS_ENV = "SM_ELASTIC_MAX_SHRINKS"
REFORM_TIMEOUT_ENV = "SM_REFORM_TIMEOUT_S"
REFORM_DRAIN_TIMEOUT_ENV = "SM_REFORM_DRAIN_TIMEOUT_S"


class ElasticConfig:
    """Snapshot of the elastic knobs, resolved ONCE at session build
    (``register_cluster``) so no decision path re-reads env mid-job — the
    same trace-env-read discipline as the histogram knob snapshot."""

    def __init__(
        self, enabled, min_hosts, max_shrinks, reform_timeout_s, drain_timeout_s
    ):
        self.enabled = bool(enabled)
        self.min_hosts = int(min_hosts)
        self.max_shrinks = int(max_shrinks)
        self.reform_timeout_s = float(reform_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)

    def __repr__(self):
        return (
            "ElasticConfig(enabled={}, min_hosts={}, max_shrinks={}, "
            "reform_timeout_s={}, drain_timeout_s={})".format(
                self.enabled,
                self.min_hosts,
                self.max_shrinks,
                self.reform_timeout_s,
                self.drain_timeout_s,
            )
        )


def resolve_elastic_config():
    """Read the elastic knobs (clamped, warn-once via envconfig)."""
    return ElasticConfig(
        enabled=env_bool(ELASTIC_ENV, False),
        min_hosts=env_int(ELASTIC_MIN_HOSTS_ENV, 1, minimum=1),
        max_shrinks=env_int(ELASTIC_MAX_SHRINKS_ENV, 2, minimum=0, maximum=64),
        reform_timeout_s=env_float(REFORM_TIMEOUT_ENV, 60.0, minimum=1.0, maximum=3600.0),
        drain_timeout_s=env_float(
            REFORM_DRAIN_TIMEOUT_ENV, 300.0, minimum=1.0, maximum=7200.0
        ),
    )


class ReformRequested(Exception):
    """Raised at a round boundary by :class:`ElasticMembershipCallback` to
    unwind the training loop for a membership reform. Carries everything
    ``perform_reform`` needs; never escapes ``supervised_train``."""

    def __init__(self, survivors, reason, generation, epoch=None):
        self.survivors = sorted(survivors)
        self.reason = str(reason)
        self.generation = int(generation)
        self.epoch = epoch
        super(ReformRequested, self).__init__(
            "membership reform requested (generation {}, reason {}): "
            "survivors {}".format(generation, reason, self.survivors)
        )


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.hosts = None
        self.current_host = None
        self.config = None
        self.peer_addrs = None  # {host: (addr, port)} — loopback drills only
        self.generation = 0
        self.shrinks = 0
        self.membership_log = []
        self.pending = None
        self.draining = False  # a reform is past the drain (being executed)
        self.drain_timer = None


_state = _State()


def register_cluster(hosts, current_host, config=None, peer_addrs=None):
    """Record membership + resolve the elastic config snapshot.

    Called once at session build from ``algorithm_train._pre_exec`` on every
    participant (and by drills with explicit loopback ``peer_addrs``).
    """
    with _state.lock:
        _state.hosts = sorted(hosts)
        _state.current_host = current_host
        _state.config = config if config is not None else resolve_elastic_config()
        _state.peer_addrs = dict(peer_addrs) if peer_addrs else None
        world = len(_state.hosts)
        cfg = _state.config
    REGISTRY.gauge(
        "cluster_world_size", "Hosts in the current (possibly shrunken) membership"
    ).set(world)
    if cfg.enabled:
        logger.info(
            "elastic membership armed: world size %d, floor %d host(s), at "
            "most %d shrink(s), reform deadline %.0fs",
            world, cfg.min_hosts, cfg.max_shrinks, cfg.reform_timeout_s,
        )
    return cfg


def _reset_for_tests():
    global _state
    with _state.lock:
        timer = _state.drain_timer
    if timer is not None:
        timer.cancel()
    _state = _State()


def is_active():
    with _state.lock:
        return _state.hosts is not None and _state.config is not None and _state.config.enabled


def current_hosts():
    with _state.lock:
        return list(_state.hosts) if _state.hosts else None


def world_size():
    with _state.lock:
        return len(_state.hosts) if _state.hosts else 0


def generation():
    with _state.lock:
        return _state.generation


def membership_log():
    """Append-only transition log (copies): one entry per completed shrink,
    stamped into every subsequent checkpoint manifest."""
    with _state.lock:
        return [dict(t) for t in _state.membership_log]


def peer_addrs():
    """{host: (addr, port)} override map for loopback drills, or None —
    production resolves hostnames and the default abort port."""
    with _state.lock:
        return dict(_state.peer_addrs) if _state.peer_addrs else None


# ----------------------------------------------------------- shrink decision
def propose_survivors(stale_host):
    """Rank 0's shrink proposal for a stale host, or None with the reason
    the legacy exit-80 path applies. Reads only the resolved snapshot."""
    with _state.lock:
        hosts = list(_state.hosts or [])
        cfg = _state.config
        shrinks = _state.shrinks
    if cfg is None or not cfg.enabled:
        return None
    if stale_host not in hosts:
        logger.info(
            "elastic: stale host %s is not in the current membership %s "
            "(already shrunk away); ignoring", stale_host, hosts,
        )
        return None
    survivors = [h for h in hosts if h != stale_host]
    if len(survivors) < cfg.min_hosts:
        logger.warning(
            "elastic: cannot shrink below the %d-host floor (%s survivors "
            "would remain); falling back to the coordinated abort",
            cfg.min_hosts, len(survivors),
        )
        return None
    if shrinks >= cfg.max_shrinks:
        logger.warning(
            "elastic: shrink budget exhausted (%d of %d); falling back to "
            "the coordinated abort", shrinks, cfg.max_shrinks,
        )
        return None
    return survivors


def coordinate_shrink(survivors, reason, epoch=None, **fields):
    """Rank 0: fan the shrink proposal out over the abort channel, then arm
    the local reform. Returns the pending request.

    The frame goes to EVERY current member except this host — survivors
    re-form, and an excluded host that turns out to be alive (false-stale:
    transient partition, GC pause) learns it was declared dead and exits 80
    through ``on_shrink_frame``'s excluded branch instead of zombie-training
    at the old membership. The frame carries ``verb: "shrink"``, the
    survivor list, and the next generation; the abort listener's
    duplicate-frame suppression makes racing detections deliver exactly one
    reform per generation.
    """
    from ..parallel.distributed import broadcast_abort

    with _state.lock:
        current_host = _state.current_host
        hosts = list(_state.hosts or [])
        gen = _state.generation + 1
        peer_addrs = dict(_state.peer_addrs or {}) or None
    extra = {
        "verb": "shrink",
        "survivors": sorted(survivors),
        "generation": gen,
    }
    peers = [h for h in hosts if h != current_host]
    delivered = broadcast_abort(
        peers, reason, source=current_host, extra=extra, peer_addrs=peer_addrs
    )
    logger.warning(
        "elastic shrink (generation %d, reason %s): notified %d/%d "
        "members; dropping to world size %d",
        gen, reason, delivered, len(peers), len(survivors),
    )
    request_reform(survivors, reason, generation=gen, epoch=epoch, **fields)
    return pending_reform()


def on_shrink_frame(msg):
    """Survivor side of the fan-out (wired from ``watchdog._on_abort_frame``
    for frames carrying the shrink verb)."""
    survivors = msg.get("survivors")
    if not isinstance(survivors, list) or not survivors:
        logger.warning("elastic: ignoring shrink frame without survivors: %r", msg)
        return
    with _state.lock:
        current_host = _state.current_host
    if current_host is not None and current_host not in survivors:
        # the proposer declared US dead (asymmetric partition / clock skew):
        # there is no membership to continue in — exit through the legacy
        # coordinated-abort path so the platform restarts this host
        from . import watchdog

        logger.error(
            "elastic: shrink frame excludes this host (%s not in %s); "
            "aborting with the cluster exit code", current_host, survivors,
        )
        watchdog.request_abort(
            "shrunk_away", EXIT_CLUSTER_ABORT, source=msg.get("source")
        )
        return
    request_reform(
        survivors,
        msg.get("reason", "shrink"),
        generation=msg.get("generation"),
    )


def request_reform(survivors, reason, generation=None, epoch=None, **fields):
    """Arm a pending reform; idempotent per generation (a duplicate or
    stale-generation request is a logged no-op). Thread-safe — callers are
    the aggregator thread (rank 0) and the abort-listener thread (peers);
    the training thread consumes via :func:`pending_reform`."""
    with _state.lock:
        if _state.hosts is None:
            logger.warning(
                "elastic: reform requested but no cluster is registered; ignoring"
            )
            return False
        gen = int(generation) if generation is not None else _state.generation + 1
        if gen <= _state.generation:
            logger.info(
                "elastic: ignoring reform request for past generation %d "
                "(current %d)", gen, _state.generation,
            )
            return False
        if _state.pending is not None and _state.pending["generation"] >= gen:
            logger.info(
                "elastic: reform already pending (generation %d); ignoring "
                "duplicate request", _state.pending["generation"],
            )
            return False
        _state.pending = {
            "survivors": sorted(survivors),
            "reason": str(reason),
            "generation": gen,
            "epoch": epoch,
        }
        _state.pending.update(fields)
        _state.draining = False
        drain_timeout = (
            _state.config.drain_timeout_s if _state.config is not None else 300.0
        )
        # the drain-deadline demotion: the drain point is the next round
        # boundary, but a survivor wedged INSIDE a jitted collective (the
        # dead host was mid-psum with us) never reaches one. Without this
        # timer the elastic gate would turn the legacy fail-fast exit 80
        # into an indefinite hang — strictly worse than SM_ELASTIC unset.
        # Every rank arms its own timer when its reform arms; consumption
        # (perform_reform starting) disarms it.
        timer = threading.Timer(drain_timeout, _drain_deadline_expired, args=(gen,))
        timer.daemon = True
        _state.drain_timer = timer
    timer.start()
    logger.warning(
        "elastic: reform armed (generation %d, reason %s); the training "
        "loop will drain the current round and re-rendezvous as %s "
        "(coordinated abort if the drain takes more than %.0fs — a wedged "
        "collective cannot drain)",
        gen, reason, sorted(survivors), drain_timeout,
    )
    return True


def _drain_deadline_expired(generation_armed):
    """Timer body: the reform armed at ``generation_armed`` was never
    consumed — this survivor is stuck inside a collective the dead host
    poisoned and will never reach a round boundary. Demote the shrink to
    the legacy coordinated-abort exit so the job fails fast and restarts
    at the old membership, exactly as with ``SM_ELASTIC`` unset."""
    with _state.lock:
        stale = (
            _state.pending is not None
            and _state.pending["generation"] == generation_armed
            and not _state.draining
        )
    if not stale:
        return
    from . import watchdog

    logger.error(
        "elastic: reform (generation %d) was never drained within the "
        "deadline — this rank is wedged in a collective; demoting the "
        "shrink to the coordinated abort", generation_armed,
    )
    watchdog.request_abort(
        "reform_drain_timeout", EXIT_CLUSTER_ABORT, generation=generation_armed
    )


def pending_reform():
    with _state.lock:
        return dict(_state.pending) if _state.pending is not None else None


# ------------------------------------------------------------ training hooks
class ElasticMembershipCallback:
    """Booster-protocol callback: the drain point of the shrink protocol.

    Sits after the checkpoint saver so the just-finished (consensus-passed)
    round lands on disk before the loop unwinds; raising at the round
    boundary IS the in-flight-work drain."""

    def after_iteration(self, model, epoch, evals_log):
        req = pending_reform()
        if req is not None:
            raise ReformRequested(
                req["survivors"], req["reason"], req["generation"], epoch=epoch
            )
        return False


def maybe_elastic_callback():
    """-> an ElasticMembershipCallback when the plane is armed, else None."""
    return ElasticMembershipCallback() if is_active() else None


def drain_callbacks(callbacks):
    """Best-effort teardown of a callback stack abandoned by a reform:
    stop every thread-owning callback (round watchdog monitor, checkpoint
    deleter) so the old generation can't fire a stale exit-79 or hold the
    checkpoint dir while the new generation rebuilds."""
    for cb in callbacks or []:
        inner = getattr(cb, "inner", cb)
        stop = getattr(inner, "stop", None)
        if callable(stop):
            try:
                stop()
            except Exception:
                logger.exception("elastic drain: error stopping %r", inner)


# ------------------------------------------------------------------- reform
def perform_reform(req, on_reform=None, master_addr=None, port=None):
    """Execute one membership reform on the training thread.

    Drain (tear down the heartbeat/abort planes, settle checkpoints), then
    the retried survivor re-rendezvous, then commit: membership + generation
    + the append-only transition record, telemetry, and consensus
    re-registration. ``on_reform(new_hosts, current_host)`` is the caller's
    re-wiring hook (jax.distributed re-init, plane restarts). Any failure
    aborts this survivor with ``EXIT_REFORM_FAILED`` (82) — the abort path
    dumps the flight recorder, and the re-raise covers test harnesses that
    stub the hard exit.
    """
    from ..telemetry.tracing import trace_span

    with _state.lock:
        current_host = _state.current_host
        cfg = _state.config
        old_hosts = list(_state.hosts or [])
        # the drain happened: this rank reached a round boundary and is now
        # executing the reform — disarm the wedged-collective demotion
        _state.draining = True
        timer, _state.drain_timer = _state.drain_timer, None
    if timer is not None:
        timer.cancel()
    reason = req.reason
    try:
        with trace_span(
            "cluster.reform",
            attributes={
                "generation": req.generation,
                "reason": reason,
                "old_world_size": len(old_hosts),
                "new_world_size": len(req.survivors),
            },
        ):
            with trace_span("reform.drain"):
                _teardown_planes()
            with trace_span("reform.rendezvous"):
                from ..parallel.distributed import reform_cluster

                cluster, membership = reform_cluster(
                    req.survivors,
                    current_host,
                    req.generation,
                    timeout=cfg.reform_timeout_s if cfg else 60.0,
                    master_addr=master_addr,
                    port=port,
                )
            transition = _commit_transition(req, old_hosts)
            emit_metric("training.membership", **transition)
            REGISTRY.counter(
                "elastic_shrink_total",
                "Completed shrink-to-continue membership transitions",
                {"reason": reason},
            ).inc()
            REGISTRY.gauge(
                "cluster_world_size",
                "Hosts in the current (possibly shrunken) membership",
            ).set(len(req.survivors))
            from . import consensus

            consensus.register_cluster(req.survivors, current_host)
            if on_reform is not None:
                on_reform(list(req.survivors), current_host)
        logger.warning(
            "elastic: reform complete — training continues at world size %d "
            "(generation %d)", len(req.survivors), req.generation,
        )
        return cluster
    except Exception as e:
        logger.exception(
            "elastic: reform FAILED at generation %d (%s); aborting this "
            "survivor with exit %d — restart resumes at the old membership",
            req.generation, e, EXIT_REFORM_FAILED,
        )
        from . import watchdog

        watchdog.request_abort(
            "reform_failed",
            EXIT_REFORM_FAILED,
            generation=req.generation,
            survivors=list(req.survivors),
            error=str(e),
        )
        raise


def _teardown_planes():
    """Stop the per-generation control planes before re-rendezvous: the
    heartbeat sender/aggregator (its membership is the OLD world), the abort
    listener (rebound by the caller's re-wiring hook), and the checkpoint
    deleters (the resumed generation builds fresh savers)."""
    from ..telemetry.cluster import stop_cluster_telemetry

    stop_cluster_telemetry()
    from . import watchdog

    watchdog.stop_abort_plane()
    from . import checkpointing

    checkpointing.flush_checkpoints()


def _commit_transition(req, old_hosts):
    """Advance the membership state and append the transition record."""
    surviving_ranks = [
        old_hosts.index(h) for h in req.survivors if h in old_hosts
    ]
    with _state.lock:
        transition = {
            "event": "shrink",
            "generation": req.generation,
            "old_world_size": len(old_hosts),
            "new_world_size": len(req.survivors),
            "epoch": req.epoch,
            "reason": req.reason,
            "surviving_ranks": surviving_ranks,
            "hosts": list(req.survivors),
        }
        _state.membership_log.append(transition)
        _state.hosts = list(req.survivors)
        _state.generation = req.generation
        _state.shrinks += 1
        _state.pending = None
        _state.draining = False
    return dict(transition)


def _disarm_pending(why):
    """Cancel an armed-but-unconsumed reform (drain timer included).

    The normal-completion path: a shrink verdict that lands during or after
    the FINAL round has no remaining rounds to reform for — without this,
    the drain-deadline timer would exit-80 a successfully finished job in
    the middle of its model save. Returns the disarmed request, or None.
    """
    with _state.lock:
        pending, _state.pending = _state.pending, None
        timer, _state.drain_timer = _state.drain_timer, None
        _state.draining = False
    if timer is not None:
        timer.cancel()
    if pending is not None:
        logger.warning(
            "elastic: pending reform (generation %d) disarmed — %s",
            pending["generation"], why,
        )
    return pending


def supervised_train(train_once, on_reform=None, master_addr=None, reform_port=None):
    """Run ``train_once()`` under the elastic reform loop.

    ``train_once`` builds its callbacks (so each generation gets a fresh
    stack, re-reads the checkpoint, and rebuilds the booster session on the
    new mesh) and returns the trained model. On :class:`ReformRequested` the
    reform executes and the loop re-enters; with the plane inert this is a
    zero-cost passthrough. The loop is bounded by ``SM_ELASTIC_MAX_SHRINKS``
    via the shrink-decision gate, not here. A reform still pending when
    training returns normally (the shrink verdict raced the last round) is
    disarmed — there are no rounds left to reform for, and its drain timer
    must not fire into the post-training saves.
    """
    while True:
        try:
            result = train_once()
        except ReformRequested as req:
            logger.warning(
                "elastic: training unwound for reform at epoch %s "
                "(generation %d, reason %s)", req.epoch, req.generation, req.reason,
            )
            perform_reform(
                req, on_reform=on_reform, master_addr=master_addr, port=reform_port
            )
        else:
            _disarm_pending(
                "training completed before the drain point; no rounds remain"
            )
            return result
