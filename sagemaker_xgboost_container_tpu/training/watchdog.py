"""Failure-domain supervision: round watchdog + coordinated abort.

The jitted round step psums gradient histograms across every host in the
mesh, so a single wedged device or dead peer leaves *all* surviving ranks
blocked inside a collective forever — the job burns its full time budget
doing nothing, and spot-safe checkpoints never get their final flush. The
cluster telemetry plane (telemetry/cluster.py) can *see* the failure; this
module *acts* on it, closing the detect->decide->recover loop:

* **RoundWatchdog** — a booster-protocol callback plus a monitor thread.
  Every ``after_iteration`` pets the watchdog; if no round completes within
  ``SM_ROUND_DEADLINE_S`` the process flushes the checkpoint machinery,
  emits one ``training.abort`` record, and hard-exits with
  ``EXIT_ROUND_DEADLINE`` (79) so the platform restarts the job and
  ``load_checkpoint`` resumes at the last saved round.
* **request_abort** — the one local abort path, shared by the watchdog, the
  abort listener, and rank 0's stale-host decision. Idempotent: concurrent
  triggers (watchdog firing while an abort frame arrives) flush once and
  exit once.
* **abort plane** (``SM_ABORT_ON_STALE``) — every participating host runs an
  ``AbortListener`` (parallel/distributed.py); when rank 0's heartbeat
  aggregator declares a host stale it broadcasts one abort frame to every
  peer and aborts itself with ``EXIT_CLUSTER_ABORT`` (80), so the whole
  cluster exits cleanly instead of deadlocking in the psum.

The main thread is typically *inside* a jitted collective when any of this
fires, which is why the exit is ``os._exit`` from a supervisor thread:
there is no way to unwind a blocked XLA dispatch from Python.

Everything is env-gated and inert by default: no deadline -> no watchdog
thread; ``SM_ABORT_ON_STALE`` unset -> no listener socket.
"""

import logging
import os
import threading
import time

from ..constants import EXIT_CLUSTER_ABORT, EXIT_ROUND_DEADLINE
from ..constants import SM_MODEL_DIR as SM_MODEL_DIR_ENV
from ..telemetry.emit import emit_metric
from ..utils.envconfig import env_bool, env_float
from . import checkpointing

logger = logging.getLogger(__name__)

ROUND_DEADLINE_ENV = "SM_ROUND_DEADLINE_S"
ABORT_ON_STALE_ENV = "SM_ABORT_ON_STALE"

# test hook: chaos tests replace this to observe the exit instead of dying
_exit = os._exit

_abort_lock = threading.Lock()
_aborting = False


def round_deadline_s():
    return env_float(ROUND_DEADLINE_ENV, 0.0, minimum=0.0)


def abort_on_stale_enabled():
    return env_bool(ABORT_ON_STALE_ENV, False)


def request_abort(reason, exit_code, **fields):
    """Flush checkpoints, emit one ``training.abort`` record, hard-exit.

    Safe to call from any thread (and designed to be — the caller is a
    supervisor thread while the main thread is wedged). First caller wins;
    later triggers return immediately so racing supervisors can't
    double-flush or fight over the exit code.
    """
    global _aborting
    with _abort_lock:
        if _aborting:
            return
        _aborting = True
    logger.error(
        "ABORTING training (%s, exit code %d): flushing checkpoints and "
        "exiting so the platform can restart and resume", reason, exit_code
    )
    try:
        checkpointing.flush_checkpoints()
    except Exception:
        logger.exception("checkpoint flush during abort failed; exiting anyway")
    # post-mortem for the hung round: dump the flight recorder (last-N
    # finished spans + every still-open span, incl. the wedged round /
    # collective / consensus check) before the hard exit. SM_TRACE gated
    # and internally fail-safe — a broken disk cannot block the exit.
    # Without an explicit SM_TRACE_EXPORT_DIR the dump lands in a durable,
    # platform-uploaded location — the live checkpoint dir (same place the
    # flush above just settled), else the model dir — never only in a cwd
    # that dies with the container.
    try:
        from ..telemetry import tracing

        dump_dir = None
        dirs = checkpointing.active_checkpoint_dirs()
        if dirs:
            dump_dir = dirs[0]
        else:
            dump_dir = os.environ.get(SM_MODEL_DIR_ENV) or None
        dump_path = tracing.dump_flight_recorder(
            default_dir=dump_dir, reason=reason, exit_code=exit_code
        )
        if dump_path:
            fields = dict(fields, flight_recorder=dump_path)
    except Exception:
        logger.exception("flight-recorder dump failed; exiting anyway")
    emit_metric("training.abort", reason=reason, exit_code=exit_code, **fields)
    _exit(exit_code)


def _reset_abort_for_tests():
    global _aborting
    with _abort_lock:
        _aborting = False


class RoundWatchdog:
    """Deadline supervisor riding the booster callback protocol.

    ``before_training`` arms it (the first deadline window also covers the
    initial XLA compile — size ``SM_ROUND_DEADLINE_S`` accordingly);
    ``after_iteration`` pets it; ``after_training`` disarms it. The monitor
    thread wakes at ``deadline/4`` granularity, so detection latency is at
    most ~1.25x the deadline.
    """

    def __init__(self, deadline_s, on_expire=None, check_interval=None):
        self.deadline_s = float(deadline_s)
        self.on_expire = on_expire or self._default_expire
        self.check_interval = check_interval or max(self.deadline_s / 4.0, 0.05)
        self._last_pet = None
        self._round = -1
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # ----------------------------------------------------- callback protocol
    def before_training(self, model):
        with self._lock:
            self._last_pet = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="round-watchdog"
        )
        self._thread.start()
        logger.info(
            "round watchdog armed: abort if any round exceeds %.1fs",
            self.deadline_s,
        )
        return model

    def after_iteration(self, model, epoch, evals_log):
        with self._lock:
            self._last_pet = time.monotonic()
            self._round = epoch
        return False

    def after_training(self, model):
        self.stop()
        return model

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------- internals
    def _run(self):
        while not self._stop.wait(self.check_interval):
            with self._lock:
                last, rnd = self._last_pet, self._round
            if last is None:
                continue
            stalled = time.monotonic() - last
            if stalled > self.deadline_s:
                self.on_expire(rnd, stalled)
                return

    def _default_expire(self, last_round, stalled_s):
        logger.error(
            "round watchdog expired: no round completed for %.1fs "
            "(deadline %.1fs, last finished round %d) — device hang or dead "
            "peer stalling the collective",
            stalled_s,
            self.deadline_s,
            last_round,
        )
        request_abort(
            "round_deadline",
            EXIT_ROUND_DEADLINE,
            last_round=last_round,
            stalled_s=round(stalled_s, 1),
            deadline_s=self.deadline_s,
        )


def maybe_round_watchdog():
    """-> a RoundWatchdog when ``SM_ROUND_DEADLINE_S`` > 0, else None."""
    deadline = round_deadline_s()
    if deadline <= 0:
        return None
    return RoundWatchdog(deadline)


# ------------------------------------------------------------- abort plane
def _frame_exit_code(msg):
    """Exit code carried by an abort frame, defaulting to the cluster-abort
    code. Bounded to the supervision range (79-99) so a malformed/malicious
    frame can't make a rank exit 0 (platform would NOT restart it)."""
    try:
        code = int(msg.get("exit_code", EXIT_CLUSTER_ABORT))
    except (TypeError, ValueError):
        return EXIT_CLUSTER_ABORT
    return code if 79 <= code <= 99 else EXIT_CLUSTER_ABORT


def _on_abort_frame(msg):
    if msg.get("verb") == "shrink":
        # elastic membership: the frame proposes a survivor set instead of
        # demanding an exit — hand it to the shrink plane (which falls back
        # to a plain abort when this host was itself declared dead)
        from . import elastic

        elastic.on_shrink_frame(msg)
        return
    request_abort(
        str(msg.get("reason", "cluster_abort")),
        _frame_exit_code(msg),
        source=msg.get("source"),
    )


_listener_lock = threading.Lock()
_active_listener = None


def start_abort_plane(hosts, current_host, port=None):
    """Start this host's abort listener.

    Gated on ``SM_ABORT_ON_STALE`` — or on an armed elastic plane
    (``SM_ELASTIC``), whose shrink frames arrive over the same channel.
    Every participant — including rank 0, for one uniform code path — gets
    a listener; rank 0 additionally wires the heartbeat aggregator's
    stale-host detection to :func:`handle_stale_host` (telemetry/cluster.py).
    Returns the listener or None when the plane is disabled. The active
    listener is tracked so a membership reform can tear it down and rebind
    (:func:`stop_abort_plane`).
    """
    from . import elastic

    if not (abort_on_stale_enabled() or elastic.is_active()):
        return None
    if len(hosts) <= 1:
        return None
    from ..parallel.distributed import AbortListener

    stop_abort_plane()
    try:
        listener = AbortListener(handler=_on_abort_frame, port=port).start()
    except OSError as e:
        logger.warning(
            "abort listener could not bind (%s); this host will rely on the "
            "jax.distributed heartbeat timeout instead", e
        )
        return None
    logger.info(
        "abort listener up on port %d (host %s)", listener.port, current_host
    )
    global _active_listener
    with _listener_lock:
        _active_listener = listener
    return listener


def stop_abort_plane():
    """Stop the tracked abort listener (reform teardown / test cleanup)."""
    global _active_listener
    with _listener_lock:
        listener, _active_listener = _active_listener, None
    if listener is not None:
        try:
            listener.stop()
        except Exception:
            logger.exception("error stopping abort listener")


def handle_stale_host(hosts, current_host, stale_rank, stale_host, age_s):
    """Rank 0's detection -> action decision for a stale host.

    With the elastic plane armed and its floors satisfied
    (``SM_ELASTIC_MIN_HOSTS`` survivors, shrink budget left), propose a
    survivor set and shrink-to-continue; otherwise the legacy coordinated
    abort (exit 80) — byte-identical behavior when ``SM_ELASTIC`` is unset.

    One membership transition at a time: while a reform is already in
    flight, further stale verdicts are DEFERRED, not folded in — a second
    proposal before the first commits would reuse the same generation with
    a survivor set still containing the first dead host, dooming the
    rendezvous. The post-reform aggregator starts fresh over the survivors
    and re-detects a host that is still dead, triggering the next
    generation's shrink (or the legacy abort, if the floors say so).
    """
    from . import elastic

    if elastic.is_active() and elastic.pending_reform() is not None:
        logger.warning(
            "stale host %s (rank %d) detected while a membership reform is "
            "in flight; deferring — the re-formed cluster's aggregator "
            "re-detects it and decides at the next generation",
            stale_host, stale_rank,
        )
        return
    survivors = elastic.propose_survivors(stale_host)
    if survivors is not None:
        elastic.coordinate_shrink(
            survivors,
            "stale_host",
            stale_rank=stale_rank,
            stale_host=stale_host,
            age_s=round(age_s, 1),
        )
        return
    coordinate_abort(
        hosts,
        current_host,
        "stale_host",
        peer_addrs=elastic.peer_addrs(),
        stale_rank=stale_rank,
        stale_host=stale_host,
        age_s=round(age_s, 1),
    )


def coordinate_abort(
    hosts, current_host, reason, exit_code=EXIT_CLUSTER_ABORT, peer_addrs=None, **fields
):
    """Rank 0: broadcast one abort frame to every peer, then abort locally.

    ``exit_code`` rides inside the frame so every rank exits with the SAME
    distinguishing code (80 for stale-host aborts, 81 for consensus
    divergence) — the job log's exit code names the supervisor that fired
    no matter which rank's log you're reading. ``peer_addrs`` optionally
    maps hosts to (addr, port) pairs (loopback drills); production resolves
    hostnames on the default abort port.
    """
    from ..parallel.distributed import broadcast_abort

    peers = [h for h in hosts if h != current_host]
    delivered = broadcast_abort(
        peers, reason, source=current_host, exit_code=exit_code, peer_addrs=peer_addrs
    )
    logger.error(
        "coordinated abort (%s): notified %d/%d peers", reason, delivered, len(peers)
    )
    request_abort(reason, exit_code, peers_notified=delivered, **fields)
