"""Out-of-fold prediction recorder for repeated k-fold CV.

Parity with reference prediction_utils.py:25-118: accumulates validation-fold
predictions across repeats, aggregates (mean for regression/probability, mode
for class labels), and writes ``predictions.csv`` to SM_OUTPUT_DATA_DIR.
"""

import logging
import os

import numpy as np
from scipy import stats

from ..toolkit import exceptions as exc

PREDICTIONS_OUTPUT_FILE = "predictions.csv"
EXAMPLE_ROWS_EXCEPTION_COUNT = 100

logger = logging.getLogger(__name__)


class ValidationPredictionRecorder:
    def __init__(self, y_true, num_cv_round, classification, output_data_dir):
        self.y_true = np.asarray(y_true).copy()
        n = len(self.y_true)
        self.num_cv_round = num_cv_round
        self.y_pred = np.zeros((n, num_cv_round))
        self.y_prob = self.y_pred.copy() if classification else None
        self.cv_repeat_counter = np.zeros(n, dtype=int)
        self.classification = classification
        self.output_data_dir = output_data_dir
        self._pred_ndim = None

    def record(self, indices, predictions):
        predictions = np.asarray(predictions)
        if self._pred_ndim is None:
            self._pred_ndim = predictions.ndim
        elif self._pred_ndim != predictions.ndim:
            raise exc.AlgorithmError(
                "Expected predictions with ndim={}, got ndim={}.".format(
                    self._pred_ndim, predictions.ndim
                )
            )
        repeat_idx = self.cv_repeat_counter[indices]
        if np.any(repeat_idx == self.num_cv_round):
            rows = repeat_idx[repeat_idx == self.num_cv_round][:EXAMPLE_ROWS_EXCEPTION_COUNT]
            raise exc.AlgorithmError(
                "More than {} repeated predictions for same row were provided. "
                "Example row indices where this is the case: {}.".format(
                    self.num_cv_round, rows
                )
            )
        if self.classification:
            if predictions.ndim > 1:
                labels = np.argmax(predictions, axis=-1)
                proba = predictions[np.arange(len(labels)), labels]
            else:
                labels = (predictions > 0.5).astype(int)
                proba = predictions
            self.y_pred[indices, repeat_idx] = labels
            self.y_prob[indices, repeat_idx] = proba
        else:
            self.y_pred[indices, repeat_idx] = predictions
        self.cv_repeat_counter[indices] += 1

    def _aggregate(self):
        if not np.all(self.cv_repeat_counter == self.num_cv_round):
            rows = self.cv_repeat_counter[
                self.cv_repeat_counter != self.num_cv_round
            ][:EXAMPLE_ROWS_EXCEPTION_COUNT]
            raise exc.AlgorithmError(
                "For some rows number of repeated validation set predictions provided "
                "is not {}. Example row indices where this is the case: {}".format(
                    self.num_cv_round, rows
                )
            )
        columns = [self.y_true]
        if self.classification:
            columns.append(self.y_prob.mean(axis=-1))
            mode = stats.mode(self.y_pred, axis=1, keepdims=True).mode
            columns.append(mode[:, 0] if mode.ndim > 1 else mode)
        else:
            columns.append(self.y_pred.mean(axis=-1))
        return np.vstack(columns).T

    def save(self):
        os.makedirs(self.output_data_dir, exist_ok=True)
        path = os.path.join(self.output_data_dir, PREDICTIONS_OUTPUT_FILE)
        logger.info("Storing predictions on validation set(s) in %s", path)
        np.savetxt(path, self._aggregate(), delimiter=",", fmt="%f")
