from .algorithm_train import sagemaker_train, train_job  # noqa: F401
