"""Algorithm-mode training orchestration: ``sagemaker_train`` + ``train_job``.

The control flow mirrors the reference (algorithm_mode/train.py:116-500):
validate HPs and channels, load + validate data matrices, pick single-host vs
multi-host, run boosting with the callback stack, optionally repeated k-fold
CV with out-of-fold prediction recording, and save model(s) master-only under
the exact ``xgboost-model[-fold]`` names. Errors matching the known customer
substrings re-raise as UserError (reference :461-467).

The compute underneath is the XLA booster (models/booster.py); "use_dask_gpu_
training" is rejected up-front — the data-parallel TPU mesh subsumes that
path.
"""

import logging
import os

import numpy as np
from sklearn.model_selection import RepeatedKFold, RepeatedStratifiedKFold

from ..algorithm import channels as cv
from ..algorithm import hyperparameters as hpv
from ..algorithm import metrics as metrics_mod
from ..constants import CUSTOMER_ERRORS, MODEL_NAME
from ..data.content_types import get_content_type
from ..data.readers import (
    check_data_redundancy,
    get_data_matrix,
    get_size,
    validate_data_file_path,
)
from ..parallel import distributed
from ..telemetry import register_runtime_gauges, span, start_cluster_telemetry
from ..toolkit import exceptions as exc
from ..toolkit.channels import PIPE_MODE
from ..models import booster
from . import train_utils
from .callbacks import get_callbacks
from .prediction_utils import ValidationPredictionRecorder

logger = logging.getLogger(__name__)

SM_OUTPUT_DATA_DIR = "SM_OUTPUT_DATA_DIR"


def _streaming_plan(train_cfg, train_size, combine_train_val, is_pipe, num_hosts):
    """Decide whole-file vs chunked ingest. -> (use_streaming, max_bin, cfg).

    ``SM_INGEST_MODE=chunked`` forces the chunked path (and raises on an
    unsupported config rather than silently falling back); ``whole`` pins
    the legacy readers; ``auto`` streams a supported single-host job whose
    local channel exceeds one chunk. Multi-host ``auto`` stays on the
    whole-file path: the decision must be identical on every rank before
    any rendezvous exists, and local channel sizes (ShardedByS3Key) are
    not — forcing ``chunked`` via env is uniform by construction.
    """
    from ..data import streaming

    cfg = streaming.resolve_ingest_config()
    if train_cfg is None or is_pipe:
        # forced chunked must refuse, not silently fall back (the documented
        # contract — every other unsupported combination raises)
        if cfg.mode == "chunked":
            raise exc.UserError(
                "SM_INGEST_MODE=chunked is not supported for {}; use "
                "SM_INGEST_MODE=whole.".format(
                    "Pipe-mode input" if is_pipe
                    else "jobs without a validated training config"
                )
            )
        return False, None, cfg
    if cfg.mode == "whole":
        return False, None, cfg
    ok, why, max_bin = streaming.supports_streaming(train_cfg)
    if combine_train_val and ok:
        ok, why = False, "k-fold CV slices float features per fold"
    if cfg.mode == "chunked":
        if not ok:
            raise exc.UserError(
                "SM_INGEST_MODE=chunked is not supported for this job ({}); "
                "use SM_INGEST_MODE=whole or adjust the config.".format(why)
            )
        return True, max_bin, cfg
    # auto
    if not ok or num_hosts > 1:
        return False, None, cfg
    return train_size > cfg.chunk_bytes, max_bin, cfg


def get_validated_data_matrices(
    train_path, validate_path, content_type, csv_weights=0, is_pipe=False,
    combine_train_val=False, train_cfg=None, sm_hosts=None, sm_current_host=None,
):
    """Size/format-check both channels and parse them into DataMatrix objects.

    With the streaming plane armed (``_streaming_plan``) the channels ingest
    chunk-by-chunk into pre-binned matrices instead (``data/streaming.py``):
    the training channel builds the (rank-agreed) cuts, the validation
    channel bins with them. Failures of the chunked plane raise
    ``streaming.IngestError`` — the caller converts them to exit 85.
    """
    train_size = get_size(train_path, is_pipe) if train_path else 0
    val_size = get_size(validate_path, is_pipe) if validate_path else 0

    if not is_pipe:
        if train_size > 0:
            validate_data_file_path(train_path, content_type)
        if val_size > 0:
            validate_data_file_path(validate_path, content_type)

    num_hosts = len(sm_hosts) if sm_hosts else 1
    use_streaming, max_bin, _cfg = _streaming_plan(
        train_cfg, train_size, combine_train_val, is_pipe, num_hosts
    )
    if use_streaming:
        from ..data import streaming

        if streaming.channel_has_sidecars(content_type, train_path, validate_path):
            if _cfg.mode == "chunked":
                raise exc.UserError(
                    "SM_INGEST_MODE=chunked cannot honor libsvm .weight/"
                    ".group sidecar files; remove them or use "
                    "SM_INGEST_MODE=whole."
                )
            logger.info(
                "channel carries libsvm .weight/.group sidecar files; "
                "using the whole-file readers (chunked ingest cannot "
                "honor them)"
            )
            use_streaming = False
    if use_streaming:
        hosts = sm_hosts if num_hosts > 1 else None
        # job-scoped quarantine/budget state: a second ingest in this
        # process (local mode, an elastic-reform replay) must not inherit
        # the previous run's consumed skip budget or carry its quarantine
        # entries into this model's manifest
        streaming.reset_ingest_state()
        logger.info(
            "Streaming (chunked) channel ingest armed: max_bin=%d, %d host(s)",
            max_bin, num_hosts,
        )
        # every host joins the ingest exchange regardless of local channel
        # size (a data-less host contributes an empty sketch and returns
        # None) — peers must never hang waiting for its summary
        train_dmatrix = streaming.ingest_channel(
            train_path, content_type, max_bin, channel="train",
            csv_weights=csv_weights, hosts=hosts, current_host=sm_current_host,
        )
        val_dmatrix = None
        if validate_path is not None and (val_size > 0 or num_hosts > 1):
            val_dmatrix = streaming.ingest_channel(
                validate_path, content_type, max_bin, channel="validation",
                csv_weights=csv_weights,
                cut_points=train_dmatrix.cut_points if train_dmatrix else None,
                hosts=hosts, current_host=sm_current_host,
            )
            if train_dmatrix is None:
                # a train-data-less rank still joined the validation
                # exchange (peers must never hang waiting for it), but
                # without the agreed train cuts its local validation matrix
                # was re-sketched against itself — it must not leak into
                # eval; the rank exits via the existing no-data contract
                val_dmatrix = None
        return train_dmatrix, val_dmatrix, train_dmatrix

    train_dmatrix = (
        get_data_matrix(train_path, content_type, csv_weights=csv_weights, is_pipe=is_pipe)
        if train_size > 0
        else None
    )
    val_dmatrix = (
        get_data_matrix(validate_path, content_type, csv_weights=csv_weights, is_pipe=is_pipe)
        if val_size > 0
        else None
    )

    train_val_dmatrix = train_dmatrix
    if combine_train_val and train_dmatrix is not None and val_dmatrix is not None:
        logger.info("Read both train and validation data into one DataMatrix")
        train_val_dmatrix = train_dmatrix.concat(val_dmatrix)
    return train_dmatrix, val_dmatrix, train_val_dmatrix


def sagemaker_train(
    train_config,
    data_config,
    train_path,
    val_path,
    model_dir,
    sm_hosts,
    sm_current_host,
    checkpoint_config,
):
    """Validate config, load data, select execution mode, run train_job."""
    # XLA compile / RSS / device-buffer gauges: registered before any jax
    # work so the first compile is counted (adds no threads; jax-absent and
    # CPU-only paths no-op)
    register_runtime_gauges()
    metrics = metrics_mod.initialize()
    hyperparameters = hpv.initialize(metrics)
    validated_train_config = hyperparameters.validate(train_config)
    if validated_train_config.get("updater"):
        validated_train_config["updater"] = ",".join(validated_train_config["updater"])

    channels = cv.initialize()
    validated_data_config = channels.validate(data_config)

    file_type = get_content_type(validated_data_config["train"].get("ContentType"))
    input_mode = validated_data_config["train"].get("TrainingInputMode")
    csv_weights = validated_train_config.get("csv_weights", 0)
    is_pipe = input_mode == PIPE_MODE

    validation_channel = validated_data_config.get("validation", None)
    combine_train_val = "_kfold" in validated_train_config
    if val_path is not None:
        if train_path == val_path or os.path.basename(train_path) == os.path.basename(val_path):
            logger.warning(
                "Found same path for training and validation. This is not recommended "
                "and results may not be correct."
            )
        elif not is_pipe:
            check_data_redundancy(train_path, val_path)

    num_hosts = len(sm_hosts)
    checkpoint_dir = checkpoint_config.get("LocalPath", None)

    if validated_train_config.pop("use_dask_gpu_training", "false") == "true":
        raise exc.UserError(
            "use_dask_gpu_training is not available in the TPU container: there are no "
            "CUDA devices. Distributed training runs data-parallel over the TPU mesh "
            "automatically — remove this hyperparameter."
        )

    with span("data_ingest", emit=True):
        from ..data import streaming

        try:
            train_dmatrix, val_dmatrix, train_val_dmatrix = get_validated_data_matrices(
                train_path, val_path, file_type, csv_weights, is_pipe,
                combine_train_val, train_cfg=validated_train_config,
                sm_hosts=sm_hosts, sm_current_host=sm_current_host,
            )
        except streaming.IngestError as e:
            # the chunked plane's failure contract: every rank reached this
            # same verdict from the same allgathered state — flight-recorder
            # dump + EXIT_INGEST_FAILED (85) on all of them
            streaming.abort_on_ingest_failure(e)
            # only reachable when the exit is patched (tests): classify as
            # a platform failure so the failure file names the ingest error
            raise exc.PlatformError(str(e))
    missing_validation_data = validation_channel and not val_dmatrix

    train_args = dict(
        train_cfg=validated_train_config,
        train_dmatrix=train_dmatrix,
        val_dmatrix=val_dmatrix,
        train_val_dmatrix=train_val_dmatrix,
        model_dir=model_dir,
        checkpoint_dir=checkpoint_dir,
    )

    if num_hosts > 1:
        logger.info("Distributed node training with %d hosts: %s", num_hosts, sm_hosts)
        distributed.wait_hostname_resolution(sm_hosts)
        include_in_training = True
        if not train_dmatrix:
            logger.warning(
                "Host %s does not have training data and will not be used in "
                "distributed training. Please divide the training data across "
                "instances properly.",
                sm_current_host,
            )
            include_in_training = False
        if missing_validation_data:
            logger.warning(
                "Host %s does not have validation data in the validation channel and "
                "will not be used in distributed training.",
                sm_current_host,
            )
            include_in_training = False
        def _pre_exec(participating_hosts, current_host):
            # order matters: jax.distributed first (it must precede any JAX
            # computation), then the elastic membership registration (its
            # resolved SM_ELASTIC snapshot gates the abort listener), then
            # the abort listener (it must be up before rank 0's aggregator
            # can ever decide to broadcast), then the heartbeat plane over
            # the RE-FORMED cluster — ranks must match the participating
            # host list, not the original SM_HOSTS (hosts without data
            # already exited)
            maybe_init_jax_distributed(participating_hosts, current_host)
            from . import elastic

            if combine_train_val:
                # k-fold CV trains many per-fold callback stacks with no
                # single resume point to reform around — shrink-to-continue
                # is out of scope there, so leave the plane unregistered
                # (inert callback, legacy stale-host abort applies)
                if elastic.resolve_elastic_config().enabled:
                    logger.warning(
                        "SM_ELASTIC is not supported for k-fold CV jobs; a "
                        "dead host takes the legacy coordinated abort"
                    )
            else:
                elastic.register_cluster(participating_hosts, current_host)
            from .watchdog import start_abort_plane

            start_abort_plane(participating_hosts, current_host)
            start_cluster_telemetry(participating_hosts, current_host)
            # membership for the consensus guard (SM_CONSENSUS_EVERY): the
            # digest allgather runs over the RE-FORMED cluster, same as the
            # heartbeat plane — hosts without data already exited
            from .consensus import register_cluster

            register_cluster(participating_hosts, current_host)
            # trace export files are per-rank (trace-rank<r>.json); the rank
            # follows the re-formed cluster like everything above
            from ..telemetry import tracing

            tracing.set_rank(sorted(participating_hosts).index(current_host))
            # fleet observability plane last: span shipping needs the rank
            # set above, and the rank-0 collector/status endpoint bind over
            # the re-formed cluster like the heartbeat plane (inert unless
            # SM_FLEET_TRACE / SM_STATUS_PORT are set)
            from ..telemetry import fleet

            fleet.start_fleet_plane(participating_hosts, current_host)

        distributed.distributed_run(
            exec_fun=train_job,
            args=train_args,
            include_in_training=include_in_training,
            hosts=sm_hosts,
            current_host=sm_current_host,
            pre_exec=_pre_exec,
        )
    elif num_hosts == 1:
        if train_dmatrix:
            if missing_validation_data:
                raise exc.UserError("No data in validation channel path {}".format(val_path))
            logger.info("Single node training.")
            train_args.update({"is_master": True})
            # single-host jobs still get the /status endpoint (and, with
            # SM_FLEET_TRACE, a one-lane merged trace over loopback)
            from ..telemetry import fleet

            fleet.start_fleet_plane([sm_current_host], sm_current_host)
            train_job(**train_args)
        else:
            raise exc.UserError("No data in training channel path {}".format(train_path))
    else:
        raise exc.PlatformError("Number of hosts should be an int greater than or equal to 1")


def _training_mesh(num_devices_cap=None):
    """Data-parallel mesh over every visible device (None on one device).

    Under multi-host ``jax.distributed``, jax.devices() spans the whole job,
    so the same Mesh construction covers pod-scale data parallelism — the TPU
    replacement for the reference's Rabit worker group (SURVEY.md §2.3).
    """
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    n = len(devices)
    if num_devices_cap:
        n = min(n, int(num_devices_cap))
    if n <= 1:
        return None
    return Mesh(np.array(devices[:n]), axis_names=("data",))


def _accelerator_runtime_present():
    """True when an accelerator backend could come up: the libtpu wheel
    (TPU images) or any registered PJRT plugin. Never initializes a
    backend. CPU-only hosts (no plugin) return False, so auto-mode skips
    distributed init there — the pre-r4 behavior."""
    import importlib.util

    if importlib.util.find_spec("libtpu") is not None:
        return True
    try:
        from importlib.metadata import entry_points

        eps = entry_points()
        group = (
            eps.select(group="jax_plugins")
            if hasattr(eps, "select")
            else eps.get("jax_plugins", [])
        )
        if len(list(group)):
            return True
    except Exception:  # metadata backends vary; absence of evidence -> no accel
        pass
    try:
        import jax_plugins  # namespace package populated by installed plugins

        return len(list(getattr(jax_plugins, "__path__", []))) > 0
    except ImportError:
        return False


def maybe_init_jax_distributed(sm_hosts, sm_current_host, port=12355):
    """Bring up the multi-host XLA runtime (coordinator = sorted hosts[0]).

    Mirrors the reference's deterministic rank convention
    (distributed.py:155,:207). Gated to accelerator platforms: the CPU
    simulation tests drive the mesh path in-process instead.

    Mid-train host loss: there is no worker-rejoin analog of the reference
    tracker's ``recover`` path (dmlc_patch/tracker.py:341-353) — when a host
    stops heartbeating, the coordination service poisons the collectives and
    every surviving host terminates within ~GRAFT_HEARTBEAT_TIMEOUT_S
    (default 100s; the job FAILS, it never continues on partial data).
    Recovery is restart + checkpoint resume (training/checkpointing.py picks
    up at the last saved round — the same story as the reference's spot
    training). Failure semantics regression-tested in
    tests/test_parallel.py::test_host_loss_aborts_survivors.
    """
    import jax

    if len(sm_hosts) <= 1:
        return False
    mode = os.environ.get("SM_JAX_DISTRIBUTED", "auto")
    if mode == "off":
        return False
    # Platform detection WITHOUT jax.default_backend(): touching the backend
    # would initialize it, and jax.distributed.initialize() must run first
    # ("must be called before any JAX computations") — the previous
    # default_backend() probe would have PlatformError'd every real
    # multi-host TPU job at startup. Read the requested-platform config;
    # when unset, sniff for an accelerator runtime (libtpu wheel / PJRT
    # plugin) instead of initializing one.
    platforms = (
        os.environ.get("JAX_PLATFORMS")
        or getattr(jax.config, "jax_platforms", None)
        or ""
    )
    if platforms:
        cpu_only = set(platforms.split(",")) <= {"cpu"}
    else:
        cpu_only = not _accelerator_runtime_present()
    if cpu_only and mode != "on":
        # "auto" skips CPU (the in-process mesh tests cover that path);
        # "on" forces a real multi-process CPU cluster — used by the
        # docker-compose image tier to exercise true cross-host training
        logger.info("Skipping jax.distributed on the CPU backend")
        return False
    hosts = sorted(sm_hosts)
    try:
        import inspect

        kwargs = {}
        # older jax (the >=0.4.30 contract floor) has no heartbeat kwarg;
        # there the runtime's built-in default applies
        if "heartbeat_timeout_seconds" in inspect.signature(
            jax.distributed.initialize
        ).parameters:
            kwargs["heartbeat_timeout_seconds"] = int(
                os.environ.get("GRAFT_HEARTBEAT_TIMEOUT_S", "100")
            )
        jax.distributed.initialize(
            coordinator_address="{}:{}".format(hosts[0], port),
            num_processes=len(hosts),
            process_id=hosts.index(sm_current_host),
            **kwargs,
        )
        logger.info(
            "jax.distributed up: %d processes, %d global devices",
            len(hosts),
            jax.device_count(),
        )
        return True
    except Exception as e:
        # record the failure for the /status endpoint before raising: a
        # wedged multi-host bring-up is exactly when an operator curls
        # /status instead of grepping eight hosts' logs
        from ..telemetry import fleet

        fleet.note_status(backend_init_error=str(e))
        raise exc.PlatformError(
            "Failed to initialize the multi-host XLA runtime", caused_by=e
        )


def _reinit_jax_distributed(sm_hosts, sm_current_host):
    """Re-init the multi-host XLA runtime at the shrunken world size.

    The elastic reform hook: tear down the old coordination client (whose
    membership still includes the dead host) and bring the runtime back up
    over the survivor list. On CPU-auto paths (drills, single-accelerator
    hosts) both halves are no-ops, exactly like startup.
    """
    import jax

    try:
        state = getattr(jax.distributed, "global_state", None)
        if state is not None and getattr(state, "client", None) is not None:
            jax.distributed.shutdown()
    except Exception as e:
        # a coordination client wedged on the dead host may refuse a clean
        # shutdown; re-init decides whether that is fatal
        logger.warning("jax.distributed shutdown before re-init failed: %s", e)
    return maybe_init_jax_distributed(sm_hosts, sm_current_host)


def train_job(
    train_cfg, train_dmatrix, val_dmatrix, train_val_dmatrix, model_dir, checkpoint_dir, is_master
):
    """Run boosting (or repeated k-fold CV) on this node; save master-only.

    With the elastic plane armed (``SM_ELASTIC``), the single-model branch
    runs under ``elastic.supervised_train``: a membership reform unwinds the
    boosting loop at a round boundary, survivors re-rendezvous, and this
    function's ``train_once`` closure rebuilds everything per generation —
    fresh callbacks (which re-read the last digest-verified checkpoint and
    validate the recorded world-size transition), a fresh mesh over the
    re-initialized runtime, and a rebuilt booster session under the SAME
    hist-knobs snapshot. ``is_master`` survives a shrink unchanged: the
    master is the sorted-first participant, and only the master's own
    aggregator can propose a shrink — a dead master is not survivable (the
    legacy jax heartbeat timeout applies) and is documented as such.
    """
    train_cfg = dict(train_cfg)
    num_devices_cap = train_cfg.pop("_num_devices", None)
    mesh = _training_mesh(num_devices_cap)
    # r2: ranking objectives shard rows by group and survival:cox gathers
    # global risk sets inside the jitted round, so every objective trains on
    # a data-parallel mesh
    num_round = train_cfg.pop("num_round")
    save_model_on_termination = train_cfg.pop("save_model_on_termination", "false")

    # fleet observability: planned rounds feed the /status ETA, and kill -3
    # becomes a live inspection dump (flight recorder + skew snapshot)
    # instead of the default core-dump abort — both no-ops when unobserved
    from ..telemetry import fleet

    fleet.note_status(rounds_planned=num_round)
    fleet.install_sigquit_handler(default_dir=model_dir)

    tuning_objective_metric_param = train_cfg.pop("_tuning_objective_metric", None)
    eval_metric = train_cfg.get("eval_metric")
    cleaned_eval_metric, configured_feval, tuning_objective_metric = (
        train_utils.get_eval_metrics_and_feval(tuning_objective_metric_param, eval_metric)
    )
    if cleaned_eval_metric:
        train_cfg["eval_metric"] = cleaned_eval_metric
    else:
        train_cfg.pop("eval_metric", None)

    early_stopping_rounds = train_cfg.pop("early_stopping_rounds", None)
    early_stopping_data_name = "validation" if val_dmatrix else None
    early_stopping_metric = None
    if early_stopping_rounds:
        if tuning_objective_metric:
            early_stopping_metric = tuning_objective_metric[-1]
        elif eval_metric:
            early_stopping_metric = eval_metric[-1]

    logger.info(
        "Train matrix has %d rows and %d columns",
        train_dmatrix.num_row,
        train_dmatrix.num_col,
    )
    if val_dmatrix:
        logger.info("Validation matrix has %d rows", val_dmatrix.num_row)

    # Default to batching several boosting rounds per device dispatch when no
    # per-round host artifact is required (checkpoint files / intermediate
    # model saves must land every round for spot safety). Metrics that can't
    # ride back from the device (feval, ranking metrics) no longer force
    # K=1: the booster keeps the fused dispatch and host-evaluates once per
    # K rounds (docs/DESIGN.md §Round pipeline). Explicit
    # _rounds_per_dispatch always wins.
    if (
        not checkpoint_dir
        and save_model_on_termination != "true"
        and "_rounds_per_dispatch" not in train_cfg
    ):
        train_cfg["_rounds_per_dispatch"] = int(
            os.environ.get("SM_ROUNDS_PER_DISPATCH_DEFAULT", "8")
        )

    try:
        kfold = train_cfg.pop("_kfold", None)
        watchlist = [(train_dmatrix, "train")]
        if val_dmatrix is not None:
            watchlist.append((val_dmatrix, "validation"))

        from .profiling import xla_trace

        if kfold is None:
            from ..ops.histogram import resolve_hist_knobs
            from . import elastic

            # one knob snapshot for the whole job: every generation the
            # reform loop rebuilds the session with, so a shrink can never
            # pick up mid-job env drift
            hist_knobs = resolve_hist_knobs()
            mesh_box = {"mesh": mesh}

            def _train_once():
                xgb_model, iteration, callbacks = get_callbacks(
                    model_dir=model_dir,
                    checkpoint_dir=checkpoint_dir,
                    early_stopping_data_name=early_stopping_data_name,
                    early_stopping_metric=early_stopping_metric,
                    early_stopping_rounds=early_stopping_rounds,
                    save_model_on_termination=save_model_on_termination,
                    is_master=is_master,
                    num_round=num_round,
                    num_rows=train_dmatrix.num_row,
                    train_cfg=train_cfg,
                )
                try:
                    with xla_trace(), span("train", emit=True):
                        return booster.train(
                            train_cfg,
                            train_dmatrix,
                            num_boost_round=num_round - iteration,
                            evals=watchlist,
                            feval=configured_feval,
                            callbacks=callbacks,
                            xgb_model=xgb_model,
                            mesh=mesh_box["mesh"],
                            hist_knobs=hist_knobs,
                        )
                except elastic.ReformRequested:
                    # the abandoned generation's threads (watchdog monitor,
                    # checkpoint deleter) must not outlive it — a stale
                    # watchdog firing mid-reform would exit 79 a healthy
                    # survivor
                    elastic.drain_callbacks(callbacks)
                    raise

            def _on_reform(new_hosts, current_host):
                # per-generation re-wiring: runtime first (as at startup),
                # then the mesh over the new device set, then the control
                # planes over the survivor list
                _reinit_jax_distributed(new_hosts, current_host)
                mesh_box["mesh"] = _training_mesh(num_devices_cap)
                from .watchdog import start_abort_plane

                start_abort_plane(new_hosts, current_host)
                start_cluster_telemetry(new_hosts, current_host)
                from ..telemetry import tracing

                tracing.set_rank(sorted(new_hosts).index(current_host))
                from ..telemetry import fleet

                fleet.start_fleet_plane(new_hosts, current_host)

            bst = elastic.supervised_train(_train_once, on_reform=_on_reform)
        else:
            num_cv_round = train_cfg.pop("_num_cv_round", 1)
            logger.info(
                "Run %s-round of %s-fold cross validation with %s rows",
                num_cv_round,
                kfold,
                train_val_dmatrix.num_row,
            )
            bst = []
            evals_results = []
            num_class = train_cfg.get("num_class", None)
            objective = train_cfg.get("objective") or ""
            classification_problem = bool(num_class) or objective.startswith("binary:")
            y = train_val_dmatrix.get_label() if classification_problem else None
            rkf = (
                RepeatedStratifiedKFold(n_splits=kfold, n_repeats=num_cv_round)
                if y is not None
                else RepeatedKFold(n_splits=kfold, n_repeats=num_cv_round)
            )
            val_pred = ValidationPredictionRecorder(
                y_true=train_val_dmatrix.get_label(),
                num_cv_round=num_cv_round,
                classification=classification_problem,
                output_data_dir=os.environ[SM_OUTPUT_DATA_DIR],
            )
            splits = list(rkf.split(X=range(train_val_dmatrix.num_row), y=y))

            parallel_folds = _try_parallel_cv(
                train_cfg=train_cfg,
                train_val_dmatrix=train_val_dmatrix,
                splits=splits,
                num_round=num_round,
                kfold=kfold,
                checkpoint_dir=checkpoint_dir,
                early_stopping_rounds=early_stopping_rounds,
                configured_feval=configured_feval,
                save_model_on_termination=save_model_on_termination,
            )
            if parallel_folds is not None:
                bst, evals_results = parallel_folds
                for k, (train_idx, val_idx) in enumerate(splits):
                    cv_val = train_val_dmatrix.slice(val_idx)
                    val_pred.record(val_idx, bst[k].predict(cv_val.features))
                    if (k + 1) % kfold == 0:
                        logger.info(
                            "The metrics of round %d cross validation",
                            (k + 1) // kfold,
                        )
                        print_cv_metric(num_round, evals_results[k + 1 - kfold : k + 1])
            else:
                for train_idx, val_idx in splits:
                    cv_train = train_val_dmatrix.slice(train_idx)
                    cv_val = train_val_dmatrix.slice(val_idx)
                    xgb_model, iteration, callbacks = get_callbacks(
                        model_dir=model_dir,
                        checkpoint_dir=checkpoint_dir,
                        early_stopping_data_name=early_stopping_data_name,
                        early_stopping_metric=early_stopping_metric,
                        early_stopping_rounds=early_stopping_rounds,
                        save_model_on_termination=save_model_on_termination,
                        is_master=is_master,
                        fold=len(bst),
                        num_round=num_round,
                        num_rows=cv_train.num_row,
                        train_cfg=train_cfg,
                    )

                    class _EvalsRecorder:
                        def __init__(self):
                            self.log = {}

                        def after_iteration(self, model, epoch, evals_log):
                            self.log = {k: dict(v) for k, v in evals_log.items()}
                            return False

                    recorder = _EvalsRecorder()
                    logger.info("Train cross validation fold %d", (len(bst) % kfold) + 1)
                    fold_booster = booster.train(
                        train_cfg,
                        cv_train,
                        num_boost_round=num_round - iteration,
                        evals=[(cv_train, "train"), (cv_val, "validation")],
                        feval=configured_feval,
                        callbacks=callbacks + [recorder],
                        xgb_model=xgb_model,
                        mesh=mesh,
                    )
                    bst.append(fold_booster)
                    evals_results.append(recorder.log)
                    val_pred.record(val_idx, fold_booster.predict(cv_val.features))
                    if len(bst) % kfold == 0:
                        logger.info(
                            "The metrics of round %d cross validation", len(bst) // kfold
                        )
                        print_cv_metric(num_round, evals_results[-kfold:])
            val_pred.save()
            if num_cv_round > 1:
                logger.info(
                    "The overall metrics of %s-round cross validation", num_cv_round
                )
                print_cv_metric(num_round, evals_results)
    except Exception as e:
        for customer_error_message in CUSTOMER_ERRORS:
            if customer_error_message in str(e):
                raise exc.UserError(str(e))
        if isinstance(e, (exc.UserError, exc.PlatformError)):
            raise
        raise exc.AlgorithmError("XGB train call failed with exception:\n {}".format(e))

    os.makedirs(model_dir, exist_ok=True)
    if is_master:
        from ..data import streaming
        from ..utils import integrity
        from . import elastic

        def _save_with_manifest(model, model_location):
            model.save_model(model_location)
            try:
                # the manifest travels inside model.tar.gz: serving
                # digest-verifies the artifact at load. Best-effort — a
                # failed sidecar write must not fail a finished job (the
                # model loads manifest-less, exactly like older runs).
                # A model that trained through elastic shrinks carries the
                # full membership log, and one that trained past quarantined
                # input chunks carries the agreed quarantine record — the
                # provenance for "this artifact lost those rows".
                # model telemetry (SM_MODEL_TELEMETRY): the final learning
                # curve and the drift-PSI baseline ride in the manifest too,
                # so serving gets the training-time distribution for free
                from ..telemetry import model as model_telemetry

                integrity.write_manifest(
                    model_location,
                    fingerprint=integrity.config_fingerprint(train_cfg),
                    membership_log=elastic.membership_log() or None,
                    quarantine=streaming.quarantine_record(),
                    learning=model_telemetry.learning_summary(),
                    drift_baseline=model_telemetry.drift_baseline(),
                )
            except OSError as e:
                logger.warning(
                    "could not write model manifest for %s: %s", model_location, e
                )

        try:
            # the standalone quarantine manifest (ingest-quarantine.json)
            # rides next to the model so operators can audit skipped input
            # without parsing the model sidecar; absent when nothing skipped
            qpath = streaming.write_quarantine_manifest(model_dir)
            if qpath:
                logger.warning("ingest quarantine manifest written to %s", qpath)
        except OSError as e:
            logger.warning("could not write ingest quarantine manifest: %s", e)

        with span("model_save", emit=True):
            if not isinstance(bst, list):
                model_location = os.path.join(model_dir, MODEL_NAME)
                _save_with_manifest(bst, model_location)
                logger.debug("Stored trained model at %s", model_location)
            else:
                for fold, fold_booster in enumerate(bst):
                    model_location = os.path.join(
                        model_dir, "{}-{}".format(MODEL_NAME, fold)
                    )
                    _save_with_manifest(fold_booster, model_location)
                    logger.debug(
                        "Stored trained model %d at %s", fold, model_location
                    )

    # end-of-run trace export (SM_TRACE): one Chrome-trace file per rank
    # into SM_TRACE_EXPORT_DIR, defaulting alongside the model artifacts so
    # it travels in the output tarball. Best-effort — a failed export must
    # never fail a finished job.
    from ..telemetry import tracing

    try:
        tracing.export_traces(default_dir=model_dir)
    except Exception:
        logger.exception("trace export failed; training result unaffected")
    # fleet merge rides next to the per-rank exports: every rank flushes its
    # shipper, rank 0 writes trace-fleet.json (inert when the plane is off)
    from ..telemetry import fleet

    try:
        fleet.export_fleet_trace(default_dir=model_dir)
    except Exception:
        logger.exception("fleet trace export failed; training result unaffected")


def _try_parallel_cv(
    train_cfg,
    train_val_dmatrix,
    splits,
    num_round,
    kfold,
    checkpoint_dir,
    early_stopping_rounds,
    configured_feval,
    save_model_on_termination,
):
    """Fold-parallel CV fast path; returns (forests, evals_results) or None.

    The reference runs k x r sequential boosting jobs (algorithm_mode/
    train.py:378-459); here each local device trains whole folds in one
    vmapped XLA program (models/cv_parallel.py) — for single-process CV
    jobs, fold parallelism beats data parallelism (folds are independent, so
    there are zero collectives), so it takes precedence over the data mesh.
    Only taken when no per-fold host artifact is needed mid-training
    (checkpoints, early stopping, SIGTERM intermediate saves, feval) and the
    watchlist is device-decomposable; anything else — including multi-host
    runs — falls back to the sequential loop. ``GRAFT_PARALLEL_CV=0``
    disables (e.g. when a fold's data exceeds one device's memory and the
    data mesh is required).
    """
    import jax

    if os.environ.get("GRAFT_PARALLEL_CV", "1") != "1":
        return None
    if jax.process_count() > 1 or jax.local_device_count() <= 1:
        return None
    if checkpoint_dir or early_stopping_rounds or configured_feval is not None:
        return None
    if save_model_on_termination == "true":
        return None
    from ..models.booster import (
        OBJECTIVE_PARAM_KEYS,
        TrainConfig,
        _eval_metric_names,
    )
    from ..models.cv_parallel import parallel_cv_supported, train_cv_parallel
    from ..models.forest import Forest

    try:
        cfg = TrainConfig(dict(train_cfg))
    except Exception:
        return None  # the sequential path surfaces config errors verbatim

    def forest_factory():
        return Forest(
            objective_name=cfg.objective,
            objective_params={
                k: v
                for k, v in cfg.objective_params.items()
                if k in OBJECTIVE_PARAM_KEYS
            },
            base_score=cfg.base_score,
            num_feature=train_val_dmatrix.num_col,
            num_class=cfg.num_class,
        )

    metric_names = _eval_metric_names(cfg, forest_factory().objective())
    if not parallel_cv_supported(cfg, metric_names, False):
        return None
    logger.info(
        "Training %d CV folds in parallel across %d devices",
        len(splits),
        jax.device_count(),
    )
    forests, evals_results = train_cv_parallel(
        cfg, train_val_dmatrix, splits, num_round, metric_names, forest_factory
    )
    # per-fold per-round stdout lines in the sequential monitor's format
    # (the HPO regex contract — reference metrics.py:23-39)
    for k, res in enumerate(evals_results):
        logger.info("Train cross validation fold %d", (k % kfold) + 1)
        for r in range(num_round):
            parts = [
                "{}-{}:{:.5f}".format(data_name, metric_name, res[data_name][metric_name][r])
                for data_name in res
                for metric_name in res[data_name]
            ]
            print("[{}]\t{}".format(r, "\t".join(parts)), flush=True)
    return forests, evals_results


def print_cv_metric(num_round, evals_results):
    """One stdout line with per-metric CV means (reference train.py:489-500)."""
    report = "[{}]".format(num_round)
    data_names = evals_results[0].keys()
    metric_names = evals_results[0]["train"].keys()
    for metric_name in metric_names:
        for data_name in data_names:
            values = [r[data_name][metric_name][-1] for r in evals_results]
            report += "\t{}-{}:{:.5f}".format(data_name, metric_name, float(np.mean(values)))
    print(report, flush=True)
