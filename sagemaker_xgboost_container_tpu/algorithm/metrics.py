"""HPO metric definitions for every supported eval metric.

The regex is the contract: SageMaker HPO scrapes stdout with
``.*\\[[0-9]+\\].*#011validation-<name>:(\\S+)`` (reference:
`algorithm_mode/metrics.py:23-39`). Our evaluation monitor emits exactly
that line shape (``[<iter>]<tab>train-<m>:<v><tab>validation-<m>:<v>``,
where <tab> renders as ``#011`` in CloudWatch).
"""

from ..constants import XGB_MAXIMIZE_METRICS, XGB_MINIMIZE_METRICS
from ..toolkit.metrics import Metric, Metrics

_REGEX_TEMPLATE = ".*\\[[0-9]+\\].*#011validation-{}:(\\S+)"


def initialize():
    defs = []
    for name in XGB_MAXIMIZE_METRICS:
        defs.append(
            Metric(
                name="validation:{}".format(name),
                direction=Metric.MAXIMIZE,
                regex=_REGEX_TEMPLATE.format(name),
            )
        )
    for name in XGB_MINIMIZE_METRICS:
        defs.append(
            Metric(
                name="validation:{}".format(name),
                direction=Metric.MINIMIZE,
                regex=_REGEX_TEMPLATE.format(name),
            )
        )
    return Metrics(*defs)
