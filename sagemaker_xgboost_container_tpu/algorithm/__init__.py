from . import channels, hyperparameters, metrics  # noqa: F401
