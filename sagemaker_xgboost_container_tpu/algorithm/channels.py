"""Channel schema: train (required) / validation / code.

Parity with the reference (`algorithm_mode/channel_validation.py:20-46`):
CSV, libsvm, parquet, and recordio-protobuf in File mode under both S3
distribution types; default content type ``text/libsvm``. Pipe mode is
declared unsupported (the reference itself rejects piped CSV/parquet/recordio
at load time — data_utils.py:328-331, :399-402, :425-429).
"""

from .. import constants
from ..toolkit.channels import Channel, Channels

# Both the bare short names and the MIME forms validate, matching the
# reference's VALID_CONTENT_TYPES (data_utils.py:38-48).
VALID_CONTENT_TYPES = [
    "csv",
    "libsvm",
    "parquet",
    "recordio-protobuf",
    constants.CSV,
    constants.LIBSVM,
    constants.X_LIBSVM,
    constants.PARQUET,
    constants.RECORDIO_PROTOBUF,
]

# Pipe-mode streaming is not yet wired to the TPU ingest path.
VALID_PIPED_CONTENT_TYPES = []


def initialize():
    def data_channel(name, required):
        ch = Channel(name=name, required=required)
        for ct in VALID_CONTENT_TYPES:
            ch.add(ct, Channel.FILE_MODE, Channel.SHARDED)
            ch.add(ct, Channel.FILE_MODE, Channel.REPLICATED)
        for ct in VALID_PIPED_CONTENT_TYPES:
            ch.add(ct, Channel.PIPE_MODE, Channel.SHARDED)
            ch.add(ct, Channel.PIPE_MODE, Channel.REPLICATED)
        return ch

    code = Channel(name="code", required=False)
    code.add("text/python", Channel.FILE_MODE, Channel.REPLICATED)

    channels = Channels(
        data_channel(constants.TRAIN_CHANNEL, required=True),
        data_channel(constants.VAL_CHANNEL, required=False),
        code,
    )
    channels.set_default_content_type(constants.LIBSVM)
    return channels
