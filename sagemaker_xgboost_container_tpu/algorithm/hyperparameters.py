"""The full XGBoost hyperparameter schema, declared against our toolkit engine.

Parity target: every hyperparameter the reference container accepts
(`algorithm_mode/hyperparameter_validation.py:21-346`) validates identically
here — names, ranges, dependency rules, aliases — with two TPU-specific
deviations:

* ``tree_method=gpu_hist`` is rejected with a clear UserError (there is no
  CUDA in this build; the XLA histogram builder is the ``hist`` path).
* ``predictor=gpu_predictor`` likewise maps to a UserError; prediction always
  runs through the compiled XLA forest kernel.
* ``interaction_constraints`` leaves validate against ``[0, inf)`` rather than
  the reference's ``[1, inf)`` — feature indices are 0-based, so the
  reference's range rejects constraints naming feature 0.
* ``updater=grow_quantile_histmaker`` passes range validation here; the
  reference's range list omits it even though its own dependency validator
  allows it (an upstream inconsistency we resolve in favor of accepting).

Internal (underscore-prefixed) flags: ``_kfold``, ``_num_cv_round``,
``_tuning_objective_metric`` as in the reference, plus ``_num_devices``
(TPU mesh width override for testing).
"""

from ..constants import XGB_MAXIMIZE_METRICS, XGB_MINIMIZE_METRICS
from ..toolkit import exceptions as exc
from ..toolkit.hyperparameters import (
    CategoricalHyperparameter,
    CommaSeparatedListHyperparameter,
    ContinuousHyperparameter,
    Hyperparameters,
    IntegerHyperparameter,
    Interval,
    NestedListHyperparameter,
    TupleHyperparameter,
    dependencies_validator,
    range_validator,
)

TREE_METHODS = ["auto", "exact", "approx", "hist"]
GPU_TREE_METHOD = "gpu_hist"

OBJECTIVES = [
    "aft_loss_distribution",
    "binary:logistic",
    "binary:logitraw",
    "binary:hinge",
    "count:poisson",
    "multi:softmax",
    "multi:softprob",
    "rank:pairwise",
    "rank:ndcg",
    "rank:map",
    "reg:linear",
    "reg:squarederror",
    "reg:logistic",
    "reg:gamma",
    "reg:pseudohubererror",
    "reg:squaredlogerror",
    "reg:absoluteerror",
    "reg:tweedie",
    "survival:aft",
    "survival:cox",
]

TREE_UPDATERS = [
    "grow_colmaker",
    "distcol",
    "grow_histmaker",
    "grow_skmaker",
    "sync",
    "refresh",
    "prune",
    "grow_quantile_histmaker",
]
TREE_GROW_UPDATERS = ["grow_colmaker", "distcol", "grow_histmaker", "grow_quantile_histmaker"]
LINEAR_UPDATERS = ["shotgun", "coord_descent"]
PROCESS_UPDATE_UPDATERS = ["refresh", "prune"]


def initialize(metrics):
    """Build the Hyperparameters registry. ``metrics`` supplies the legal
    values of ``_tuning_objective_metric`` (HPO objective selection)."""

    @range_validator(TREE_METHODS)
    def tree_method_range(choices, value):
        if value == GPU_TREE_METHOD:
            raise exc.UserError(
                "tree_method 'gpu_hist' is not available in the TPU container: there is no "
                "CUDA device. Use tree_method 'hist' — it runs the XLA histogram tree "
                "builder on TPU."
            )
        return value in choices

    @range_validator(["auto", "cpu_predictor"])
    def predictor_range(choices, value):
        if value == "gpu_predictor":
            raise exc.UserError(
                "predictor 'gpu_predictor' is not available in the TPU container; "
                "prediction always uses the compiled XLA forest kernel. Use 'auto'."
            )
        return value in choices

    @dependencies_validator(["booster", "process_type"])
    def check_updater(value, deps):
        if deps.get("booster") == "gblinear":
            if len(value) != 1 or value[0] not in LINEAR_UPDATERS:
                raise exc.UserError(
                    "Linear updater should be one of these options: {}.".format(
                        ", ".join("'{}'".format(u) for u in LINEAR_UPDATERS)
                    )
                )
            return
        if deps.get("process_type") == "update":
            if not all(u in PROCESS_UPDATE_UPDATERS for u in value):
                raise exc.UserError(
                    "process_type 'update' can only be used with updater 'refresh' and 'prune'"
                )
            return
        if not all(u in TREE_UPDATERS for u in value):
            raise exc.UserError(
                "Tree updater should be selected from these options: {}.".format(
                    ", ".join("'{}'".format(u) for u in TREE_UPDATERS + LINEAR_UPDATERS)
                )
            )
        n_grow = sum(1 for u in value if u in TREE_GROW_UPDATERS)
        if n_grow > 1:
            raise exc.UserError(
                "Only one tree grow plugin can be selected. Choose one from the following: "
                + ", ".join("'{}'".format(u) for u in TREE_GROW_UPDATERS)
            )

    @dependencies_validator(["num_class"])
    def check_objective(value, deps):
        num_class = deps.get("num_class")
        if value in ("multi:softmax", "multi:softprob") and num_class is None:
            raise exc.UserError(
                "Require input for parameter 'num_class' for multi-classification"
            )
        if value is None and num_class is not None:
            raise exc.UserError(
                "Do not need to setup parameter 'num_class' for learning task other than "
                "multi-classification."
            )

    @range_validator(XGB_MAXIMIZE_METRICS + XGB_MINIMIZE_METRICS)
    def eval_metric_range(supported, metric):
        if "<function" in metric:
            raise exc.UserError(
                "User defined evaluation metric {} is not supported yet.".format(metric)
            )
        if "@" in metric:
            base, _, threshold = metric.partition("@")
            base = base.strip()
            if base not in ("error", "ndcg", "map"):
                raise exc.UserError(
                    "Metric '{}' is not supported. Parameter 'eval_metric' with customized "
                    "threshold should be one of these options: 'error', 'ndcg', 'map'.".format(
                        metric
                    )
                )
            try:
                float(threshold.strip())
            except ValueError:
                raise exc.UserError(
                    "Threshold value 't' in '{}@t' expects float input.".format(base)
                )
            return True
        return metric in supported

    @dependencies_validator(["objective"])
    def check_eval_metric(value, deps):
        objective = deps.get("objective", "reg:squarederror")
        if "auc" in value and not any(
            objective.startswith(prefix) for prefix in ("binary:", "rank:")
        ):
            raise exc.UserError(
                "Metric 'auc' can only be applied for classification and ranking problems."
            )
        if "aft-nloglik" in value and objective != "survival:aft":
            raise exc.UserError(
                "Metric 'aft-nloglik' can only be applied for 'survival:aft' objective."
            )

    @dependencies_validator(["tree_method"])
    def check_monotone(value, deps):
        if value is not None and deps.get("tree_method") not in ("exact", "hist"):
            raise exc.UserError(
                "monotone_constraints can be used only when the tree_method parameter is set "
                "to either 'exact' or 'hist'."
            )

    @dependencies_validator(["tree_method"])
    def check_interaction(value, deps):
        if value is not None and deps.get("tree_method") not in ("exact", "hist", "approx"):
            raise exc.UserError(
                "interaction_constraints can be used only when the tree_method parameter is "
                "set to either 'exact', 'hist' or 'approx'."
            )

    hps = Hyperparameters(
        IntegerHyperparameter(
            name="num_round",
            required=True,
            range=Interval(min_closed=1),
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=1, max_closed=4000, scale=Interval.LINEAR_SCALE
            ),
        ),
        IntegerHyperparameter(
            name="csv_weights", range=Interval(min_closed=0, max_closed=1), required=False
        ),
        IntegerHyperparameter(
            name="early_stopping_rounds", range=Interval(min_closed=1), required=False
        ),
        CategoricalHyperparameter(
            name="booster", range=["gbtree", "gblinear", "dart"], required=False
        ),
        IntegerHyperparameter(
            name="verbosity", range=Interval(min_closed=0, max_closed=3), required=False
        ),
        IntegerHyperparameter(name="nthread", range=Interval(min_closed=1), required=False),
        ContinuousHyperparameter(
            name="eta",
            range=Interval(min_closed=0, max_closed=1),
            required=False,
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=0.1, max_closed=0.5, scale=Interval.LINEAR_SCALE
            ),
        ),
        ContinuousHyperparameter(
            name="gamma",
            range=Interval(min_closed=0),
            required=False,
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=0, max_closed=5, scale=Interval.LINEAR_SCALE
            ),
        ),
        IntegerHyperparameter(
            name="max_depth",
            range=Interval(min_closed=0),
            required=False,
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=0, max_closed=10, scale=Interval.LINEAR_SCALE
            ),
        ),
        ContinuousHyperparameter(
            name="min_child_weight",
            range=Interval(min_closed=0),
            required=False,
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=0, max_closed=120, scale=Interval.LINEAR_SCALE
            ),
        ),
        ContinuousHyperparameter(
            name="max_delta_step",
            range=Interval(min_closed=0),
            required=False,
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=0, max_closed=10, scale=Interval.LINEAR_SCALE
            ),
        ),
        ContinuousHyperparameter(
            name="subsample",
            range=Interval(min_open=0, max_closed=1),
            required=False,
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=0.5, max_closed=1, scale=Interval.LINEAR_SCALE
            ),
        ),
        ContinuousHyperparameter(
            name="colsample_bytree",
            range=Interval(min_open=0, max_closed=1),
            required=False,
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=0.5, max_closed=1, scale=Interval.LINEAR_SCALE
            ),
        ),
        ContinuousHyperparameter(
            name="colsample_bylevel",
            range=Interval(min_open=0, max_closed=1),
            required=False,
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=0.1, max_closed=1, scale=Interval.LINEAR_SCALE
            ),
        ),
        ContinuousHyperparameter(
            name="colsample_bynode",
            range=Interval(min_open=0, max_closed=1),
            required=False,
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=0.1, max_closed=1, scale=Interval.LINEAR_SCALE
            ),
        ),
        ContinuousHyperparameter(
            name="lambda",
            range=Interval(min_closed=0),
            required=False,
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=0, max_closed=1000, scale=Interval.LINEAR_SCALE
            ),
        ),
        ContinuousHyperparameter(
            name="alpha",
            range=Interval(min_closed=0),
            required=False,
            tunable=True,
            tunable_recommended_range=Interval(
                min_closed=0, max_closed=1000, scale=Interval.LINEAR_SCALE
            ),
        ),
        CategoricalHyperparameter(name="tree_method", range=tree_method_range, required=False),
        ContinuousHyperparameter(
            name="sketch_eps", range=Interval(min_open=0, max_open=1), required=False
        ),
        ContinuousHyperparameter(
            name="scale_pos_weight", range=Interval(min_open=0), required=False
        ),
        CommaSeparatedListHyperparameter(
            name="updater",
            range=TREE_UPDATERS + LINEAR_UPDATERS,
            dependencies=check_updater,
            required=False,
        ),
        CategoricalHyperparameter(name="dsplit", range=["row", "col"], required=False),
        IntegerHyperparameter(
            name="refresh_leaf", range=Interval(min_closed=0, max_closed=1), required=False
        ),
        CategoricalHyperparameter(
            name="process_type", range=["default", "update"], required=False
        ),
        CategoricalHyperparameter(
            name="grow_policy", range=["depthwise", "lossguide"], required=False
        ),
        IntegerHyperparameter(name="max_leaves", range=Interval(min_closed=0), required=False),
        IntegerHyperparameter(name="max_bin", range=Interval(min_closed=0), required=False),
        CategoricalHyperparameter(name="predictor", range=predictor_range, required=False),
        TupleHyperparameter(
            name="monotone_constraints",
            range=[-1, 0, 1],
            required=False,
            dependencies=check_monotone,
        ),
        NestedListHyperparameter(
            name="interaction_constraints",
            range=Interval(min_closed=0),
            required=False,
            dependencies=check_interaction,
        ),
        CategoricalHyperparameter(
            name="sample_type", range=["uniform", "weighted"], required=False
        ),
        CategoricalHyperparameter(
            name="normalize_type", range=["tree", "forest"], required=False
        ),
        ContinuousHyperparameter(
            name="rate_drop", range=Interval(min_closed=0, max_closed=1), required=False
        ),
        IntegerHyperparameter(
            name="one_drop", range=Interval(min_closed=0, max_closed=1), required=False
        ),
        ContinuousHyperparameter(
            name="skip_drop", range=Interval(min_closed=0, max_closed=1), required=False
        ),
        ContinuousHyperparameter(
            name="lambda_bias", range=Interval(min_closed=0, max_closed=1), required=False
        ),
        ContinuousHyperparameter(
            name="tweedie_variance_power",
            range=Interval(min_open=1, max_open=2),
            required=False,
        ),
        CategoricalHyperparameter(
            name="objective", range=OBJECTIVES, dependencies=check_objective, required=False
        ),
        IntegerHyperparameter(name="num_class", range=Interval(min_closed=2), required=False),
        ContinuousHyperparameter(
            name="base_score", range=Interval(min_closed=0), required=False
        ),
        IntegerHyperparameter(
            name="_kfold", range=Interval(min_closed=2), required=False, tunable=False
        ),
        IntegerHyperparameter(
            name="_num_cv_round", range=Interval(min_closed=1), required=False, tunable=False
        ),
        CategoricalHyperparameter(
            name="_tuning_objective_metric", range=metrics.names, required=False
        ),
        CommaSeparatedListHyperparameter(
            name="eval_metric",
            range=eval_metric_range,
            dependencies=check_eval_metric,
            required=False,
        ),
        IntegerHyperparameter(
            name="seed",
            range=Interval(min_open=-(2**31), max_open=2**31 - 1),
            required=False,
        ),
        IntegerHyperparameter(
            name="num_parallel_tree", range=Interval(min_closed=1), required=False
        ),
        CategoricalHyperparameter(
            name="save_model_on_termination", range=["true", "false"], required=False
        ),
        CategoricalHyperparameter(
            name="aft_loss_distribution",
            range=["normal", "logistic", "extreme"],
            required=False,
        ),
        ContinuousHyperparameter(
            name="aft_loss_distribution_scale", range=Interval(min_closed=0), required=False
        ),
        CategoricalHyperparameter(
            name="deterministic_histogram", range=["true", "false"], required=False
        ),
        CategoricalHyperparameter(
            name="sampling_method", range=["uniform", "gradient_based"], required=False
        ),
        IntegerHyperparameter(
            name="prob_buffer_row", range=Interval(min_open=1.0), required=False
        ),
        # Accepted for API compatibility with the reference; always an error on
        # TPU because there is no Dask-CUDA substrate in this image.
        CategoricalHyperparameter(
            name="use_dask_gpu_training", range=["true", "false"], required=False
        ),
        # TPU-internal: cap the number of mesh devices used for training.
        IntegerHyperparameter(
            name="_num_devices", range=Interval(min_closed=1), required=False, tunable=False
        ),
        # TPU-internal: build K trees per device dispatch (quiet runs only;
        # forced back to 1 when eval sets need per-round metrics).
        IntegerHyperparameter(
            name="_rounds_per_dispatch",
            range=Interval(min_closed=1),
            required=False,
            tunable=False,
        ),
    )

    hps.declare_alias("eta", "learning_rate")
    hps.declare_alias("gamma", "min_split_loss")
    hps.declare_alias("lambda", "reg_lambda")
    hps.declare_alias("alpha", "reg_alpha")

    return hps
