"""Single source of truth for the framework's dependency-version contract.

The reference pins its dependency set in the image and asserts it from
inside a running container (reference test/integration/local/
test_versions.py + test/resources/versions/train.py). Here the contract
lives in one importable module consumed by three enforcement points:

* ``setup.py`` turns SUPPORTED into ``install_requires`` specifiers, so pip
  refuses to install the package against an unsupported stack;
* the image build gate (docker/Dockerfile.tpu) calls :func:`assert_supported`
  so an image never ships with a drifted dependency;
* ``tests/test_versions.py`` asserts the live environment satisfies the
  contract (the in-repo analog of the reference's in-image version test).

This module must stay importable WITHOUT the package's dependencies
installed (setup.py loads it before they exist) — stdlib imports only at
module level.
"""

# floors, chosen at the versions the framework is developed/tested against;
# no upper bounds (jax moves fast and upper-pinning a container base image
# causes more breakage than it prevents — widen deliberately, with tests)
SUPPORTED = {
    "jax": ">=0.4.30",
    "numpy": ">=1.24",
    "scipy": ">=1.10",
    "pandas": ">=1.5",
    "pyarrow": ">=10.0",
    "scikit-learn": ">=1.2",
    "protobuf": ">=3.20",
    # violations() itself needs it, and python:…-slim images don't ship it
    # (pip only vendors a private copy)
    "packaging": ">=21.0",
}


def install_requires():
    """setup.py install_requires list derived from the contract."""
    return [name + spec for name, spec in sorted(SUPPORTED.items())]


def violations():
    """[(package, installed_version_or_None, required_spec), ...] for every
    contract entry the live environment fails."""
    import importlib.metadata as md

    from packaging.specifiers import SpecifierSet
    from packaging.version import Version

    bad = []
    for name, spec in sorted(SUPPORTED.items()):
        try:
            installed = md.version(name)
        except md.PackageNotFoundError:
            bad.append((name, None, spec))
            continue
        if Version(installed) not in SpecifierSet(spec):
            bad.append((name, installed, spec))
    return bad


def assert_supported():
    """Raise RuntimeError listing every contract violation (image gate)."""
    bad = violations()
    if bad:
        raise RuntimeError(
            "dependency contract violated: "
            + "; ".join(
                "{} {} (need {})".format(n, v or "MISSING", s) for n, v, s in bad
            )
        )


if __name__ == "__main__":
    assert_supported()
    print("dependency contract OK")
