"""sklearn-backed "custom" metrics exposed as a feval during training.

Same split as the reference (metrics/custom_metrics.py:233-280): metrics the
booster doesn't implement natively ride the feval channel and are printed in
the same stdout line as native metrics, so the HPO regex contract covers them
uniformly. Per the xgboost >= 1.2 convention, the feval receives the *raw
margin* (log-odds for binary, [n, C] margins for multiclass) and converts to
class labels itself (reference :38-44).

Order stability matters for distributed training: the configured metric list
is preserved as given; callers pass a sorted union (train_utils.py).
"""

import numpy as np
from sklearn.metrics import (
    accuracy_score,
    balanced_accuracy_score,
    f1_score,
    mean_absolute_error,
    mean_squared_error,
    precision_score,
    r2_score,
    recall_score,
)

from ..constants import MULTI_CLASS_F1_BINARY_ERROR
from ..toolkit import exceptions as exc


def sigmoid(x):
    """Margin -> probability; tanh form is stable for large |x|."""
    return 0.5 * (1 + np.tanh(0.5 * x))


def margin_to_class_label(preds):
    """Raw margin -> class label (argmax for multiclass, >0 for binary)."""
    preds = np.asarray(preds)
    if preds.ndim > 1:
        return np.argmax(preds, axis=-1)
    return (preds > 0.0).astype(int)


def _classification(metricfunc, check_binary=False):
    def compute(preds, dtrain):
        if np.asarray(preds).size == 0:
            return 0.0
        labels = dtrain.get_label()
        pred_labels = margin_to_class_label(preds)
        if check_binary and len(np.unique(labels)) > 2:
            raise exc.UserError(MULTI_CLASS_F1_BINARY_ERROR)
        return float(metricfunc(labels, pred_labels))

    return compute


def _regression(metricfunc):
    def compute(preds, dtrain):
        return float(metricfunc(dtrain.get_label(), np.asarray(preds)))

    return compute


CUSTOM_METRICS = {
    "accuracy": _classification(accuracy_score),
    "balanced_accuracy": _classification(balanced_accuracy_score),
    "f1": _classification(lambda y, p: f1_score(y, p, average="macro")),
    "f1_binary": _classification(
        lambda y, p: f1_score(y, p, average="binary"), check_binary=True
    ),
    "f1_macro": _classification(lambda y, p: f1_score(y, p, average="macro")),
    "mse": _regression(mean_squared_error),
    "rmse": _regression(lambda y, p: float(np.sqrt(mean_squared_error(y, p)))),
    "mae": _regression(mean_absolute_error),
    "precision": _classification(precision_score),
    "precision_macro": _classification(
        lambda y, p: precision_score(y, p, average="macro")
    ),
    "precision_micro": _classification(
        lambda y, p: precision_score(y, p, average="micro")
    ),
    "r2": _regression(r2_score),
    "recall": _classification(recall_score),
    "recall_macro": _classification(lambda y, p: recall_score(y, p, average="macro")),
    "recall_micro": _classification(lambda y, p: recall_score(y, p, average="micro")),
}


def get_custom_metrics(eval_metrics):
    """Subset of the requested metrics that we must compute via feval.

    Keeps input order — it must be consistent across hosts (reference
    custom_metrics.py:252-258).
    """
    return [m for m in eval_metrics if m in CUSTOM_METRICS]


def configure_feval(custom_metric_list):
    """Build the feval callable: (margin, dtrain) -> [(name, value), ...]."""

    def custom_feval(preds, dtrain):
        return [(name, CUSTOM_METRICS[name](preds, dtrain)) for name in custom_metric_list]

    return custom_feval
