from .custom_metrics import CUSTOM_METRICS, configure_feval, get_custom_metrics  # noqa: F401
