"""Persistent XLA compilation cache (``GRAFT_COMPILE_CACHE_DIR``).

First-round XLA compile is the dominant fixed cost of every short job: a
repeat training run, a CV fold sweep re-entering the same program shapes in
a fresh process, and every ``bench.py`` probe child all pay it again.
jax ships a persistent on-disk compilation cache
(``jax_compilation_cache_dir``) keyed by the serialized HLO + compile
options + backend version; arming it turns those repeat compiles into disk
reads (ROADMAP item 4a: first-round compile stops polluting short probes).

One knob: ``GRAFT_COMPILE_CACHE_DIR`` names the cache directory (created if
missing). Resolved ONCE per process at training-session build time — the
same host-side-snapshot discipline as the histogram knobs (the traced round
path never reads env), and jax reads the config at compile time, so arming
must happen before the first dispatch, never mid-job. Unset (the default)
leaves jax's in-memory jit cache as the only cache — bit-for-bit today's
behavior.

Cache correctness is jax's own contract (the key covers the executable's
identity including backend/toolchain versions); a corrupt or unwritable
directory degrades to a logged warning, never a failed job.
"""

import logging
import os
import threading

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_resolved = None  # None = not yet resolved; "" = resolved to disabled


def maybe_enable_compile_cache():
    """Arm jax's persistent compilation cache if the knob is set.

    Returns the armed directory path, or None when the knob is unset or
    arming failed. Idempotent and process-once: the first call resolves
    ``GRAFT_COMPILE_CACHE_DIR`` and every later call returns the same
    answer (flipping the env mid-process has no effect — sessions must see
    one consistent compile-cache state, like every other session knob).
    """
    global _resolved
    with _lock:
        if _resolved is not None:
            return _resolved or None
        path = os.environ.get("GRAFT_COMPILE_CACHE_DIR", "").strip()
        if not path:
            _resolved = ""
            return None
        import jax

        prev_dir = jax.config.jax_compilation_cache_dir
        try:
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            # cache every executable: short probes and repeat jobs pay many
            # small compiles, which the default write thresholds would skip
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            # jax latches its cache state at the FIRST compile of the
            # process: if anything jitted before this call (a model-load
            # predict warmup, preprocessing), the new dir would silently
            # never be read or written — clear the latch so arming works
            # regardless of prior compiles
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        except Exception as e:  # arming is an optimization, never an outage
            logger.warning(
                "GRAFT_COMPILE_CACHE_DIR=%r could not be armed: %s", path, e
            )
            try:
                # don't leave the cache half-armed while reporting disabled
                jax.config.update("jax_compilation_cache_dir", prev_dir)
            except Exception:
                pass
            _resolved = ""
            return None
        _resolved = path
        logger.info("persistent XLA compilation cache armed at %s", path)
        return path
