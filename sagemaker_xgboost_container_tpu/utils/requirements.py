"""Best-effort install of a user module's requirements.txt.

The reference installs a customer requirements.txt before loading
script-mode code (mms_patch/model_server.py:158-166, hard-failing on pip
errors) and the training toolkit does the same for training scripts. Same
semantics here, with one hardening on top of the reference: the install is
constrained so a customer pin cannot silently downgrade the framework's own
runtime (jax/numpy/...) underneath the live training job or model server.
Shared by the training and serving script-mode loaders.
"""

import logging
import os
import subprocess
import sys
import tempfile

from ..toolkit import exceptions as exc

logger = logging.getLogger(__name__)

# packages the framework itself depends on at runtime: a user
# requirements.txt may ADD packages freely but must not move these out from
# under the running server (ADVICE r2)
FRAMEWORK_CRITICAL = ("jax", "jaxlib", "libtpu", "numpy", "scipy", "pandas", "pyarrow")


def _write_constraints_file():
    """Pin the currently-installed versions of framework-critical packages
    into a pip constraints file. Returns the path, or None if nothing is
    pinnable (constraints only apply to packages the resolver touches, so
    absent packages need no entry)."""
    try:
        import importlib.metadata as md
    except ImportError:  # pragma: no cover - py<3.8
        return None
    pins = []
    for pkg in FRAMEWORK_CRITICAL:
        try:
            ver = md.version(pkg)
        except md.PackageNotFoundError:
            continue
        # PEP 440 local labels ('0.4.30+tpu...') name builds pip cannot
        # resolve against an index, so an exact pin would fail every user
        # install (ADVICE r3) — while pinning the *public* version would let
        # pip silently swap the platform build for the index wheel. Neither
        # is right: skip the pin and leave that package unguarded.
        if "+" in ver:
            logger.info(
                "Not constraining %s==%s (local build label; pip cannot "
                "resolve it against an index)", pkg, ver,
            )
            continue
        pins.append("{}=={}".format(pkg, ver))
    if not pins:
        return None
    fd, path = tempfile.mkstemp(prefix="graft-constraints-", suffix=".txt")
    with os.fdopen(fd, "w") as f:
        f.write("\n".join(pins) + "\n")
    return path


def install_requirements_if_present(code_dir):
    """pip-install ``code_dir/requirements.txt`` when it exists.

    The install runs under a constraints file pinning the framework's
    critical dependencies at their current versions — a conflicting customer
    pin fails loudly (UserError) instead of downgrading the live runtime.
    Set GRAFT_PIP_NO_CONSTRAINTS=1 to opt out. Raises UserError on pip
    failure (customer-fixable: bad pins, no network in the deployment
    environment, etc. — reference behavior)."""
    path = os.path.join(code_dir, "requirements.txt")
    if not os.path.isfile(path):
        return False
    logger.info("Installing packages from %s...", path)
    cmd = [sys.executable, "-m", "pip", "install", "-r", path]
    cpath = None
    if os.environ.get("GRAFT_PIP_NO_CONSTRAINTS") != "1":
        cpath = _write_constraints_file()
        if cpath:
            with open(cpath) as f:
                logger.info(
                    "Constraining framework-critical packages: %s",
                    ", ".join(f.read().split()),
                )
            cmd += ["-c", cpath]
    try:
        subprocess.check_call(cmd)
    except subprocess.CalledProcessError as e:
        raise exc.UserError(
            "Failed to install packages from the user module's "
            "requirements.txt ({}). If it pins a framework-critical package "
            "({}) to an incompatible version, remove the pin or set "
            "GRAFT_PIP_NO_CONSTRAINTS=1 to override at your own risk.".format(
                path, ", ".join(FRAMEWORK_CRITICAL)
            )
        ) from e
    finally:
        if cpath:
            try:
                os.unlink(cpath)
            except OSError:
                pass
    return True
