"""Best-effort install of a user module's requirements.txt.

The reference installs a customer requirements.txt before loading
script-mode code (mms_patch/model_server.py:158-166, hard-failing on pip
errors) and the training toolkit does the same for training scripts. Same
semantics here; shared by the training and serving script-mode loaders.
"""

import logging
import os
import subprocess
import sys

from ..toolkit import exceptions as exc

logger = logging.getLogger(__name__)


def install_requirements_if_present(code_dir):
    """pip-install ``code_dir/requirements.txt`` when it exists.

    Raises UserError on pip failure (customer-fixable: bad pins, no
    network in the deployment environment, etc. — reference behavior)."""
    path = os.path.join(code_dir, "requirements.txt")
    if not os.path.isfile(path):
        return False
    logger.info("Installing packages from %s...", path)
    cmd = [sys.executable, "-m", "pip", "install", "-r", path]
    try:
        subprocess.check_call(cmd)
    except subprocess.CalledProcessError as e:
        raise exc.UserError(
            "Failed to install packages from the user module's "
            "requirements.txt ({})".format(path)
        ) from e
    return True
