"""Console logging configuration (reference algorithm_mode/integration.py:16-52)."""

import logging
import logging.config


def setup_main_logger(name):
    """dictConfig console logger; returns the configured logger."""
    logging.config.dictConfig(
        {
            "version": 1,
            "disable_existing_loggers": False,
            "formatters": {
                "standard": {
                    "format": "[%(asctime)s:%(levelname)s] %(message)s",
                    "datefmt": "%Y-%m-%d:%H:%M:%S",
                }
            },
            "handlers": {
                "console": {
                    "class": "logging.StreamHandler",
                    "formatter": "standard",
                    "stream": "ext://sys.stdout",
                }
            },
            "root": {"level": "INFO", "handlers": ["console"]},
        }
    )
    return logging.getLogger(name)
