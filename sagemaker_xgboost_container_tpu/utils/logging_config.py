"""Console logging configuration (reference algorithm_mode/integration.py:16-52).

The root level honors ``SAGEMAKER_CONTAINER_LOG_LEVEL`` (reference parity:
the platform sets it from the Estimator's ``container_log_level``, as either
a name like ``DEBUG`` or a stdlib numeric level like ``10``); unset or
unparseable values fall back to INFO.
"""

import logging
import logging.config
import os

LOG_LEVEL_ENV = "SAGEMAKER_CONTAINER_LOG_LEVEL"


def _resolve_level():
    raw = os.environ.get(LOG_LEVEL_ENV, "").strip()
    if not raw:
        return "INFO"
    if raw.isdigit():
        return int(raw)
    level = logging.getLevelName(raw.upper())
    # getLevelName returns the string "Level <raw>" for unknown names
    return raw.upper() if isinstance(level, int) else "INFO"


def setup_main_logger(name):
    """dictConfig console logger; returns the configured logger.

    The console handler carries the request-correlation filter
    (``telemetry.correlation.RequestIdFilter``): on serving request threads
    every record gains the active request ID — both as ``record.request_id``
    and as a ``[rid=...]`` suffix — so a slow invocation can be traced from
    access log through batcher warnings to the response header.
    """
    logging.config.dictConfig(
        {
            "version": 1,
            "disable_existing_loggers": False,
            "filters": {
                "request_id": {
                    "()": "sagemaker_xgboost_container_tpu.telemetry"
                    ".correlation.RequestIdFilter"
                }
            },
            "formatters": {
                "standard": {
                    "format": "[%(asctime)s:%(levelname)s] %(message)s",
                    "datefmt": "%Y-%m-%d:%H:%M:%S",
                }
            },
            "handlers": {
                "console": {
                    "class": "logging.StreamHandler",
                    "formatter": "standard",
                    "filters": ["request_id"],
                    "stream": "ext://sys.stdout",
                }
            },
            "root": {"level": _resolve_level(), "handlers": ["console"]},
        }
    )
    return logging.getLogger(name)
