"""State integrity: content digests, manifest sidecars, model validation.

The container's durable state travels through paths that trust bytes
blindly: a restarted training job resumes from whatever
``xgboost-checkpoint.<iter>`` parses, and a serving endpoint loads whatever
artifact lands in the model dir. This module is the shared vocabulary that
closes that gap:

* **content digests** — sha256 over exact file bytes (``file_digest``) and
  over a Forest's committed trees in a canonical packed byte layout
  (``forest_digest``, the host mirror of the packed-tree u32 view the
  distributed bit-identity tests assert on),
* **manifest sidecars** — a versioned JSON file next to every checkpoint
  (``<name>.manifest``): model digest + byte count, boosting iteration, and
  a config fingerprint (objective/tree_method/max_bin/max_depth/world size/
  versions). ``training/checkpointing._atomic_save`` writes them;
  ``load_checkpoint`` refuses candidates whose digest disagrees,
* **resume validation** — ``validate_resume`` compares a checkpoint's
  fingerprint against the live job's and warns (or refuses under
  ``SM_RESUME_STRICT=true``): resuming under a different binning or
  objective config silently forks the model,
* **model validation** — ``check_model_file`` (digest, when a manifest
  travels with the artifact) + ``validate_model`` (structural: children in
  range, finite thresholds/values, consistent tree bookkeeping) turn a
  corrupt serving artifact into one clear load-time error instead of an
  inscrutable downstream predict failure.

Everything here is host-side numpy/hashlib — nothing touches the jitted
round path, so integrity checks add no device work.
"""

import hashlib
import json
import logging
import os

import numpy as np

logger = logging.getLogger(__name__)

MANIFEST_SUFFIX = ".manifest"
MANIFEST_VERSION = 1

RESUME_STRICT_ENV = "SM_RESUME_STRICT"

# config keys whose disagreement between a checkpoint and the live job means
# the resumed model would be built under different split candidates or a
# different loss — the silent-fork failure mode the resume validator exists
# to catch. Version/world-size drift is reported too but carries its own
# line so the operator can tell re-shard from re-config.
_FINGERPRINT_KEYS = (
    "objective",
    "tree_method",
    "max_bin",
    "max_depth",
    "world_size",
    "jax_version",
    "package_version",
)


class IntegrityError(RuntimeError):
    """A state artifact failed digest or structural verification."""


def resume_strict():
    from .envconfig import env_bool

    return env_bool(RESUME_STRICT_ENV, False)


# ------------------------------------------------------------------ digests
def sha256_bytes(data):
    return hashlib.sha256(data).hexdigest()


def file_digest(path, chunk_size=1 << 20):
    """Streaming sha256 of a file's exact bytes."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(chunk_size), b""):
            h.update(chunk)
    return h.hexdigest()


# canonical (name, dtype) layout for one tree's arrays. Float fields hash
# their raw IEEE bytes — the same u32-view identity the hist-comm
# equivalence suite asserts — so two trees agree iff they are bit-identical,
# not merely approximately equal.
_TREE_DIGEST_FIELDS = (
    ("feature", np.int32),
    ("threshold", np.float32),
    ("default_left", np.uint8),
    ("left", np.int32),
    ("right", np.int32),
    ("value", np.float32),
    ("base_weight", np.float32),
    ("gain", np.float32),
    ("sum_hess", np.float32),
)


def forest_digest(model):
    """sha256 over the model's committed state in canonical packed bytes.

    Tree models: every tree field the trainer commits (including
    categorical-split category sets on BYO/refreshed models) plus the
    per-tree class ids and round boundaries — i.e. exactly the state that
    must agree across ranks under the bit-identical-trees contract. Linear
    models (gblinear): the weight and bias arrays. Deterministic across
    processes/hosts (fixed field order, fixed dtypes).
    """
    h = hashlib.sha256()
    trees = getattr(model, "trees", None)
    if trees is None:
        # gblinear: the consensus-relevant state is weights + bias
        h.update(b"linear")
        for name in ("weights", "bias"):
            arr = np.ascontiguousarray(
                np.asarray(getattr(model, name, np.zeros(0)), np.float32)
            )
            h.update(arr.tobytes())
        return h.hexdigest()
    h.update(np.asarray(model.tree_info, np.int32).tobytes())
    h.update(np.asarray(model.iteration_indptr, np.int64).tobytes())
    for tree in trees:
        for name, dtype in _TREE_DIGEST_FIELDS:
            arr = np.ascontiguousarray(np.asarray(getattr(tree, name), dtype))
            h.update(arr.tobytes())
        for node in sorted(getattr(tree, "categories", {}) or {}):
            cats = np.ascontiguousarray(np.asarray(tree.categories[node], np.int64))
            # node id + set size prefix the variable-length array so
            # {1: [2]} can never collide with {1: [], 2: []} (same
            # injectivity rule as the per-tree node-count prefix below)
            h.update(np.asarray([node, cats.size], np.int64).tobytes())
            h.update(cats.tobytes())
        # length-prefix per tree so (tree of 3 nodes + tree of 5) can never
        # collide with (tree of 5 + tree of 3) concatenations
        h.update(np.asarray([tree.num_nodes], np.int64).tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------- manifests
def manifest_path(model_path):
    return str(model_path) + MANIFEST_SUFFIX


def build_manifest(
    model_path,
    iteration=None,
    fingerprint=None,
    digest=None,
    size=None,
    membership_log=None,
    quarantine=None,
    learning=None,
    drift_baseline=None,
):
    """Manifest dict for a model file — THE schema definition; every writer
    (checkpoint sidecars, final-model sidecars) goes through here. ``digest``
    / ``size`` override the on-disk read for callers that measured the temp
    file before renaming it into place. ``membership_log`` (elastic
    shrink-to-continue) is the append-only list of recorded world-size
    transitions the model trained through — the artifact later resumes
    validate ``world_size`` drift against. ``quarantine`` (streaming
    ingest) records the cross-rank-agreed set of input chunks the job
    trained *without* — the provenance record for 'this artifact lost
    those rows to corrupt input' (data/streaming.quarantine_record).
    ``learning`` (model telemetry, SM_MODEL_TELEMETRY) is the final
    learning-curve summary; ``drift_baseline`` is the training-time
    per-feature bin-occupancy histogram the serving drift monitor computes
    PSI against — both stamped only when the plane was armed."""
    manifest = {
        "manifest_version": MANIFEST_VERSION,
        "sha256": digest if digest is not None else file_digest(model_path),
        "bytes": int(size) if size is not None else os.path.getsize(model_path),
    }
    if iteration is not None:
        manifest["iteration"] = int(iteration)
    if fingerprint is not None:
        manifest["fingerprint"] = dict(fingerprint)
    if membership_log:
        manifest["membership_log"] = [dict(t) for t in membership_log]
    if quarantine:
        manifest["quarantine"] = dict(quarantine)
    if learning:
        manifest["learning"] = dict(learning)
    if drift_baseline:
        manifest["drift_baseline"] = dict(drift_baseline)
    return manifest


def dump_manifest_atomic(target_path, manifest, tmp_path):
    """THE manifest serialization + atomic landing: write ``manifest`` as
    sorted-key JSON to ``tmp_path``, rename over ``target_path``, and remove
    the temp on any failure. Both sidecar writers (checkpoint manifests with
    their retry wrapper, final-model manifests) go through here so the wire
    format and the no-debris guarantee can never diverge."""
    try:
        with open(tmp_path, "w") as f:
            json.dump(manifest, f, sort_keys=True)
        os.replace(tmp_path, target_path)
    except BaseException:
        try:
            os.remove(tmp_path)
        except OSError:
            pass
        raise


def write_manifest(model_path, iteration=None, fingerprint=None, membership_log=None,
                   quarantine=None, learning=None, drift_baseline=None):
    """Write ``model_path``'s sidecar manifest (tmp + rename, best-effort
    atomic). Used for final model artifacts in ``model_dir`` — serving's
    ``check_model_file`` digest-verifies any artifact whose manifest
    traveled with it. (Checkpoint manifests go through the checkpoint
    layer's retried atomic writer instead.)"""
    manifest = build_manifest(
        model_path,
        iteration=iteration,
        fingerprint=fingerprint,
        membership_log=membership_log,
        quarantine=quarantine,
        learning=learning,
        drift_baseline=drift_baseline,
    )
    target = manifest_path(model_path)
    # dot-prefixed temp: the serving loader skips dotfiles, so a crash here
    # can never leave a file the model dir scan would try to load (nor
    # package temp debris into model.tar.gz)
    tmp = os.path.join(
        os.path.dirname(target) or ".", "." + os.path.basename(target) + ".tmp"
    )
    dump_manifest_atomic(target, manifest, tmp)
    return manifest


def read_manifest(model_path):
    """Manifest dict for ``model_path``'s sidecar, or None.

    Missing sidecar -> None (older runs are manifest-less by design). A
    sidecar that exists but doesn't parse or lacks the digest returns None
    with a warning — the caller falls back to content-level validation, the
    exact behavior a corrupt *model* gets.
    """
    path = manifest_path(model_path)
    try:
        with open(path, "r") as f:
            manifest = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        logger.warning("ignoring unreadable manifest %s: %s", path, e)
        return None
    if not isinstance(manifest, dict) or not isinstance(manifest.get("sha256"), str):
        logger.warning("ignoring malformed manifest %s (no sha256)", path)
        return None
    if manifest.get("bytes") is not None:
        # a bit-rotted sidecar can be valid JSON with a garbage byte count;
        # it must degrade to "no usable manifest" (content-level fallback),
        # never crash the resume scan or the serving load
        try:
            manifest["bytes"] = int(manifest["bytes"])
        except (TypeError, ValueError):
            logger.warning("ignoring malformed manifest %s (bad byte count)", path)
            return None
    return manifest


def verify_file_against_manifest(model_path, manifest):
    """Raise IntegrityError when the file's bytes disagree with the manifest.

    ``manifest`` must come from :func:`read_manifest`, which guarantees a
    string digest and an int (or absent) byte count — anything less usable
    was already degraded to ``None`` there.
    """
    expected = manifest["sha256"]
    size = manifest.get("bytes")
    if size is not None and os.path.getsize(model_path) != int(size):
        raise IntegrityError(
            "{}: byte count {} != manifest {}".format(
                model_path, os.path.getsize(model_path), size
            )
        )
    actual = file_digest(model_path)
    if actual != expected:
        raise IntegrityError(
            "{}: sha256 {} != manifest {}".format(model_path, actual, expected)
        )


def check_model_file(model_path):
    """Digest-verify ``model_path`` against its sidecar manifest.

    -> ``"verified"`` when a manifest exists and the digest matches,
    ``"no_manifest"`` when no (usable) sidecar travels with the artifact
    (older runs, BYO models). Raises :class:`IntegrityError` on mismatch.
    """
    manifest = read_manifest(model_path)
    if manifest is None:
        return "no_manifest"
    verify_file_against_manifest(model_path, manifest)
    return "verified"


# -------------------------------------------------------------- fingerprint
def config_fingerprint(train_cfg, world_size=None):
    """The live job's config identity, as stored in checkpoint manifests.

    Captures the knobs that change split candidates or the loss (objective,
    tree_method, max_bin, max_depth), the data-parallel world size (binning
    merges per-host sketches, so a resharded resume re-bins), and the
    jax/package versions (a partial restart under version skew is how ranks
    end up tracing different round programs).
    """
    cfg = dict(train_cfg or {})
    if world_size is None:
        world_size = _live_world_size()
    return {
        "objective": str(cfg.get("objective", "reg:squarederror")),
        "tree_method": str(cfg.get("tree_method", "auto")),
        "max_bin": str(cfg.get("max_bin", "")),
        "max_depth": str(cfg.get("max_depth", "")),
        "world_size": int(world_size),
        "jax_version": _jax_version(),
        "package_version": _package_version(),
    }


def _live_world_size():
    # the elastic membership plane owns the cluster world size once it is
    # registered (it survives shrinks, and the CPU drill tiers simulate
    # hosts without one jax process per host); jax.process_count() is the
    # fallback for the plain multi-process path
    try:
        from ..training import elastic

        world = elastic.world_size()
        if world > 0:
            return int(world)
    except Exception:
        pass
    try:
        import jax

        return int(jax.process_count())
    except Exception:  # jax absent or uninitialized: single-process
        return 1


def _jax_version():
    try:
        import jax

        return str(jax.__version__)
    except Exception:
        return "absent"


def _package_version():
    try:
        from .. import __version__

        return str(__version__)
    except Exception:
        return "unknown"


def fingerprint_mismatches(expected, actual):
    """[(key, expected, actual), ...] for keys present in either dict."""
    out = []
    for key in _FINGERPRINT_KEYS:
        if key in (expected or {}) or key in (actual or {}):
            ev = (expected or {}).get(key)
            av = (actual or {}).get(key)
            if str(ev) != str(av):
                out.append((key, ev, av))
    return out


def _world_size_transition_recorded(old, new, membership_log):
    """True when the recorded transitions connect checkpoint world size
    ``old`` to live world size ``new``, in EITHER direction, chains
    included: a checkpoint written at 8 is resumable at 6 when 8→7 and 7→6
    are both on the log, and a checkpoint written at 2 after a recorded
    3→2 shrink is resumable when the platform restarts the job at the
    original 3 hosts (the resume re-shards back up — the 2 was a
    sanctioned, recorded state, not config skew)."""
    try:
        old, new = int(old), int(new)
    except (TypeError, ValueError):
        return False
    edges = {}
    for t in membership_log or []:
        try:
            a, b = int(t["old_world_size"]), int(t["new_world_size"])
        except (KeyError, TypeError, ValueError):
            continue
        edges.setdefault(a, set()).add(b)
        edges.setdefault(b, set()).add(a)
    seen, frontier = {old}, [old]
    while frontier:
        for nxt in edges.get(frontier.pop(), ()):
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
    return new in seen and new != old


def validate_resume(checkpoint_path, live_fingerprint, membership_log=None):
    """Compare the resume candidate's manifest fingerprint to the live job.

    Manifest-less checkpoints (older runs) pass silently. A fingerprint
    mismatch warns with the differing keys; under ``SM_RESUME_STRICT=true``
    it refuses (UserError) — resuming a hist model under different binning
    or a different objective silently changes what the remaining rounds
    optimize, the exact failure this guard exists to surface.

    **Recorded membership transitions** (elastic shrink-to-continue) are the
    one sanctioned exception: a ``world_size``-only drift covered by the
    transition log — the live plane's (``membership_log``) or the one
    stamped into the checkpoint's own manifest — resumes cleanly (INFO, not
    a warning, and never a strict-mode refusal): the shrink was a recorded,
    validated event, not config skew.
    """
    if checkpoint_path is None:
        return True
    manifest = read_manifest(checkpoint_path)
    if manifest is None or "fingerprint" not in manifest:
        return True
    diffs = fingerprint_mismatches(manifest["fingerprint"], live_fingerprint)
    if not diffs:
        return True
    ws_diffs = [d for d in diffs if d[0] == "world_size"]
    if ws_diffs and len(ws_diffs) == len(diffs):
        transitions = list(membership_log or []) + list(
            manifest.get("membership_log") or []
        )
        _key, ckpt_ws, live_ws = ws_diffs[0]
        if _world_size_transition_recorded(ckpt_ws, live_ws, transitions):
            logger.info(
                "resuming from %s across a recorded membership transition "
                "(world size %s -> %s): the shrink is on the membership log, "
                "rows repartition over the new data axis",
                checkpoint_path,
                ckpt_ws,
                live_ws,
            )
            return True
    detail = ", ".join(
        "{}: checkpoint={!r} live={!r}".format(k, ev, av) for k, ev, av in diffs
    )
    if resume_strict():
        from ..toolkit import exceptions as exc

        raise exc.UserError(
            "Refusing to resume from {}: its config fingerprint disagrees "
            "with the live job ({}). Align the configuration or clear the "
            "checkpoint dir; set {}=false to resume anyway (the remaining "
            "rounds would train under different binning/objective "
            "semantics).".format(checkpoint_path, detail, RESUME_STRICT_ENV)
        )
    logger.warning(
        "resuming from %s despite a config-fingerprint mismatch (%s); the "
        "remaining rounds will train under the LIVE config — set %s=true to "
        "refuse instead",
        checkpoint_path,
        detail,
        RESUME_STRICT_ENV,
    )
    return False


# --------------------------------------------------------- model validation
def _require(cond, tree_idx, message):
    if not cond:
        raise IntegrityError("tree {}: {}".format(tree_idx, message))


def validate_model(model):
    """Structural validation of a loaded model; raises IntegrityError.

    For tree models (Forest): every tree's arrays are consistent lengths,
    child indices of split nodes land inside the tree (and never self-loop),
    split thresholds and leaf values are finite, split feature ids are in
    range, and the forest bookkeeping (tree_info, iteration_indptr) matches
    the tree list. For linear models: finite weights. Anything else (user
    module model_fn objects) passes — their contract is their own.

    These are exactly the invariants the compiled predict kernels assume; a
    violated one produces garbage predictions or out-of-bounds gathers deep
    inside XLA, which is why a corrupt artifact must die HERE with a
    nameable error.
    """
    if isinstance(model, list):
        for m in model:
            validate_model(m)
        return
    trees = getattr(model, "trees", None)
    if trees is None:
        weights = getattr(model, "weights", None)
        if weights is not None and not np.all(np.isfinite(np.asarray(weights))):
            raise IntegrityError("linear model has non-finite weights")
        return
    num_feature = int(getattr(model, "num_feature", 0) or 0)
    num_group = int(getattr(model, "num_output_group", 1) or 1)
    tree_info = list(getattr(model, "tree_info", []))
    indptr = list(getattr(model, "iteration_indptr", [0, len(trees)]))
    if len(tree_info) != len(trees):
        raise IntegrityError(
            "tree_info length {} != {} trees".format(len(tree_info), len(trees))
        )
    if any(not 0 <= int(c) < num_group for c in tree_info):
        raise IntegrityError(
            "tree_info class ids out of range for {} output group(s)".format(num_group)
        )
    if (
        not indptr
        or indptr[0] != 0
        or indptr[-1] != len(trees)
        or any(b < a for a, b in zip(indptr, indptr[1:]))
    ):
        raise IntegrityError(
            "iteration_indptr is not a monotone partition of {} trees".format(len(trees))
        )
    for i, tree in enumerate(trees):
        n = int(tree.num_nodes)
        _require(n >= 1, i, "empty tree")
        for field in ("threshold", "default_left", "left", "right", "value"):
            _require(
                len(np.asarray(getattr(tree, field))) == n,
                i,
                "field {!r} length != {} nodes".format(field, n),
            )
        left = np.asarray(tree.left, np.int64)
        right = np.asarray(tree.right, np.int64)
        is_leaf = left < 0
        _require(
            bool(np.all((right < 0) == is_leaf)),
            i,
            "split nodes must have both children (left/right leaf flags disagree)",
        )
        split = ~is_leaf
        if np.any(split):
            ids = np.nonzero(split)[0]
            _require(
                bool(np.all((left[split] < n) & (right[split] < n))),
                i,
                "child index out of range (>= {} nodes)".format(n),
            )
            _require(
                bool(np.all((left[split] != ids) & (right[split] != ids))),
                i,
                "split node is its own child",
            )
            # categorical split nodes route by the per-node category set,
            # not the threshold — some xgboost exporters leave NaN there
            numeric_split = split.copy()
            for node in getattr(tree, "categories", {}) or {}:
                if 0 <= int(node) < n:
                    numeric_split[int(node)] = False
            _require(
                bool(np.all(np.isfinite(np.asarray(tree.threshold)[numeric_split]))),
                i,
                "non-finite split threshold",
            )
            feature = np.asarray(tree.feature, np.int64)[split]
            _require(bool(np.all(feature >= 0)), i, "negative split feature id")
            if num_feature > 0:
                _require(
                    bool(np.all(feature < num_feature)),
                    i,
                    "split feature id >= num_feature {}".format(num_feature),
                )
        _require(
            bool(np.all(np.isfinite(np.asarray(tree.value)[is_leaf]))),
            i,
            "non-finite leaf value",
        )
