"""Defensive env-var parsing for operational knobs.

Serving/config knobs are tuning levers, not correctness inputs: a typo in
one (``GRAFT_HOST_PREDICT_ROWS=off``) must degrade to the default, never
turn into a per-request exception and a serving outage.
"""

import os


def env_int(name, default):
    """int(os.environ[name]) with fallback to ``default`` on absent,
    empty, or malformed values."""
    raw = os.getenv(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        return default
