"""Defensive env-var parsing for operational knobs.

Serving/config knobs are tuning levers, not correctness inputs: a typo in
one (``GRAFT_HOST_PREDICT_ROWS=off``) must degrade to the default, never
turn into a per-request exception and a serving outage. Malformed values
log exactly one warning per variable per process (warn-once) so a typo is
visible in the job log without a reporter thread flooding it every
interval.

Range validation: out-of-range values clamp to the violated bound (an
``SM_HEARTBEAT_TIMEOUT_S=-3`` means "the operator wanted a short timeout" —
clamping to the 0.1s minimum honors the intent where a hard fallback to
the default would not). Note the clamp bound is the caller's choice: for
knobs where the minimum IS the disabled value (interval knobs with
``minimum=0``), a negative value disables the feature — the warn-once
makes that visible in the job log.
"""

import logging
import math
import os
import threading

logger = logging.getLogger(__name__)

_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("0", "false", "no", "off")

_warn_lock = threading.Lock()
_warned = set()


def _warn_once(name, raw, expected, used):
    with _warn_lock:
        if name in _warned:
            return
        _warned.add(name)
    logger.warning(
        "ignoring malformed %s=%r (expected %s); using %r", name, raw, expected, used
    )


def _clamp(name, raw, value, minimum, maximum):
    if minimum is not None and value < minimum:
        _warn_once(name, raw, ">= {}".format(minimum), minimum)
        return minimum
    if maximum is not None and value > maximum:
        _warn_once(name, raw, "<= {}".format(maximum), maximum)
        return maximum
    return value


def env_int(name, default, minimum=None, maximum=None):
    """int(os.environ[name]) with fallback to ``default`` on absent,
    empty, or malformed values; out-of-range values clamp (warn-once)."""
    raw = os.getenv(name)
    if raw is None or raw == "":
        return default
    try:
        value = int(raw)
    except ValueError:
        _warn_once(name, raw, "an integer", default)
        return default
    return _clamp(name, raw, value, minimum, maximum)


def env_float(name, default, minimum=None, maximum=None):
    """float(os.environ[name]) with fallback to ``default`` on absent,
    empty, or malformed values; out-of-range values clamp (warn-once)."""
    raw = os.getenv(name)
    if raw is None or raw == "":
        return default
    try:
        value = float(raw)
    except ValueError:
        _warn_once(name, raw, "a number", default)
        return default
    if not math.isfinite(value):  # NaN/inf: _clamp can't catch NaN, and an
        # inf interval would arm a wait() that never fires — both malformed
        _warn_once(name, raw, "a finite number", default)
        return default
    return _clamp(name, raw, value, minimum, maximum)


def env_port(name, default):
    """TCP-port env knob: ``env_int`` clamped to the valid port range.

    Every control-plane port (rendezvous, heartbeat, abort, consensus,
    reform) shares this rule; a knob like ``SM_REFORM_PORT=0`` clamps to 1
    with the usual warn-once rather than silently binding an ephemeral
    port the peers could never guess.
    """
    return env_int(name, default, minimum=1, maximum=65535)


def env_bool(name, default):
    """Boolean env knob: 1/true/yes/on and 0/false/no/off (case-insensitive);
    absent/empty -> ``default``; anything else -> ``default`` with a single
    warning."""
    raw = os.getenv(name)
    if raw is None or raw == "":
        return default
    lowered = raw.strip().lower()
    if lowered in _TRUTHY:
        return True
    if lowered in _FALSY:
        return False
    _warn_once(name, raw, "a boolean ({}/{})".format("|".join(_TRUTHY), "|".join(_FALSY)), default)
    return default
