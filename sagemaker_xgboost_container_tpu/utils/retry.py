"""Bounded transient-failure retries with jittered exponential backoff.

Channel ingest and checkpoint writes sit on network filesystems (S3 via
Fast File mode, EBS under load) where transient ``OSError``s are routine; a
single blip must not fail a multi-hour training job, and an unbounded retry
loop must not mask a real outage. Policy:

* bounded attempts (``SM_IO_RETRY_ATTEMPTS``, default 3 — i.e. 2 retries),
* exponential backoff from ``SM_IO_RETRY_BACKOFF_S`` (default 0.1s) with
  half-to-full jitter so a fleet of hosts doesn't retry in lockstep,
* one WARNING per call-site per process (warn-once, same contract as
  envconfig); every retry is counted in ``io_retries_total{site=...}`` and
  exhaustion in ``io_retry_exhausted_total{site=...}``,
* only ``retry_on`` exception types retry (default ``OSError`` — which
  covers IOError, ConnectionError, socket.timeout); semantic errors
  (UserError, parse failures) propagate immediately.
"""

import logging
import random
import threading
import time

from .envconfig import env_float, env_int

logger = logging.getLogger(__name__)

RETRY_ATTEMPTS_ENV = "SM_IO_RETRY_ATTEMPTS"
RETRY_BACKOFF_ENV = "SM_IO_RETRY_BACKOFF_S"

_warn_lock = threading.Lock()
_warned_sites = set()


def _warn_once_per_site(site, error, attempt, attempts, delay):
    with _warn_lock:
        if site in _warned_sites:
            return
        _warned_sites.add(site)
    logger.warning(
        "transient failure at %s (attempt %d/%d): %s — retrying in %.2fs; "
        "further retries are counted in io_retries_total without logging",
        site,
        attempt,
        attempts,
        error,
        delay,
    )


def reset_warnings():
    """Test hook: clear the warn-once memory."""
    with _warn_lock:
        _warned_sites.clear()


def retry_attempts():
    return env_int(RETRY_ATTEMPTS_ENV, 3, minimum=1, maximum=20)


def retry_backoff_s():
    return env_float(RETRY_BACKOFF_ENV, 0.1, minimum=0.0, maximum=30.0)


def retry_transient(
    fn,
    site,
    retry_on=(OSError,),
    attempts=None,
    backoff_s=None,
    sleep=time.sleep,
    rng=random.random,
):
    """Run ``fn()`` with bounded retries on transient errors.

    ``site`` names the call site for the warn-once guard and metric labels
    (e.g. ``"reader.csv"``). The final failure re-raises the original
    exception unchanged so callers' error taxonomy keeps working.
    """
    from ..telemetry.registry import REGISTRY

    max_attempts = attempts if attempts is not None else retry_attempts()
    base = backoff_s if backoff_s is not None else retry_backoff_s()
    for attempt in range(1, max_attempts + 1):
        try:
            return fn()
        except retry_on as e:
            if attempt >= max_attempts:
                REGISTRY.counter(
                    "io_retry_exhausted_total",
                    "Operations that failed after exhausting retries",
                    {"site": site},
                ).inc()
                logger.warning(
                    "giving up on %s after %d attempt(s): %s", site, attempt, e
                )
                raise
            # exponential backoff with half-to-full jitter: delay in
            # [0.5, 1.0] x base*2^(attempt-1); jitter decorrelates a host
            # fleet hammering the same recovering filesystem
            delay = base * (2 ** (attempt - 1)) * (0.5 + rng() / 2.0)
            REGISTRY.counter(
                "io_retries_total", "Transient-failure retries", {"site": site}
            ).inc()
            _warn_once_per_site(site, e, attempt, max_attempts, delay)
            if delay > 0:
                sleep(delay)
