"""Process-wide warn-once guard.

Shared by modules that log a condition the first time only (further hits
stay visible through metrics, not log spam). Key by a module-prefixed
string (``"ingest.empty_files"``) so unrelated callers never collide.
"""

import threading

_lock = threading.Lock()
_seen = set()


def warn_once(log, key, message, *args):
    """Log ``message`` via ``log.warning`` the first time ``key`` is seen
    in this process; later calls are silent. -> True when it logged."""
    with _lock:
        if key in _seen:
            return False
        _seen.add(key)
    log.warning(message, *args)
    return True


def reset_warnings():
    """Test hook: forget every key (the next warn_once fires again)."""
    with _lock:
        _seen.clear()
