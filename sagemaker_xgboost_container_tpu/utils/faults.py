"""Deterministic fault injection for chaos tests and failure drills.

None of the container's failure paths (watchdog abort, retrying readers,
load-shedding, SIGTERM model flush) are testable against *real* hardware
faults — a wedged TPU host or a mid-upload kill cannot be scripted in CI.
This module gives every failure path a named **fault point**: production
code calls ``fault_point("data.read", path=...)`` at the spot where the
real world could misbehave, and the ``SM_FAULT_SPEC`` env var (or a direct
``configure()`` call in tests) arms deterministic misbehavior there.

Spec grammar (entries separated by ``;`` or ``,``)::

    SM_FAULT_SPEC = "<point>:<action>[:<param>][@<n>|@<n>+] [; ...]"

    data.read:error:boom          every hit raises OSError("boom")
    data.read:error@2             only the 2nd hit raises
    checkpoint.save:error@3+      3rd hit and every one after
    training.round_end:sleep:30   every round stalls 30s (watchdog drills)
    training.round_end:sigterm@3  3rd round delivers SIGTERM to this process
    sync.accept:drop              raises ConnectionError (socket drop)
    batcher.dispatch:exit:9       hard-exits the process (host death)
    training.round_end:kill@4     4th round SIGKILLs this process (dead host)
    batcher.dispatch:sleep:120@2  wedges the 2nd predict dispatch (the
                                  stuck-predict watchdog drill)
    serving.decode:error:bad      every payload decode 415s
    predict.dispatch:sleep:5      request-thread predict stalls (deadline
                                  drills); serving.encode is its twin on
                                  the response side
    data.chunk:error:rot@2        the 2nd streaming-ingest chunk read fails
                                  (retry->skip->quarantine drills; @2+ with
                                  a small SM_INGEST_MAX_BAD_CHUNKS drills
                                  budget exhaustion -> exit 85)
    train.gradient_poison:nan@5   the 5th round's margins are poisoned with
                                  NaN before dispatch (numeric-health drill:
                                  the learning-telemetry guard must catch it
                                  and abort with exit 87)

Actions: ``error[:msg]`` -> OSError, ``drop`` -> ConnectionError,
``sleep:<seconds>``, ``sigterm`` (os.kill SIGTERM), ``exit:<code>``
(``os._exit`` — simulated host death, no cleanup), ``kill`` (SIGKILL to
self — the kill-rank drill helper: unlike ``exit``, not even atexit/flush
machinery runs, exactly like a preempted or OOM-killed host; arm it on one
rank's env to kill that specific rank deterministically), ``nan`` (no
raise — ``fault_point`` returns truthy and the *call site* poisons its own
data; used by numeric-poison drills where the corruption must flow through
the real device pipeline rather than short-circuit it).

**Zero overhead when unarmed**: with ``SM_FAULT_SPEC`` unset the module
global stays ``None`` and ``fault_point`` is a single attribute read and
return — no dict lookup, no lock, no allocation. Malformed spec entries
are skipped with one warning each (a typo in a chaos drill must not take
down the job being drilled).
"""

import logging
import os
import signal
import threading
import time

logger = logging.getLogger(__name__)

FAULT_SPEC_ENV = "SM_FAULT_SPEC"

_ACTIONS = ("error", "drop", "sleep", "sigterm", "exit", "kill", "nan")

# None = inert (the common case); else {point: [_Rule, ...]}
_ACTIVE = None


class _Rule:
    """One armed fault: an action bound to a point with a hit window."""

    def __init__(self, point, action, param=None, start=1, only=None):
        self.point = point
        self.action = action
        self.param = param
        self.start = start  # first hit (1-based) the rule fires on
        self.only = only    # fire on exactly this hit, or None for start+
        self.hits = 0
        self.fired = 0
        self._lock = threading.Lock()

    def fire(self, ctx):
        with self._lock:
            self.hits += 1
            hit = self.hits
        if self.only is not None:
            if hit != self.only:
                return
        elif hit < self.start:
            return
        with self._lock:
            self.fired += 1
        logger.warning(
            "fault injected at %r (hit %d): %s%s ctx=%r",
            self.point,
            hit,
            self.action,
            ":{}".format(self.param) if self.param is not None else "",
            ctx,
        )
        if self.action == "error":
            raise OSError(self.param or "fault-injected IO error at {}".format(self.point))
        if self.action == "drop":
            raise ConnectionError(
                self.param or "fault-injected connection drop at {}".format(self.point)
            )
        if self.action == "sleep":
            time.sleep(float(self.param))
            return
        if self.action == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
            # give the handler a beat to run before the caller proceeds
            time.sleep(float(self.param) if self.param else 5.0)
            return
        if self.action == "exit":
            os._exit(int(self.param) if self.param else 1)
        if self.action == "kill":
            # the kill-rank drill: SIGKILL leaves no chance for handlers,
            # flushes, or socket shutdowns — the honest stand-in for a
            # preempted/OOM-killed host in elastic-membership drills
            os.kill(os.getpid(), signal.SIGKILL)
        if self.action == "nan":
            # no raise: the call site owns the poisoning so the bad values
            # travel the same device path real numeric corruption would
            return True
        return None


def _parse_entry(entry):
    """``point:action[:param][@n[+]]`` -> _Rule (raises ValueError)."""
    entry = entry.strip()
    if not entry:
        return None
    spec, start, only = entry, 1, None
    if "@" in entry:
        spec, _, trigger = entry.rpartition("@")
        trigger = trigger.strip()
        if trigger.endswith("+"):
            start = int(trigger[:-1])
        else:
            only = int(trigger)
        if (only is not None and only < 1) or start < 1:
            raise ValueError("hit trigger must be >= 1")
    parts = spec.split(":", 2)
    if len(parts) < 2:
        raise ValueError("expected <point>:<action>")
    point, action = parts[0].strip(), parts[1].strip()
    param = parts[2].strip() if len(parts) == 3 else None
    if not point or action not in _ACTIONS:
        raise ValueError("unknown action {!r} (one of {})".format(action, _ACTIONS))
    if action == "sleep":
        float(param)  # validate eagerly, not at fire time
    if action == "exit" and param is not None:
        int(param)
    return _Rule(point, action, param=param, start=start, only=only)


def configure(spec):
    """(Re)arm the harness from a spec string; ``None``/empty disarms.

    Malformed entries are skipped with a warning — a chaos drill with a
    typo'd entry still injects its valid ones.
    """
    global _ACTIVE
    if not spec or not spec.strip():
        _ACTIVE = None
        return None
    rules = {}
    for raw in spec.replace(";", ",").split(","):
        try:
            rule = _parse_entry(raw)
        except (ValueError, TypeError) as e:
            logger.warning("ignoring malformed %s entry %r: %s", FAULT_SPEC_ENV, raw, e)
            continue
        if rule is not None:
            rules.setdefault(rule.point, []).append(rule)
    _ACTIVE = rules or None
    if _ACTIVE:
        logger.warning(
            "fault injection ARMED at %d point(s): %s",
            len(_ACTIVE),
            ", ".join(sorted(_ACTIVE)),
        )
    return _ACTIVE


def configure_from_env():
    """Arm from ``SM_FAULT_SPEC`` (called once at import; tests re-call)."""
    return configure(os.getenv(FAULT_SPEC_ENV))


def reset():
    """Disarm every fault (test teardown)."""
    global _ACTIVE
    _ACTIVE = None


def fault_counts():
    """-> {point: total fires} for armed points (test assertions)."""
    active = _ACTIVE
    if not active:
        return {}
    return {
        point: sum(r.fired for r in rules) for point, rules in active.items()
    }


def fault_point(name, **ctx):
    """Declare a named fault point. Inert (one global read) unless armed.

    Returns truthy when a ``nan`` rule fired — the call site then poisons
    its own data in place; every other action either raises or returns
    falsy, so existing callers that ignore the return are unaffected.
    """
    active = _ACTIVE
    if active is None:
        return None
    rules = active.get(name)
    if not rules:
        return None
    fired = False
    for rule in rules:
        if rule.fire(ctx):
            fired = True
    return fired or None


configure_from_env()
