"""Multi-host cluster lifecycle: rendezvous, membership sync, clean exits.

TPU-native re-design of the reference's Rabit lifecycle (distributed.py:42-263
+ the vendored tracker in dmlc_patch/tracker.py). What survives is the
*semantics*, not the machinery:

* ranks are deterministic: sorted hostnames, master = rank 0
  (reference distributed.py:155, :207),
* before training, hosts exchange "do I have data?" and hosts without data
  exit(0) while the rest re-form the cluster (the reference's double rabit
  init, :78-109),
* DNS wait with exponential backoff up to ~15 min before any distributed work
  (:30-39).

What's gone: the tree/ring allreduce topology and per-iteration model
broadcast — gradient histograms are psum'd *inside* the jitted round step
over the JAX mesh (ICI/DCN), which XLA schedules; there is nothing to
hand-route. The TCP exchange here is a tiny metadata-only allgather used
once at startup (the analog of RabitHelper.synchronize, :125-138), not a
training-path collective. ``jax.distributed.initialize`` (coordinator =
sorted-hosts[0]) brings up the multi-host XLA runtime itself.
"""

import json
import logging
import socket
import struct
import threading
import time

from ..toolkit import exceptions as exc
from ..utils.envconfig import env_float, env_port
from ..utils.faults import fault_point

logger = logging.getLogger(__name__)

DEFAULT_PORT = 9099

SYNC_RECV_TIMEOUT_ENV = "SM_SYNC_RECV_TIMEOUT_S"

ABORT_PORT_ENV = "SM_ABORT_PORT"
# NOT the rendezvous (9099) or heartbeat (9199) ports: the abort channel
# must stay reachable while both of those are mid-conversation
DEFAULT_ABORT_PORT = 9299

# an abort/rendezvous frame is small JSON; a stray HTTP client's request
# line parses as a ~500MB u32 length — reject before allocating on it
MAX_CONTROL_FRAME_BYTES = 1 << 20


def sync_recv_timeout():
    return env_float(SYNC_RECV_TIMEOUT_ENV, 30.0, minimum=0.1, maximum=600.0)


def wait_hostname_resolution(sm_hosts, max_wait_seconds=900):
    """Block until every host resolves in DNS (exponential backoff)."""
    delay = 1.0
    deadline = time.time() + max_wait_seconds
    for host in sm_hosts:
        while True:
            try:
                socket.gethostbyname(host)
                break
            except socket.gaierror:
                if time.time() > deadline:
                    raise exc.PlatformError(
                        "Could not resolve hostname {} within {}s".format(
                            host, max_wait_seconds
                        )
                    )
                time.sleep(min(delay, 30.0))
                delay *= 2


def frame_message(obj):
    """Length-prefixed JSON framing: ``<u32 little-endian length><payload>``.

    The one wire format shared by the rendezvous allgather below and the
    cluster telemetry heartbeats (telemetry/cluster.py) — a single framing
    implementation keeps the two protocols trivially interoperable and
    testable off-socket.
    """
    payload = json.dumps(obj).encode()
    return struct.pack("<I", len(payload)) + payload


def send_message(sock, obj):
    sock.sendall(frame_message(obj))


def recv_message(sock, timeout=None):
    """One framed message under a TOTAL deadline.

    Historically this was the *unbounded* reader (a ``recv`` loop whose
    per-chunk timeout reset forever — the exact trickle-wedge class
    ``recv_message_bounded`` was built to kill, and the graftlint
    ``socket-unbounded`` rule now rejects). It survives as a convenience
    wrapper over the bounded reader with the rendezvous default deadline
    (``SM_SYNC_RECV_TIMEOUT_S``); pass ``timeout`` to override.
    """
    return recv_message_bounded(
        sock, sync_recv_timeout() if timeout is None else timeout
    )


def recv_message_bounded(sock, timeout, max_bytes=MAX_CONTROL_FRAME_BYTES):
    """Read one framed message under a TOTAL deadline.

    A per-recv timeout that resets on every chunk lets a peer trickling
    one byte per timeout window hold the reader indefinitely — exactly
    the wedge this reader exists to bound (and ``recv_message`` now
    delegates here rather than risk it). Also sanity-caps the length
    prefix so a stray client can't make us block on (or allocate) a
    garbage frame. Shared by the rendezvous collect loop, the heartbeat
    aggregator, and the abort listener.
    """
    deadline = time.monotonic() + timeout

    def _read(n):
        buf = b""
        while len(buf) < n:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("frame read deadline exceeded")
            sock.settimeout(remaining)
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    (length,) = struct.unpack("<I", _read(4))
    if max_bytes is not None and length > max_bytes:
        raise ValueError("oversized control frame ({} bytes)".format(length))
    return json.loads(_read(length).decode())


# historical private names, kept for in-repo callers
_send_msg = send_message
_recv_msg = recv_message


class Cluster:
    """Deterministic-rank host group with a one-shot metadata allgather."""

    def __init__(self, hosts, current_host, port=DEFAULT_PORT):
        self.hosts = sorted(hosts)
        self.current_host = current_host
        self.port = port
        self.rank = self.hosts.index(current_host)
        self.master_host = self.hosts[0]

    @property
    def is_master(self):
        return self.rank == 0

    @property
    def num_hosts(self):
        return len(self.hosts)

    def _missing_ranks_error(self, results, timeout):
        missing = sorted(set(range(self.num_hosts)) - set(results))
        return exc.PlatformError(
            "Cluster rendezvous timed out after {}s: missing rank(s) {} "
            "(hosts {}). Those hosts are down, unreachable on port {}, or "
            "sending too slowly.".format(
                timeout,
                missing,
                [self.hosts[r] for r in missing],
                self.port,
            )
        )

    def synchronize(self, payload, timeout=300, recv_timeout=None,
                    max_frame_bytes=MAX_CONTROL_FRAME_BYTES):
        """Allgather small JSON payloads across hosts -> list in rank order.

        Master accepts one connection per worker, collects payloads, sends
        the full rank-ordered list back (the reference's synchronize,
        distributed.py:125-138). Single-host clusters short-circuit.

        Every blocking step is deadlined: ``timeout`` bounds the whole
        collect loop (accept used to be the only deadlined call — a worker
        that connected and then stalled or trickled bytes hung the master
        forever), and each connection's recv runs under ``recv_timeout``
        (``SM_SYNC_RECV_TIMEOUT_S``, default 30s) via the total-deadline
        frame reader. On expiry the master raises ``PlatformError`` naming
        the missing ranks/hosts.

        ``max_frame_bytes`` bounds each received frame; exchanges whose
        payloads legitimately exceed the 1 MiB control default (the ingest
        sketch allgather scales with features x wire cap x world size)
        pass a budget sized to what they actually send — every rank must
        pass the same value or the reply is refused on the smaller side.
        """
        if self.num_hosts == 1:
            return [payload]
        if recv_timeout is None:
            recv_timeout = sync_recv_timeout()
        if self.is_master:
            results = {0: payload}
            server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            server.bind(("0.0.0.0", self.port))
            server.listen(self.num_hosts)
            deadline = time.monotonic() + timeout
            conns = []
            try:
                while len(results) < self.num_hosts:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise self._missing_ranks_error(results, timeout)
                    server.settimeout(remaining)
                    try:
                        conn, addr = server.accept()
                    except socket.timeout:
                        raise self._missing_ranks_error(results, timeout)
                    fault_point("sync.accept", addr=addr)
                    try:
                        msg = recv_message_bounded(
                            conn, min(recv_timeout, remaining),
                            max_bytes=max_frame_bytes,
                        )
                        rank = int(msg["rank"])
                        if not 0 <= rank < self.num_hosts or rank in results:
                            raise ValueError(
                                "invalid or duplicate rank {}".format(rank)
                            )
                        payload_value = msg["payload"]
                    except (OSError, ValueError, KeyError, TypeError) as e:
                        # a wedged/trickling/garbage peer (stray client,
                        # out-of-range or already-claimed rank): drop the
                        # conn and keep collecting — a *real* rank stays
                        # missing and the overall deadline names it
                        logger.warning(
                            "rendezvous: dropping connection from %s (%s); "
                            "its rank remains outstanding", addr, e
                        )
                        conn.close()
                        continue
                    results[rank] = payload_value
                    conns.append(conn)
                ordered = [results[r] for r in range(self.num_hosts)]
                for conn in conns:
                    try:
                        # recv_message_bounded left the conn at whatever
                        # sliver of its frame deadline remained; give the
                        # reply its own full send budget
                        conn.settimeout(recv_timeout)
                        _send_msg(conn, ordered)
                    except OSError as e:
                        # the worker will retry its own connect loop; the
                        # allgather result is already complete for the rest
                        logger.warning("rendezvous: reply send failed: %s", e)
                    finally:
                        conn.close()
            finally:
                server.close()
            return ordered
        # worker: connect with retry (master may be slow to bind)
        deadline = time.monotonic() + timeout
        last_err = None
        while time.monotonic() < deadline:
            try:
                sock = socket.create_connection((self.master_host, self.port), timeout=10)
                try:
                    _send_msg(sock, {"rank": self.rank, "payload": payload})
                    return recv_message_bounded(
                        sock, max(deadline - time.monotonic(), 0.1),
                        max_bytes=max_frame_bytes,
                    )
                finally:
                    sock.close()
            except (ConnectionError, OSError) as e:
                last_err = e
                time.sleep(1.0)
        raise exc.PlatformError(
            "Could not synchronize with master {}".format(self.master_host),
            caused_by=last_err,
        )


# --------------------------------------------------------------- abort plane
def abort_port():
    return env_port(ABORT_PORT_ENV, DEFAULT_ABORT_PORT)


class AbortListener:
    """Per-host abort endpoint: accept one framed ``{"type": "abort"}`` JSON
    message and hand it to ``handler``.

    The listener exists because a dead peer stalls every survivor *inside*
    a jitted collective — no in-band channel can reach them. Rank 0's
    stale-host detector (telemetry/cluster.py) broadcasts an abort frame
    here so every rank exits cleanly (checkpoint flushed, distinct exit
    code) instead of deadlocking. Daemon thread, bounded accept timeout,
    junk frames dropped; the handler is responsible for the actual abort
    (training/watchdog.request_abort).
    """

    def __init__(self, handler, port=None, frame_timeout=5.0):
        self.handler = handler
        self.frame_timeout = frame_timeout
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("0.0.0.0", abort_port() if port is None else port))
        self._server.listen(8)
        self._server.settimeout(0.2)
        self.port = self._server.getsockname()[1]
        self._stop = threading.Event()
        # duplicate-frame suppression: two ranks detecting the same dead
        # host each broadcast the same frame; the handler must fire once
        # per distinct frame, and racing deliveries must serialize (the
        # dispatch lock) so conflicting exit codes resolve first-wins
        # rather than interleaving
        self._dispatch_lock = threading.Lock()
        self._seen_frames = set()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="abort-listener"
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        if self._thread.ident is not None:  # never-started listeners close clean
            self._thread.join(timeout)
        try:
            self._server.close()
        except OSError:
            pass

    def _run(self):
        while not self._stop.is_set():
            try:
                conn, addr = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # socket closed under us
            try:
                msg = recv_message_bounded(conn, self.frame_timeout)
            except Exception as e:
                logger.debug("abort listener: dropping malformed frame: %s", e)
                continue
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
            self._dispatch(msg, addr)
        try:
            self._server.close()
        except OSError:
            pass

    def _dispatch(self, msg, addr):
        """Hand one decoded frame to the handler — idempotently.

        Two ranks detecting the same dead host broadcast frames that differ
        only in ``source``; the event key drops it, so the second delivery
        is a logged no-op instead of a double-fired abort/shrink. Distinct
        frames (a later shrink generation, a different reason) still pass.
        Dispatch is serialized under a lock so racing deliveries can't
        interleave — the first frame's verdict (exit code, survivor set)
        settles before the next is even considered.
        """
        if not (isinstance(msg, dict) and msg.get("type") == "abort"):
            logger.warning("abort listener: ignoring non-abort frame from %s", addr)
            return False
        key = json.dumps(
            {k: v for k, v in msg.items() if k != "source"}, sort_keys=True
        )
        with self._dispatch_lock:
            if key in self._seen_frames:
                logger.info(
                    "abort listener: duplicate %s frame from %s suppressed "
                    "(already handled)",
                    msg.get("verb", "abort"),
                    msg.get("source", addr[0]),
                )
                return False
            self._seen_frames.add(key)
            logger.error(
                "%s frame received from %s (reason: %s)",
                msg.get("verb", "abort"),
                msg.get("source", addr[0]),
                msg.get("reason", "unspecified"),
            )
            try:
                self.handler(msg)
            except Exception:
                logger.exception("abort handler failed")
            return True


def broadcast_abort(
    hosts,
    reason,
    source=None,
    port=None,
    timeout=2.0,
    exit_code=None,
    extra=None,
    peer_addrs=None,
):
    """Best-effort abort fan-out: one framed message per host, bounded
    connect/send timeouts, failures logged not raised (a host that's
    already dead is exactly why we're broadcasting). Returns the number of
    hosts the frame was delivered to. ``exit_code`` (when given) rides in
    the frame so receivers exit with the broadcaster's distinguishing code
    (watchdog._frame_exit_code bounds it receiver-side). ``extra`` fields
    merge into the frame — the elastic plane rides a ``verb: "shrink"`` plus
    the survivor set here instead of inventing a second control channel.
    ``peer_addrs`` optionally maps a host to its ``(addr, port)`` pair
    (loopback drills, where every "host" is 127.0.0.1 on a distinct port);
    unmapped hosts resolve by name on the default port."""
    default_port = abort_port() if port is None else port
    frame = {"type": "abort", "reason": reason, "source": source}
    if exit_code is not None:
        frame["exit_code"] = int(exit_code)
    if extra:
        frame.update(extra)
    delivered = 0
    for host in hosts:
        addr, target_port = (peer_addrs or {}).get(host, (host, None))
        if target_port is None:
            target_port = default_port
        fault_point("abort.broadcast", host=host)
        try:
            sock = socket.create_connection((addr, target_port), timeout=timeout)
            try:
                sock.settimeout(timeout)
                sock.sendall(frame_message(frame))
                delivered += 1
            finally:
                sock.close()
        except OSError as e:
            logger.warning("abort broadcast to %s:%d failed: %s", addr, target_port, e)
    return delivered


REFORM_PORT_ENV = "SM_REFORM_PORT"
# NOT the rendezvous (9099), heartbeat (9199), abort (9299), or consensus
# (9399) ports: survivors re-rendezvous while the dead host's half-open
# conversations on those ports may still be draining
DEFAULT_REFORM_PORT = 9499


def reform_port():
    return env_port(REFORM_PORT_ENV, DEFAULT_REFORM_PORT)


def reform_cluster(
    survivors,
    current_host,
    generation,
    payload=None,
    port=None,
    timeout=60.0,
    master_addr=None,
):
    """Survivor re-rendezvous: one bounded allgather over the shrunken host
    list -> (new Cluster, rank-ordered membership payloads).

    The elastic-membership analog of the startup handshake: every survivor
    runs the same retried, deadline-bounded ``Cluster.synchronize`` on the
    dedicated reform port (``SM_REFORM_PORT``), exchanging
    ``{host, generation, ...payload}``. The handshake retries through
    ``utils.retry`` (site ``rendezvous.reform`` — one port-rebind race or
    connect blip must not turn a survivable shrink into exit 82), and the
    ``rendezvous.reform`` fault point makes reform failure drillable. A
    generation mismatch in any reply is a hard error: a peer answering with
    a different shrink generation missed (or double-counted) a membership
    transition and MUST NOT silently join — the two sides would disagree on
    the world size their checkpoints and consensus checks assume.

    ``master_addr`` overrides DNS resolution of the survivor master
    (loopback drills), exactly like the consensus exchange.
    """
    cluster = Cluster(
        survivors, current_host, port=reform_port() if port is None else port
    )
    if master_addr is not None:
        cluster.master_host = master_addr
    message = {"host": current_host, "generation": int(generation)}
    message.update(payload or {})

    def _handshake():
        fault_point(
            "rendezvous.reform",
            host=current_host,
            generation=generation,
            survivors=len(survivors),
        )
        return cluster.synchronize(message, timeout=timeout)

    from ..utils.retry import retry_transient

    membership = retry_transient(
        _handshake,
        site="rendezvous.reform",
        retry_on=(OSError, exc.PlatformError),
    )
    generations = {int(m.get("generation", -1)) for m in membership}
    if generations != {int(generation)}:
        raise exc.PlatformError(
            "cluster reform handshake mixed shrink generations {} (expected "
            "{}): a survivor missed a membership transition; aborting reform "
            "rather than training under disagreeing world sizes".format(
                sorted(generations), generation
            )
        )
    return cluster, membership


def distributed_run(
    exec_fun, args, include_in_training, hosts, current_host, port=DEFAULT_PORT, pre_exec=None
):
    """Membership-aware distributed execution (the reference's rabit_run).

    1. allgather {host, include_in_training};
    2. hosts without data log and exit(0) — the cluster re-forms without them;
    3. ``pre_exec(participating_hosts, current_host)`` runs on every
       participant (jax.distributed bring-up for the re-formed cluster — the
       analog of the reference's second rabit init, distributed.py:88-106);
    4. the rest run ``exec_fun(**args, is_master=...)`` where master is the
       first participating host in sorted order.
    """
    cluster = Cluster(hosts, current_host, port=port)
    membership = cluster.synchronize(
        {"host": current_host, "include_in_training": bool(include_in_training)}
    )
    participating = sorted(
        m["host"] for m in membership if m["include_in_training"]
    )
    if not participating:
        raise exc.UserError(
            "Not a single machine in the cluster has training data; "
            "unable to train the model."
        )
    if not include_in_training:
        logger.warning(
            "Host %s does not have data, exiting from cluster.", current_host
        )
        return None
    if pre_exec is not None:
        pre_exec(participating, current_host)
    is_master = participating[0] == current_host
    args = dict(args)
    args["is_master"] = is_master
    return exec_fun(**args)
