"""Multi-host cluster lifecycle: rendezvous, membership sync, clean exits.

TPU-native re-design of the reference's Rabit lifecycle (distributed.py:42-263
+ the vendored tracker in dmlc_patch/tracker.py). What survives is the
*semantics*, not the machinery:

* ranks are deterministic: sorted hostnames, master = rank 0
  (reference distributed.py:155, :207),
* before training, hosts exchange "do I have data?" and hosts without data
  exit(0) while the rest re-form the cluster (the reference's double rabit
  init, :78-109),
* DNS wait with exponential backoff up to ~15 min before any distributed work
  (:30-39).

What's gone: the tree/ring allreduce topology and per-iteration model
broadcast — gradient histograms are psum'd *inside* the jitted round step
over the JAX mesh (ICI/DCN), which XLA schedules; there is nothing to
hand-route. The TCP exchange here is a tiny metadata-only allgather used
once at startup (the analog of RabitHelper.synchronize, :125-138), not a
training-path collective. ``jax.distributed.initialize`` (coordinator =
sorted-hosts[0]) brings up the multi-host XLA runtime itself.
"""

import json
import logging
import socket
import struct
import time

from ..toolkit import exceptions as exc

logger = logging.getLogger(__name__)

DEFAULT_PORT = 9099


def wait_hostname_resolution(sm_hosts, max_wait_seconds=900):
    """Block until every host resolves in DNS (exponential backoff)."""
    delay = 1.0
    deadline = time.time() + max_wait_seconds
    for host in sm_hosts:
        while True:
            try:
                socket.gethostbyname(host)
                break
            except socket.gaierror:
                if time.time() > deadline:
                    raise exc.PlatformError(
                        "Could not resolve hostname {} within {}s".format(
                            host, max_wait_seconds
                        )
                    )
                time.sleep(min(delay, 30.0))
                delay *= 2


def recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def frame_message(obj):
    """Length-prefixed JSON framing: ``<u32 little-endian length><payload>``.

    The one wire format shared by the rendezvous allgather below and the
    cluster telemetry heartbeats (telemetry/cluster.py) — a single framing
    implementation keeps the two protocols trivially interoperable and
    testable off-socket.
    """
    payload = json.dumps(obj).encode()
    return struct.pack("<I", len(payload)) + payload


def send_message(sock, obj):
    sock.sendall(frame_message(obj))


def recv_message(sock):
    (length,) = struct.unpack("<I", recv_exact(sock, 4))
    return json.loads(recv_exact(sock, length).decode())


# historical private names, kept for in-repo callers
_recv_exact = recv_exact
_send_msg = send_message
_recv_msg = recv_message


class Cluster:
    """Deterministic-rank host group with a one-shot metadata allgather."""

    def __init__(self, hosts, current_host, port=DEFAULT_PORT):
        self.hosts = sorted(hosts)
        self.current_host = current_host
        self.port = port
        self.rank = self.hosts.index(current_host)
        self.master_host = self.hosts[0]

    @property
    def is_master(self):
        return self.rank == 0

    @property
    def num_hosts(self):
        return len(self.hosts)

    def synchronize(self, payload, timeout=300):
        """Allgather small JSON payloads across hosts -> list in rank order.

        Master accepts one connection per worker, collects payloads, sends
        the full rank-ordered list back (the reference's synchronize,
        distributed.py:125-138). Single-host clusters short-circuit.
        """
        if self.num_hosts == 1:
            return [payload]
        if self.is_master:
            results = {0: payload}
            server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            server.bind(("0.0.0.0", self.port))
            server.listen(self.num_hosts)
            server.settimeout(timeout)
            conns = []
            try:
                while len(results) < self.num_hosts:
                    conn, _ = server.accept()
                    msg = _recv_msg(conn)
                    results[int(msg["rank"])] = msg["payload"]
                    conns.append(conn)
                ordered = [results[r] for r in range(self.num_hosts)]
                for conn in conns:
                    _send_msg(conn, ordered)
                    conn.close()
            finally:
                server.close()
            return ordered
        # worker: connect with retry (master may be slow to bind)
        deadline = time.time() + timeout
        last_err = None
        while time.time() < deadline:
            try:
                sock = socket.create_connection((self.master_host, self.port), timeout=10)
                try:
                    _send_msg(sock, {"rank": self.rank, "payload": payload})
                    sock.settimeout(timeout)
                    return _recv_msg(sock)
                finally:
                    sock.close()
            except (ConnectionError, OSError) as e:
                last_err = e
                time.sleep(1.0)
        raise exc.PlatformError(
            "Could not synchronize with master {}".format(self.master_host),
            caused_by=last_err,
        )


def distributed_run(
    exec_fun, args, include_in_training, hosts, current_host, port=DEFAULT_PORT, pre_exec=None
):
    """Membership-aware distributed execution (the reference's rabit_run).

    1. allgather {host, include_in_training};
    2. hosts without data log and exit(0) — the cluster re-forms without them;
    3. ``pre_exec(participating_hosts, current_host)`` runs on every
       participant (jax.distributed bring-up for the re-formed cluster — the
       analog of the reference's second rabit init, distributed.py:88-106);
    4. the rest run ``exec_fun(**args, is_master=...)`` where master is the
       first participating host in sorted order.
    """
    cluster = Cluster(hosts, current_host, port=port)
    membership = cluster.synchronize(
        {"host": current_host, "include_in_training": bool(include_in_training)}
    )
    participating = sorted(
        m["host"] for m in membership if m["include_in_training"]
    )
    if not participating:
        raise exc.UserError(
            "Not a single machine in the cluster has training data; "
            "unable to train the model."
        )
    if not include_in_training:
        logger.warning(
            "Host %s does not have data, exiting from cluster.", current_host
        )
        return None
    if pre_exec is not None:
        pre_exec(participating, current_host)
    is_master = participating[0] == current_host
    args = dict(args)
    args["is_master"] = is_master
    return exec_fun(**args)
