from .distributed import Cluster, distributed_run, wait_hostname_resolution  # noqa: F401
