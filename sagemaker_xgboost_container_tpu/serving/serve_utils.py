"""Scoring-path utilities: payload parse, model load, predict, selectable
inference, response encoders.

Behavior parity with reference serve_utils.py:

* ``parse_content_data`` (:121-155): csv/libsvm/recordio -> matrix,
* ``get_loaded_booster`` (:171-197): load every non-dotfile in the model dir
  as an ensemble (env-gated), each file pickle-or-native,
* ``predict`` (:200-262): feature-count consistency checks per content type,
  best-iteration ranges, ensemble vote (softmax/hinge) or average,
* selectable inference (:265-548): VALID_OBJECTIVES key matrix, per-key
  extraction, and csv/json/jsonlines/recordio encoders.

The predictor underneath is the compiled XLA forest kernel; model files may
be our/xgboost JSON, xgboost UBJSON, legacy xgboost binary, or pickled
xgboost Boosters (models/compat.py handles the foreign formats).
"""

import io
import json
import logging
import os

import numpy as np
from scipy import stats

from .. import constants
from ..constants import (
    BINARY_HINGE,
    BINARY_LOG,
    BINARY_LOGRAW,
    MULTI_SOFTMAX,
    MULTI_SOFTPROB,
    REG_ABSOLUTEERR,
    REG_GAMMA,
    REG_LOG,
    REG_SQUAREDERR,
    REG_TWEEDIE,
)
from ..data.content_types import CSV, LIBSVM, RECORDIO_PROTOBUF, get_content_type
from ..data.recordio import record_pb2, _frame
from ..models.compat import load_model_any_format
from ..toolkit import exceptions as exc
from ..utils import integrity
from ..utils.faults import fault_point
from . import encoder

logger = logging.getLogger(__name__)

PKL_FORMAT = "pkl_format"
XGB_FORMAT = "xgb_format"

# classification selectable inference keys
PREDICTED_LABEL = "predicted_label"
LABELS = "labels"
PROBABILITY = "probability"
PROBABILITIES = "probabilities"
RAW_SCORE = "raw_score"
RAW_SCORES = "raw_scores"
# regression selectable inference keys
PREDICTED_SCORE = "predicted_score"

TOP_LEVEL_OUT_KEY = "predictions"
SCORE_OUT_KEY = "score"

ALL_VALID_SELECT_KEYS = [
    PREDICTED_LABEL,
    LABELS,
    PROBABILITY,
    PROBABILITIES,
    RAW_SCORE,
    RAW_SCORES,
    PREDICTED_SCORE,
]

VALID_OBJECTIVES = {
    REG_SQUAREDERR: [PREDICTED_SCORE],
    REG_LOG: [PREDICTED_SCORE],
    REG_GAMMA: [PREDICTED_SCORE],
    REG_ABSOLUTEERR: [PREDICTED_SCORE],
    REG_TWEEDIE: [PREDICTED_SCORE],
    BINARY_LOG: [PREDICTED_LABEL, LABELS, PROBABILITY, PROBABILITIES, RAW_SCORE, RAW_SCORES],
    BINARY_LOGRAW: [PREDICTED_LABEL, LABELS, RAW_SCORE, RAW_SCORES],
    BINARY_HINGE: [PREDICTED_LABEL, LABELS, RAW_SCORE, RAW_SCORES],
    MULTI_SOFTMAX: [PREDICTED_LABEL, LABELS, RAW_SCORE, RAW_SCORES],
    MULTI_SOFTPROB: [PREDICTED_LABEL, LABELS, PROBABILITY, PROBABILITIES, RAW_SCORE, RAW_SCORES],
}


def parse_content_data(input_data, input_content_type):
    """Request body + content type -> (DataMatrix, canonical content type)."""
    # chaos hook: payload decode (both serving apps funnel through here) —
    # error drills the 415 path, sleep drills the decode-stage deadline
    fault_point("serving.decode", content_type=input_content_type)
    content_type = get_content_type(input_content_type)
    payload = input_data
    if content_type == CSV:
        try:
            decoded = payload.strip().decode("utf-8")
            dtest = encoder.csv_to_matrix(decoded, dtype=float)
        except Exception as e:
            raise RuntimeError(
                "Loading csv data failed with Exception, please ensure data "
                "is in csv format:\n {}\n {}".format(type(e), e)
            )
    elif content_type == LIBSVM:
        try:
            decoded = payload.strip().decode("utf-8")
            dtest = encoder.libsvm_to_matrix(decoded)
        except Exception as e:
            raise RuntimeError(
                "Loading libsvm data failed with Exception, please ensure data "
                "is in libsvm format:\n {}\n {}".format(type(e), e)
            )
    elif content_type == RECORDIO_PROTOBUF:
        try:
            dtest = encoder.recordio_protobuf_to_matrix(payload)
        except Exception as e:
            raise RuntimeError(
                "Loading recordio-protobuf data failed with Exception, please "
                "ensure data is in recordio-protobuf format: {} {}".format(type(e), e)
            )
    else:
        raise RuntimeError("Content-type {} is not supported.".format(input_content_type))
    return dtest, content_type


def _get_full_model_paths(model_dir):
    for name in sorted(os.listdir(model_dir)):
        path = os.path.join(model_dir, name)
        if os.path.isfile(path):
            if name.startswith("."):
                continue
            if name.endswith(integrity.MANIFEST_SUFFIX):
                # integrity sidecars describe a model file; they are never
                # themselves a model (an ensemble load would choke on one)
                continue
            yield path


def _note_model_verify_fail(stage):
    from ..telemetry import REGISTRY

    REGISTRY.counter(
        "model_verify_fail_total",
        "Serving model artifacts rejected at load (digest, parse, or "
        "structural validation)",
        {"stage": stage},
    ).inc()


def _load_verified(path):
    """Load one model artifact with the full integrity gauntlet.

    Three stages, each with its own ``model_verify_fail_total{stage}``
    series so the metric names WHICH defense fired: ``digest`` (bytes
    disagree with the sidecar manifest that traveled with the artifact),
    ``parse`` (not loadable in any supported format), ``structure`` (parsed,
    but the trees violate the invariants the compiled predict kernels
    assume — children out of range, non-finite thresholds/values,
    inconsistent bookkeeping). A corrupt artifact dies here as a distinct
    5xx at load/ping time instead of an inscrutable predict-time error.
    """
    fault_point("model.load", path=path)
    manifest = integrity.read_manifest(path)
    if manifest is not None:
        try:
            integrity.verify_file_against_manifest(path, manifest)
        except (integrity.IntegrityError, OSError) as e:
            _note_model_verify_fail("digest")
            logger.error("MODEL VERIFICATION FAILED (digest): %s", e)
            raise integrity.IntegrityError(
                "Model artifact {} failed digest verification against its "
                "manifest: {}".format(path, e)
            )
    try:
        forest, source_format = load_model_any_format(path)
    except Exception as e:
        _note_model_verify_fail("parse")
        logger.error("MODEL VERIFICATION FAILED (parse): %s: %s", path, e)
        raise
    try:
        integrity.validate_model(forest)
    except integrity.IntegrityError as e:
        _note_model_verify_fail("structure")
        logger.error("MODEL VERIFICATION FAILED (structure): %s: %s", path, e)
        raise integrity.IntegrityError(
            "Model artifact {} is structurally invalid: {}".format(path, e)
        )
    _maybe_arm_drift(manifest)
    return forest, source_format


def _maybe_arm_drift(manifest):
    """Arm the serving drift monitor from the per-feature bin-occupancy
    baseline the trainer stamped into the model manifest (SM_MODEL_TELEMETRY
    plane, docs/observability.md §Model window). The window quacks like a
    breaker: registering it with the lifecycle makes sustained PSI above
    SM_DRIFT_PSI_MAX surface as DEGRADED in serving_state via the /ping
    polls, exactly like an SLO burn — visibility, not shedding. Best-effort:
    an unarmed plane, a baseline-less manifest, or a telemetry failure must
    never fail a model load."""
    if not manifest:
        return
    try:
        from ..telemetry import model as model_telemetry

        window = model_telemetry.maybe_install_drift(manifest.get("drift_baseline"))
        if window is not None:
            from . import lifecycle

            lifecycle.observe(window)
    except Exception:
        logger.debug("drift monitor arm failed", exc_info=True)


def observe_drift(features, predictions=None):
    """Feed one request's (canonicalized) feature matrix and predictions to
    the drift window. Inert when SM_MODEL_TELEMETRY is off or no baseline
    traveled with the model; never raises — telemetry must not fail a
    prediction that already succeeded."""
    try:
        from ..telemetry import model as model_telemetry

        window = model_telemetry.active_drift()
        if window is not None:
            window.observe(features, predictions)
    except Exception:
        logger.debug("drift observe failed", exc_info=True)


def get_loaded_booster(model_dir, ensemble=False):
    """Load model file(s) from the directory; ensemble loads all of them.

    Every artifact goes through verified loading (``_load_verified``):
    digest check when a manifest sidecar traveled with the model, format
    parse, then structural validation of the trees — single-model, MME
    load, and MME eviction/reload all share this one gate.
    """
    paths = list(_get_full_model_paths(model_dir))
    if not paths:
        raise RuntimeError("No model files found in {}".format(model_dir))
    paths = paths if ensemble else paths[:1]
    models, formats = [], []
    for path in paths:
        forest, source_format = _load_verified(path)
        models.append(forest)
        formats.append(source_format)
    if ensemble and len(models) > 1:
        return models, formats
    return models[0], formats[0]


def _check_feature_count(forest, dtest, content_type):
    x = forest.num_feature
    y = dtest.num_col
    if content_type == LIBSVM:
        if y > x + 1:
            raise ValueError(
                "Feature size of libsvm inference data {} is larger than feature size "
                "of trained model {}.".format(y, x)
            )
    elif content_type in (CSV, RECORDIO_PROTOBUF):
        if not (x == y or x == y + 1):
            raise ValueError(
                "Feature size of {} inference data {} is not consistent with feature "
                "size of trained model {}.".format(content_type, y, x)
            )
    else:
        raise ValueError("Content type {} is not supported".format(content_type))


def canonicalize_features(forest, dtest):
    """Width-adjust request features to the model's expectation."""
    features = dtest.features
    if features.shape[1] < forest.num_feature:
        features = dtest.pad_features(forest.num_feature).features
    elif features.shape[1] > forest.num_feature:
        features = features[:, : forest.num_feature]
    return features


def best_iteration_range(forest):
    best_iteration = forest.attributes.get("best_iteration")
    if best_iteration is None:
        return None
    return (0, int(best_iteration) + 1)


def warmup_predict_async(model):
    """Pre-compile the first device predict buckets in the background.

    Payloads at or below GRAFT_HOST_PREDICT_ROWS run the host numpy path
    (never compile); the first request ABOVE it pays the XLA compile of its
    row bucket — tens of seconds on a TPU endpoint, easily tripping client
    timeouts right after deploy. Warming the smallest device bucket plus a
    representative batch bucket at model-load time moves that cost off the
    request path. Fire-and-forget daemon thread; failures only log.
    GRAFT_PREDICT_WARMUP=0 disables (any other value, including typos,
    degrades to the default: enabled)."""
    if os.getenv("GRAFT_PREDICT_WARMUP", "1").lower() in ("0", "false", "off", "no"):
        return

    def _warm():
        try:
            from ..data.native import forest_predictor_available
            from ..models.forest import _host_predict_rows, predict_bucket

            # host-path sizes compile nothing, but they DO lazily build the
            # C++ traversal (g++ on dev trees without a packaged .so) —
            # trigger that load here, off the request path
            forest_predictor_available()
            t = _host_predict_rows()
            # distinct device buckets only: the smallest one past the host
            # threshold plus a representative batch bucket (skipping sizes
            # the host path would swallow, which compile nothing)
            sizes = sorted({predict_bucket(t + 1), predict_bucket(max(256, t + 1))})
            for m in model if isinstance(model, list) else [model]:
                d = int(getattr(m, "num_feature", 0) or 0)
                if d <= 0:
                    continue
                for n in sizes:
                    m.predict(
                        np.zeros((n, d), np.float32),
                        iteration_range=best_iteration_range(m),
                    )
        except Exception as e:  # a failed warmup must never break serving
            logging.getLogger(__name__).info("predict warmup skipped: %s", e)

    import threading

    threading.Thread(target=_warm, daemon=True, name="predict-warmup").start()


def predict(model, model_format, dtest, input_content_type, objective=None):
    """Run (possibly ensemble) prediction with feature-size validation."""
    boosters = model if isinstance(model, list) else [model]
    content_type = get_content_type(input_content_type)
    _check_feature_count(boosters[0], dtest, content_type)

    def _one(forest):
        return forest.predict(
            canonicalize_features(forest, dtest),
            iteration_range=best_iteration_range(forest),
        )

    if isinstance(model, list):
        outs = [_one(b) for b in boosters]
        if objective in (MULTI_SOFTMAX, BINARY_HINGE):
            result = stats.mode(np.stack(outs), axis=0, keepdims=False).mode
        else:
            result = np.mean(outs, axis=0)
    else:
        result = _one(model)
    observe_drift(canonicalize_features(boosters[0], dtest), result)
    return result


def is_selectable_inference_output():
    return constants.SAGEMAKER_INFERENCE_OUTPUT in os.environ


def get_selected_output_keys():
    if is_selectable_inference_output():
        return os.environ[constants.SAGEMAKER_INFERENCE_OUTPUT].replace(" ", "").lower().split(",")
    raise RuntimeError(
        "'SAGEMAKER_INFERENCE_OUTPUT' environment variable is not present. "
        "Selectable inference content is not enabled."
    )


def _get_labels(objective, num_class=""):
    if "binary:" in objective:
        return [0, 1]
    if "multi:" in objective and num_class:
        return list(range(int(num_class)))
    return np.nan


def _get_predicted_label(objective, raw_prediction):
    if objective in (BINARY_HINGE, MULTI_SOFTMAX):
        return np.asarray(raw_prediction).item()
    if objective == BINARY_LOG:
        return int(raw_prediction > 0.5)
    if objective == BINARY_LOGRAW:
        return int(raw_prediction > 0)
    if objective == MULTI_SOFTPROB:
        return int(np.argmax(raw_prediction))
    return np.nan


def _get_probability(objective, raw_prediction):
    if objective == MULTI_SOFTPROB:
        return float(max(raw_prediction))
    if objective == BINARY_LOG:
        return float(raw_prediction)
    return np.nan


def _get_probabilities(objective, raw_prediction):
    if objective == MULTI_SOFTPROB:
        return np.asarray(raw_prediction).tolist()
    if objective == BINARY_LOG:
        p1 = float(raw_prediction)
        return [1.0 - p1, p1]
    return np.nan


def _get_raw_score(objective, raw_prediction):
    if objective == MULTI_SOFTPROB:
        return float(max(raw_prediction))
    if objective in (BINARY_LOGRAW, BINARY_HINGE, BINARY_LOG, MULTI_SOFTMAX):
        return float(raw_prediction)
    return np.nan


def _get_raw_scores(objective, raw_prediction):
    if objective == MULTI_SOFTPROB:
        return np.asarray(raw_prediction).tolist()
    if objective in (BINARY_LOGRAW, BINARY_HINGE, BINARY_LOG, MULTI_SOFTMAX):
        p1 = float(raw_prediction)
        return [1.0 - p1, p1]
    return np.nan


def get_selected_predictions(raw_predictions, selected_keys, objective, num_class=""):
    """Per-row dicts of the selected content keys (reference :397-450)."""
    if objective not in VALID_OBJECTIVES:
        raise ValueError(
            "Objective `{}` unsupported for selectable inference predictions.".format(objective)
        )
    valid = set(selected_keys) & set(VALID_OBJECTIVES[objective])
    invalid = set(selected_keys) - set(VALID_OBJECTIVES[objective])

    predictions = []
    for raw in raw_predictions:
        out = {}
        if PREDICTED_LABEL in valid:
            out[PREDICTED_LABEL] = _get_predicted_label(objective, raw)
        if LABELS in valid:
            out[LABELS] = _get_labels(objective, num_class=num_class)
        if PROBABILITY in valid:
            out[PROBABILITY] = _get_probability(objective, raw)
        if PROBABILITIES in valid:
            out[PROBABILITIES] = _get_probabilities(objective, raw)
        if RAW_SCORE in valid:
            out[RAW_SCORE] = _get_raw_score(objective, raw)
        if RAW_SCORES in valid:
            out[RAW_SCORES] = _get_raw_scores(objective, raw)
        if PREDICTED_SCORE in valid:
            out[PREDICTED_SCORE] = float(np.asarray(raw).item())
        for key in invalid:
            out[key] = np.nan
        predictions.append(out)
    return predictions


def _encode_selected_predictions_csv(predictions, ordered_keys_list):
    lines = []
    for prediction in predictions:
        cells = []
        for key in ordered_keys_list:
            value = prediction[key]
            cells.append('"{}"'.format(value) if isinstance(value, list) else str(value))
        lines.append(",".join(cells))
    return "\n".join(lines)


def _encode_selected_predictions_recordio_protobuf(predictions):
    bio = io.BytesIO()
    for item in predictions:
        record = record_pb2.Record()
        for key, value in item.items():
            values = value if isinstance(value, list) else [value]
            record.label[key].float32_tensor.values.extend(float(v) for v in values)
        bio.write(_frame(record.SerializeToString()))
    return bio.getvalue()


def encode_selected_predictions(predictions, selected_content_keys, accept):
    if accept == "application/json":
        return json.dumps({TOP_LEVEL_OUT_KEY: predictions})
    if accept == "application/jsonlines":
        return encoder.json_to_jsonlines({TOP_LEVEL_OUT_KEY: predictions})
    if accept == "application/x-recordio-protobuf":
        return _encode_selected_predictions_recordio_protobuf(predictions)
    if accept == "text/csv":
        csv_response = _encode_selected_predictions_csv(predictions, selected_content_keys)
        if os.getenv(constants.SAGEMAKER_BATCH):
            return csv_response + "\n"
        return csv_response
    raise RuntimeError("Cannot encode selected predictions into accept type '{}'.".format(accept))


def encode_predictions_as_json(predictions):
    """``{"predictions": [{"score": ...}, ...]}`` (SageMaker CDF format)."""
    return json.dumps(
        {TOP_LEVEL_OUT_KEY: [{SCORE_OUT_KEY: pred} for pred in predictions]}
    )


def is_ensemble_enabled():
    return os.environ.get(constants.SAGEMAKER_INFERENCE_ENSEMBLE, "true") == "true"
