"""Inference payload decoding: request bytes -> DataMatrix.

Parity with reference encoder.py:35-142 (csv delimiter sniffing with alnum
fallback, blank-cell -> NaN, libsvm 1-based index shift at serve time, recordio
passthrough) and the jsonlines conversion helper. Decoders return label-free
DataMatrix objects for the predict path.
"""

import csv as csv_module
import io
import json

import numpy as np
import scipy.sparse as sp

from .. import constants
from ..data.matrix import DataMatrix
from ..data.recordio import read_recordio_protobuf
from ..toolkit import exceptions as exc


def _clean_csv_cells(line, delimiter):
    return ["nan" if cell == "" else cell for cell in line.split(delimiter)]


# csv.Sniffer's preferred-delimiter set plus '|'; every delimiter the old
# always-sniff path could produce for numeric payloads stays reachable
_DELIM_CANDIDATES = (",", "\t", ";", "|", " ", ":")


def _sniff_delimiter(first_line):
    """csv.Sniffer costs ~0.4 ms per call — dominating single-row serve
    payloads — so the unambiguous cases (zero or exactly one candidate
    delimiter present) short-circuit it; only ambiguous lines (e.g. both
    ',' and ' ' present) pay for the full Sniffer.

    The probe line is stripped first: a single-column payload with
    incidental leading/trailing whitespace (``b"1.0 "``) must not sniff
    ``' '`` and grow a phantom NaN column (ADVICE r5 — the reference's
    always-sniff path never did)."""
    first_line = first_line.strip()
    present = [c for c in _DELIM_CANDIDATES if c in first_line]
    if not present:
        return ","
    if len(present) == 1:
        return present[0]
    try:
        sniffed = csv_module.Sniffer().sniff(first_line).delimiter
    except Exception:
        sniffed = ","
    return "," if sniffed.isalnum() else sniffed


def csv_to_matrix(input_data, dtype=np.float32):
    """CSV request body (no label column) -> DataMatrix."""
    text = input_data.decode() if isinstance(input_data, (bytes, bytearray)) else input_data
    delimiter = _sniff_delimiter(text.split("\n")[0][:512])
    rows = [_clean_csv_cells(line, delimiter) for line in text.split("\n") if line != ""]
    data = np.asarray(rows).astype(dtype)
    return DataMatrix(data)


def libsvm_to_matrix(string_like):
    """LIBSVM request body (no labels) -> DataMatrix.

    Serve-time payloads conventionally use standard 1-based libsvm indices;
    when every index is >= 1 they are shifted down by one (reference
    encoder.py:78-81 / serve_utils.py:110-113).
    """
    if isinstance(string_like, (bytes, bytearray)):
        string_like = string_like.decode("utf-8")
    row_ids, col_ids, values = [], [], []
    n_rows = 0
    for line in string_like.strip().split("\n"):
        tokens = line.strip().split()
        for token in tokens:
            if ":" in token:
                idx, _, val = token.partition(":")
                row_ids.append(n_rows)
                col_ids.append(int(idx))
                values.append(float(val))
        n_rows += 1
    if not values:
        return DataMatrix(np.full((max(n_rows, 0), 0), np.nan, np.float32))
    col_ids = np.asarray(col_ids, np.int64)
    if col_ids.min() >= 1:
        col_ids = col_ids - 1
    csr = sp.csr_matrix(
        (np.asarray(values, np.float32), (np.asarray(row_ids), col_ids)),
        shape=(n_rows, int(col_ids.max()) + 1),
    )
    return DataMatrix(csr)


def recordio_protobuf_to_matrix(string_like):
    features, _labels = read_recordio_protobuf(bytes(string_like))
    return DataMatrix(features)


_decoders = {
    constants.CSV: csv_to_matrix,
    constants.LIBSVM: libsvm_to_matrix,
    constants.X_LIBSVM: libsvm_to_matrix,
    constants.X_RECORDIO_PROTOBUF: recordio_protobuf_to_matrix,
}


def json_to_jsonlines(json_data):
    """``{"predictions": [...]}`` -> one JSON document per line (bytes)."""
    resp = json_data if isinstance(json_data, dict) else json.loads(json_data)
    if len(resp.keys()) != 1:
        raise ValueError("JSON response is not compatible for conversion to jsonlines.")
    bio = io.BytesIO()
    for value in resp.values():
        for entry in value:
            bio.write(bytes(json.dumps(entry) + "\n", "UTF-8"))
    return bio.getvalue()


def decode(obj, content_type):
    media_type = str(content_type).split(";")[0].strip()
    decoder = _decoders.get(media_type)
    if decoder is None:
        raise exc.UserError("Content type {} is not supported".format(media_type))
    return decoder(obj)
