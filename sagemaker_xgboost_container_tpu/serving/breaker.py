"""Batcher-saturation circuit breaker: graceful degradation for serving.

When the TPU can't keep up, the batcher's bounded job queue starts
rejecting (``JobQueueFull``) and callers start timing out. Pre-breaker,
every such request still paid full decode cost and surfaced as a 400 —
wrong status (the client did nothing wrong) and no backpressure signal, so
load balancers kept routing traffic at a drowning instance. The breaker
turns saturation into protocol:

* ``closed``  — normal flow; consecutive saturation events are counted.
* ``open``    — after ``SM_SHED_REJECTION_THRESHOLD`` consecutive events,
  /invocations sheds immediately with **503 + Retry-After** (no decode, no
  queue pressure) for ``SM_SHED_COOLDOWN_S``; ``/ping`` reports 503 so the
  platform stops routing new connections to the degraded instance.
* ``half_open`` — after the cooldown, exactly one probe request flows; its
  success closes the breaker (and /ping recovers), another saturation
  event re-opens it for a fresh cooldown.

State transitions are counted in ``serving_breaker_transitions_total`` and
the current state is the ``serving_breaker_open`` gauge (0 closed, 1 open,
0.5 half-open); shed requests count in ``serving_shed_total``. Set
``SM_LOAD_SHEDDING=false`` to disable (saturation then surfaces as
per-request 503s without the fast-path shed or the /ping flip).
"""

import logging
import math
import threading
import time

from ..telemetry.registry import REGISTRY
from ..utils.envconfig import env_bool, env_float, env_int

logger = logging.getLogger(__name__)

LOAD_SHEDDING_ENV = "SM_LOAD_SHEDDING"
SHED_THRESHOLD_ENV = "SM_SHED_REJECTION_THRESHOLD"
SHED_COOLDOWN_ENV = "SM_SHED_COOLDOWN_S"
SHED_RETRY_AFTER_ENV = "SM_SHED_RETRY_AFTER_S"

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

_STATE_GAUGE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 0.5}


def load_shedding_enabled():
    return env_bool(LOAD_SHEDDING_ENV, True)


def retry_after_hint():
    """Whole-second Retry-After (>= 1) for stateless 503 sites (MME path)."""
    value = env_float(SHED_RETRY_AFTER_ENV, 0.0, minimum=0.0, maximum=3600.0)
    if not value:
        value = env_float(SHED_COOLDOWN_ENV, 5.0, minimum=0.1, maximum=3600.0)
    return max(1, int(math.ceil(value)))


class CircuitBreaker:
    """Thread-safe three-state breaker driven by saturation events.

    ``clock`` is injectable for tests. All methods are cheap enough for the
    request path: one lock acquire and a couple of comparisons.
    """

    def __init__(
        self,
        name="default",
        threshold=None,
        cooldown_s=None,
        retry_after_s=None,
        registry=None,
        clock=time.monotonic,
    ):
        self.enabled = load_shedding_enabled()
        self.threshold = (
            threshold
            if threshold is not None
            else env_int(SHED_THRESHOLD_ENV, 5, minimum=1, maximum=10000)
        )
        self.cooldown_s = (
            cooldown_s
            if cooldown_s is not None
            else env_float(SHED_COOLDOWN_ENV, 5.0, minimum=0.1, maximum=3600.0)
        )
        default_retry = retry_after_s if retry_after_s is not None else env_float(
            SHED_RETRY_AFTER_ENV, 0.0, minimum=0.0, maximum=3600.0
        )
        # 0 = "derive from the cooldown", the honest default hint
        self._retry_after_s = default_retry or self.cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_out = False
        self._probe_at = 0.0
        reg = registry or REGISTRY
        labels = {"breaker": name}
        self._m_shed = reg.counter(
            "serving_shed_total", "Requests shed with 503 while degraded", labels
        )
        self._m_state = reg.gauge(
            "serving_breaker_open",
            "Breaker state (0 closed, 0.5 half-open, 1 open)",
            labels,
        )
        self._m_transitions = lambda state: reg.counter(
            "serving_breaker_transitions_total",
            "Breaker state transitions",
            dict(labels, state=state),
        )
        self._m_state.set(0.0)

    # ------------------------------------------------------------- internals
    def _transition(self, state):
        # lock held by caller
        if state == self._state:
            return
        self._state = state
        self._m_state.set(_STATE_GAUGE[state])
        self._m_transitions(state).inc()
        if state == OPEN:
            logger.warning(
                "circuit breaker OPEN: shedding /invocations with 503 for "
                "%.1fs and reporting /ping unready (threshold %d saturation "
                "events reached)",
                self.cooldown_s,
                self.threshold,
            )
        elif state == CLOSED:
            logger.info("circuit breaker closed: serving recovered")

    # ------------------------------------------------------------ public api
    @property
    def state(self):
        with self._lock:
            return self._state

    @property
    def degraded(self):
        """True while /ping should report unready.

        Only a *cooling-down* OPEN breaker is unready. Once the cooldown
        elapses the state advances to half-open and /ping reports ready —
        necessary for recovery, because a platform that honors the unready
        signal stops routing /invocations entirely, and with zero traffic
        ``allow()`` would otherwise never run to move the state machine.
        """
        with self._lock:
            if (
                self._state == OPEN
                and self._clock() - self._opened_at >= self.cooldown_s
            ):
                self._transition(HALF_OPEN)
                self._probe_out = False
            return self._state == OPEN

    def allow(self):
        """-> False when this request should be shed right now (503)."""
        if not self.enabled:
            return True
        with self._lock:
            if self._state == CLOSED:
                return True
            now = self._clock()
            if self._state == OPEN:
                if now - self._opened_at < self.cooldown_s:
                    self._m_shed.inc()
                    return False
                self._transition(HALF_OPEN)
                self._probe_out = False
            # half-open: one probe in flight at a time — but a probe that
            # dies before reaching predict (decode error, client hangup)
            # never reports back, so an aged-out token is reissued rather
            # than wedging the breaker half-open forever
            if self._probe_out and now - self._probe_at < self.cooldown_s:
                self._m_shed.inc()
                return False
            self._probe_out = True
            self._probe_at = now
            return True

    def force_open(self, reason="forced"):
        """Trip the breaker open right now (predict watchdog: a wedged
        dispatch must shed and flip /ping without waiting for threshold
        saturation events). Re-forcing while already open restarts the
        cooldown, so the breaker stays open for as long as the caller keeps
        seeing the problem; recovery then rides the normal half-open probe.
        """
        if not self.enabled:
            return
        with self._lock:
            already_open = self._state == OPEN
            self._opened_at = self._clock()
            self._probe_out = False
            self._transition(OPEN)
        if not already_open:
            logger.warning("circuit breaker forced OPEN: %s", reason)

    def record_saturation(self):
        """One saturation event (JobQueueFull or a batch-queue timeout)."""
        if not self.enabled:
            return
        with self._lock:
            self._consecutive += 1
            self._probe_out = False
            if self._state == HALF_OPEN or (
                self._state == CLOSED and self._consecutive >= self.threshold
            ):
                self._opened_at = self._clock()
                self._transition(OPEN)

    def record_success(self):
        """A predict made it through.

        Closes the breaker only from half-open (the probe proving recovery).
        A success while OPEN is a straggler admitted *before* the breaker
        tripped — the queue it left behind is still saturated, so it must
        not cancel the cooldown.
        """
        if not self.enabled:
            return
        with self._lock:
            self._consecutive = 0
            if self._state == HALF_OPEN:
                self._probe_out = False
                self._transition(CLOSED)

    def retry_after_s(self):
        """Whole-second Retry-After hint (>= 1) for 503 responses."""
        with self._lock:
            if self._state == OPEN:
                remaining = self.cooldown_s - (self._clock() - self._opened_at)
            else:
                remaining = self._retry_after_s
        return max(1, int(math.ceil(max(remaining, 0.0))))
