"""Serving lifecycle & request-plane supervision.

The reference container delegates serving lifecycle to its MMS/gunicorn
frontend (PAPER.md §1): the Java frontend owns readiness, drain on SIGTERM,
per-request timeouts, and worker supervision, and the Python handlers never
have to. Our single process owns the TPU *and* the HTTP surface, so the
same contract has to live here:

* **Health state machine** — ``starting → ready → degraded → draining →
  stopped``, consulted by ``/ping`` on both serving apps. ``degraded`` is
  derived live from the circuit breaker(s) this lifecycle was told about
  (the PR-3 saturation breaker and the predict watchdog both flip it);
  ``draining``/``stopped`` answer 503 + ``Retry-After`` so the load
  balancer deregisters the instance while in-flight work finishes.
* **In-flight latch** — the WSGI middleware reports request start/finish
  (finish = the response body fully written, via the result iterable's
  ``close()``), feeding the ``serving_inflight`` gauge and the drain wait.
* **Request deadlines** — ``SM_REQUEST_DEADLINE_S`` arms a per-request
  budget apportioned across the ``decode`` / ``queue`` / ``predict`` /
  ``encode`` stages. Expiry raises :class:`DeadlineExceeded` (a
  ``TimeoutError`` subclass, so the existing saturation handling turns it
  into 503 + ``Retry-After`` through the breaker feed) and counts
  ``serving_deadline_exceeded_total{stage}``.
* **Predict watchdog** — ``SM_PREDICT_STUCK_S`` arms a monitor thread (the
  PR-3 round-watchdog pattern) that detects a batcher wedged inside one
  dispatch (tunneled-TPU stall: the exec lock never releases, every later
  request hangs). On detection it trips the breaker open, emits one
  ``serving.stuck`` record with the flight-recorder span tree, and — per
  ``SM_PREDICT_STUCK_ACTION`` — either keeps shedding until the dispatch
  returns (``shed``, default) or aborts the process with
  ``EXIT_PREDICT_STUCK`` so the platform restarts a clean one (``abort``).
  Never a silent wedge.

Everything is resolved ONCE at lifecycle construction via ``envconfig``
and inert by default: no deadline knob -> no per-request clock reads, no
stuck knob -> no monitor thread, and with no lifecycle installed (tests
constructing the WSGI apps directly) every hook below is a no-op.
"""

import logging
import os
import threading
import time

from ..constants import EXIT_PREDICT_STUCK
from ..constants import SM_MODEL_DIR as SM_MODEL_DIR_ENV
from ..telemetry import tracing
from ..telemetry.emit import emit_metric
from ..telemetry.registry import REGISTRY
from ..utils.envconfig import env_bool, env_float

logger = logging.getLogger(__name__)

GRACEFUL_DRAIN_ENV = "SM_GRACEFUL_DRAIN"
DRAIN_TIMEOUT_ENV = "SM_DRAIN_TIMEOUT_S"
REQUEST_DEADLINE_ENV = "SM_REQUEST_DEADLINE_S"
PREDICT_STUCK_ENV = "SM_PREDICT_STUCK_S"
PREDICT_STUCK_ACTION_ENV = "SM_PREDICT_STUCK_ACTION"

STARTING, READY, DEGRADED, DRAINING, STOPPED = (
    "starting", "ready", "degraded", "draining", "stopped",
)

#: ``serving_state`` gauge encoding (documented in docs/observability.md)
_STATE_GAUGE = {STARTING: 0.0, READY: 1.0, DEGRADED: 2.0, DRAINING: 3.0, STOPPED: 4.0}

_STUCK_ACTIONS = ("shed", "abort")

#: request budget stages (closed label set for the deadline counter)
STAGES = ("decode", "queue", "predict", "encode")

# test hook: chaos tests replace this to observe the exit instead of dying
_exit = os._exit

_abort_lock = threading.Lock()
_aborting = False


def _stuck_action():
    raw = (os.getenv(PREDICT_STUCK_ACTION_ENV) or "shed").strip().lower()
    if raw not in _STUCK_ACTIONS:
        logger.warning(
            "ignoring malformed %s=%r (expected one of %s); using 'shed'",
            PREDICT_STUCK_ACTION_ENV, raw, _STUCK_ACTIONS,
        )
        return "shed"
    return raw


class DeadlineExceeded(TimeoutError):
    """A request blew its ``SM_REQUEST_DEADLINE_S`` budget in ``stage``.

    Subclasses ``TimeoutError`` deliberately: the invocation paths already
    turn batcher timeouts into 503 + ``Retry-After`` and feed the breaker —
    deadline expiry is the same saturation protocol, just attributed to a
    stage.
    """

    def __init__(self, stage, budget_s):
        super(DeadlineExceeded, self).__init__(
            "request deadline exceeded in stage {!r} (budget {:.3f}s)".format(
                stage, budget_s
            )
        )
        self.stage = stage
        self.budget_s = budget_s


def note_deadline_exceeded(stage, registry=None):
    """Count one per-stage deadline expiry (label set bounded by STAGES)."""
    reg = registry or REGISTRY
    reg.counter(
        "serving_deadline_exceeded_total",
        "Requests that blew the SM_REQUEST_DEADLINE_S budget, by stage",
        {"stage": stage if stage in STAGES else "other"},
    ).inc()


def expire(stage, budget_s, registry=None):
    """Count and raise a :class:`DeadlineExceeded` for ``stage``."""
    note_deadline_exceeded(stage, registry=registry)
    raise DeadlineExceeded(stage, budget_s)


class RequestDeadline:
    """One request's time budget, drawn down across stages.

    Stages don't get fixed slices: each draws from whatever remains when it
    runs (a slow decode leaves less for predict), which matches how the
    wall clock actually bills the client. ``check(stage)`` raises when the
    budget is gone; ``remaining()`` bounds blocking waits (the batcher's
    queue/dispatch wait).
    """

    __slots__ = ("budget_s", "_deadline", "_clock")

    def __init__(self, budget_s, clock=time.monotonic):
        self.budget_s = float(budget_s)
        self._clock = clock
        self._deadline = clock() + self.budget_s

    def remaining(self):
        return max(0.0, self._deadline - self._clock())

    def expired(self):
        return self._clock() >= self._deadline

    def check(self, stage):
        if self.expired():
            expire(stage, self.budget_s)


class PredictWatchdog:
    """Monitor thread detecting a batcher wedged inside one dispatch.

    The batcher's worker holds ``_exec_lock`` around every ``predict_fn``
    run; a dispatch that never returns (wedged device runtime) therefore
    hangs every later request with no error — the failure mode the queue
    timeout converts into 60s client timeouts, forever. The watchdog polls
    each registered batcher's :meth:`dispatch_age_s`; one stuck episode:

    * trips the associated breaker OPEN on every check while stuck (the
      cooldown keeps restarting, so ``/ping`` stays 503 and new requests
      shed instead of queueing behind the wedge),
    * emits ONE ``serving.stuck`` record with the in-flight span tree
      (flight-recorder dump when ``SM_TRACE`` is armed),
    * with ``action='abort'``, aborts the process with
      ``EXIT_PREDICT_STUCK`` — a restart gets a clean device runtime.

    When the dispatch finally returns, the episode clears with a log line
    and the breaker recovers through its normal half-open probe.
    """

    def __init__(self, stuck_s, action="shed", check_interval=None,
                 clock=time.monotonic):
        self.stuck_s = float(stuck_s)
        self.action = action
        if check_interval is None:
            # the re-forced breaker is what keeps /ping unready while stuck:
            # checking less often than the breaker cooldown would let it
            # half-open between checks and flap a wedged instance back into
            # rotation, so the interval stays under half the cooldown
            from .breaker import SHED_COOLDOWN_ENV

            cooldown = env_float(
                SHED_COOLDOWN_ENV, 5.0, minimum=0.1, maximum=3600.0
            )
            check_interval = min(self.stuck_s / 4.0, cooldown / 2.0)
        self.check_interval = max(check_interval, 0.05)
        self._clock = clock
        self._lock = threading.Lock()
        self._targets = {}   # name -> (batcher, breaker)
        self._stuck = set()  # names in a stuck episode (log/record once)
        self._stop = threading.Event()
        self._thread = None

    def register(self, name, batcher, breaker=None):
        with self._lock:
            self._targets[name] = (batcher, breaker)
        self.start()

    def unregister(self, name):
        with self._lock:
            self._targets.pop(name, None)
            self._stuck.discard(name)

    def start(self):
        with self._lock:
            if self._thread is not None:
                return self
            # fresh event per thread generation: a start() after stop()
            # must not inherit the set event (the new thread would exit on
            # its first wait — an armed-looking watchdog checking nothing),
            # and the old thread keeps ITS event so it still stops
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._run, args=(self._stop,),
                daemon=True, name="predict-watchdog",
            )
            self._thread.start()
        logger.info(
            "predict watchdog armed: %s after a dispatch exceeds %.1fs",
            self.action, self.stuck_s,
        )
        return self

    def stop(self):
        with self._lock:
            stop_event = self._stop
            thread, self._thread = self._thread, None
        stop_event.set()
        if thread is not None:
            thread.join(timeout=5.0)

    # ------------------------------------------------------------- internals
    def _run(self, stop_event):
        while not stop_event.wait(self.check_interval):
            try:
                self.check_once()
            except Exception:
                logger.exception("predict watchdog check failed; continuing")

    def check_once(self):
        with self._lock:
            targets = dict(self._targets)
        for name, (batcher, breaker) in targets.items():
            age = batcher.dispatch_age_s()
            if age is not None and age > self.stuck_s:
                self._handle_stuck(name, batcher, breaker, age)
            else:
                with self._lock:
                    was_stuck = name in self._stuck
                    self._stuck.discard(name)
                if was_stuck:
                    logger.warning(
                        "predict dispatch on batcher %r returned after a "
                        "stuck episode; breaker recovers via its half-open "
                        "probe", name,
                    )

    def _handle_stuck(self, name, batcher, breaker, age):
        with self._lock:
            first = name not in self._stuck
            self._stuck.add(name)
        # keep the breaker's cooldown restarting every check: while the
        # dispatch is wedged the instance must stay unready and shedding
        if breaker is not None:
            breaker.force_open("predict_stuck")
        if not first:
            if self.action == "abort":
                self._abort(name, batcher, age, dump=None)
            return
        requests, rows = batcher.dispatch_info()
        logger.error(
            "predict dispatch STUCK on batcher %r: one dispatch has run "
            "%.1fs (> %.1fs deadline, %d request(s) / %d row(s) aboard) — "
            "wedged device runtime; action=%s",
            name, age, self.stuck_s, requests, rows, self.action,
        )
        dump = tracing.dump_flight_recorder(
            default_dir=os.environ.get(SM_MODEL_DIR_ENV) or None,
            reason="predict_stuck",
        )
        fields = {
            "batcher": name,
            "stuck_s": round(age, 1),
            "deadline_s": self.stuck_s,
            "requests": requests,
            "rows": rows,
            "action": self.action,
        }
        if dump:
            fields["flight_recorder"] = dump
        emit_metric("serving.stuck", **fields)
        if self.action == "abort":
            self._abort(name, batcher, age, dump=dump)

    def _abort(self, name, batcher, age, dump=None):
        abort_serving(
            "predict_stuck",
            EXIT_PREDICT_STUCK,
            batcher=name,
            stuck_s=round(age, 1),
            flight_recorder=dump,
        )


def abort_serving(reason, exit_code, **fields):
    """Dump the flight recorder, emit one ``serving.abort`` record, hard-exit.

    The serving twin of ``training/watchdog.request_abort``: safe from any
    thread, first caller wins (a drain timing out while the watchdog aborts
    must not fight over the exit code), and the dump can never block the
    exit.
    """
    global _aborting
    with _abort_lock:
        if _aborting:
            return
        _aborting = True
    logger.error(
        "ABORTING serving (%s, exit code %d): the platform restarts a "
        "clean instance", reason, exit_code,
    )
    try:
        if not fields.get("flight_recorder"):
            fields["flight_recorder"] = tracing.dump_flight_recorder(
                default_dir=os.environ.get(SM_MODEL_DIR_ENV) or None,
                reason=reason,
                exit_code=exit_code,
            )
    except Exception:
        logger.exception("flight-recorder dump failed; exiting anyway")
    fields = {k: v for k, v in fields.items() if v is not None}
    emit_metric("serving.abort", reason=reason, exit_code=exit_code, **fields)
    _exit(exit_code)


def _reset_abort_for_tests():
    global _aborting
    with _abort_lock:
        _aborting = False


class ServingLifecycle:
    """The serving process's health state machine + in-flight latch.

    One instance per server process, installed via :func:`install`; the
    WSGI apps and middleware consult it through the module-level helpers so
    code paths without a server (unit tests, bench legs) behave exactly as
    before.
    """

    def __init__(self, registry=None, clock=time.monotonic):
        # knobs resolve exactly once, here (envconfig: malformed values
        # warn-once and fall back; out-of-range clamp)
        self.graceful_drain = env_bool(GRACEFUL_DRAIN_ENV, True)
        self.drain_timeout_s = env_float(
            DRAIN_TIMEOUT_ENV, 30.0, minimum=0.0, maximum=3600.0
        )
        self.request_deadline_s = env_float(
            REQUEST_DEADLINE_ENV, 0.0, minimum=0.0, maximum=3600.0
        )
        self.predict_stuck_s = env_float(
            PREDICT_STUCK_ENV, 0.0, minimum=0.0, maximum=3600.0
        )
        self.predict_stuck_action = _stuck_action()
        self._clock = clock
        self._cond = threading.Condition()
        self._publish_lock = threading.Lock()
        self._base_state = STARTING
        self._last_published = STARTING
        self._inflight = 0
        self._breakers = []
        reg = registry or REGISTRY
        self._m_inflight = reg.gauge(
            "serving_inflight",
            "In-flight HTTP requests (response not yet fully written)",
        )
        self._m_state = reg.gauge(
            "serving_state",
            "Lifecycle state (0 starting, 1 ready, 2 degraded, 3 draining, "
            "4 stopped)",
        )
        self._m_drain = reg.gauge(
            "serving_drain_seconds",
            "Duration of the last SIGTERM drain (set when the drain settles)",
        )
        self._m_inflight.set(0.0)
        self._m_state.set(_STATE_GAUGE[STARTING])
        self.watchdog = None
        if self.predict_stuck_s > 0:
            self.watchdog = PredictWatchdog(
                self.predict_stuck_s, action=self.predict_stuck_action
            )

    # ----------------------------------------------------------- state plane
    @property
    def state(self):
        """Effective state: ``degraded`` is derived live from the breakers
        so ``/ping`` can never disagree with the shed decision. Reading it
        also publishes the effective value (gauge + one ``serving.lifecycle``
        record per change) — ``/ping`` polls it every few seconds on a real
        endpoint, so ready↔degraded flips reach the telemetry surface even
        though no code path "transitions" into the derived state."""
        return self._publish_state()

    def _publish_state(self):
        """Derive + publish under one lock hold.

        The derivation happens INSIDE the publish critical section: a
        publisher that derived its value before losing the CPU would
        otherwise overwrite a newer publication with a stale one (e.g. a
        /ping poll stamping `ready` over the drain's `draining` and leaving
        the gauge wrong for the whole drain). Re-deriving at publish time
        makes late publishers converge on the current truth instead.
        """
        with self._publish_lock:
            with self._cond:
                base = self._base_state
            effective = base
            if base == READY and any(b.degraded for b in self._breakers):
                effective = DEGRADED
            prev, self._last_published = self._last_published, effective
            if prev != effective:
                self._m_state.set(_STATE_GAUGE[effective])
                emit_metric("serving.lifecycle", state=effective, prev=prev)
                logger.info("serving lifecycle: %s -> %s", prev, effective)
            return effective

    @property
    def accepting(self):
        """False once draining/stopped: new /invocations + /ping get 503."""
        with self._cond:
            return self._base_state not in (DRAINING, STOPPED)

    def note_breaker(self, breaker):
        """Tell the lifecycle about a breaker feeding the degraded signal."""
        if breaker is not None and breaker not in self._breakers:
            self._breakers.append(breaker)

    def _set_state(self, state, only_from=None):
        """Atomically move the base state. ``only_from`` makes it a
        compare-and-set — the guard and the write share one lock hold, so a
        mark_ready racing a SIGTERM can never overwrite DRAINING with READY.
        Returns the previous state, or None when the guard refused."""
        with self._cond:
            if only_from is not None and self._base_state not in only_from:
                return None
            prev, self._base_state = self._base_state, state
        self._publish_state()
        return prev

    def mark_ready(self):
        """First successful model load: ``starting -> ready`` (idempotent,
        and atomic with the drain guard: a load completing mid-drain never
        un-drains)."""
        self._set_state(READY, only_from=(STARTING,))

    def begin_drain(self):
        """Stop accepting: /ping flips 503 so the load balancer deregisters.
        Returns False when already draining/stopped (duplicate SIGTERM)."""
        return self._set_state(DRAINING, only_from=(STARTING, READY)) is not None

    def mark_stopped(self):
        self._set_state(STOPPED)

    # -------------------------------------------------------- in-flight latch
    def request_started(self):
        with self._cond:
            self._inflight += 1
            self._m_inflight.set(float(self._inflight))

    def request_finished(self):
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            self._m_inflight.set(float(self._inflight))
            self._cond.notify_all()

    @property
    def inflight(self):
        with self._cond:
            return self._inflight

    def wait_drained(self, timeout):
        """Block until in-flight hits 0; -> False on timeout (wedged)."""
        deadline = self._clock() + max(0.0, timeout)
        with self._cond:
            while self._inflight > 0:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def observe_drain_seconds(self, seconds):
        self._m_drain.set(round(seconds, 3))

    # ------------------------------------------------------------- deadlines
    def request_deadline(self):
        """-> a fresh :class:`RequestDeadline`, or None when the knob is off."""
        if self.request_deadline_s <= 0:
            return None
        return RequestDeadline(self.request_deadline_s, clock=self._clock)

    # -------------------------------------------------------------- watchdog
    def register_batcher(self, name, batcher, breaker=None):
        self.note_breaker(breaker)
        if self.watchdog is not None:
            self.watchdog.register(name, batcher, breaker)

    def unregister_batcher(self, name):
        if self.watchdog is not None:
            self.watchdog.unregister(name)

    def shutdown(self):
        """Stop owned threads (tests / bench churn teardown)."""
        if self.watchdog is not None:
            self.watchdog.stop()


# ------------------------------------------------------- module-level install
_install_lock = threading.Lock()
_current = None


def install(lifecycle):
    """Make ``lifecycle`` the process's active lifecycle and wire the WSGI
    in-flight tracker. Returns the lifecycle for chaining."""
    global _current
    from ..telemetry import wsgi as telemetry_wsgi

    with _install_lock:
        _current = lifecycle
    telemetry_wsgi.set_request_tracker(lifecycle)
    emit_metric("serving.lifecycle", state=lifecycle.state, prev=None)
    return lifecycle


def uninstall():
    """Clear the active lifecycle (tests / bench churn)."""
    global _current
    from ..telemetry import wsgi as telemetry_wsgi

    with _install_lock:
        lifecycle, _current = _current, None
    telemetry_wsgi.set_request_tracker(None)
    if lifecycle is not None:
        lifecycle.shutdown()
    return lifecycle


def current():
    return _current


# Convenience hooks: every one is a no-op without an installed lifecycle so
# apps built directly in tests keep today's behavior byte-for-byte.
def mark_ready():
    lifecycle = _current
    if lifecycle is not None:
        lifecycle.mark_ready()


def accepting():
    lifecycle = _current
    return True if lifecycle is None else lifecycle.accepting


def observe(breaker=None):
    """Publish the effective state from a readiness poll.

    The /ping handlers call this each poll: the LB's health-check cadence is
    what surfaces derived ready<->degraded flips to the gauge/records (no
    code path "transitions" into the derived state, so something has to
    read it). ``breaker`` lets the handler register its breaker late —
    the apps are often built before a lifecycle is installed, and a
    breaker-without-batcher config would otherwise never be noted.
    Returns the effective state, or None with no lifecycle installed.
    """
    lifecycle = _current
    if lifecycle is None:
        return None
    if breaker is not None:
        lifecycle.note_breaker(breaker)
    return lifecycle.state


def request_deadline():
    lifecycle = _current
    return None if lifecycle is None else lifecycle.request_deadline()


def register_batcher(name, batcher, breaker=None):
    lifecycle = _current
    if lifecycle is not None:
        lifecycle.register_batcher(name, batcher, breaker)


def unregister_batcher(name):
    lifecycle = _current
    if lifecycle is not None:
        lifecycle.unregister_batcher(name)
