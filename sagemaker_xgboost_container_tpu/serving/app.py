"""The scoring WSGI application: /ping, /invocations, /execution-parameters,
and (env-gated) /metrics.

Route + status-code parity with the reference Flask app
(algorithm_mode/serve.py:138-249): 204 on empty payload, 415 on undecodable
content, 400 on predict failure, 406 on bad accept, 500 on model-load
failure; accept negotiation falls back to SAGEMAKER_DEFAULT_INVOCATIONS_ACCEPT
(default text/csv); MAX_CONTENT_LENGTH (6MB default) returns 413.

Implemented as a dependency-free WSGI callable (no flask/gunicorn in this
image) so it can run under any WSGI server — ours is the threaded server in
``server.py``. One process owns the TPU; worker threads share the compiled
forest kernel (predictions are pure jitted functions, safe across threads),
replacing the reference's worker-per-copy + nthread=1 workaround
(serve.py:92-107).
"""

import http.client
import json
import logging
import multiprocessing
import os
import threading

from .. import constants
from ..telemetry import instrument_wsgi
from ..toolkit import exceptions as exc
from ..utils.faults import fault_point
from . import lifecycle, serve_utils
from .lifecycle import DeadlineExceeded

logger = logging.getLogger(__name__)

SUPPORTED_ACCEPTS = [
    "application/json",
    "application/jsonlines",
    "application/x-recordio-protobuf",
    "text/csv",
]

PARSED_MAX_CONTENT_LENGTH = int(os.getenv("MAX_CONTENT_LENGTH", "6291456"))


def number_of_workers():
    return multiprocessing.cpu_count()


class ScoringService:
    """Lazy model holder for the single-model endpoint."""

    def __init__(self, model_dir=None):
        self.model_dir = model_dir or os.getenv(constants.SM_MODEL_DIR, "/opt/ml/model")
        self.model = None
        self.model_format = None
        self._batcher = None
        self._load_lock = threading.Lock()
        from .breaker import CircuitBreaker

        self.breaker = CircuitBreaker(name="single")

    def load_model(self):
        # lock: concurrent first requests on the threaded server must not
        # each load the model (and each spawn a warmup compile burst)
        with self._load_lock:
            if self.model is None:
                self.model, self.model_format = serve_utils.get_loaded_booster(
                    self.model_dir, serve_utils.is_ensemble_enabled()
                )
                if not isinstance(self.model, list) and os.getenv(
                    "SAGEMAKER_SERVING_BATCHING", "true"
                ).lower() == "true":
                    from ..utils.envconfig import env_int
                    from .batcher import PredictBatcher

                    model = self.model
                    rng = serve_utils.best_iteration_range(model)
                    # bounded queue (the MME/MMS knob, same default): an
                    # unbounded queue under saturation just converts
                    # overload into 60s client timeouts — JobQueueFull is
                    # what lets the circuit breaker shed load instead
                    self._batcher = PredictBatcher(
                        lambda feats: model.predict(feats, iteration_range=rng),
                        max_queue=env_int(
                            "SAGEMAKER_MODEL_JOB_QUEUE_SIZE", 100, minimum=1
                        ),
                    )
                    # predict watchdog (SM_PREDICT_STUCK_S): a wedged
                    # dispatch trips THIS breaker so /ping flips + sheds
                    lifecycle.register_batcher("single", self._batcher, self.breaker)
                # compile the first device buckets off the request path
                serve_utils.warmup_predict_async(self.model)
                # first successful load: the lifecycle leaves `starting`
                lifecycle.mark_ready()
        return self.model_format

    @property
    def objective(self):
        model = self.model[0] if isinstance(self.model, list) else self.model
        return model.objective_name if model else None

    @property
    def num_class(self):
        model = self.model[0] if isinstance(self.model, list) else self.model
        return str(model.num_class or "") if model else ""

    def predict(self, dtest, content_type, deadline=None):
        if self._batcher is not None:
            from ..data.content_types import get_content_type

            serve_utils._check_feature_count(
                self.model, dtest, get_content_type(content_type)
            )
            feats = serve_utils.canonicalize_features(self.model, dtest)
            preds = self._batcher.predict(feats, deadline=deadline)
            serve_utils.observe_drift(feats, preds)
            return preds
        result = serve_utils.predict(
            self.model, self.model_format, dtest, content_type, objective=self.objective
        )
        if deadline is not None:
            # the direct path can't be interrupted mid-predict; bill the
            # stage after the fact so expiry still answers 503, not a slow 200
            deadline.check("predict")
        return result


def _response(start_response, status, body=b"", content_type="text/plain", extra_headers=None):
    if isinstance(body, str):
        body = body.encode("utf-8")
    headers = [("Content-Type", content_type), ("Content-Length", str(len(body)))]
    if extra_headers:
        headers.extend(extra_headers)
    start_response(
        "{} {}".format(status, http.client.responses.get(status, "")),
        headers,
    )
    return [body]


def _shed_response(start_response, breaker, detail):
    """503 + Retry-After: the load-shedding contract (docs/robustness.md)."""
    return _response(
        start_response,
        http.client.SERVICE_UNAVAILABLE,
        "Temporarily overloaded: {}. Retry after the indicated delay.".format(detail),
        extra_headers=[("Retry-After", str(breaker.retry_after_s()))],
    )


def _drain_response(start_response):
    """503 + Retry-After while draining/stopped: the load balancer must
    deregister this instance and route the retry elsewhere
    (docs/robustness.md §Serving lifecycle)."""
    from .breaker import retry_after_hint

    return _response(
        start_response,
        http.client.SERVICE_UNAVAILABLE,
        "draining: instance is shutting down",
        extra_headers=[("Retry-After", str(retry_after_hint()))],
    )


def parse_accept(environ):
    accept = environ.get("HTTP_ACCEPT", "").split(";")[0].strip().lower()
    if not accept or accept == "*/*":
        return os.getenv(constants.SAGEMAKER_DEFAULT_INVOCATIONS_ACCEPT, "text/csv")
    if accept not in SUPPORTED_ACCEPTS:
        raise ValueError(
            "Accept type {} is not supported. Please use supported accept types: {}.".format(
                accept, SUPPORTED_ACCEPTS
            )
        )
    return accept


def _read_body(environ, limit=None):
    try:
        length = int(environ.get("CONTENT_LENGTH") or 0)
    except ValueError:
        length = 0
    if length > (PARSED_MAX_CONTENT_LENGTH if limit is None else limit):
        raise exc.UserError("Payload too large")
    return environ["wsgi.input"].read(length) if length else b""


def make_app(scoring_service=None, hooks=None):
    """Build the WSGI callable.

    hooks: optional script-mode override dict with any of model_fn/input_fn/
    predict_fn/output_fn/transform_fn (reference serving.py:63-134).
    """
    service = scoring_service or ScoringService()
    hooks = hooks or {}
    # duck-typed services (tests, script-mode shims) may not carry one
    breaker = getattr(service, "breaker", None)
    from .batcher import JobQueueFull

    def handle_invocations(environ, start_response):
        if not lifecycle.accepting():
            # draining/stopped: new work is refused so in-flight requests
            # can settle before the listener closes (SIGTERM drain)
            return _drain_response(start_response)
        if breaker is not None and not breaker.allow():
            # open breaker: shed before decode — the whole point is that a
            # drowning instance stops paying per-request parse costs
            return _shed_response(start_response, breaker, "shedding load")
        # per-request budget (SM_REQUEST_DEADLINE_S): stages draw down one
        # shared deadline; None when the knob is unset (zero overhead)
        deadline = lifecycle.request_deadline()
        payload = _read_body(environ)
        if len(payload) == 0:
            return _response(start_response, http.client.NO_CONTENT)
        content_type = environ.get("CONTENT_TYPE", "text/csv")

        try:
            accept = parse_accept(environ)
        except ValueError as e:
            return _response(start_response, http.client.NOT_ACCEPTABLE, str(e))

        if "transform_fn" in hooks:
            try:
                model = _hooked_model(service, hooks)
                result, out_type = hooks["transform_fn"](model, payload, content_type, accept)
                return _response(start_response, http.client.OK, result, out_type)
            except Exception as e:
                logger.exception("transform_fn failed")
                return _response(start_response, http.client.BAD_REQUEST, str(e))

        try:
            if "input_fn" in hooks:
                dtest = hooks["input_fn"](payload, content_type)
                parsed_type = content_type.split(";")[0]
            else:
                dtest, parsed_type = serve_utils.parse_content_data(payload, content_type)
        except Exception as e:
            logger.exception("decode failed")
            return _response(start_response, http.client.UNSUPPORTED_MEDIA_TYPE, str(e))
        if deadline is not None:
            deadline.check("decode")

        try:
            model = _hooked_model(service, hooks)
        except Exception as e:
            logger.exception("model load failed")
            return _response(
                start_response,
                http.client.INTERNAL_SERVER_ERROR,
                "Unable to load model: %s" % e,
            )

        try:
            # chaos hook: the request-thread predict stage (distinct from
            # the worker-side batcher.dispatch point) — error drills the 400
            # path, sleep drills per-stage deadline expiry
            fault_point("predict.dispatch", content_type=parsed_type)
            if "predict_fn" in hooks:
                preds = hooks["predict_fn"](dtest, model)
                if deadline is not None:
                    # bill a slow user predict_fn to the predict stage, like
                    # the direct path — not to whatever check runs next
                    deadline.check("predict")
            elif deadline is not None:
                preds = service.predict(dtest, parsed_type, deadline=deadline)
            else:
                # positional-only call keeps duck-typed services (script-mode
                # shims, tests) working when no deadline is armed
                preds = service.predict(dtest, parsed_type)
        except (JobQueueFull, TimeoutError) as e:
            # saturation, not a client error: 503 + Retry-After (MMS parity —
            # the reference's frontend 503s on a full job queue) and feed the
            # breaker so a sustained storm flips /ping and sheds pre-decode
            logger.warning("predict saturated: %s", e)
            if breaker is not None:
                breaker.record_saturation()
                return _shed_response(start_response, breaker, str(e))
            return _response(
                start_response, http.client.SERVICE_UNAVAILABLE, str(e)
            )
        except Exception as e:
            logger.exception("predict failed")
            return _response(
                start_response,
                http.client.BAD_REQUEST,
                "Unable to evaluate payload provided: %s" % e,
            )
        # chaos hook: response encoding (slow/failed serialization of a big
        # prediction set); the deadline check right after attributes a budget
        # blown before encoding even starts to the `encode` stage
        fault_point("serving.encode", accept=accept)
        if deadline is not None:
            deadline.check("encode")
        if breaker is not None:
            # success only once the deadline cleared too: recording it before
            # the encode check would reset the consecutive-saturation counter
            # on every request of an encode-stage expiry storm, and the
            # breaker could never reach its threshold
            breaker.record_success()

        if "output_fn" in hooks:
            try:
                body, out_type = hooks["output_fn"](preds, accept)
                return _response(start_response, http.client.OK, body, out_type)
            except Exception as e:
                return _response(start_response, http.client.INTERNAL_SERVER_ERROR, str(e))

        if serve_utils.is_selectable_inference_output():
            try:
                keys = serve_utils.get_selected_output_keys()
                selected = serve_utils.get_selected_predictions(
                    preds, keys, service.objective, num_class=service.num_class
                )
                body = serve_utils.encode_selected_predictions(selected, keys, accept)
                return _response(start_response, http.client.OK, body, accept)
            except Exception as e:
                logger.exception("selectable inference failed")
                return _response(start_response, http.client.INTERNAL_SERVER_ERROR, str(e))

        import numpy as np

        preds_list = np.asarray(preds).tolist()
        if os.getenv(constants.SAGEMAKER_BATCH):
            body = "\n".join(map(str, preds_list)) + "\n"
        elif accept == "application/json":
            body = serve_utils.encode_predictions_as_json(preds_list)
        elif accept == "application/jsonlines":
            body = serve_utils.encode_selected_predictions(
                [{"score": p} for p in preds_list], ["score"], accept
            )
        elif accept == "application/x-recordio-protobuf":
            from ..data.recordio import write_recordio_protobuf

            body = write_recordio_protobuf(
                np.asarray(preds_list, np.float32).reshape(len(preds_list), -1)
            )
        else:
            body = "\n".join(
                ",".join(map(str, p)) if isinstance(p, list) else str(p)
                for p in preds_list
            )
        return _response(start_response, http.client.OK, body, accept)

    def app(environ, start_response):
        path = environ.get("PATH_INFO", "/")
        method = environ.get("REQUEST_METHOD", "GET")
        try:
            if path == "/ping" and method == "GET":
                if not lifecycle.accepting():
                    # draining/stopped: unready so the load balancer
                    # deregisters while in-flight requests finish
                    return _drain_response(start_response)
                # each readiness poll publishes the derived ready<->degraded
                # state (serving_state gauge + serving.lifecycle records);
                # the shed decision itself stays breaker-driven below
                lifecycle.observe(breaker)
                if breaker is not None and breaker.degraded:
                    # flip readiness while shedding: the platform should
                    # stop routing to this instance until it recovers
                    return _response(
                        start_response,
                        http.client.SERVICE_UNAVAILABLE,
                        "degraded: shedding load",
                        extra_headers=[
                            ("Retry-After", str(breaker.retry_after_s()))
                        ],
                    )
                try:
                    _hooked_model(service, hooks)
                    # script-mode model_fn loads bypass ScoringService.
                    # load_model, so readiness is also marked here
                    lifecycle.mark_ready()
                    return _response(start_response, http.client.OK)
                except Exception as e:
                    logger.exception("ping model load failed")
                    return _response(
                        start_response, http.client.INTERNAL_SERVER_ERROR, str(e)
                    )
            if path == "/execution-parameters" and method == "GET":
                parameters = {
                    "MaxConcurrentTransforms": number_of_workers(),
                    "BatchStrategy": "MULTI_RECORD",
                    "MaxPayloadInMB": int(PARSED_MAX_CONTENT_LENGTH / (1024**2)),
                }
                return _response(
                    start_response,
                    http.client.OK,
                    json.dumps(parameters),
                    "application/json",
                )
            if path == "/invocations" and method == "POST":
                return handle_invocations(environ, start_response)
            return _response(start_response, http.client.NOT_FOUND, "not found")
        except exc.UserError as e:
            return _response(start_response, http.client.REQUEST_ENTITY_TOO_LARGE, str(e))
        except DeadlineExceeded as e:
            # decode/encode-stage expiry surfaces here (the predict-stage
            # ones ride the TimeoutError clause above): same saturation
            # protocol — 503 + Retry-After through the breaker feed
            logger.warning("request deadline exceeded: %s", e)
            if breaker is not None:
                breaker.record_saturation()
                return _shed_response(start_response, breaker, str(e))
            return _response(start_response, http.client.SERVICE_UNAVAILABLE, str(e))
        except Exception as e:  # last-resort 500
            logger.exception("unhandled serving error")
            return _response(start_response, http.client.INTERNAL_SERVER_ERROR, str(e))

    # middleware owns /metrics (SM_SERVING_METRICS gate) + per-route metrics
    return instrument_wsgi(app)


def _hooked_model(service, hooks):
    if "model_fn" in hooks:
        if service.model is None:
            service.model = hooks["model_fn"](service.model_dir)
            service.model_format = "user"
        return service.model
    service.load_model()
    return service.model
