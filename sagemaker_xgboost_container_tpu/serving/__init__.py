from .app import ScoringService, make_app  # noqa: F401
from .server import serving_entrypoint  # noqa: F401
