"""Multi-model endpoint: the MMS (Java frontend) replacement.

The reference runs multi-model endpoints through the multi-model-server Java
process + per-model Python workers (serving_mms.py / mms_patch). On TPU one
process owns the chip, so the frontend collapses into a pure-Python model
manager exposing MMS's REST surface (exercised by the reference's
test/integration/local/test_multiple_model_endpoint.py:32-101):

* ``POST   /models``                 {"model_name": n, "url": dir}  -> load
* ``GET    /models``                 -> list
* ``GET    /models/<name>``          -> describe
* ``DELETE /models/<name>``          -> unload
* ``POST   /models/<name>/invoke``   -> predict

Loaded models hold compiled predict kernels; an LRU cap (env
``SAGEMAKER_MAX_MODELS``, default unlimited) evicts the coldest model.

Operational knobs, mirroring the reference's MMS sizing contract
(serving_mms.py:72-137):

* ``SAGEMAKER_MAX_REQUEST_SIZE`` / ``MAX_CONTENT_LENGTH`` — payload cap,
  default 6MB, hard-capped at MMS's 20MB limit (serving_mms.py:34-35).
* ``SAGEMAKER_MODEL_JOB_QUEUE_SIZE`` — per-model pending-request bound
  (default 100, serving_mms.py:37); beyond it invokes get 503.
* ``SAGEMAKER_NUM_MODEL_WORKERS`` — accepted for contract parity; compute
  concurrency on a single-TPU-owner architecture comes from the request
  coalescer, not worker processes, so values other than 1 only log.
* JVM heap knobs (SAGEMAKER_MAX_HEAP_SIZE etc.) have no analog — no JVM.
"""

import collections
import http.client
import json
import logging
import os
import threading

from . import lifecycle, serve_utils
from ..toolkit import exceptions as exc
from ..utils.envconfig import env_int
from ..utils.faults import fault_point
from .app import _drain_response, _read_body, _response, _shed_response, parse_accept
from .batcher import JobQueueFull, PredictBatcher
from .lifecycle import DeadlineExceeded

logger = logging.getLogger(__name__)

MAX_CONTENT_LEN_LIMIT = 20 * 1024**2  # MMS hard cap, reference serving_mms.py:35


def _max_request_size():
    """Payload cap: SAGEMAKER_MAX_REQUEST_SIZE, else MAX_CONTENT_LENGTH,
    else 6MB — hard-capped at MMS's 20MB (reference serving_mms.py:80-83)."""
    value = env_int(
        "SAGEMAKER_MAX_REQUEST_SIZE", env_int("MAX_CONTENT_LENGTH", 6 * 1024**2)
    )
    return min(value, MAX_CONTENT_LEN_LIMIT)


def _drop_batcher_metrics(name):
    """Unload/evict lifecycle: retire the model's batcher metric series so
    model churn on a long-lived endpoint can't grow the registry (and the
    /metrics exposition + snapshot records) without bound. A reload of the
    same name starts fresh series — acceptable: the model was gone."""
    from ..telemetry import REGISTRY

    REGISTRY.remove_matching("batcher", name)


def _job_queue_size():
    return env_int("SAGEMAKER_MODEL_JOB_QUEUE_SIZE", 100)


class ModelManager:
    def __init__(self, max_models=None):
        self._models = collections.OrderedDict()  # name -> (model, fmt, dir, batcher)
        self._lock = threading.Lock()
        self.max_models = max_models or int(os.getenv("SAGEMAKER_MAX_MODELS", "0")) or None
        # manager-level breaker: MME has no per-model /ping, so degradation
        # (sustained saturation, a stuck predict dispatch) is endpoint-wide —
        # exactly the MMS frontend's behavior. Rides the existing
        # SM_LOAD_SHEDDING gate; with it off, saturation stays per-request.
        from .breaker import CircuitBreaker

        self.breaker = CircuitBreaker(name="mme")

    def load(self, name, url):
        model_dir = url
        if not os.path.isdir(model_dir):
            raise FileNotFoundError("model url {} is not a directory".format(url))
        model, fmt = serve_utils.get_loaded_booster(
            model_dir, serve_utils.is_ensemble_enabled()
        )
        batcher = None
        if not isinstance(model, list):
            rng = serve_utils.best_iteration_range(model)
            batcher = PredictBatcher(
                lambda feats, _m=model, _r=rng: _m.predict(feats, iteration_range=_r),
                max_queue=_job_queue_size(),
                name=name,  # per-model metric series, bounded by the LRU cap
            )
        workers = os.getenv("SAGEMAKER_NUM_MODEL_WORKERS")
        if workers and workers != "1":
            logger.info(
                "SAGEMAKER_NUM_MODEL_WORKERS=%s accepted; concurrency on a "
                "single-TPU-owner endpoint comes from request coalescing",
                workers,
            )
        with self._lock:
            if name in self._models:
                raise KeyError("model {} is already loaded".format(name))
            self._models[name] = (model, fmt, model_dir, batcher)
            if self.max_models and len(self._models) > self.max_models:
                evicted, _ = self._models.popitem(last=False)
                _drop_batcher_metrics(evicted)
                lifecycle.unregister_batcher(evicted)
                logger.info("Evicted model %s (LRU cap %d)", evicted, self.max_models)
            # compile the first device buckets off the request path — only
            # for a model that survived registration AND the LRU eviction
            # above (a discarded model must not burn the single TPU); the
            # spawn rides inside the lock so a concurrent load can't evict
            # it in between
            if name in self._models:
                serve_utils.warmup_predict_async(model)
                if batcher is not None:
                    # predict watchdog: a wedged dispatch on ANY model's
                    # batcher wedges the whole single-TPU process, so it
                    # trips the endpoint-wide breaker; registered only for a
                    # model that survived insertion + LRU eviction
                    lifecycle.register_batcher(name, batcher, self.breaker)

    def unload(self, name):
        with self._lock:
            if name not in self._models:
                raise KeyError(name)
            del self._models[name]
            _drop_batcher_metrics(name)
            lifecycle.unregister_batcher(name)

    def get(self, name):
        with self._lock:
            if name not in self._models:
                raise KeyError(name)
            self._models.move_to_end(name)
            return self._models[name]

    def list(self):
        with self._lock:
            return [
                {"modelName": name, "modelUrl": entry[2]}
                for name, entry in self._models.items()
            ]


def make_mme_app(manager=None):
    manager = manager or ModelManager()

    def app(environ, start_response):
        path = environ.get("PATH_INFO", "/").rstrip("/")
        method = environ.get("REQUEST_METHOD", "GET")
        try:
            if path == "/ping" and method == "GET":
                if not lifecycle.accepting():
                    # draining/stopped: deregister while in-flight invokes
                    # settle (docs/robustness.md §Serving lifecycle)
                    return _drain_response(start_response)
                # publish derived ready<->degraded on every readiness poll
                lifecycle.observe(manager.breaker)
                if manager.breaker.degraded:
                    return _response(
                        start_response,
                        http.client.SERVICE_UNAVAILABLE,
                        "degraded: shedding load",
                        extra_headers=[
                            ("Retry-After", str(manager.breaker.retry_after_s()))
                        ],
                    )
                return _response(start_response, http.client.OK, json.dumps({"status": "Healthy"}), "application/json")

            if path == "/models" and method == "GET":
                body = json.dumps({"models": manager.list()})
                return _response(start_response, http.client.OK, body, "application/json")

            if path == "/models" and method == "POST":
                params = _query_params(environ)
                if environ.get("CONTENT_TYPE", "").startswith("application/json"):
                    payload = json.loads(_read_body(environ) or b"{}")
                else:
                    payload = {}
                name = payload.get("model_name") or params.get("model_name")
                url = payload.get("url") or params.get("url")
                if not name or not url:
                    return _response(
                        start_response, http.client.BAD_REQUEST, "model_name and url required"
                    )
                try:
                    manager.load(name, url)
                except KeyError as e:
                    return _response(start_response, http.client.CONFLICT, str(e))
                except FileNotFoundError as e:
                    return _response(start_response, http.client.NOT_FOUND, str(e))
                except Exception as e:
                    logger.exception("model load failed")
                    return _response(start_response, http.client.INTERNAL_SERVER_ERROR, str(e))
                return _response(
                    start_response,
                    http.client.OK,
                    json.dumps({"status": "Workers scaled for model: " + name}),
                    "application/json",
                )

            if path.startswith("/models/"):
                remainder = path[len("/models/"):]
                if remainder.endswith("/invoke"):
                    name = remainder[: -len("/invoke")]
                    if method != "POST":
                        return _response(start_response, http.client.METHOD_NOT_ALLOWED)
                    return _invoke(manager, name, environ, start_response)
                name = remainder
                if method == "GET":
                    try:
                        _model, fmt, model_dir, _batcher = manager.get(name)
                    except KeyError:
                        return _response(start_response, http.client.NOT_FOUND, "model not found")
                    body = json.dumps([{"modelName": name, "modelUrl": model_dir, "format": fmt}])
                    return _response(start_response, http.client.OK, body, "application/json")
                if method == "DELETE":
                    try:
                        manager.unload(name)
                    except KeyError:
                        return _response(start_response, http.client.NOT_FOUND, "model not found")
                    return _response(
                        start_response,
                        http.client.OK,
                        json.dumps({"status": "Model \"{}\" unregistered".format(name)}),
                        "application/json",
                    )
            # single-model invocations path also works when exactly one model loaded
            if path == "/invocations" and method == "POST":
                models = manager.list()
                if len(models) != 1:
                    return _response(
                        start_response, http.client.BAD_REQUEST,
                        "multi-model endpoint: use /models/<name>/invoke",
                    )
                return _invoke(manager, models[0]["modelName"], environ, start_response)
            return _response(start_response, http.client.NOT_FOUND, "not found")
        except DeadlineExceeded as e:
            # decode/encode-stage expiry (the predict-stage ones are handled
            # inside _invoke): saturation protocol, not a client error
            logger.warning("request deadline exceeded: %s", e)
            manager.breaker.record_saturation()
            return _shed_response(start_response, manager.breaker, str(e))
        except Exception as e:
            logger.exception("unhandled MME error")
            return _response(start_response, http.client.INTERNAL_SERVER_ERROR, str(e))

    from ..telemetry import instrument_wsgi

    return instrument_wsgi(app)


def _query_params(environ):
    from urllib.parse import parse_qs

    qs = parse_qs(environ.get("QUERY_STRING", ""))
    return {k: v[0] for k, v in qs.items()}


def _invoke(manager, name, environ, start_response):
    if not lifecycle.accepting():
        return _drain_response(start_response)
    if not manager.breaker.allow():
        # open breaker (sustained saturation or a stuck predict dispatch):
        # shed before decode, endpoint-wide — the MMS frontend analog
        return _shed_response(start_response, manager.breaker, "shedding load")
    deadline = lifecycle.request_deadline()
    try:
        model, fmt, _dir, batcher = manager.get(name)
    except KeyError:
        return _response(start_response, http.client.NOT_FOUND, "model not found")
    try:
        payload = _read_body(environ, limit=_max_request_size())
    except exc.UserError as e:
        return _response(start_response, http.client.REQUEST_ENTITY_TOO_LARGE, str(e))
    if not payload:
        return _response(start_response, http.client.NO_CONTENT)
    content_type = environ.get("CONTENT_TYPE", "text/csv")
    try:
        dtest, parsed_type = serve_utils.parse_content_data(payload, content_type)
    except Exception as e:
        return _response(start_response, http.client.UNSUPPORTED_MEDIA_TYPE, str(e))
    if deadline is not None:
        deadline.check("decode")
    try:
        accept = parse_accept(environ)
    except ValueError as e:
        return _response(start_response, http.client.NOT_ACCEPTABLE, str(e))
    try:
        fault_point("predict.dispatch", model=name, content_type=parsed_type)
        first = model[0] if isinstance(model, list) else model
        if batcher is not None:
            from ..data.content_types import get_content_type

            serve_utils._check_feature_count(first, dtest, get_content_type(parsed_type))
            feats = serve_utils.canonicalize_features(first, dtest)
            preds = batcher.predict(feats, deadline=deadline)
            serve_utils.observe_drift(feats, preds)
        else:
            preds = serve_utils.predict(
                model, fmt, dtest, parsed_type, objective=first.objective_name
            )
            if deadline is not None:
                deadline.check("predict")
    except (JobQueueFull, TimeoutError) as e:
        # saturation (incl. per-stage deadline expiry): 503 + Retry-After,
        # feeding the endpoint-wide breaker so a sustained storm flips
        # /ping and sheds pre-decode (same shed contract as the single-model
        # app; the per-model queue bound is the MMS analog)
        manager.breaker.record_saturation()
        return _shed_response(start_response, manager.breaker, str(e))
    except Exception as e:
        logger.exception("invoke predict failed")
        return _response(start_response, http.client.BAD_REQUEST, str(e))
    fault_point("serving.encode", model=name, accept=accept)
    if deadline is not None:
        deadline.check("encode")
    # success only after the deadline cleared: recording it before the
    # encode check would reset the saturation counter every request and an
    # encode-expiry storm could never open the breaker
    manager.breaker.record_success()
    import numpy as np

    preds_list = np.asarray(preds).tolist()
    if accept == "application/json":
        body = serve_utils.encode_predictions_as_json(preds_list)
    else:
        body = "\n".join(
            ",".join(map(str, p)) if isinstance(p, list) else str(p) for p in preds_list
        )
    return _response(start_response, http.client.OK, body, accept)
