"""Handler services: the model_fn/input_fn/predict_fn/output_fn pipeline.

Parity with the reference's two MMS handlers:

* ``AlgorithmHandlerService`` (algorithm_mode/handler_service.py:32-121):
  default handlers backed by serve_utils — multi-model endpoints and batch
  transform use these,
* ``UserModuleHandlerService`` (handler_service.py:25-92): script-mode MME,
  where ``model_fn`` MUST come from the user module (the default raises).

Both expose ``handle(payload, content_type, accept, model_dir)`` so any
frontend (our WSGI apps, batch drivers) can run the same pipeline.
"""

import json

import numpy as np

from ..toolkit import exceptions as exc
from . import encoder, serve_utils


class InferenceError(Exception):
    def __init__(self, message, status):
        super().__init__(message)
        self.status = status


class AlgorithmHandlerService:
    """Default algorithm-mode handlers."""

    def __init__(self):
        self._model = None
        self._format = None

    def model_fn(self, model_dir):
        self._model, self._format = serve_utils.get_loaded_booster(
            model_dir, serve_utils.is_ensemble_enabled()
        )
        return self._model

    def input_fn(self, input_data, content_type):
        try:
            return serve_utils.parse_content_data(input_data, content_type)
        except Exception as e:
            raise InferenceError(str(e), 415)

    def predict_fn(self, data, model):
        dtest, content_type = data
        first = model[0] if isinstance(model, list) else model
        try:
            return serve_utils.predict(
                model, self._format, dtest, content_type, objective=first.objective_name
            )
        except Exception as e:
            raise InferenceError(str(e), 400)

    def output_fn(self, prediction, accept):
        preds_list = np.asarray(prediction).tolist()
        if accept == "application/json":
            return serve_utils.encode_predictions_as_json(preds_list), accept
        if accept == "application/jsonlines":
            body = encoder.json_to_jsonlines(
                {"predictions": [{"score": p} for p in preds_list]}
            )
            return body, accept
        if accept == "text/csv":
            # NOTE: the reference's MME csv join flattens nested lists
            # "legacy-invalid on purpose" (handler_service.py:103-104); we emit
            # proper csv rows instead.
            body = "\n".join(
                ",".join(map(str, p)) if isinstance(p, list) else str(p)
                for p in preds_list
            )
            return body, accept
        raise InferenceError("Accept type {} is not supported".format(accept), 406)

    def handle(self, payload, content_type, accept, model_dir):
        if self._model is None:
            self.model_fn(model_dir)
        data = self.input_fn(payload, content_type)
        preds = self.predict_fn(data, self._model)
        return self.output_fn(preds, accept)


class UserModuleHandlerService(AlgorithmHandlerService):
    """Script-mode handlers: user module overrides; model_fn is mandatory."""

    def __init__(self, user_module=None):
        super().__init__()
        self.user_module = user_module

    def _hook(self, name):
        return getattr(self.user_module, name, None) if self.user_module else None

    def model_fn(self, model_dir):
        hook = self._hook("model_fn")
        if hook is None:
            raise exc.UserError(
                "A model_fn implementation is required in the user module for "
                "multi-model endpoints in script mode."
            )
        self._model = hook(model_dir)
        self._format = "user"
        return self._model

    def input_fn(self, input_data, content_type):
        hook = self._hook("input_fn")
        if hook is not None:
            return hook(input_data, content_type)
        return super().input_fn(input_data, content_type)

    def predict_fn(self, data, model):
        hook = self._hook("predict_fn")
        if hook is not None:
            return hook(data, model)
        return super().predict_fn(data, model)

    def output_fn(self, prediction, accept):
        hook = self._hook("output_fn")
        if hook is not None:
            out = hook(prediction, accept)
            return out if isinstance(out, tuple) else (out, accept)
        return super().output_fn(prediction, accept)

    def handle(self, payload, content_type, accept, model_dir):
        transform = self._hook("transform_fn")
        if transform is not None:
            if self._model is None:
                self.model_fn(model_dir)
            out = transform(self._model, payload, content_type, accept)
            return out if isinstance(out, tuple) else (out, accept)
        return super().handle(payload, content_type, accept, model_dir)
