"""The ``serve`` entrypoint: threaded WSGI server + mode dispatch.

Reference: serving.py:140-169 (gunicorn+Flask or MMS). Here a single process
owns the TPU and a thread pool handles HTTP (no gunicorn/gevent in the
image; prediction is a compiled XLA kernel, so the GIL is released during
compute and worker-per-copy is unnecessary). Dispatch:

* SAGEMAKER_MULTI_MODEL=true  -> multi-model manager app (mme.py),
* user inference module found -> its model_fn/input_fn/predict_fn/output_fn/
  transform_fn override the algorithm handlers (serving.py:63-134),
* otherwise                    -> algorithm-mode scoring app.

``OMP_NUM_THREADS`` defaults to 1 as in the reference (serving.py:46-60) so
host-side numpy work doesn't oversubscribe the VM.
"""

import importlib.util
import logging
import os
import signal
import sys
import threading
from socketserver import ThreadingMixIn
from wsgiref.simple_server import WSGIRequestHandler, WSGIServer, make_server

from .. import constants
from .. import telemetry
from ..utils.envconfig import env_float
from ..utils.logging_config import setup_main_logger
from .app import ScoringService, make_app
from .mme import make_mme_app

logger = logging.getLogger(__name__)

METRICS_INTERVAL_ENV = "SM_METRICS_EMIT_INTERVAL_S"

HOOK_NAMES = ("model_fn", "input_fn", "predict_fn", "output_fn", "transform_fn")


class _ThreadedWSGIServer(ThreadingMixIn, WSGIServer):
    daemon_threads = True
    allow_reuse_address = True
    # socketserver's default listen backlog of 5 RSTs concurrent connects
    # beyond it (observed: 16 parallel clients losing connections); the
    # reference's gunicorn default is 2048
    request_queue_size = 2048


class _QuietHandler(WSGIRequestHandler):
    def log_message(self, format, *args):  # route access logs through logging
        logger.debug("%s - %s", self.address_string(), format % args)


def set_default_serving_env_if_unspecified():
    os.environ.setdefault("OMP_NUM_THREADS", constants.ONE_THREAD_PER_PROCESS)


def is_multi_model():
    return os.environ.get("SAGEMAKER_MULTI_MODEL", "").lower() == "true"


def _load_user_hooks(model_dir):
    """Import the customer's inference script if present; return hook dict."""
    program = os.environ.get("SAGEMAKER_PROGRAM")
    candidates = []
    if program:
        for base in (
            os.environ.get("SAGEMAKER_SUBMIT_DIRECTORY", ""),
            os.path.join(model_dir, "code"),
            model_dir,
        ):
            if base:
                candidates.append(os.path.join(base, program))
    script = next((c for c in candidates if os.path.isfile(c)), None)
    if script is None:
        return {}
    from ..utils.requirements import install_requirements_if_present

    install_requirements_if_present(os.path.dirname(script))
    spec = importlib.util.spec_from_file_location("user_inference_module", script)
    module = importlib.util.module_from_spec(spec)
    sys.path.insert(0, os.path.dirname(script))
    spec.loader.exec_module(module)
    hooks = {name: getattr(module, name) for name in HOOK_NAMES if hasattr(module, name)}
    logger.info("Loaded user serving hooks from %s: %s", script, sorted(hooks))
    return hooks


def build_app():
    if is_multi_model():
        logger.info("Starting multi-model endpoint manager")
        return make_mme_app()
    model_dir = os.getenv(constants.SM_MODEL_DIR, "/opt/ml/model")
    hooks = _load_user_hooks(model_dir)
    return make_app(ScoringService(model_dir), hooks=hooks)


class MetricsReporter:
    """Stop-able periodic ``serving.snapshot`` emitter.

    ``Event.wait(interval)`` instead of a bare ``time.sleep`` so the loop is
    killable: tests and graceful shutdown call :meth:`stop` and the thread
    exits within one wait, instead of leaking an unkillable daemon per
    server start."""

    def __init__(self, interval, registry):
        self.interval = interval
        self._registry = registry
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="metrics-reporter"
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                telemetry.emit_metric(
                    "serving.snapshot", **telemetry.snapshot_fields(self._registry)
                )
            except Exception:
                logger.exception("metrics reporter failed; continuing")


def start_metrics_reporter(interval=None, registry=None):
    """Start a daemon emitting one ``serving.snapshot`` structured record every
    ``SM_METRICS_EMIT_INTERVAL_S`` seconds — the CloudWatch-scrapable view of
    serving metrics for fleets without a Prometheus scraper. Off by default
    (interval unset/0/malformed — malformed values warn once via envconfig).
    Returns a :class:`MetricsReporter` stop handle, or None when disabled."""
    if interval is None:
        interval = env_float(METRICS_INTERVAL_ENV, 0.0, minimum=0.0)
    if interval <= 0:
        return None
    reporter = MetricsReporter(interval, registry or telemetry.REGISTRY).start()
    logger.info("Emitting serving metric snapshots every %.1fs", interval)
    return reporter


def serving_entrypoint(port=None, block=True):
    set_default_serving_env_if_unspecified()
    setup_main_logger(__name__)
    port = int(port or os.getenv("SAGEMAKER_BIND_TO_PORT", 8080))
    # device-runtime gauges (XLA compile count/seconds, RSS, live device
    # bytes) feed /metrics and the snapshot records from serving startup on
    telemetry.register_runtime_gauges()
    app = build_app()
    logger.info(
        "GET /metrics is %s (gate: %s=true)",
        "enabled" if telemetry.metrics_endpoint_enabled() else "disabled",
        telemetry.METRICS_ENDPOINT_ENV,
    )
    reporter = start_metrics_reporter()
    httpd = make_server(
        "0.0.0.0", port, app, server_class=_ThreadedWSGIServer, handler_class=_QuietHandler
    )

    def _shutdown(signo, frame):
        logger.info("Received signal %s, shutting down", signo)
        if reporter is not None:
            reporter.stop(timeout=2.0)
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _shutdown)
    logger.info("Serving on port %d", port)
    if block:
        httpd.serve_forever()
    return httpd


def main():
    serving_entrypoint()


if __name__ == "__main__":
    main()
