"""The ``serve`` entrypoint: threaded WSGI server + mode dispatch.

Reference: serving.py:140-169 (gunicorn+Flask or MMS). Here a single process
owns the TPU and a thread pool handles HTTP (no gunicorn/gevent in the
image; prediction is a compiled XLA kernel, so the GIL is released during
compute and worker-per-copy is unnecessary). Dispatch:

* SAGEMAKER_MULTI_MODEL=true  -> multi-model manager app (mme.py),
* user inference module found -> its model_fn/input_fn/predict_fn/output_fn/
  transform_fn override the algorithm handlers (serving.py:63-134),
* otherwise                    -> algorithm-mode scoring app.

``OMP_NUM_THREADS`` defaults to 1 as in the reference (serving.py:46-60) so
host-side numpy work doesn't oversubscribe the VM.
"""

import hashlib
import importlib.util
import logging
import os
import signal
import sys
import threading
import time
from socketserver import ThreadingMixIn
from wsgiref.simple_server import WSGIRequestHandler, WSGIServer, make_server

from .. import constants
from .. import telemetry
from ..constants import EXIT_DRAIN_TIMEOUT
from ..utils.envconfig import env_float
from ..utils.logging_config import setup_main_logger
from . import lifecycle as lifecycle_mod
from .app import ScoringService, make_app
from .mme import make_mme_app

logger = logging.getLogger(__name__)

METRICS_INTERVAL_ENV = "SM_METRICS_EMIT_INTERVAL_S"

HOOK_NAMES = ("model_fn", "input_fn", "predict_fn", "output_fn", "transform_fn")


class _ThreadedWSGIServer(ThreadingMixIn, WSGIServer):
    daemon_threads = True
    allow_reuse_address = True
    # socketserver's default listen backlog of 5 RSTs concurrent connects
    # beyond it (observed: 16 parallel clients losing connections); the
    # reference's gunicorn default is 2048
    request_queue_size = 2048


class _QuietHandler(WSGIRequestHandler):
    def log_message(self, format, *args):  # route access logs through logging
        logger.debug("%s - %s", self.address_string(), format % args)


def set_default_serving_env_if_unspecified():
    os.environ.setdefault("OMP_NUM_THREADS", constants.ONE_THREAD_PER_PROCESS)


def is_multi_model():
    return os.environ.get("SAGEMAKER_MULTI_MODEL", "").lower() == "true"


def _load_user_hooks(model_dir):
    """Import the customer's inference script if present; return hook dict.

    Import hygiene: the script dir lands on ``sys.path`` (user scripts
    import sibling helpers, lazily too — so a successful load keeps it,
    without duplicating an entry already there), and the module registers
    in ``sys.modules`` under a name derived from the script path (pickle /
    dataclass machinery resolves classes through it; a fixed name would
    alias distinct scripts). A FAILED exec rolls both back, so a broken
    script can't poison a retried load with a half-initialized module or a
    stale path entry.
    """
    program = os.environ.get("SAGEMAKER_PROGRAM")
    candidates = []
    if program:
        for base in (
            os.environ.get("SAGEMAKER_SUBMIT_DIRECTORY", ""),
            os.path.join(model_dir, "code"),
            model_dir,
        ):
            if base:
                candidates.append(os.path.join(base, program))
    script = next((c for c in candidates if os.path.isfile(c)), None)
    if script is None:
        return {}
    from ..utils.requirements import install_requirements_if_present

    install_requirements_if_present(os.path.dirname(script))
    script_dir = os.path.dirname(script)
    module_name = "user_inference_{}".format(
        hashlib.sha1(os.path.abspath(script).encode("utf-8")).hexdigest()[:12]
    )
    spec = importlib.util.spec_from_file_location(module_name, script)
    module = importlib.util.module_from_spec(spec)
    inserted = script_dir not in sys.path
    if inserted:
        sys.path.insert(0, script_dir)
    sys.modules[module_name] = module
    try:
        spec.loader.exec_module(module)
    except BaseException:
        sys.modules.pop(module_name, None)
        if inserted:
            try:
                sys.path.remove(script_dir)
            except ValueError:
                pass
        raise
    hooks = {name: getattr(module, name) for name in HOOK_NAMES if hasattr(module, name)}
    logger.info("Loaded user serving hooks from %s: %s", script, sorted(hooks))
    return hooks


def build_app():
    if is_multi_model():
        logger.info("Starting multi-model endpoint manager")
        app = make_mme_app()
        # MME starts empty by design (models arrive via POST /models):
        # there is no warmup to gate readiness on
        lifecycle_mod.mark_ready()
        return app
    model_dir = os.getenv(constants.SM_MODEL_DIR, "/opt/ml/model")
    hooks = _load_user_hooks(model_dir)
    return make_app(ScoringService(model_dir), hooks=hooks)


class MetricsReporter:
    """Stop-able periodic ``serving.snapshot`` emitter.

    ``Event.wait(interval)`` instead of a bare ``time.sleep`` so the loop is
    killable: tests and graceful shutdown call :meth:`stop` and the thread
    exits within one wait, instead of leaking an unkillable daemon per
    server start."""

    def __init__(self, interval, registry):
        self.interval = interval
        self._registry = registry
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="metrics-reporter"
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                telemetry.emit_metric(
                    "serving.snapshot", **telemetry.snapshot_fields(self._registry)
                )
            except Exception:
                logger.exception("metrics reporter failed; continuing")


def start_metrics_reporter(interval=None, registry=None):
    """Start a daemon emitting one ``serving.snapshot`` structured record every
    ``SM_METRICS_EMIT_INTERVAL_S`` seconds — the CloudWatch-scrapable view of
    serving metrics for fleets without a Prometheus scraper. Off by default
    (interval unset/0/malformed — malformed values warn once via envconfig).
    Returns a :class:`MetricsReporter` stop handle, or None when disabled."""
    if interval is None:
        interval = env_float(METRICS_INTERVAL_ENV, 0.0, minimum=0.0)
    if interval <= 0:
        return None
    reporter = MetricsReporter(interval, registry or telemetry.REGISTRY).start()
    logger.info("Emitting serving metric snapshots every %.1fs", interval)
    return reporter


def drain_and_shutdown(httpd, lifecycle, reporter=None):
    """Settle in-flight work, then close the listener. The SIGTERM contract:

    1. ``begin_drain()`` — /ping answers 503 + Retry-After so the load
       balancer deregisters, /invocations refuses new work the same way.
       The listener stays OPEN: a connect that raced the drain gets an
       orderly 503, never a RST.
    2. In-flight requests (WSGI latch: response bodies fully written) get
       ``SM_DRAIN_TIMEOUT_S`` to finish.
    3. Drained -> stop the accept loop, close the listener, exit 0.
       Still-wedged requests past the deadline -> flight-recorder dump +
       one ``serving.abort`` record and a distinct exit code (83) so the
       platform log names the failure instead of a mystery SIGKILL.

    Legacy mode (``SM_GRACEFUL_DRAIN=false``) skips the wait but still
    shuts the server down in an orderly fashion (no ``SystemExit`` from a
    signal handler; daemon request threads die with the process, exactly
    the pre-drain behavior).

    Shared by the SIGTERM handler, the serve drill, and bench_serve's churn
    leg. Returns True on a clean drain (False only from the test hook's
    fake exit).
    """
    drain_start = time.monotonic()
    if lifecycle is not None and lifecycle.graceful_drain:
        lifecycle.begin_drain()
        drained = lifecycle.wait_drained(lifecycle.drain_timeout_s)
        lifecycle.observe_drain_seconds(time.monotonic() - drain_start)
        if not drained:
            logger.error(
                "drain timed out after %.1fs with %d request(s) still in "
                "flight — wedged predict; exiting %d for a clean restart",
                lifecycle.drain_timeout_s, lifecycle.inflight, EXIT_DRAIN_TIMEOUT,
            )
            if reporter is not None:
                reporter.stop(timeout=2.0)
            from .lifecycle import abort_serving

            abort_serving(
                "drain_timeout",
                EXIT_DRAIN_TIMEOUT,
                inflight=lifecycle.inflight,
                drain_timeout_s=lifecycle.drain_timeout_s,
            )
            return False  # only reachable when the exit hook is faked
        logger.info(
            "drain complete in %.2fs; closing the listener",
            time.monotonic() - drain_start,
        )
    elif lifecycle is not None:
        lifecycle.begin_drain()  # still flip /ping for the shutdown window
        logger.info("graceful drain disabled (%s=false): immediate shutdown",
                    lifecycle_mod.GRACEFUL_DRAIN_ENV)
    if reporter is not None:
        reporter.stop(timeout=2.0)
    telemetry.stop_fleet_plane()
    httpd.shutdown()
    httpd.server_close()
    if lifecycle is not None:
        lifecycle.mark_stopped()
    return True


def serving_entrypoint(port=None, block=True):
    set_default_serving_env_if_unspecified()
    setup_main_logger(__name__)
    port = int(port or os.getenv("SAGEMAKER_BIND_TO_PORT", 8080))
    # device-runtime gauges (XLA compile count/seconds, RSS, live device
    # bytes) feed /metrics and the snapshot records from serving startup on
    telemetry.register_runtime_gauges()
    # lifecycle state machine + in-flight latch + (env-gated) predict
    # watchdog; knobs resolve once here (docs/robustness.md §Serving
    # lifecycle)
    lifecycle = lifecycle_mod.install(lifecycle_mod.ServingLifecycle())
    app = build_app()
    # SLO window (armed by instrument_wsgi inside build_app when
    # SM_SLO_P95_MS is set) quacks like a breaker: a sustained burn over
    # the error budget shows as DEGRADED in serving_state/serving.state
    # without flipping /ping — an SLO miss sheds nothing by itself
    slo_window = telemetry.slo.active_window()
    if slo_window is not None:
        lifecycle_mod.observe(slo_window)
    # kill -3 dumps the flight recorder + status snapshot without killing
    # the endpoint (the wedged-predict watchdog owns the abort path)
    telemetry.install_sigquit_handler()
    # live /status endpoint (SM_STATUS_PORT) on the serving host too — the
    # drift section (docs/observability.md §Model window) is a serving-side
    # document; self-gated: no thread or socket unless the knob is set
    current_host = os.getenv("SM_CURRENT_HOST", "localhost")
    telemetry.start_fleet_plane([current_host], current_host)
    logger.info(
        "GET /metrics is %s (gate: %s=true)",
        "enabled" if telemetry.metrics_endpoint_enabled() else "disabled",
        telemetry.METRICS_ENDPOINT_ENV,
    )
    reporter = start_metrics_reporter()
    httpd = make_server(
        "0.0.0.0", port, app, server_class=_ThreadedWSGIServer, handler_class=_QuietHandler
    )

    shutdown_state = {"thread": None}
    shutdown_lock = threading.Lock()

    def _shutdown(signo, frame):
        # The handler runs ON the main thread, which is blocked inside
        # serve_forever: both the drain wait and httpd.shutdown() (which
        # blocks until the serve loop acknowledges) would deadlock here.
        # Hand the whole sequence to a supervisor thread and return, letting
        # serve_forever keep answering 503s until the drain settles.
        logger.info("Received signal %s, draining before shutdown", signo)
        with shutdown_lock:
            if shutdown_state["thread"] is not None:
                return  # duplicate SIGTERM while already draining
            shutdown_state["thread"] = threading.Thread(
                target=drain_and_shutdown,
                args=(httpd, lifecycle),
                kwargs={"reporter": reporter},
                daemon=True,
                name="serving-drain",
            )
            shutdown_state["thread"].start()

    signal.signal(signal.SIGTERM, _shutdown)
    logger.info("Serving on port %d", port)
    if block:
        httpd.serve_forever()
        with shutdown_lock:
            drainer = shutdown_state["thread"]
        if drainer is not None:
            drainer.join(timeout=lifecycle.drain_timeout_s + 10.0)
    return httpd


def main():
    serving_entrypoint()


if __name__ == "__main__":
    main()
