"""Request coalescing for TPU serving.

The reference scales serving by forking a gunicorn worker per CPU, each with
its own model copy (serve.py:38-39, :92-107). On TPU one process owns the
chip, so throughput under concurrency comes from *batching*: concurrent
/invocations requests are coalesced into one padded forest-kernel dispatch
and the per-row results are scattered back to their callers.

A single daemon worker drains the queue; callers block on an Event with a
timeout. Batching is shape-safe: requests joining a batch must share the
feature width (they do — one model per endpoint); row counts concatenate and
the predict path's power-of-two bucketing keeps the jit cache small.
"""

import logging
import queue
import threading
import time

import numpy as np

from ..models.forest import _host_predict_rows
from ..telemetry import POW2_BUCKETS, REGISTRY, get_request_id, tracing
from ..utils.faults import fault_point
from . import lifecycle

logger = logging.getLogger(__name__)

# linger is bounded by max_wait_ms (default 2ms) — sub-ms buckets
_LINGER_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05)


class _Pending:
    __slots__ = ("features", "event", "result", "error", "ctx", "dispatched")

    def __init__(self, features):
        self.features = features
        self.event = threading.Event()
        self.result = None
        self.error = None
        # caller's trace context (SM_TRACE): carried across the queue so the
        # worker's dispatch span joins the request's trace tree
        self.ctx = tracing.current_context()
        # set by the worker when the batch holding this request starts its
        # dispatch: a deadline expiring after that is a `predict`-stage
        # expiry, before it a `queue`-stage one
        self.dispatched = False


class JobQueueFull(Exception):
    """Raised when the bounded job queue rejects a request (the MMS analog:
    SAGEMAKER_MODEL_JOB_QUEUE_SIZE, reference serving_mms.py:100 — MMS
    returns 503 when a model's job queue is exhausted)."""


class PredictBatcher:
    """Coalesce predict calls into batched kernel dispatches.

    ``predict_fn(features) -> np.ndarray`` must be thread-safe (ours is: a
    pure jitted kernel). ``max_batch_rows`` bounds padding waste;
    ``max_wait_ms`` bounds added latency under low load; ``max_queue``
    (None = unbounded) bounds in-flight requests, rejecting beyond it.

    Ordering note: requests are NOT strictly FIFO under light concurrency.
    The idle inline fast path runs small requests on the caller's thread
    under a non-blocking exec lock, so a new request can execute ahead of
    one the worker has already dequeued (held while parked on that lock).
    The reordering is bounded to a single overtaken request and is harmless
    for stateless prediction — but any future stateful use (sequence-
    sensitive accounting, streaming sessions) must not assume arrival-order
    execution.
    """

    def __init__(
        self,
        predict_fn,
        max_batch_rows=16384,
        max_wait_ms=2.0,
        max_queue=None,
        name="default",
        registry=None,
    ):
        self.predict_fn = predict_fn
        self.max_batch_rows = max_batch_rows
        self.max_wait_ms = max_wait_ms
        # metric identity is (name, labels). Live cardinality stays bounded:
        # MME unload/evict retires a model's series (mme._drop_batcher_metrics),
        # so churn through many model names cannot grow the registry forever.
        reg = registry or REGISTRY
        labels = {"batcher": name}
        self._m_requests = reg.counter(
            "batcher_requests_total", "Predict calls accepted", labels
        )
        self._m_inline = reg.counter(
            "batcher_inline_total", "Idle fast-path runs on the caller thread", labels
        )
        self._m_rejected = reg.counter(
            "batcher_rejected_total", "JobQueueFull rejections", labels
        )
        self._m_timeouts = reg.counter(
            "batcher_queue_timeout_total",
            "Callers that gave up waiting (zombie pendings: the worker may "
            "still dispatch their rows)",
            labels,
        )
        self._m_dispatch = reg.counter(
            "batcher_dispatch_total", "Kernel dispatches (batches executed)", labels
        )
        self._m_coalesced = reg.counter(
            "batcher_coalesced_requests_total",
            "Requests that shared a dispatch with at least one other "
            "(coalescing ratio = this / batcher_requests_total)",
            labels,
        )
        self._m_queue_depth = reg.gauge(
            "batcher_queue_depth", "Requests waiting in the coalescing queue", labels
        )
        self._m_batch_rows = reg.histogram(
            "batcher_batch_rows", "Rows per dispatched batch", labels, POW2_BUCKETS
        )
        self._m_batch_requests = reg.histogram(
            "batcher_batch_requests",
            "Requests coalesced per dispatched batch",
            labels,
            POW2_BUCKETS,
        )
        self._m_linger = reg.histogram(
            "batcher_linger_seconds",
            "Time spent collecting a batch before dispatch",
            labels,
            _LINGER_BUCKETS,
        )
        # test-and-set under a lock: a timeout storm expires many waiters at
        # the same instant, and the log-once guard must hold exactly then
        self._timeout_log_lock = threading.Lock()
        self._timeout_logged = False
        self._rejection_logged = False
        # bounded queue -> the limit is atomic (put_nowait raises Full);
        # a qsize() check-then-put would race under concurrent WSGI threads.
        # Clamped to >=1 when bounded: Queue(maxsize=0) means UNLIMITED in
        # Python, which would invert a SAGEMAKER_MODEL_JOB_QUEUE_SIZE=0 knob
        # into the unbounded queueing it exists to prevent.
        self.max_queue = None if max_queue is None else max(1, max_queue)
        self._queue = queue.Queue(maxsize=self.max_queue or 0)
        self._carry = None  # width-mismatched request deferred to next batch
        self._exec_lock = threading.Lock()  # held around every predict_fn run
        # current-dispatch bookkeeping for the predict watchdog
        # (lifecycle.PredictWatchdog): started timestamp + (requests, rows)
        # of the batch inside predict_fn right now, None/zeros when idle
        self._dispatch_lock = threading.Lock()
        self._dispatch_started = None
        self._dispatch_meta = (0, 0)
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def dispatch_age_s(self):
        """Seconds the in-flight predict_fn run has been executing, or None
        when no dispatch is in flight (the predict-watchdog probe)."""
        with self._dispatch_lock:
            started = self._dispatch_started
        return None if started is None else time.monotonic() - started

    def dispatch_info(self):
        """-> (requests, rows) of the in-flight dispatch (0, 0 when idle)."""
        with self._dispatch_lock:
            return self._dispatch_meta

    def _dispatch_begin(self, requests, rows):
        with self._dispatch_lock:
            self._dispatch_started = time.monotonic()
            self._dispatch_meta = (requests, rows)

    def _dispatch_end(self):
        with self._dispatch_lock:
            self._dispatch_started = None
            self._dispatch_meta = (0, 0)

    def predict(self, features, timeout=60.0, deadline=None):
        feats = np.asarray(features, np.float32)
        # Idle fast path: nothing queued and the worker is not mid-batch ->
        # run predict_fn on the caller's thread, skipping the cross-thread
        # queue/Event handoff (~0.7 ms of condvar ping-pong per request on
        # a 1-core host). The exec lock keeps predict_fn single-flight:
        # under any concurrency the non-blocking acquire fails and requests
        # take the coalescing queue exactly as before. Restricted to
        # host-path-sized payloads: the numpy traversal cannot hang, so
        # forgoing the queue path's wait-timeout is safe — device-sized
        # payloads keep the worker handoff and its TimeoutError bound (the
        # tunneled-TPU wedge failure mode).
        if (
            0 < feats.shape[0] <= _host_predict_rows()
            and self._queue.empty()
            and self._exec_lock.acquire(blocking=False)
        ):
            try:
                if self._queue.empty() and self._carry is None:
                    if deadline is not None:
                        deadline.check("predict")
                    self._m_requests.inc()
                    self._m_inline.inc()
                    with tracing.trace_span(
                        "batcher.inline",
                        attributes={"rows": int(feats.shape[0])},
                    ):
                        self._dispatch_begin(1, int(feats.shape[0]))
                        try:
                            return np.asarray(self.predict_fn(feats))
                        finally:
                            self._dispatch_end()
            finally:
                self._exec_lock.release()
        if deadline is not None:
            # a request whose budget is already gone must not take a queue
            # slot another request could use
            deadline.check("queue")
        pending = _Pending(feats)
        # the queue span covers enqueue -> (result | rejection | timeout) on
        # the caller's thread; the worker's dispatch span is its cross-thread
        # sibling in the same trace (joined via pending.ctx)
        qspan = tracing.start_span(
            "batcher.queue", attributes={"rows": int(feats.shape[0])}
        )
        try:
            try:
                self._queue.put_nowait(pending)
            except queue.Full:
                self._m_rejected.inc()
                with self._timeout_log_lock:
                    should_log, self._rejection_logged = not self._rejection_logged, True
                if should_log:
                    logger.warning(
                        "rejecting prediction (request %s): job queue full (%s "
                        "pending). Further rejections are counted in "
                        "batcher_rejected_total without logging.",
                        get_request_id() or "untracked",
                        self.max_queue,
                    )
                raise JobQueueFull(
                    "job queue full ({} pending)".format(self.max_queue)
                )
            self._m_requests.inc()
            self._m_queue_depth.set(self._queue.qsize())
            # SM_REQUEST_DEADLINE_S bounds queue wait PLUS dispatch: the
            # caller never blocks past the smaller of its legacy timeout and
            # the remaining request budget
            wait_s = timeout
            if deadline is not None:
                wait_s = min(timeout, deadline.remaining())
            if not pending.event.wait(wait_s):
                if deadline is not None and deadline.expired():
                    # same zombie accounting as the legacy timeout (the
                    # worker may still dispatch the abandoned rows), but
                    # attributed to the stage the budget died in
                    self._m_timeouts.inc()
                    lifecycle.expire(
                        "predict" if pending.dispatched else "queue",
                        deadline.budget_s,
                    )
                # zombie pending: this caller gives up, but the worker still
                # holds the _Pending and may dispatch its rows later — wasted
                # compute that a timeout storm multiplies. Count every one;
                # log the first at WARNING so the storm is visible without
                # flooding the log.
                self._m_timeouts.inc()
                with self._timeout_log_lock:
                    should_log, self._timeout_logged = not self._timeout_logged, True
                if should_log:
                    logger.warning(
                        "prediction (request %s) timed out after %.1fs in the "
                        "batch queue; the batch worker may still dispatch the "
                        "abandoned rows. Further timeouts are counted in "
                        "batcher_queue_timeout_total without logging.",
                        get_request_id() or "untracked",
                        timeout,
                    )
                raise TimeoutError("prediction timed out in the batch queue")
            if pending.error is not None:
                raise pending.error
            return pending.result
        finally:
            tracing.finish_span(qspan)

    # ------------------------------------------------------------------ int
    def _drain_batch(self, first, wait):
        """Collect a batch starting from ``first``.

        ``wait``: whether to linger max_wait_ms for stragglers. A lone
        request on an idle endpoint must NOT pay the linger (it would add
        max_wait_ms to every p50); under concurrency the queue accumulates
        while predict_fn runs, so coalescing happens even with wait=False.
        The worker passes wait=True only after a batch that actually
        coalesced — evidence of concurrent load.
        """
        batch = [first]
        rows = first.features.shape[0]
        # ONE deadline for the whole batch: re-arming the timeout per
        # straggler would let a trickle of arrivals defer dispatch unboundedly
        deadline = time.monotonic() + (self.max_wait_ms / 1000.0 if wait else 0.0)
        while rows < self.max_batch_rows:
            try:
                remaining = deadline - time.monotonic()
                if remaining > 0:
                    nxt = self._queue.get(timeout=remaining)
                else:
                    nxt = self._queue.get_nowait()
            except queue.Empty:
                break
            if nxt.features.shape[1] != first.features.shape[1]:
                # different width (e.g. mid-flight model swap): defer to its
                # own batch (re-putting could block on a bounded queue)
                # graftlint: disable=shared-state-unlocked — the only caller
                # (_worker) holds _exec_lock around every _drain_batch call
                self._carry = nxt
                break
            batch.append(nxt)
            rows += nxt.features.shape[0]
        return batch

    def _worker(self):
        loaded = False  # previous batch coalesced -> linger for stragglers
        while True:
            # swap the carry out UNDER the exec lock so the inline fast
            # path's `self._carry is None` check (made while holding it)
            # always observes a consistent value (graftlint
            # shared-state-unlocked). The lock is dropped before the drain
            # below, so an inline run may still execute between this swap
            # and the carried request's dispatch — that ordering was always
            # permitted; the lock only makes the state transition atomic.
            with self._exec_lock:
                first, self._carry = self._carry, None
            if first is None:
                first = self._queue.get()
            # drain INSIDE the exec lock: while an inline run holds it, the
            # worker must not vacuum the queue into a private batch — queued
            # requests have to keep counting against max_queue so the
            # JobQueueFull bound stays meaningful (at most one request — the
            # one just dequeued — sits outside the queue while blocked here)
            with self._exec_lock:
                drain_start = time.monotonic()
                batch = self._drain_batch(first, wait=loaded)
                loaded = len(batch) > 1
                self._m_linger.observe(time.monotonic() - drain_start)
                self._m_queue_depth.set(self._queue.qsize())
                self._m_dispatch.inc()
                self._m_batch_requests.observe(len(batch))
                self._m_batch_rows.observe(
                    sum(p.features.shape[0] for p in batch)
                )
                if len(batch) > 1:
                    self._m_coalesced.inc(len(batch))
                # worker-thread dispatch span, parented to the first traced
                # request in the batch so its trace id survives the thread
                # hop (coalesced peers are named in the args)
                ctx = next((p.ctx for p in batch if p.ctx is not None), None)
                with tracing.trace_span(
                    "batcher.dispatch",
                    parent=ctx,
                    attributes={
                        "requests": len(batch),
                        "rows": sum(p.features.shape[0] for p in batch),
                    },
                ):
                    self._dispatch_begin(
                        len(batch), sum(p.features.shape[0] for p in batch)
                    )
                    for pending in batch:
                        pending.dispatched = True
                    try:
                        # chaos hook: a sleep here wedges the dispatch worker
                        # (tunneled-TPU stall), backing the queue up into
                        # JobQueueFull — the breaker drill's saturation source
                        fault_point("batcher.dispatch", requests=len(batch))
                        stacked = (
                            batch[0].features
                            if len(batch) == 1
                            else np.concatenate(
                                [p.features for p in batch], axis=0
                            )
                        )
                        out = np.asarray(self.predict_fn(stacked))
                        offset = 0
                        for pending in batch:
                            k = pending.features.shape[0]
                            pending.result = out[offset : offset + k]
                            offset += k
                            pending.event.set()
                    except Exception as e:  # propagate to every caller in batch
                        for pending in batch:
                            pending.error = e
                            pending.event.set()
                    finally:
                        self._dispatch_end()
