"""Serving SLO plane: rolling-window latency percentiles vs an explicit
target, with burn-rate.

The serving stack has latency *metrics* (``serving_request_seconds``) but no
*objective* to judge them against: ROADMAP item 3's "millions of users"
scale-out needs a machine-readable "are we inside SLO right now" signal that
a fleet scheduler, the PR-9 lifecycle, and bench_serve.py can all consult.
This module provides it:

* ``SM_SLO_P95_MS`` arms the plane (unset/0 = completely inert: no window,
  no metric series, no per-request work beyond one ``is None`` test);
* every ``/invocations`` latency lands in a rolling ``SM_SLO_WINDOW_S``
  window (default 300 s);
* a sample over the target counts ``serving_slo_violation_total`` and the
  window's violating fraction over the 5% error budget (a p95 target
  tolerates 5% of requests above it) is published as
  ``serving_slo_burn_rate`` — 1.0 means burning exactly the budget,
  above 1.0 the SLO is being missed;
* the window object quacks like a circuit breaker (``.degraded``), so the
  serving lifecycle folds a sustained SLO burn into its derived
  ``degraded`` state (serving/lifecycle.py ``note_breaker``) — visible in
  ``serving_state`` and the ``serving.state`` records without flipping
  ``/ping`` (an SLO miss sheds nothing by itself; the saturation breaker
  owns that).

Fed by the WSGI middleware (telemetry/wsgi.py) for the ``/invocations``
route on BOTH serving apps, and read by bench_serve.py's steady-state leg
and the rank-0 ``/status`` endpoint (telemetry/fleet.py).
"""

import collections
import logging
import threading
import time

from ..utils.envconfig import env_float
from .registry import REGISTRY, percentile

logger = logging.getLogger(__name__)

SLO_P95_ENV = "SM_SLO_P95_MS"
SLO_WINDOW_ENV = "SM_SLO_WINDOW_S"

DEFAULT_WINDOW_S = 300.0

#: a p95 objective tolerates 5% of requests above the target; burn rate is
#: the measured violating fraction divided by this budget
ERROR_BUDGET = 0.05

#: below this many samples the window stays out of ``degraded`` — a single
#: cold-start request must not flip the lifecycle state
MIN_SAMPLES = 20


def slo_target_ms():
    return env_float(SLO_P95_ENV, 0.0, minimum=0.0)


def slo_window_s():
    return env_float(SLO_WINDOW_ENV, DEFAULT_WINDOW_S, minimum=1.0)


class SloWindow:
    """Rolling latency window vs a p95 target.

    ``observe`` is O(amortized 1): append + trim + an incremental violation
    count; percentiles are computed only in :meth:`snapshot` (scrape /
    status / bench cadence, not request cadence). ``clock`` is injectable
    so the burn-rate math is unit-testable without sleeping.
    """

    def __init__(self, target_p95_ms, window_s=None, registry=None, clock=None):
        self.target_p95_ms = float(target_p95_ms)
        self.window_s = float(window_s if window_s is not None else slo_window_s())
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._samples = collections.deque()  # (t, latency_ms, violating)
        self._violating = 0
        reg = registry or REGISTRY
        # created (at zero) on install so both serving apps expose the
        # serving_slo_* series from the first scrape, not the first miss
        self._m_violations = reg.counter(
            "serving_slo_violation_total",
            "Requests over the SM_SLO_P95_MS latency target",
        )
        self._m_burn = reg.gauge(
            "serving_slo_burn_rate",
            "Rolling-window SLO violation fraction over the 5% error budget",
        )
        self._m_burn.set(0.0)

    # ------------------------------------------------------------- feed path
    def observe_seconds(self, elapsed_s):
        self.observe_ms(float(elapsed_s) * 1000.0)

    def observe_ms(self, latency_ms):
        now = self._clock()
        violating = latency_ms > self.target_p95_ms
        with self._lock:
            self._samples.append((now, float(latency_ms), violating))
            if violating:
                self._violating += 1
            self._trim_locked(now)
            burn = self._burn_locked()
        if violating:
            self._m_violations.inc()
        self._m_burn.set(round(burn, 4))

    def _trim_locked(self, now):
        cutoff = now - self.window_s
        samples = self._samples
        while samples and samples[0][0] < cutoff:
            _t, _ms, was_violating = samples.popleft()
            if was_violating:
                self._violating -= 1

    def _burn_locked(self):
        n = len(self._samples)
        if n == 0:
            return 0.0
        return (self._violating / n) / ERROR_BUDGET

    # ------------------------------------------------------------ read paths
    @property
    def degraded(self):
        """Breaker-shaped hook for the serving lifecycle: True while the
        window holds enough samples and the burn rate exceeds 1.0 (the
        error budget is being spent faster than the objective allows)."""
        with self._lock:
            self._trim_locked(self._clock())
            return len(self._samples) >= MIN_SAMPLES and self._burn_locked() > 1.0

    def snapshot(self):
        """-> dict(target/window/samples/p50/p95/violation_rate/burn_rate/
        degraded) — the shape bench_serve's steady leg and ``/status``
        publish."""
        with self._lock:
            self._trim_locked(self._clock())
            lat = [ms for _t, ms, _v in self._samples]
            n = len(lat)
            violating = self._violating
            burn = self._burn_locked()
        return {
            "target_p95_ms": self.target_p95_ms,
            "window_s": self.window_s,
            "samples": n,
            "p50_ms": round(percentile(lat, 0.5), 3) if lat else 0.0,
            "p95_ms": round(percentile(lat, 0.95), 3) if lat else 0.0,
            "violation_rate": round(violating / n, 4) if n else 0.0,
            "burn_rate": round(burn, 4),
            "degraded": n >= MIN_SAMPLES and burn > 1.0,
        }


# ------------------------------------------------------------ process plane
_window_lock = threading.Lock()
_window = None


def maybe_install(registry=None):
    """Arm the process-wide SLO window when ``SM_SLO_P95_MS`` is set > 0.

    Called by the WSGI middleware at app-construction time, so BOTH serving
    apps (single-model and MME) get the same window and the
    ``serving_slo_*`` series without either importing this module
    explicitly. Idempotent; returns the active window or None (disarmed —
    zero objects, zero series)."""
    global _window
    if _window is not None:
        return _window
    target = slo_target_ms()
    if target <= 0:
        return None
    with _window_lock:
        if _window is None:
            _window = SloWindow(target, registry=registry)
            logger.info(
                "serving SLO armed: p95 target %.1f ms over a %.0fs window",
                _window.target_p95_ms,
                _window.window_s,
            )
    return _window


def active_window():
    """The installed window, or None when the plane is disarmed."""
    return _window


def _reset_for_tests():
    global _window
    with _window_lock:
        _window = None
