"""Per-request correlation IDs for the serving path.

A slow invocation on a busy endpoint is currently untraceable: the WSGI
access log, the batcher's timeout warning, and the response the client saw
have nothing in common to join on. This module gives every request one ID:

* honored from the client when present — ``X-Request-Id`` directly, or a
  ``request_id=``/``trace_id=`` pair inside
  ``X-Amzn-SageMaker-Custom-Attributes`` (the SageMaker-blessed passthrough
  header for invocation metadata);
* generated otherwise (uuid4 hex);
* stored in a thread-local for the duration of the request (the threaded
  WSGI server runs one request per thread, and the batcher's timeout/
  rejection warnings fire on the caller's — i.e. the request's — thread);
* echoed back in the ``X-Request-Id`` response header;
* attached to every log record emitted on the request thread via
  :class:`RequestIdFilter` (installed by ``setup_main_logger``).
"""

import logging
import re
import threading
import uuid

REQUEST_ID_HEADER = "X-Request-Id"
CUSTOM_ATTRIBUTES_HEADER = "X-Amzn-SageMaker-Custom-Attributes"

# WSGI environ keys for the two honored headers
_ENV_REQUEST_ID = "HTTP_X_REQUEST_ID"
_ENV_CUSTOM_ATTRIBUTES = "HTTP_X_AMZN_SAGEMAKER_CUSTOM_ATTRIBUTES"

# IDs become log fields and response headers: restrict to a safe charset and
# a bounded length so a hostile header can't inject log lines or bloat them
_SAFE_ID = re.compile(r"[^A-Za-z0-9._-]")
_MAX_ID_LEN = 64

_CUSTOM_ATTR_KEYS = ("request_id", "trace_id")

_tls = threading.local()


def new_request_id():
    return uuid.uuid4().hex


def _sanitize(raw):
    if not raw:
        return None
    cleaned = _SAFE_ID.sub("", str(raw).strip())[:_MAX_ID_LEN]
    return cleaned or None


def extract_request_id(environ):
    """Resolve the request ID for a WSGI request: honor the client's when
    present, generate otherwise. Always returns a non-empty safe string."""
    rid = _sanitize(environ.get(_ENV_REQUEST_ID))
    if rid:
        return rid
    attrs = environ.get(_ENV_CUSTOM_ATTRIBUTES, "")
    if attrs:
        for part in attrs.split(","):
            key, _, value = part.partition("=")
            if key.strip().lower() in _CUSTOM_ATTR_KEYS:
                rid = _sanitize(value)
                if rid:
                    return rid
    return new_request_id()


def set_request_id(rid):
    _tls.request_id = rid


def get_request_id():
    """The current thread's request ID, or None outside a request."""
    return getattr(_tls, "request_id", None)


def clear_request_id():
    _tls.request_id = None


class RequestIdFilter(logging.Filter):
    """Attach the active request ID to log records.

    Sets ``record.request_id`` (always, ``-`` outside a request) for
    structured formatters, and appends ``[rid=...]`` to the message when a
    request is active so the default console format carries it without a
    format-string change. Idempotent across multiple handlers.
    """

    def filter(self, record):
        rid = get_request_id()
        record.request_id = rid or "-"
        if rid and not getattr(record, "_rid_tagged", False):
            record._rid_tagged = True
            record.msg = "{} [rid={}]".format(record.msg, rid)
        return True
