"""Unified telemetry layer: registry, spans, stdout records, /metrics,
and the multi-host cluster plane.

Import surface for the rest of the container:

    from ..telemetry import REGISTRY            # process-wide registry
    from ..telemetry import span, PhaseRecorder # phase timing
    from ..telemetry import emit_metric         # structured stdout records
    from ..telemetry import instrument_wsgi     # serving middleware
    from ..telemetry import start_cluster_telemetry  # heartbeats + rank-0 agg
    from ..telemetry import register_runtime_gauges  # XLA/RSS/device gauges
    from ..telemetry import get_request_id      # serving request correlation
    from ..telemetry import start_fleet_plane   # span shipping + /status
    from ..telemetry import fleet, slo          # fleet view / serving SLO

See docs/observability.md for the full metric catalogue and env knobs.
"""

from . import tracing  # noqa: F401  (hierarchical tracer: telemetry.tracing)
from . import device  # noqa: F401  (device window: telemetry.device)
from . import fleet  # noqa: F401  (fleet trace/skew/status: telemetry.fleet)
from .cluster import (  # noqa: F401
    CLUSTER_METRICS_ENV,
    HEARTBEAT_INTERVAL_ENV,
    ROUND_STATE,
    compile_stats,
    refresh_runtime_gauges,
    register_runtime_gauges,
    start_cluster_telemetry,
)
from .correlation import (  # noqa: F401
    REQUEST_ID_HEADER,
    RequestIdFilter,
    get_request_id,
)
from . import slo  # noqa: F401  (serving SLO window: telemetry.slo)
from .fleet import (  # noqa: F401
    FLEET_TRACE_ENV,
    STATUS_PORT_ENV,
    install_sigquit_handler,
    start_fleet_plane,
    stop_fleet_plane,
)
from .emit import (  # noqa: F401
    STRUCTURED_METRICS_ENV,
    emit_metric,
    get_round_fields,
    set_round_fields,
    snapshot_fields,
    structured_enabled,
)
from .prometheus import CONTENT_TYPE, render_text  # noqa: F401
from .registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    POW2_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    percentile,
)
from .spans import (  # noqa: F401
    PhaseRecorder,
    active_recorder,
    pop_recorder,
    push_recorder,
    span,
)
from .wsgi import (  # noqa: F401
    METRICS_ENDPOINT_ENV,
    instrument_wsgi,
    metrics_endpoint_enabled,
)
