"""Hierarchical tracing: spans with parent links, a bounded flight recorder,
and Chrome-trace/Perfetto JSON export.

The PR-1 span layer is a flat ``{phase: seconds}`` accumulator: it can say a
round spent 1.4 ms in ``checkpoint``, but not that the checkpoint's manifest
write happened *inside* round 12, or that the first round's 40 s was an XLA
compile and not tree building. This module adds the missing structure while
keeping the dependency-free, env-gated discipline of the rest of the
telemetry layer:

* **Spans** — id + parent link + attributes + wall window, propagated
  through a thread-local stack so nested ``span()``/``trace_span()`` calls
  form a tree without any caller threading context by hand. Cross-thread
  hops (the serving batcher's worker) pass an explicit parent context.
* **Flight recorder** — finished spans land in a bounded ring buffer
  (``SM_TRACE_BUFFER`` spans); a hung or aborting process dumps the last N
  spans — including still-open ones, flagged ``in_flight`` — as the
  post-mortem for "which round / which collective was live when the
  watchdog fired" (wired into ``watchdog.request_abort``, exits 79/80/81).
* **Chrome-trace export** — one JSON file per rank (``trace-rank<r>.json``),
  loadable in ``chrome://tracing`` / Perfetto / TensorBoard's trace viewer.
  Events are complete (``"ph": "X"``) events in microseconds with
  ``span_id``/``parent_id``/``trace_id`` in ``args`` so the tree survives
  the export round-trip.

Everything is gated on ``SM_TRACE``: unset (the default) means the fast
path is one cached-boolean check per call site — no spans, no buffer
growth, no threads (the tracer never creates any), no export files.
"""

import contextlib
import json
import logging
import os
import threading
import time
import uuid

from ..utils.envconfig import env_bool, env_int

logger = logging.getLogger(__name__)

TRACE_ENV = "SM_TRACE"
TRACE_BUFFER_ENV = "SM_TRACE_BUFFER"
TRACE_EXPORT_DIR_ENV = "SM_TRACE_EXPORT_DIR"
# read by models/booster.py (_TrainingSession resolves it once, host-side,
# at session construction — never on the traced round path)
DEVICE_SYNC_ENV = "SM_TRACE_DEVICE_SYNC"

DEFAULT_BUFFER_SPANS = 4096

# perf_counter base: Chrome-trace ts only needs internal consistency, and a
# monotonic clock keeps spans orderable across NTP steps
_T0 = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _T0) * 1e6


def new_id():
    return uuid.uuid4().hex[:16]


class Span:
    """One timed node in the trace tree. Finish on the thread that started
    it (the thread-local stack is popped by identity)."""

    __slots__ = (
        "trace_id",
        "span_id",
        "parent_id",
        "name",
        "attributes",
        "start_us",
        "dur_us",
        "tid",
        "thread_name",
        "seq",
    )

    def __init__(self, name, trace_id, parent_id, attributes=None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_id()
        self.parent_id = parent_id
        self.attributes = dict(attributes or {})
        self.start_us = _now_us()
        self.dur_us = None  # None while open
        self.tid = threading.get_ident()
        self.thread_name = threading.current_thread().name
        # recorder-append sequence number, stamped when the span lands in
        # the flight recorder — the fleet shipper's drain watermark
        # (telemetry/fleet.py ships spans with seq > last-shipped)
        self.seq = None

    def context(self):
        return (self.trace_id, self.span_id)


# --------------------------------------------------------------------- state
_tls = threading.local()

_state_lock = threading.Lock()
_enabled = None  # cached SM_TRACE verdict; None = not yet resolved
_rank = 0
_recorder = None  # deque of finished Span, created lazily
_live = {}  # span_id -> open Span (for flight-recorder dumps)
_seq = 0  # monotonic recorder-append counter (survives ring-buffer drops)


def enabled():
    """Cached ``SM_TRACE`` verdict — the per-call-site fast path is one
    function call and a boolean test. Tests toggle via ``_reset_for_tests``."""
    global _enabled
    value = _enabled
    if value is None:
        with _state_lock:
            if _enabled is None:
                _enabled = env_bool(TRACE_ENV, False)
            value = _enabled
    return value


def set_rank(rank):
    """Record this process's rank for export file names/metadata (wired by
    the distributed-training pre-exec; standalone processes stay rank 0)."""
    global _rank
    _rank = int(rank)


def get_rank():
    return _rank


def _get_recorder():
    global _recorder
    rec = _recorder
    if rec is None:
        import collections

        with _state_lock:
            if _recorder is None:
                _recorder = collections.deque(
                    maxlen=env_int(
                        TRACE_BUFFER_ENV, DEFAULT_BUFFER_SPANS, minimum=16
                    )
                )
            rec = _recorder
    return rec


def _reset_for_tests():
    """Drop the cached enable verdict, the ring buffer, live spans, and the
    current thread's span stack (other threads' stacks die with them)."""
    global _enabled, _recorder, _rank, _seq
    with _state_lock:
        _enabled = None
        _recorder = None
        _rank = 0
        _seq = 0
        _live.clear()
    _tls.stack = []


def _stack():
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current_span():
    stack = _stack()
    return stack[-1] if stack else None


def current_context():
    """(trace_id, span_id) of this thread's innermost open span, or None.
    Hand it to another thread (``parent=`` on start) to keep its spans in
    the same tree — the batcher worker pattern."""
    span = current_span()
    return span.context() if span is not None else None


def _resolve_parent(parent, trace_id, root):
    """-> (trace_id, parent_id) honoring explicit parent > thread-local >
    fresh root. ``parent`` may be a Span or a (trace_id, span_id) tuple."""
    if parent is not None:
        if isinstance(parent, Span):
            return parent.trace_id, parent.span_id
        return parent[0], parent[1]
    if not root:
        implicit = current_span()
        if implicit is not None:
            return implicit.trace_id, implicit.span_id
    return trace_id or new_id(), None


# ----------------------------------------------------------------- span API
def start_span(name, attributes=None, parent=None, trace_id=None, root=False):
    """Open a span (None when tracing is disabled). ``parent`` overrides the
    thread-local context (cross-thread); ``trace_id`` seeds a new trace (the
    serving request id); ``root=True`` ignores any open span on this thread."""
    if not enabled():
        return None
    tid, parent_id = _resolve_parent(parent, trace_id, root)
    span = Span(name, tid, parent_id, attributes)
    _stack().append(span)
    with _state_lock:
        _live[span.span_id] = span
    return span


def finish_span(span, **attributes):
    """Close ``span`` (no-op on None), merging ``attributes``, and append it
    to the flight recorder."""
    if span is None:
        return
    span.dur_us = max(_now_us() - span.start_us, 0.0)
    if attributes:
        span.attributes.update(attributes)
    stack = _stack()
    if span in stack:
        stack.remove(span)
    # append under the state lock: snapshot_spans() copies the deque under
    # the same lock, and a lock-free append racing that copy would raise
    # "deque mutated during iteration" — on the abort path that would cost
    # the flight-recorder dump at exactly the moment it exists for
    recorder = _get_recorder()  # resolve BEFORE the lock (it may take it)
    global _seq
    with _state_lock:
        _live.pop(span.span_id, None)
        _seq += 1
        span.seq = _seq
        recorder.append(span)


@contextlib.contextmanager
def trace_span(name, attributes=None, parent=None, trace_id=None, root=False):
    """Context-managed span; yields the Span (or None when disabled)."""
    if not enabled():
        yield None
        return
    span = start_span(
        name, attributes=attributes, parent=parent, trace_id=trace_id, root=root
    )
    try:
        yield span
    finally:
        finish_span(span)


def record_span(name, duration_s=0.0, attributes=None, parent=None):
    """Record an already-completed span ending *now* (for event-driven
    durations: an XLA compile reported by ``jax.monitoring``, a calibrated
    collective). Parented to the current thread context unless overridden."""
    if not enabled():
        return None
    tid, parent_id = _resolve_parent(parent, None, False)
    span = Span(name, tid, parent_id, attributes)
    span.dur_us = max(float(duration_s), 0.0) * 1e6
    span.start_us = max(span.start_us - span.dur_us, 0.0)
    recorder = _get_recorder()
    global _seq
    with _state_lock:
        _seq += 1
        span.seq = _seq
        recorder.append(span)
    return span


def record_compile(duration_s):
    """An XLA backend compile as a span (fed by the ``jax.monitoring``
    listener in telemetry/cluster.py) — first-round compile becomes a
    visible tree node instead of anonymous ``build_eval`` time."""
    return record_span(
        "xla.compile", duration_s, attributes={"kind": "backend_compile"}
    )


# ------------------------------------------------------------------- export
def snapshot_spans(include_open=False):
    """Finished spans oldest-first (plus open ones, ``in_flight``-flagged,
    when asked — the abort-dump view of what was live). The deque copy runs
    under the state lock so concurrent finish/record appends from serving
    or supervisor threads can never break the abort-path dump."""
    recorder = _get_recorder()
    with _state_lock:
        spans = list(recorder)
    if include_open:
        now_us = _now_us()
        with _state_lock:
            open_spans = list(_live.values())
        for span in open_spans:
            ghost = Span(span.name, span.trace_id, span.parent_id, span.attributes)
            ghost.span_id = span.span_id
            ghost.start_us = span.start_us
            ghost.dur_us = max(now_us - span.start_us, 0.0)
            ghost.tid = span.tid
            ghost.thread_name = span.thread_name
            ghost.attributes["in_flight"] = True
            spans.append(ghost)
    return spans


def span_to_wire(span):
    """Canonical flat-dict form of a finished span: the fleet shipper's wire
    payload (telemetry/fleet.py) and the event-builder input — one
    serialization for the local export and the cross-rank merge."""
    wire = {
        "name": span.name,
        "trace_id": span.trace_id,
        "span_id": span.span_id,
        "start_us": round(span.start_us, 3),
        "dur_us": round(span.dur_us or 0.0, 3),
        "tid": span.tid,
        "thread_name": span.thread_name,
    }
    if span.parent_id:
        wire["parent_id"] = span.parent_id
    if span.attributes:
        wire["attributes"] = dict(span.attributes)
    return wire


def events_from_wire(wire_spans, pid, process_label):
    """Chrome-trace events (process/thread metadata + complete "X" events)
    for one pid lane. ``pid`` is the rank, so per-rank lanes stack in a
    single Perfetto view — both the per-rank export and the merged
    ``trace-fleet.json`` build their lanes through this one function."""
    events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": process_label},
        }
    ]
    thread_names = {}
    for wire in wire_spans:
        thread_names.setdefault(wire.get("tid", 0), wire.get("thread_name", ""))
    for tid, tname in sorted(thread_names.items(), key=lambda kv: str(kv[0])):
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": tname},
            }
        )
    for wire in wire_spans:
        args = dict(wire.get("attributes") or {})
        args["span_id"] = wire.get("span_id")
        args["trace_id"] = wire.get("trace_id")
        if wire.get("parent_id"):
            args["parent_id"] = wire["parent_id"]
        events.append(
            {
                "name": wire.get("name", "?"),
                "cat": "span",
                "ph": "X",
                "pid": pid,
                "tid": wire.get("tid", 0),
                "ts": round(float(wire.get("start_us") or 0.0), 3),
                "dur": round(float(wire.get("dur_us") or 0.0), 3),
                "args": args,
            }
        )
    return events


def chrome_trace_doc(spans=None, extra_metadata=None):
    """-> Chrome-trace JSON object (dict): ``traceEvents`` of complete
    ("X") events in microseconds plus process/thread metadata events. Rank
    is the pid (per-rank files merge cleanly in one Perfetto view)."""
    if spans is None:
        spans = snapshot_spans()
    rank = get_rank()
    events = events_from_wire(
        [span_to_wire(span) for span in spans],
        pid=rank,
        process_label="rank {} (os pid {})".format(rank, os.getpid()),
    )
    metadata = {"rank": rank, "os_pid": os.getpid(), "spans": len(spans)}
    if extra_metadata:
        metadata.update(extra_metadata)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": metadata,
    }


def _write_doc(directory, filename, doc):
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, filename)
    with open(path, "w") as f:
        json.dump(doc, f)
        f.write("\n")
    return path


def export_traces(default_dir=None, filename=None):
    """End-of-run export: write this rank's Chrome trace into
    ``SM_TRACE_EXPORT_DIR`` (falling back to ``default_dir`` — the model
    dir on training jobs) and emit one ``training.trace_export`` record.
    Returns the path, or None when tracing is disabled / no dir resolves."""
    if not enabled():
        return None
    directory = os.environ.get(TRACE_EXPORT_DIR_ENV) or default_dir
    if not directory:
        return None
    doc = chrome_trace_doc()
    path = _write_doc(
        directory, filename or "trace-rank{}.json".format(get_rank()), doc
    )
    from .emit import emit_metric

    emit_metric(
        "training.trace_export", path=path, spans=doc["otherData"]["spans"]
    )
    logger.info(
        "exported %d trace spans to %s", doc["otherData"]["spans"], path
    )
    return path


def dump_flight_recorder(default_dir=None, reason=None, exit_code=None):
    """Abort-path dump: the last-N finished spans *plus* every still-open
    span (the wedged round / collective, flagged ``in_flight``) into
    ``flight-recorder-rank<r>.json``. Never raises — the exit must happen
    even when the disk is the thing that is broken. Returns the path or
    None (disabled, or the write failed)."""
    if not enabled():
        return None
    directory = os.environ.get(TRACE_EXPORT_DIR_ENV) or default_dir or "."
    extra = {}
    if reason is not None:
        extra["abort_reason"] = reason
    if exit_code is not None:
        extra["exit_code"] = exit_code
    try:
        doc = chrome_trace_doc(
            spans=snapshot_spans(include_open=True), extra_metadata=extra
        )
        path = _write_doc(
            directory, "flight-recorder-rank{}.json".format(get_rank()), doc
        )
    except Exception as e:
        logger.error("flight-recorder dump failed (%s); continuing abort", e)
        return None
    logger.error(
        "flight recorder dumped to %s (%d spans, incl. in-flight)",
        path,
        doc["otherData"]["spans"],
    )
    return path
