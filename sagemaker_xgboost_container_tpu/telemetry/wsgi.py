"""WSGI instrumentation middleware + the ``GET /metrics`` route.

Wraps both serving apps (single-model ``make_app`` and the MME manager) so
every request records, per normalized route:

* ``serving_requests_total{route, code}`` — code collapsed to its class
  (``2xx``/``4xx``/...) to keep cardinality fixed,
* ``serving_request_seconds{route}`` — end-to-end latency histogram,
* ``serving_request_bytes{route}`` — request payload size histogram.

Routes are normalized to a closed set (``/ping``, ``/invocations``,
``/execution-parameters``, ``/metrics``, ``/models``, ``other``) — raw paths
(model names, typos, scanners) never become label values.

``/metrics`` is env-gated and OFF by default: SageMaker endpoints only
expose ``/ping`` + ``/invocations``, and an always-on introspection route
would leak operational detail on public endpoints. Set
``SM_SERVING_METRICS=true`` to serve the Prometheus exposition.
"""

import http.client
import time

from ..utils.envconfig import env_bool
from . import tracing
from .correlation import (
    REQUEST_ID_HEADER,
    clear_request_id,
    extract_request_id,
    set_request_id,
)
from .registry import REGISTRY

METRICS_ENDPOINT_ENV = "SM_SERVING_METRICS"

_KNOWN_ROUTES = ("/ping", "/invocations", "/execution-parameters", "/metrics")

# 1KB .. 8MB payload buckets (MAX_CONTENT_LENGTH default is 6MB)
_BYTE_BUCKETS = tuple(float(2 ** i) for i in range(10, 24))


def metrics_endpoint_enabled():
    return env_bool(METRICS_ENDPOINT_ENV, False)


def _route_label(path):
    if path in _KNOWN_ROUTES:
        return path
    if path.startswith("/models"):
        return "/models"
    return "other"


def _code_class(code):
    try:
        return "{}xx".format(int(code) // 100)
    except (TypeError, ValueError):
        return "5xx"


def instrument_wsgi(app, registry=None):
    """Wrap ``app`` with request metrics and the /metrics route."""
    reg = registry or REGISTRY

    # Hot path: resolve each (route, code) handle once and reuse it — the
    # label space is a closed set, so the cache is bounded and per-request
    # work is a single dict hit instead of registry RLock + key rebuild.
    # dict get/set are atomic under the GIL and get-or-create is idempotent,
    # so a racing double-insert converges on the same metric instance.
    handles = {}

    def _counter(route, code_class):
        key = ("c", route, code_class)
        metric = handles.get(key)
        if metric is None:
            metric = handles[key] = reg.counter(
                "serving_requests_total",
                help="Requests by route and status class",
                labels={"route": route, "code": code_class},
            )
        return metric

    def _latency(route):
        key = ("l", route)
        metric = handles.get(key)
        if metric is None:
            metric = handles[key] = reg.histogram(
                "serving_request_seconds",
                help="End-to-end request latency",
                labels={"route": route},
            )
        return metric

    def _payload(route):
        key = ("b", route)
        metric = handles.get(key)
        if metric is None:
            metric = handles[key] = reg.histogram(
                "serving_request_bytes",
                help="Request payload size",
                labels={"route": route},
                buckets=_BYTE_BUCKETS,
            )
        return metric

    def wrapped(environ, start_response):
        path = environ.get("PATH_INFO", "/")
        method = environ.get("REQUEST_METHOD", "GET")
        route = _route_label(path)

        if path == "/metrics" and method == "GET":
            if not metrics_endpoint_enabled():
                # indistinguishable from any other unknown route when gated
                body = b"not found"
                start_response(
                    "404 Not Found",
                    [("Content-Type", "text/plain"),
                     ("Content-Length", str(len(body)))],
                )
                return [body]
            from .cluster import refresh_runtime_gauges
            from .prometheus import exposition_response

            status, resp_headers, body = exposition_response(
                reg, refresh_runtime_gauges
            )
            start_response(status, resp_headers)
            _counter(route, "2xx").inc()
            return [body]

        captured = {}
        request_id = extract_request_id(environ)
        set_request_id(request_id)
        # with tracing armed, the request is a trace whose id IS the
        # correlation id (honored or generated — echoed back either way),
        # so the exported tree joins on the X-Request-Id the client saw;
        # batcher spans on the worker thread carry the same trace id
        tspan = None
        if tracing.enabled():
            tspan = tracing.start_span(
                "http.request",
                trace_id=request_id,
                root=True,
                attributes={"route": route, "method": method},
            )

        def recording_start_response(status, headers, exc_info=None):
            captured["status"] = status
            # echo the correlation ID so the client can quote it back;
            # replace (don't duplicate) any header the inner app set
            headers = [
                (k, v) for k, v in headers if k.lower() != REQUEST_ID_HEADER.lower()
            ] + [(REQUEST_ID_HEADER, request_id)]
            return start_response(status, headers, exc_info)

        try:
            length = int(environ.get("CONTENT_LENGTH") or 0)
        except (TypeError, ValueError):
            length = 0

        start = time.perf_counter()
        try:
            result = app(environ, recording_start_response)
        except Exception:
            _counter(route, "5xx").inc()
            raise
        finally:
            if tspan is not None:
                tracing.finish_span(
                    tspan, status=str(captured.get("status", "")).split(" ")[0]
                )
            clear_request_id()
        elapsed = time.perf_counter() - start

        status = captured.get("status", "500")
        _counter(route, _code_class(status.split(" ")[0])).inc()
        _latency(route).observe(elapsed)
        if length:
            _payload(route).observe(length)
        return result

    return wrapped


__all__ = [
    "instrument_wsgi",
    "metrics_endpoint_enabled",
    "METRICS_ENDPOINT_ENV",
]
