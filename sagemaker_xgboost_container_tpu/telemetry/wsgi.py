"""WSGI instrumentation middleware + the ``GET /metrics`` route.

Wraps both serving apps (single-model ``make_app`` and the MME manager) so
every request records, per normalized route:

* ``serving_requests_total{route, code}`` — code collapsed to its class
  (``2xx``/``4xx``/...) to keep cardinality fixed,
* ``serving_request_seconds{route}`` — end-to-end latency histogram,
* ``serving_request_bytes{route}`` — request payload size histogram.

Routes are normalized to a closed set (``/ping``, ``/invocations``,
``/execution-parameters``, ``/metrics``, ``/models``, ``other``) — raw paths
(model names, typos, scanners) never become label values.

``/metrics`` is env-gated and OFF by default: SageMaker endpoints only
expose ``/ping`` + ``/invocations``, and an always-on introspection route
would leak operational detail on public endpoints. Set
``SM_SERVING_METRICS=true`` to serve the Prometheus exposition.
"""

import http.client
import time

from ..utils.envconfig import env_bool
from . import tracing
from .correlation import (
    REQUEST_ID_HEADER,
    clear_request_id,
    extract_request_id,
    set_request_id,
)
from .registry import REGISTRY

METRICS_ENDPOINT_ENV = "SM_SERVING_METRICS"

# The serving lifecycle's in-flight latch (serving/lifecycle.py) registers
# itself here — a generic hook so telemetry never imports the serving layer.
# A request is "finished" only when its response body has been fully written
# (the WSGI server calls the result iterable's close() after the write loop),
# which is exactly what a graceful drain must wait for: a process that exits
# after the app returned but before the body flushed still truncates the
# response.
_request_tracker = None


def set_request_tracker(tracker):
    """Install/clear the in-flight tracker (``request_started()`` /
    ``request_finished()``). None disables tracking."""
    global _request_tracker
    _request_tracker = tracker


class _TrackedBody:
    """Wrap a WSGI result so ``request_finished`` fires exactly once, after
    the server has written (or abandoned) the whole response body."""

    def __init__(self, result, on_close):
        self._result = result
        self._on_close = on_close

    def __iter__(self):
        return iter(self._result)

    def close(self):
        try:
            close = getattr(self._result, "close", None)
            if close is not None:
                close()
        finally:
            self._on_close()


_KNOWN_ROUTES = ("/ping", "/invocations", "/execution-parameters", "/metrics")

# 1KB .. 8MB payload buckets (MAX_CONTENT_LENGTH default is 6MB)
_BYTE_BUCKETS = tuple(float(2 ** i) for i in range(10, 24))


def metrics_endpoint_enabled():
    return env_bool(METRICS_ENDPOINT_ENV, False)


def _route_label(path):
    if path in _KNOWN_ROUTES:
        return path
    if path.startswith("/models"):
        return "/models"
    return "other"


def _code_class(code):
    try:
        return "{}xx".format(int(code) // 100)
    except (TypeError, ValueError):
        return "5xx"


def instrument_wsgi(app, registry=None):
    """Wrap ``app`` with request metrics and the /metrics route."""
    reg = registry or REGISTRY

    # SLO plane: armed once at wrap time when SM_SLO_P95_MS is set, so BOTH
    # serving apps get the serving_slo_* series from the first scrape; per
    # request it costs one is-None test when disarmed
    from . import slo

    slo_window = slo.maybe_install(reg)

    # Hot path: resolve each (route, code) handle once and reuse it — the
    # label space is a closed set, so the cache is bounded and per-request
    # work is a single dict hit instead of registry RLock + key rebuild.
    # dict get/set are atomic under the GIL and get-or-create is idempotent,
    # so a racing double-insert converges on the same metric instance.
    handles = {}

    def _counter(route, code_class):
        key = ("c", route, code_class)
        metric = handles.get(key)
        if metric is None:
            metric = handles[key] = reg.counter(
                "serving_requests_total",
                help="Requests by route and status class",
                labels={"route": route, "code": code_class},
            )
        return metric

    def _latency(route):
        key = ("l", route)
        metric = handles.get(key)
        if metric is None:
            metric = handles[key] = reg.histogram(
                "serving_request_seconds",
                help="End-to-end request latency",
                labels={"route": route},
            )
        return metric

    def _payload(route):
        key = ("b", route)
        metric = handles.get(key)
        if metric is None:
            metric = handles[key] = reg.histogram(
                "serving_request_bytes",
                help="Request payload size",
                labels={"route": route},
                buckets=_BYTE_BUCKETS,
            )
        return metric

    def wrapped(environ, start_response):
        path = environ.get("PATH_INFO", "/")
        method = environ.get("REQUEST_METHOD", "GET")
        route = _route_label(path)

        # in-flight latch: started here, finished when the response body has
        # been fully written (result close) or the app died — the drain in
        # serving/lifecycle.py waits on exactly this count. Requests arriving
        # once the tracker stopped accepting (draining/stopped) are NOT
        # latched: they only ever get the fast 503, and counting them would
        # let sustained LB health-checks/retries hold the drain open past
        # its deadline and turn a healthy shutdown into an exit-83 abort.
        tracker = _request_tracker
        if tracker is not None and not getattr(tracker, "accepting", True):
            tracker = None
        finished = []

        def _finish():
            if tracker is not None and not finished:
                finished.append(True)
                tracker.request_finished()

        if tracker is not None:
            tracker.request_started()

        if path == "/metrics" and method == "GET":
            if not metrics_endpoint_enabled():
                # indistinguishable from any other unknown route when gated
                body = b"not found"
                start_response(
                    "404 Not Found",
                    [("Content-Type", "text/plain"),
                     ("Content-Length", str(len(body)))],
                )
                return _TrackedBody([body], _finish)
            try:
                from .cluster import refresh_runtime_gauges
                from .prometheus import exposition_response

                status, resp_headers, body = exposition_response(
                    reg, refresh_runtime_gauges
                )
                start_response(status, resp_headers)
                _counter(route, "2xx").inc()
            except Exception:
                _finish()
                raise
            return _TrackedBody([body], _finish)

        captured = {}
        request_id = extract_request_id(environ)
        set_request_id(request_id)
        # with tracing armed, the request is a trace whose id IS the
        # correlation id (honored or generated — echoed back either way),
        # so the exported tree joins on the X-Request-Id the client saw;
        # batcher spans on the worker thread carry the same trace id
        tspan = None
        if tracing.enabled():
            tspan = tracing.start_span(
                "http.request",
                trace_id=request_id,
                root=True,
                attributes={"route": route, "method": method},
            )

        def recording_start_response(status, headers, exc_info=None):
            captured["status"] = status
            # echo the correlation ID so the client can quote it back;
            # replace (don't duplicate) any header the inner app set
            headers = [
                (k, v) for k, v in headers if k.lower() != REQUEST_ID_HEADER.lower()
            ] + [(REQUEST_ID_HEADER, request_id)]
            return start_response(status, headers, exc_info)

        try:
            length = int(environ.get("CONTENT_LENGTH") or 0)
        except (TypeError, ValueError):
            length = 0

        start = time.perf_counter()
        try:
            result = app(environ, recording_start_response)
        except Exception:
            _counter(route, "5xx").inc()
            _finish()
            raise
        finally:
            if tspan is not None:
                tracing.finish_span(
                    tspan, status=str(captured.get("status", "")).split(" ")[0]
                )
            clear_request_id()
        elapsed = time.perf_counter() - start

        status = captured.get("status", "500")
        _counter(route, _code_class(status.split(" ")[0])).inc()
        _latency(route).observe(elapsed)
        if slo_window is not None and route == "/invocations":
            slo_window.observe_seconds(elapsed)
        if length:
            _payload(route).observe(length)
        return _TrackedBody(result, _finish) if tracker is not None else result

    return wrapped


__all__ = [
    "instrument_wsgi",
    "metrics_endpoint_enabled",
    "set_request_tracker",
    "METRICS_ENDPOINT_ENV",
]
