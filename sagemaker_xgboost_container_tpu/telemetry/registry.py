"""Process-wide metrics registry: counters, gauges, bounded-bucket histograms.

The reference container's only metric surface was CloudWatch regexes over
tracker log lines (SURVEY §5). This registry is the in-process source of
truth both export surfaces read from: the Prometheus text exposition
(``telemetry/prometheus.py``, served by ``GET /metrics``) and the structured
JSON stdout records (``telemetry/emit.py``, the CloudWatch metric-definition
contract).

Design constraints:

* dependency-free — no prometheus_client in the image; stdlib only.
* thread-safe — serving requests observe from WSGI worker threads while the
  batcher worker observes dispatches and a reporter thread snapshots.
* bounded memory — histograms hold fixed bucket counts (no raw samples), so
  a month of serving traffic costs the same bytes as a minute.

Metric identity is ``(name, sorted(labels))``: ``get``-or-create calls from
different sites return the same instance, so a reloaded MME model's batcher
continues its counters instead of zeroing them.
"""

import bisect
import math
import threading

# Latency-shaped default buckets, in seconds: 1ms .. 10s + the implicit +Inf.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# Size-shaped buckets (rows, bytes, requests-per-batch): powers of two.
POW2_BUCKETS = tuple(float(2 ** i) for i in range(0, 15))


def percentile(values, q):
    """Exact linear-interpolation percentile of an unsorted list (q in 0..1).

    The canonical quantile implementation for *raw-sample* collections
    (RoundTimer's per-round times, cluster round states). The histogram
    classes below interpolate inside fixed buckets instead — an estimate
    bounded by bucket resolution; for samples inside the finite bucket
    range the two agree to within one bucket width (property-tested in
    tests/test_telemetry.py).
    """
    if not values:
        return float("nan")
    if not 0.0 <= q <= 1.0:
        raise ValueError("quantile must be in [0, 1]")
    ordered = sorted(values)
    pos = (len(ordered) - 1) * q
    lo = math.floor(pos)
    hi = math.ceil(pos)
    if lo == hi:
        return ordered[lo]
    return ordered[lo] + (ordered[hi] - ordered[lo]) * (pos - lo)


def _label_key(labels):
    return tuple(sorted((labels or {}).items()))


class _Metric:
    __slots__ = ("name", "labels", "_lock")

    def __init__(self, name, labels):
        self.name = name
        self.labels = dict(labels or {})
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"
    __slots__ = ("_value",)

    def __init__(self, name, labels=None):
        super().__init__(name, labels)
        self._value = 0.0

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError("counters only go up (got %r)" % amount)
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge(_Metric):
    kind = "gauge"
    __slots__ = ("_value",)

    def __init__(self, name, labels=None):
        super().__init__(name, labels)
        self._value = 0.0

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount=1.0):
        self.inc(-amount)

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Fixed upper-bound buckets + sum/count; quantiles interpolated from the
    cumulative bucket counts (prometheus ``histogram_quantile`` semantics —
    an estimate bounded by bucket resolution, not an exact order statistic)."""

    kind = "histogram"
    __slots__ = ("bounds", "_counts", "_sum", "_count")

    def __init__(self, name, labels=None, buckets=None):
        super().__init__(name, labels)
        bounds = tuple(sorted(set(float(b) for b in (buckets or DEFAULT_BUCKETS))))
        if not bounds:
            raise ValueError("histogram needs at least one finite bucket bound")
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last slot is +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value):
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def snapshot(self):
        """-> (cumulative_bucket_counts aligned to bounds + [+Inf], sum, count)."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cumulative = []
        running = 0
        for c in counts:
            running += c
            cumulative.append(running)
        return cumulative, s, total

    def quantile(self, q):
        """Estimate the q-quantile (0..1) by linear interpolation inside the
        bucket containing it; observations beyond the last finite bound clamp
        to that bound."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile must be in [0, 1]")
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return float("nan")
        target = q * total
        cum = 0.0
        lower = 0.0
        for bound, cnt in zip(self.bounds, counts):
            if cnt and cum + cnt >= target:
                return lower + (bound - lower) * ((target - cum) / cnt)
            cum += cnt
            lower = bound
        return self.bounds[-1]


class MetricsRegistry:
    """Thread-safe get-or-create registry; the process-wide instance is
    ``telemetry.REGISTRY``. Tests build private registries for isolation."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self):
        self._lock = threading.RLock()
        self._families = {}  # name -> (kind, help)
        self._metrics = {}  # (name, label_key) -> metric

    def _get_or_create(self, kind, name, help_text, labels, **kwargs):
        key = (name, _label_key(labels))
        with self._lock:
            family = self._families.get(name)
            if family is not None and family[0] != kind:
                raise ValueError(
                    "metric {!r} already registered as {} (requested {})".format(
                        name, family[0], kind
                    )
                )
            metric = self._metrics.get(key)
            if metric is None:
                metric = self._KINDS[kind](name, labels=labels, **kwargs)
                self._metrics[key] = metric
                if family is None:
                    self._families[name] = (kind, help_text or "")
            return metric

    def counter(self, name, help="", labels=None):
        return self._get_or_create("counter", name, help, labels)

    def gauge(self, name, help="", labels=None):
        return self._get_or_create("gauge", name, help, labels)

    def histogram(self, name, help="", labels=None, buckets=None):
        return self._get_or_create("histogram", name, help, labels, buckets=buckets)

    def collect(self):
        """-> [(name, kind, help, [metric, ...])] sorted by name; each family's
        series sorted by label key (stable exposition output)."""
        with self._lock:
            families = dict(self._families)
            by_name = {}
            for (name, lk), metric in self._metrics.items():
                by_name.setdefault(name, []).append((lk, metric))
        out = []
        for name in sorted(by_name):
            kind, help_text = families[name]
            series = [m for _lk, m in sorted(by_name[name], key=lambda p: p[0])]
            out.append((name, kind, help_text, series))
        return out

    def remove_matching(self, label_name, label_value):
        """Drop every series whose labels carry ``label_name == label_value``.

        Lifecycle hook for label values that come and go (MME model names):
        without it, model churn on a long-lived endpoint grows the registry —
        and the /metrics exposition and snapshot records — without bound.
        Returns the number of series removed.
        """
        with self._lock:
            doomed = [
                key
                for key, metric in self._metrics.items()
                if metric.labels.get(label_name) == label_value
            ]
            for key in doomed:
                del self._metrics[key]
            return len(doomed)

    def reset(self):
        """Drop every metric (test isolation only — never during serving)."""
        with self._lock:
            self._families.clear()
            self._metrics.clear()


REGISTRY = MetricsRegistry()
