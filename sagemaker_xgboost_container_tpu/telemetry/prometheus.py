"""Prometheus text exposition (format version 0.0.4) over a MetricsRegistry.

Rendered on demand by ``GET /metrics`` (serving/app.py middleware). The
format is the de-facto scrape contract: ``# HELP``/``# TYPE`` headers, one
``name{labels} value`` line per series, histograms expanded to cumulative
``_bucket{le=...}`` series plus ``_sum``/``_count``.
"""

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(text):
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(value):
    return (
        str(value).replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')
    )


def _format_value(value):
    if value != value:  # NaN
        return "NaN"
    if value in (float("inf"), float("-inf")):
        return "+Inf" if value > 0 else "-Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _label_str(labels, extra=None):
    items = dict(labels or {})
    if extra:
        items.update(extra)
    if not items:
        return ""
    inner = ",".join(
        '{}="{}"'.format(k, _escape_label_value(v)) for k, v in sorted(items.items())
    )
    return "{" + inner + "}"


def exposition_response(registry, refresh=None):
    """-> ``(status, headers, body_bytes)`` for a ``GET /metrics`` response.

    The one scrape path shared by the serving middleware (telemetry/wsgi.py)
    and the cluster plane's standalone server (telemetry/cluster.py), so
    exposition behavior cannot diverge between the two surfaces. ``refresh``
    (if given) runs first — sampled gauges (RSS, device bytes) update at
    scrape time, event-driven ones are already current.
    """
    if refresh is not None:
        refresh(registry)
    body = render_text(registry).encode("utf-8")
    return (
        "200 OK",
        [("Content-Type", CONTENT_TYPE), ("Content-Length", str(len(body)))],
        body,
    )


def render_text(registry):
    """Render every family in ``registry`` to the exposition text."""
    lines = []
    for name, kind, help_text, series in registry.collect():
        if help_text:
            lines.append("# HELP {} {}".format(name, _escape_help(help_text)))
        lines.append("# TYPE {} {}".format(name, kind))
        for metric in series:
            if kind == "histogram":
                cumulative, total_sum, total_count = metric.snapshot()
                bounds = list(metric.bounds) + [float("inf")]
                for bound, cum in zip(bounds, cumulative):
                    lines.append(
                        "{}_bucket{} {}".format(
                            name,
                            _label_str(metric.labels, {"le": _format_value(bound)}),
                            cum,
                        )
                    )
                lines.append(
                    "{}_sum{} {}".format(
                        name, _label_str(metric.labels), _format_value(total_sum)
                    )
                )
                lines.append(
                    "{}_count{} {}".format(
                        name, _label_str(metric.labels), total_count
                    )
                )
            else:
                lines.append(
                    "{}{} {}".format(
                        name, _label_str(metric.labels), _format_value(metric.value)
                    )
                )
    return "\n".join(lines) + "\n"
