"""Model-quality plane (``SM_MODEL_TELEMETRY``): what the booster is
*learning*, whether the numbers are healthy, and whether serving traffic is
still the training distribution.

PRs 7/13/16 instrumented the *systems* around the training loop (traces,
fleet skew, device roofline/HBM); the learned model itself stayed a black
box readable only through stdout metric lines. This module opens it with
four connected pieces, all env-gated like the device plane (zero records,
zero gauges, zero threads when ``SM_MODEL_TELEMETRY`` is unset — and the
stats are read-only reductions, so committed trees are bit-identical
either way):

* **Per-round learning statistics** — the booster's fused K-round scan
  returns one extra small vector per round (grad/hess sums and min/max,
  NaN/Inf counts in gradients and margins; layout owned here by
  ``DEVICE_STAT_FIELDS``). The host folds in committed-tree statistics
  (leaf-value/split-gain distributions, depth, leaf counts) and calls
  :func:`note_learning`: one ``training.learning`` record + gauges per
  round, and a bounded history ring for forensics.
* **Numeric-health guard** — a nonzero NaN/Inf count names the first
  poisoned round; the booster dumps :func:`dump_learning_forensics`
  (``learning-forensics-rank<r>.json``, the last-K stats history) and
  aborts every rank with exit 87 (``EXIT_NUMERIC_POISON``) — rounds
  earlier and far more legibly than the cross-rank digest's exit 81.
  Like the OOM forensics, the dump itself is robustness, not telemetry:
  it runs whenever the guard trips.
* **Live learning curve** — ``EvaluationMonitor`` feeds every printed
  eval entry through :func:`note_eval`; :func:`learning_status` renders a
  ``learning`` section for the rank-0 ``/status`` endpoint (best
  iteration, train/val gap trend as an overfit early-warning), and
  :func:`learning_summary` is stamped into the model manifest.
* **Serving drift monitor** — training captures per-feature bin-occupancy
  baselines from the already-binned matrix (:func:`baseline_from_binned`,
  stamped into the manifest); serving accumulates a rolling window of
  per-feature bin and prediction histograms (:class:`DriftWindow`),
  computes PSI (population stability index) against the baseline,
  publishes the ``model_drift_psi`` gauge + ``serving.drift`` records +
  a ``/status`` drift section, and quacks like a circuit breaker
  (``.degraded``) so the serving lifecycle folds sustained drift above
  ``SM_DRIFT_PSI_MAX`` into its derived DEGRADED state exactly like the
  SLO burn — recovery is automatic when the shifted traffic ages out of
  the ``SM_DRIFT_WINDOW_S`` window.
"""

import collections
import json
import logging
import math
import os
import threading
import time

import numpy as np

from ..constants import XGB_MAXIMIZE_METRICS
from ..utils.envconfig import env_bool, env_float, env_int
from .emit import emit_metric
from .registry import REGISTRY

logger = logging.getLogger(__name__)

#: master gate: unset ⇒ no records, no gauges, no drift window
MODEL_TELEMETRY_ENV = "SM_MODEL_TELEMETRY"
#: sustained per-feature PSI above this flips the drift window degraded
DRIFT_PSI_MAX_ENV = "SM_DRIFT_PSI_MAX"
#: rolling drift-window length in seconds
DRIFT_WINDOW_ENV = "SM_DRIFT_WINDOW_S"
#: rows the window must hold before it may degrade (cold-start guard)
DRIFT_MIN_ROWS_ENV = "SM_DRIFT_MIN_ROWS"

#: the industry-standard "significant shift" PSI threshold
DEFAULT_DRIFT_PSI_MAX = 0.2
DEFAULT_DRIFT_WINDOW_S = 300.0
#: sized so sampling noise can't reach the PSI threshold: with ~PSI_GROUPS
#: comparison groups, E[PSI] of in-distribution traffic ≈ (groups-1)/rows
DEFAULT_DRIFT_MIN_ROWS = 200

#: PSI comparison resolution: baseline bins are folded into this many
#: groups of roughly equal training mass (the standard ~decile PSI layout).
#: At full max_bin resolution a small window has near-empty bins whose eps
#: floors dominate the sum — deciles keep the statistic about the
#: distribution, not the sample size.
PSI_GROUPS = 10

#: rounds of stats kept for the forensics dump and /status
HISTORY_LEN = 64

#: prediction-histogram resolution (window-local edges, first batch sets them)
PRED_BINS = 10

#: layout of the per-round stats vector the booster computes on device —
#: the scan emits exactly this, in this order, as float32; the host decodes
#: by zipping. Counts ride as floats (an f32 exactly holds counts < 2^24).
DEVICE_STAT_FIELDS = (
    "grad_sum",
    "grad_min",
    "grad_max",
    "hess_sum",
    "hess_min",
    "hess_max",
    "grad_nonfinite",
    "margin_nonfinite",
)

_state_lock = threading.Lock()
_history = collections.deque(maxlen=HISTORY_LEN)  # per-round stats dicts
_last_stats = None
_eval_curve = collections.OrderedDict()  # (data, metric) -> [(round, value)]
_drift_baseline = None  # captured at training, stamped into the manifest


def enabled():
    return env_bool(MODEL_TELEMETRY_ENV, False)


def drift_psi_max():
    return env_float(DRIFT_PSI_MAX_ENV, DEFAULT_DRIFT_PSI_MAX, minimum=0.0)


def drift_window_s():
    return env_float(DRIFT_WINDOW_ENV, DEFAULT_DRIFT_WINDOW_S, minimum=1.0)


def drift_min_rows():
    return env_int(DRIFT_MIN_ROWS_ENV, DEFAULT_DRIFT_MIN_ROWS, minimum=1)


# --------------------------------------------------------- learning statistics
def decode_device_stats(vector):
    """One round's device stats vector -> field dict (zip with the layout)."""
    values = [float(v) for v in np.asarray(vector).reshape(-1)[: len(DEVICE_STAT_FIELDS)]]
    return dict(zip(DEVICE_STAT_FIELDS, values))


def tree_stats(trees):
    """Committed-tree statistics from one round's compact ``Tree`` objects
    (``models/forest.py``) — leaf-value/split-gain distributions, depth and
    leaf counts, summed across the round's trees. Never raises; unexpected
    shapes degrade to zeros."""
    out = {
        "trees": 0,
        "leaves": 0,
        "max_depth": 0,
        "leaf_value_min": 0.0,
        "leaf_value_max": 0.0,
        "leaf_value_absmax": 0.0,
        "split_gain_sum": 0.0,
        "split_gain_max": 0.0,
    }
    leaf_values = []
    gains = []
    try:
        for tree in trees:
            out["trees"] += 1
            leaf_mask = np.asarray(tree.is_leaf, dtype=bool)
            values = np.asarray(tree.value, dtype=np.float64)
            if values.size:
                leaf_values.append(values[leaf_mask[: values.size]])
            gain = np.asarray(tree.gain, dtype=np.float64)
            if gain.size:
                gains.append(gain[~leaf_mask[: gain.size]])
            out["leaves"] += int(leaf_mask.sum())
            out["max_depth"] = max(out["max_depth"], int(tree.depth()))
        if leaf_values:
            lv = np.concatenate(leaf_values) if len(leaf_values) > 1 else leaf_values[0]
            if lv.size:
                out["leaf_value_min"] = float(lv.min())
                out["leaf_value_max"] = float(lv.max())
                out["leaf_value_absmax"] = float(np.abs(lv).max())
        if gains:
            g = np.concatenate(gains) if len(gains) > 1 else gains[0]
            if g.size:
                out["split_gain_sum"] = float(g.sum())
                out["split_gain_max"] = float(g.max())
    except Exception as e:
        logger.debug("tree stats unavailable: %s", e)
    return out


def note_learning(round_index, stats, registry=None):
    """Fold one round's learning statistics into the plane: emit the
    ``training.learning`` record, set the gauges, append the history ring.
    The caller gates on :func:`enabled` — this function assumes the plane
    is armed. Returns the record."""
    record = {"round": int(round_index)}
    record.update({k: (round(v, 6) if isinstance(v, float) else v) for k, v in stats.items()})
    global _last_stats
    with _state_lock:
        _last_stats = record
        _history.append(record)
    reg = registry or REGISTRY
    reg.gauge(
        "model_grad_nonfinite",
        "NaN/Inf gradient entries observed in the last boosting round",
    ).set(record.get("grad_nonfinite", 0.0))
    reg.gauge(
        "model_leaf_value_absmax",
        "Largest |leaf value| committed in the last boosting round",
    ).set(record.get("leaf_value_absmax", 0.0))
    reg.gauge(
        "model_split_gain_max",
        "Largest split gain committed in the last boosting round",
    ).set(record.get("split_gain_max", 0.0))
    emit_metric("training.learning", **record)
    return record


def last_learning():
    with _state_lock:
        return dict(_last_stats) if _last_stats is not None else None


def learning_history():
    with _state_lock:
        return [dict(r) for r in _history]


def first_poisoned_round(stats_rows, first_round):
    """Scan decoded per-round stat dicts for the first round whose NaN/Inf
    counters are nonzero (or whose reductions themselves went nonfinite) —
    the numeric-health guard's trigger. Returns the absolute round index or
    None."""
    for offset, stats in enumerate(stats_rows):
        nonfinite = stats.get("grad_nonfinite", 0.0) + stats.get("margin_nonfinite", 0.0)
        reductions_bad = any(
            not math.isfinite(stats.get(field, 0.0))
            for field in ("grad_sum", "hess_sum", "grad_min", "grad_max")
        )
        if nonfinite > 0 or reductions_bad:
            return int(first_round) + offset
    return None


# --------------------------------------------------------------- eval curve
def _is_maximize(metric_name):
    base = metric_name.split("@", 1)[0]
    return base in XGB_MAXIMIZE_METRICS


def note_eval(round_index, data_name, metric_name, value):
    """One printed eval entry folded into the learning curve (called by
    EvaluationMonitor, gated on :func:`enabled` there). Keeps the full
    series per (dataset, metric) and refreshes the best-iteration gauge."""
    with _state_lock:
        series = _eval_curve.setdefault((data_name, metric_name), [])
        series.append((int(round_index), float(value)))
    summary = learning_summary()
    if summary and summary.get("best_iteration") is not None:
        REGISTRY.gauge(
            "model_best_iteration",
            "Round with the best score on the last eval dataset/metric",
        ).set(summary["best_iteration"])


def learning_summary():
    """The learning-curve summary for the manifest stamp and ``/status``:
    best iteration/score on the last (dataset, metric) pair (XGBoost
    semantics), final values for every pair, and the train/val gap trend
    of the last shared metric (a rising gap is the overfit early-warning).
    None when no eval entries have been folded."""
    with _state_lock:
        if not _eval_curve:
            return None
        curve = {k: list(v) for k, v in _eval_curve.items()}
    (last_data, last_metric), last_series = list(curve.items())[-1]
    maximize = _is_maximize(last_metric)
    best_round, best_value = last_series[0]
    for rnd, val in last_series:
        if (val > best_value) if maximize else (val < best_value):
            best_round, best_value = rnd, val
    summary = {
        "rounds": len(last_series),
        "dataset": last_data,
        "metric": last_metric,
        "best_iteration": best_round,
        "best_score": round(best_value, 6),
        "final": {
            "{}-{}".format(d, m): round(series[-1][1], 6)
            for (d, m), series in curve.items()
        },
    }
    datasets = {d for d, _m in curve}
    if len(datasets) > 1:
        # train/val gap on the last metric present under two datasets
        pair = [
            (d, curve[(d, last_metric)])
            for d in datasets
            if (d, last_metric) in curve
        ]
        if len(pair) >= 2:
            pair.sort(key=lambda item: item[0] != "train")  # train first
            train_series = dict(pair[0][1])
            val_series = dict(pair[1][1])
            gaps = [
                abs(val_series[r] - train_series[r])
                for r in sorted(set(train_series) & set(val_series))
            ]
            if gaps:
                summary["gap_last"] = round(gaps[-1], 6)
                window = gaps[-5:]
                summary["gap_trend"] = round(window[-1] - window[0], 6)
    return summary


def learning_status():
    """The ``learning`` section for ``/status`` and the SIGQUIT dump: the
    last per-round stats plus the curve summary. None when the plane is
    unarmed or nothing has been folded yet."""
    if not enabled():
        return None
    doc = {}
    last = last_learning()
    if last is not None:
        doc["last_round"] = last
    summary = learning_summary()
    if summary is not None:
        doc["curve"] = summary
    return doc or None


# ------------------------------------------------------------- drift baseline
def bin_features(features, cuts_per_feature):
    """Bin a raw (rows, features) float array against per-feature cut
    points, mirroring the training-side binner exactly: bin b holds values
    v where ``v < cut[i]`` iff ``b <= i`` — i.e. ``searchsorted(cuts, v,
    side="right")``. Non-finite entries (missing values) land in the final
    missing bucket. Returns per-feature count arrays of length
    ``len(cuts) + 2`` (real bins ``0..len(cuts)`` plus missing)."""
    matrix = np.asarray(features, dtype=np.float64)
    if matrix.ndim == 1:
        matrix = matrix.reshape(1, -1)
    counts = []
    for j, cuts in enumerate(cuts_per_feature):
        edges = np.asarray(cuts, dtype=np.float64)
        vec = np.zeros(edges.size + 2, dtype=np.int64)
        if j < matrix.shape[1]:
            col = matrix[:, j]
            finite_mask = np.isfinite(col)
            bins = np.searchsorted(edges, col[finite_mask], side="right")
            vec[: edges.size + 1] = np.bincount(bins, minlength=edges.size + 1)
            vec[-1] = int((~finite_mask).sum())
        counts.append(vec)
    return counts


def baseline_from_binned(binned):
    """Per-feature bin-occupancy baseline from the training ``BinnedMatrix``
    — the binned representation makes this one ``bincount`` per feature.
    Missing values (the shared bin at index ``max_bin``) fold into a final
    missing bucket so the layout matches :func:`bin_features` (length
    ``len(cuts) + 2``). Returns the manifest-shaped dict — cut points
    travel with the fractions so serving can bin raw request features the
    same way."""
    bins = np.asarray(binned.bins)
    rows = int(bins.shape[0])
    missing_bin = int(binned.max_bin)
    features = []
    for j in range(bins.shape[1]):
        cuts = [float(c) for c in np.asarray(binned.cut_points[j]).reshape(-1)]
        full = np.bincount(bins[:, j].astype(np.int64), minlength=missing_bin + 1)
        vec = np.zeros(len(cuts) + 2, dtype=np.int64)
        real = min(len(cuts) + 1, full.size)
        vec[:real] = full[:real]
        if full.size > missing_bin:
            vec[-1] = int(full[missing_bin])
        total = max(int(vec.sum()), 1)
        features.append(
            {
                "cuts": cuts,
                "fracs": [round(float(c) / total, 6) for c in vec],
            }
        )
    return {"version": 1, "rows": rows, "features": features}


def capture_drift_baseline(binned):
    """Capture the training-distribution baseline (called by the booster
    session when the plane is armed); :func:`drift_baseline` hands it to
    the manifest writer at model-save time. Never raises."""
    global _drift_baseline
    try:
        baseline = baseline_from_binned(binned)
    except Exception as e:
        logger.warning("drift baseline capture failed: %s", e)
        return None
    with _state_lock:
        _drift_baseline = baseline
    return baseline


def drift_baseline():
    with _state_lock:
        return _drift_baseline


def psi_groups(expected, max_groups=PSI_GROUPS):
    """Map fine histogram bins onto at most ``max_groups`` contiguous
    groups of roughly equal expected mass — PSI's standard decile layout.
    The manifest keeps full max_bin resolution; only the comparison is
    coarsened. Returns an int group index per bin."""
    expected = np.asarray(expected, dtype=np.float64)
    groups = np.zeros(expected.size, dtype=np.int64)
    target = 1.0 / max_groups
    acc, g = 0.0, 0
    for i, frac in enumerate(expected):
        groups[i] = g
        acc += float(frac)
        if acc >= target and g < max_groups - 1 and i < expected.size - 1:
            acc, g = 0.0, g + 1
    return groups


def psi(expected_fracs, actual_counts, eps=1e-4):
    """Population stability index of an observed bin-count vector against
    baseline fractions: ``sum((a - e) * ln(a / e))`` with both sides
    floored at ``eps`` so empty bins don't blow up the sum."""
    expected = np.maximum(np.asarray(expected_fracs, dtype=np.float64), eps)
    counts = np.asarray(actual_counts, dtype=np.float64)
    total = counts.sum()
    if total <= 0:
        return 0.0
    actual = np.maximum(counts / total, eps)
    n = min(expected.size, actual.size)
    e, a = expected[:n], actual[:n]
    return float(np.sum((a - e) * np.log(a / e)))


class DriftWindow:
    """Rolling feature/prediction-distribution window vs the training
    baseline, shaped like the SLO window: ``observe`` accumulates, reads
    trim expired batches (automatic recovery), ``.degraded`` is the
    breaker-shaped hook the serving lifecycle folds into its derived
    state. ``clock`` is injectable so drills need not sleep."""

    def __init__(self, baseline, psi_max=None, window_s=None, min_rows=None,
                 registry=None, clock=None):
        self.baseline = baseline
        self.psi_max = float(psi_max if psi_max is not None else drift_psi_max())
        self.window_s = float(window_s if window_s is not None else drift_window_s())
        self.min_rows = int(min_rows if min_rows is not None else drift_min_rows())
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._batches = collections.deque()  # (t, rows, counts list, pred hist)
        self._expected = [
            np.asarray(f["fracs"], dtype=np.float64) for f in baseline["features"]
        ]
        self._cuts = [f["cuts"] for f in baseline["features"]]
        self._totals = [np.zeros(e.size, dtype=np.int64) for e in self._expected]
        # PSI is compared on decile-style groups, not raw max_bin bins: a
        # small window leaves fine bins empty and their eps floors would
        # dominate the sum (sample-size artifact, not drift)
        self._groups = [psi_groups(e) for e in self._expected]
        self._rows = 0
        self._pred_edges = None
        self._pred_total = np.zeros(PRED_BINS, dtype=np.int64)
        self._degraded = False
        reg = registry or REGISTRY
        # created (at zero) on install so the series exists from the first
        # scrape, not the first drifted window
        self._m_psi = reg.gauge(
            "model_drift_psi",
            "Worst per-feature PSI of the serving window vs the training baseline",
        )
        self._m_psi.set(0.0)

    # ------------------------------------------------------------- feed path
    def observe(self, features, predictions=None):
        """Fold one request's raw feature matrix (and optionally its
        predictions) into the window; refresh the PSI gauge and emit a
        ``serving.drift`` record on every degraded/recovered transition."""
        matrix = np.asarray(features)
        rows = int(matrix.shape[0]) if matrix.ndim >= 2 else 1
        counts = bin_features(matrix, self._cuts)
        pred_hist = None
        if predictions is not None:
            pred_hist = self._pred_histogram(predictions)
        now = self._clock()
        with self._lock:
            self._batches.append((now, rows, counts, pred_hist))
            for total, c in zip(self._totals, counts):
                total += c[: total.size]
            self._rows += rows
            if pred_hist is not None:
                self._pred_total += pred_hist
            self._trim_locked(now)
            worst, worst_feature, _ = self._psi_locked()
            degraded = self._rows >= self.min_rows and worst > self.psi_max
            transition = degraded != self._degraded
            self._degraded = degraded
            rows_now = self._rows
        self._m_psi.set(round(worst, 4))
        if transition:
            emit_metric(
                "serving.drift",
                drifted=degraded,
                psi=round(worst, 4),
                psi_max=self.psi_max,
                feature=worst_feature,
                rows=rows_now,
                window_s=self.window_s,
            )
        return worst

    def _pred_histogram(self, predictions):
        preds = np.asarray(predictions, dtype=np.float64).reshape(-1)
        preds = preds[np.isfinite(preds)]
        if preds.size == 0:
            return None
        if self._pred_edges is None:
            lo, hi = float(preds.min()), float(preds.max())
            if 0.0 <= lo and hi <= 1.0:
                lo, hi = 0.0, 1.0  # probability outputs: stable edges
            elif hi <= lo:
                hi = lo + 1.0
            self._pred_edges = np.linspace(lo, hi, PRED_BINS + 1)
        hist, _ = np.histogram(preds, bins=self._pred_edges)
        return hist.astype(np.int64)

    def _trim_locked(self, now):
        cutoff = now - self.window_s
        while self._batches and self._batches[0][0] < cutoff:
            _t, rows, counts, pred_hist = self._batches.popleft()
            for total, c in zip(self._totals, counts):
                total -= c[: total.size]
            self._rows -= rows
            if pred_hist is not None:
                self._pred_total -= pred_hist

    def _psi_locked(self):
        worst, worst_feature = 0.0, -1
        per_feature = []
        for j, (expected, counts, groups) in enumerate(
            zip(self._expected, self._totals, self._groups)
        ):
            n_groups = int(groups[-1]) + 1 if groups.size else 1
            e_grouped = np.bincount(groups, weights=expected, minlength=n_groups)
            a_grouped = np.bincount(
                groups, weights=counts.astype(np.float64), minlength=n_groups
            )
            value = psi(e_grouped, a_grouped)
            per_feature.append(value)
            if value > worst:
                worst, worst_feature = value, j
        return worst, worst_feature, per_feature

    # ------------------------------------------------------------ read paths
    @property
    def degraded(self):
        """Breaker-shaped hook for the serving lifecycle: True while the
        window holds enough rows and the worst per-feature PSI exceeds
        ``SM_DRIFT_PSI_MAX``. Trims first, so recovery is automatic once
        the shifted traffic ages out."""
        with self._lock:
            self._trim_locked(self._clock())
            worst, _, _ = self._psi_locked()
            return self._rows >= self.min_rows and worst > self.psi_max

    def snapshot(self):
        """-> the ``drift`` section for ``/status``: threshold, window,
        rows, worst/per-feature PSI, prediction histogram, degraded."""
        with self._lock:
            self._trim_locked(self._clock())
            worst, worst_feature, per_feature_raw = self._psi_locked()
            per_feature = [round(v, 4) for v in per_feature_raw]
            rows = self._rows
            pred = None
            total = int(self._pred_total.sum())
            if self._pred_edges is not None and total > 0:
                pred = {
                    "edges": [round(float(e), 6) for e in self._pred_edges],
                    "fracs": [
                        round(float(c) / total, 4) for c in self._pred_total
                    ],
                }
        doc = {
            "psi_max": self.psi_max,
            "window_s": self.window_s,
            "rows": rows,
            "psi": round(worst, 4),
            "worst_feature": worst_feature,
            "per_feature_psi": per_feature,
            "degraded": rows >= self.min_rows and worst > self.psi_max,
        }
        if pred is not None:
            doc["prediction"] = pred
        return doc


# ------------------------------------------------------------- process plane
_drift_lock = threading.Lock()
_drift = None


def maybe_install_drift(baseline, registry=None):
    """Arm the process-wide drift window from a manifest baseline when the
    plane is enabled. Called by serve_utils at model-load time; idempotent
    (the first loaded baseline wins — MME models share one window per
    process, like the SLO window). Returns the window or None."""
    global _drift
    if _drift is not None:
        return _drift
    if not enabled() or not baseline or not baseline.get("features"):
        return None
    with _drift_lock:
        if _drift is None:
            _drift = DriftWindow(baseline, registry=registry)
            logger.info(
                "serving drift monitor armed: %d features, PSI max %.3f over a %.0fs window",
                len(baseline["features"]),
                _drift.psi_max,
                _drift.window_s,
            )
    return _drift


def active_drift():
    """The installed drift window, or None when the plane is disarmed."""
    return _drift


def drift_status():
    """The ``drift`` section for ``/status`` (None when disarmed)."""
    window = _drift
    return window.snapshot() if window is not None else None


# ------------------------------------------------------- learning forensics
def dump_learning_forensics(reason, first_bad_round=None, default_dir=None):
    """Write ``learning-forensics-rank<r>.json`` when the numeric-health
    guard trips: the last-K per-round stats history, the first poisoned
    round, and the eval curve so far. Robustness path — runs regardless of
    ``SM_MODEL_TELEMETRY`` once the guard has stats in hand (a poisoned
    job's last act should always name the round that went bad). Never
    raises; returns the path or None."""
    try:
        from . import tracing
        from .device import _forensics_dir

        rank = tracing.get_rank()
        doc = {
            "reason": reason,
            "rank": rank,
            "stats_history": learning_history(),
        }
        if first_bad_round is not None:
            doc["first_bad_round"] = int(first_bad_round)
        summary = learning_summary()
        if summary is not None:
            doc["curve"] = summary
        directory = _forensics_dir(default_dir)
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, "learning-forensics-rank{}.json".format(rank))
        with open(path, "w") as f:
            json.dump(doc, f, default=str)
            f.write("\n")
        logger.error(
            "numeric poison: learning forensics (last %d rounds of stats) dumped to %s",
            len(doc["stats_history"]), path,
        )
        return path
    except Exception:
        logger.exception("learning forensics dump failed; aborting anyway")
        return None


def _reset_for_tests():
    global _last_stats, _drift_baseline, _drift
    with _state_lock:
        _last_stats = None
        _history.clear()
        _eval_curve.clear()
        _drift_baseline = None
    with _drift_lock:
        _drift = None
