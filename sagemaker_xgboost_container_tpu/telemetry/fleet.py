"""Fleet observability plane: cross-rank trace aggregation, span-level
straggler attribution, and the rank-0 live status endpoint.

PR 7 gave every rank a flight recorder and a per-rank Chrome-trace export;
PR 2 gave rank 0 a coarse heartbeat straggler verdict ("host X's round p50
is 3x the median"). Nobody could see the *fleet*: answering "which rank made
round N slow, and in which phase" meant collecting ``trace-rank<r>.json``
files by hand and eyeballing them side by side. This module closes that gap
with three connected pieces, all riding infrastructure earlier PRs built:

* **Span shipping** (``SM_FLEET_TRACE``) — every rank runs a
  :class:`SpanShipper` daemon (the PR-2 heartbeat pattern: ``Event.wait``
  loop, bounded connect/send timeouts, backoff, warn-once per outage) that
  drains newly finished spans from the tracing flight recorder and ships
  them as framed JSON (``parallel/distributed.py`` framing) to rank 0's
  :class:`FleetCollector`. Unset ⇒ zero threads, zero sockets, zero spans
  shipped.
* **Merged trace + skew fold** — the collector keeps a bounded per-rank
  span buffer and writes one ``trace-fleet.json`` with pid=rank lanes next
  to the per-rank exports (one Perfetto load shows every rank's round N
  stacked). As round root spans arrive it folds each round's per-rank
  ``host_dispatch`` / ``device_sync`` / ``collective.dispatch`` durations
  into a per-round skew report: the ``round_skew_ms`` gauge and a
  ``training.skew`` record naming the critical rank AND the phase that
  made it critical (host vs device vs collective vs wire).
* **Live introspection** (``SM_STATUS_PORT``) — a rank-0 HTTP endpoint
  (the ``SM_CLUSTER_METRICS`` wsgiref plumbing) serving ``/status`` (round
  progress + ETA, rolling attribution, recent skew, membership log, last
  checkpoint, backend init error, serving SLO) and ``/debug/flight`` (the
  live span snapshot — the flight recorder without the abort). The SIGQUIT
  handler (:func:`install_sigquit_handler`) dumps the same view to disk on
  ``kill -3`` without killing the job.

Timestamp caveat: span clocks are perf_counter-relative *per process*
(telemetry/tracing.py ``_T0``), so lanes in the merged trace are each
internally consistent but not aligned to a shared epoch across ranks — read
within-lane structure and cross-lane *durations*, not cross-lane offsets.
The skew fold compares durations only, so it is immune.
"""

import collections
import json
import logging
import os
import signal
import socket
import threading
import time

from ..parallel.distributed import frame_message, recv_message_bounded
from ..utils.envconfig import env_bool, env_float, env_int, env_port
from . import tracing
from .cluster import ROUND_STATE
from .emit import emit_metric
from .registry import REGISTRY, percentile

logger = logging.getLogger(__name__)

FLEET_TRACE_ENV = "SM_FLEET_TRACE"
FLEET_TRACE_PORT_ENV = "SM_FLEET_TRACE_PORT"
FLEET_FLUSH_ENV = "SM_FLEET_FLUSH_S"
STATUS_PORT_ENV = "SM_STATUS_PORT"

# next rung on the control-plane port ladder: 9099 rendezvous, 9199
# heartbeat, 9299 abort, 9399 consensus, 9499 reform, 9599 ingest
DEFAULT_FLEET_PORT = 9699
DEFAULT_FLUSH_S = 2.0
FLEET_VERSION = 1

# span batches are bigger than heartbeats (hundreds of spans per flush on a
# busy rank) but still bounded: cap the frame well below anything that
# could stall the collector, and chunk batches to stay under it
_MAX_FLEET_FRAME_BYTES = 8 << 20
_BATCH_SPANS = 512

# shipper-side retry queue bound: an unreachable collector must cost
# bounded memory, never an OOM (oldest spans drop first, counted)
_MAX_PENDING_SPANS = 8192

# per-rank collector buffer and skew-report history bounds
_SKEW_HISTORY = 64
_MAX_OPEN_ROUNDS = 128

_MAX_BACKOFF_S = 60.0

#: a rank holding > this factor x the median live HBM is memory-skewed
_MEMORY_SKEW_FACTOR = 1.5

#: /debug/profile bounds: capture length cap and the busy lock (one capture
#: at a time — jax.profiler sessions are process-global)
_PROFILE_MAX_MS = 10000
_profile_lock = threading.Lock()
_profile_seq = [0]

_HTTP_STATUS = {
    200: "200 OK",
    400: "400 Bad Request",
    404: "404 Not Found",
    409: "409 Conflict",
    500: "500 Internal Server Error",
}

#: child-span name -> attribution component (the round root's remainder is
#: "wire": time the critical rank spent that no instrumented phase explains)
_PHASE_SPANS = {
    "host_dispatch": "host",
    "device_sync": "device",
    "collective.dispatch": "collective",
}
_COMPONENTS = ("host", "device", "collective")


def fleet_enabled():
    return env_bool(FLEET_TRACE_ENV, False)


def fleet_flush_interval():
    return env_float(FLEET_FLUSH_ENV, DEFAULT_FLUSH_S, minimum=0.1, maximum=60.0)


def _fleet_timeout():
    # reuse the heartbeat plane's bounded-send knob semantics: one knob for
    # every control-plane timeout would be ideal, and it already exists
    from .cluster import heartbeat_timeout

    return heartbeat_timeout()


# ------------------------------------------------------------- status state
# Facts the trainer publishes for the /status endpoint and the SIGQUIT dump:
# planned rounds (ETA), the rolling attribution record, the last checkpoint
# written, and a backend init error when distributed startup failed.
_status_lock = threading.Lock()
_status = {}
_started_at = time.monotonic()

# /status document shape version. Bump when sections are added/renamed so
# dashboards and the fleet smoke drill can detect shape changes instead of
# KeyError-ing on them. v2 = schema_version itself + the model-telemetry
# ``learning``/``drift`` sections (SM_MODEL_TELEMETRY).
STATUS_SCHEMA_VERSION = 2


def _model_doc():
    """The model-telemetry sections shared by ``/status`` and the SIGQUIT
    dump: ``learning`` (per-round stats + curve summary) and ``drift``
    (serving PSI window). {} when SM_MODEL_TELEMETRY is unarmed — the
    sections simply don't render."""
    doc = {}
    try:
        from . import model as model_telemetry

        learning = model_telemetry.learning_status()
        if learning:
            doc["learning"] = learning
        drift = model_telemetry.drift_status()
        if drift:
            doc["drift"] = drift
    except Exception:
        logger.debug("model telemetry status unavailable", exc_info=True)
    return doc


def note_status(**fields):
    """Merge ``fields`` into the process status dict (None removes a key).
    Cheap and lock-bounded — safe from any thread, inert when nothing ever
    reads it (the dict is only rendered by /status and the SIGQUIT dump)."""
    with _status_lock:
        for key, value in fields.items():
            if value is None:
                _status.pop(key, None)
            else:
                _status[key] = value


def note_attribution(fields):
    """Publish the latest (rolling or final) training.attribution shape —
    wired from RoundTimer so /status carries mid-job attribution."""
    note_status(attribution=dict(fields))


def status_snapshot():
    with _status_lock:
        return dict(_status)


def _memory_doc(collector=None):
    """The HBM/memory section shared by ``/status`` and the SIGQUIT dump:
    this rank's device-plane view (current sample, watermark, compiled
    peak) plus, on rank 0, the per-rank watermarks the shipper delivered
    and the memory-skew verdict. {} when the device plane is unarmed and
    no rank ever shipped a watermark — the section simply doesn't render."""
    doc = {}
    try:
        from . import device

        local = device.memory_status()
        if local:
            doc["local"] = local
    except Exception:
        logger.debug("local memory status unavailable", exc_info=True)
    if collector is not None:
        snap = collector.memory_snapshot()
        if snap.get("ranks"):
            doc["ranks"] = snap["ranks"]
            if "memory_skew" in snap:
                doc["memory_skew"] = snap["memory_skew"]
    return doc


# ------------------------------------------------------------------ shipper
class SpanShipper:
    """Per-rank span shipper daemon: drains newly finished spans from the
    tracing flight recorder every ``SM_FLEET_FLUSH_S`` and ships them to
    rank 0 as framed JSON batches. Fire-and-forget like the heartbeat
    sender: bounded timeouts, capped backoff, one warning per outage, a
    bounded retry queue — an absent collector costs warnings, never rounds.

    ``span_source`` (tests, drills) overrides the recorder drain with a
    callable returning wire dicts (see ``tracing.span_to_wire``).
    """

    def __init__(
        self,
        rank,
        host,
        collector_addr,
        interval=None,
        timeout=None,
        span_source=None,
        registry=None,
    ):
        self.rank = int(rank)
        self.host = host
        self.collector_addr = collector_addr
        self.interval = float(interval if interval is not None else fleet_flush_interval())
        self.timeout = timeout if timeout is not None else _fleet_timeout()
        self._span_source = span_source
        self._last_seq = 0
        self._pending = collections.deque()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._delay = self.interval
        self._outage = False
        reg = registry or REGISTRY
        labels = {"rank": str(rank)}
        self._m_shipped = reg.counter(
            "fleet_spans_shipped_total", "Spans delivered to the rank-0 collector", labels
        )
        self._m_failed = reg.counter(
            "fleet_ship_failures_total",
            "Span batch sends that failed (collector unreachable)",
            labels,
        )
        self._m_dropped = reg.counter(
            "fleet_spans_dropped_total",
            "Spans dropped from the bounded retry queue during an outage",
            labels,
        )
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fleet-span-ship"
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout)

    def _drain(self):
        """New wire spans since the last drain (recorder-seq watermark)."""
        if self._span_source is not None:
            return list(self._span_source())
        fresh = []
        last = self._last_seq
        for span in tracing.snapshot_spans():
            if span.seq is not None and span.seq > last:
                fresh.append(tracing.span_to_wire(span))
                if span.seq > self._last_seq:
                    self._last_seq = span.seq
        return fresh

    def _memory_wire(self):
        """The device plane's latest HBM watermark (None when unarmed or
        never sampled) — rides the next span frame so rank 0 can fold a
        per-rank memory view without a second control-plane socket."""
        try:
            from . import device

            return device.watermark_wire()
        except Exception:
            return None

    def send_once(self):
        """One bounded flush attempt; returns True when nothing remains
        pending. Never raises — delivery failure is counted, backed off,
        and retried with the batch intact (bounded)."""
        with self._lock:
            self._pending.extend(self._drain())
            dropped = len(self._pending) - _MAX_PENDING_SPANS
            if dropped > 0:
                for _ in range(dropped):
                    self._pending.popleft()
                self._m_dropped.inc(dropped)
                logger.debug("fleet retry queue full; dropped %d spans", dropped)
            batch = list(self._pending)
        memory = self._memory_wire()
        if not batch and memory is None:
            return True
        sent = 0
        try:
            # a watermark with no spans still ships: one frame with an
            # empty span list carries it (the collector folds both)
            chunks = [
                batch[start : start + _BATCH_SPANS]
                for start in range(0, len(batch), _BATCH_SPANS)
            ] or [[]]
            for index, chunk in enumerate(chunks):
                payload = {
                    "type": "spans",
                    "v": FLEET_VERSION,
                    "rank": self.rank,
                    "host": self.host,
                    "spans": chunk,
                }
                if index == 0 and memory is not None:
                    payload["memory"] = memory
                sock = socket.create_connection(self.collector_addr, timeout=self.timeout)
                try:
                    sock.settimeout(self.timeout)
                    sock.sendall(frame_message(payload))
                finally:
                    sock.close()
                sent += len(chunk)
        except OSError as e:
            self._m_failed.inc()
            if not self._outage:
                self._outage = True
                logger.warning(
                    "fleet span shipping to %s:%s failed (%s); backing off — "
                    "training continues, failures counted in "
                    "fleet_ship_failures_total",
                    self.collector_addr[0],
                    self.collector_addr[1],
                    e,
                )
            self._delay = min(
                max(self._delay * 2, self.interval),
                2.0 * self.interval,
                _MAX_BACKOFF_S,
            )
        else:
            if self._outage:
                self._outage = False
                logger.info("fleet span shipping to rank 0 recovered")
            self._delay = self.interval
        if sent:
            self._m_shipped.inc(sent)
            with self._lock:
                for _ in range(min(sent, len(self._pending))):
                    self._pending.popleft()
        with self._lock:
            return not self._pending

    def flush(self):
        """Best-effort final delivery (end of training, SIGQUIT dump)."""
        return self.send_once()

    def _run(self):
        while not self._stop.wait(self._delay):
            self.send_once()


# ---------------------------------------------------------------- collector
class FleetCollector:
    """Rank-0 side: accept span batches, keep a bounded per-rank buffer for
    the merged trace, and fold per-round per-rank phase durations into skew
    reports (``round_skew_ms`` + ``training.skew``)."""

    def __init__(self, num_ranks, port=0, timeout=None, registry=None, hosts=None):
        self.num_ranks = int(num_ranks)
        self.timeout = timeout if timeout is not None else _fleet_timeout()
        self._reg = registry or REGISTRY
        self._hosts = list(hosts) if hosts else []
        self._stop = threading.Event()
        self._lock = threading.Lock()
        buffer_spans = env_int(
            tracing.TRACE_BUFFER_ENV, tracing.DEFAULT_BUFFER_SPANS, minimum=16
        )
        self._spans = {
            r: collections.deque(maxlen=buffer_spans) for r in range(self.num_ranks)
        }
        # per-rank running phase totals since that rank's last round root;
        # round roots close after their children, and batches preserve
        # recorder order, so attributing the running totals to the next
        # "round" span that arrives is exact
        self._running = {r: dict.fromkeys(_COMPONENTS, 0.0) for r in range(self.num_ranks)}
        self._rounds = {}  # round index -> {rank: per-rank entry}
        self._skew = collections.deque(maxlen=_SKEW_HISTORY)
        self._memory = {}  # rank -> latest HBM watermark (device plane)
        self._m_received = {
            r: self._reg.counter(
                "fleet_spans_received_total",
                "Spans folded in by the rank-0 collector",
                {"rank": str(r)},
            )
            for r in range(self.num_ranks)
        }
        self._m_skew = self._reg.gauge(
            "round_skew_ms", "Critical-rank minus median round latency, last folded round"
        )
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("0.0.0.0", port))
        self._server.listen(max(self.num_ranks, 8))
        self._server.settimeout(0.2)
        self.port = self._server.getsockname()[1]
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fleet-span-collect"
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout)
        try:
            self._server.close()
        except OSError:
            pass

    # ------------------------------------------------------------ fold path
    def fold(self, payload):
        """Fold one span batch into the buffers; junk is dropped."""
        if not isinstance(payload, dict) or payload.get("type") != "spans":
            return False
        try:
            rank = int(payload["rank"])
        except (KeyError, TypeError, ValueError):
            return False
        if not 0 <= rank < self.num_ranks:
            logger.warning("dropping span batch from unknown rank %r", rank)
            return False
        memory = payload.get("memory")
        if isinstance(memory, dict):
            entry = dict(memory)
            entry["host"] = payload.get("host")
            with self._lock:
                self._memory[rank] = entry
        spans = payload.get("spans")
        if not isinstance(spans, list):
            return False
        reports = []
        with self._lock:
            for wire in spans:
                if not isinstance(wire, dict):
                    continue
                self._spans[rank].append(wire)
                report = self._fold_span_locked(rank, wire)
                if report is not None:
                    reports.append(report)
        self._m_received[rank].inc(len(spans))
        for report in reports:
            self._publish_skew(report)
        return True

    def _fold_span_locked(self, rank, wire):
        name = wire.get("name")
        dur_ms = float(wire.get("dur_us") or 0.0) / 1000.0
        component = _PHASE_SPANS.get(name)
        if component is not None:
            self._running[rank][component] += dur_ms
            return None
        if name != "round":
            return None
        attrs = wire.get("attributes") or {}
        round_index = attrs.get("round")
        running, self._running[rank] = (
            self._running[rank],
            dict.fromkeys(_COMPONENTS, 0.0),
        )
        if not isinstance(round_index, int):
            return None  # the post-training tail span has no round index
        entry = {"total": dur_ms}
        entry.update(running)
        per_rank = self._rounds.setdefault(round_index, {})
        per_rank[rank] = entry
        if len(per_rank) >= self.num_ranks:
            del self._rounds[round_index]
            return self._fold_round_locked(round_index, per_rank)
        # bound the outstanding-round map: a rank that stopped shipping must
        # not grow it forever — oldest incomplete rounds are abandoned
        while len(self._rounds) > _MAX_OPEN_ROUNDS:
            del self._rounds[min(self._rounds)]
        return None

    def _fold_round_locked(self, round_index, per_rank):
        """-> one skew report for a fully reported round (>= 2 ranks)."""
        if len(per_rank) < 2:
            return None
        totals = {r: e["total"] for r, e in per_rank.items()}
        critical = max(totals, key=totals.get)
        median_ms = percentile(list(totals.values()), 0.5)
        skew_ms = totals[critical] - median_ms
        # phase attribution: per component, how much MORE the critical rank
        # spent there than the median rank; the remainder of the round not
        # explained by any instrumented phase is "wire"
        deltas = {}
        for comp in _COMPONENTS:
            values = [e[comp] for e in per_rank.values()]
            deltas[comp] = per_rank[critical][comp] - percentile(values, 0.5)
        residuals = {
            r: e["total"] - sum(e[c] for c in _COMPONENTS) for r, e in per_rank.items()
        }
        deltas["wire"] = residuals[critical] - percentile(list(residuals.values()), 0.5)
        phase = max(deltas, key=deltas.get)
        report = {
            "round": round_index,
            "critical_rank": critical,
            "phase": phase,
            "skew_ms": round(max(skew_ms, 0.0), 3),
            "round_ms": round(totals[critical], 3),
            "median_ms": round(median_ms, 3),
            "phase_excess_ms": round(max(deltas[phase], 0.0), 3),
            "ranks": len(per_rank),
        }
        for comp in _COMPONENTS:
            report["{}_ms".format(comp)] = round(per_rank[critical][comp], 3)
        report["wire_ms"] = round(max(residuals[critical], 0.0), 3)
        if self._hosts and critical < len(self._hosts):
            report["host"] = self._hosts[critical]
        self._skew.append(report)
        return report

    def _publish_skew(self, report):
        self._m_skew.set(report["skew_ms"])
        emit_metric("training.skew", **report)

    # ----------------------------------------------------------- read paths
    def skew_snapshot(self, last=None):
        with self._lock:
            reports = list(self._skew)
        return reports[-last:] if last else reports

    def span_counts(self):
        with self._lock:
            return {r: len(buf) for r, buf in self._spans.items()}

    def memory_snapshot(self):
        """Per-rank HBM watermarks + a memory-skew verdict: the rank whose
        live bytes exceed 1.5x the cross-rank median (>= 2 reporting ranks)
        is named, so skew attribution can say *memory*-skewed, not just
        slow. Empty ``ranks`` when the device plane never shipped."""
        with self._lock:
            per_rank = {r: dict(m) for r, m in self._memory.items()}
        doc = {"ranks": per_rank}
        values = {
            r: m.get("bytes_in_use", 0)
            for r, m in per_rank.items()
            if isinstance(m.get("bytes_in_use"), (int, float))
        }
        if len(values) >= 2:
            median = percentile(list(values.values()), 0.5)
            worst = max(values, key=values.get)
            if median > 0 and values[worst] > _MEMORY_SKEW_FACTOR * median:
                doc["memory_skew"] = {
                    "rank": worst,
                    "host": per_rank[worst].get("host"),
                    "bytes_in_use": int(values[worst]),
                    "median_bytes": int(median),
                    "ratio": round(values[worst] / median, 2),
                }
        return doc

    def merged_doc(self, extra_metadata=None):
        """-> the merged Chrome-trace dict: one pid=rank lane per rank that
        shipped spans, built by the same event builder as the per-rank
        exports."""
        with self._lock:
            per_rank = {r: list(buf) for r, buf in self._spans.items() if buf}
        events = []
        for rank in sorted(per_rank):
            label = "rank {}".format(rank)
            if self._hosts and rank < len(self._hosts):
                label += " ({})".format(self._hosts[rank])
            events.extend(
                tracing.events_from_wire(per_rank[rank], pid=rank, process_label=label)
            )
        metadata = {
            "merged": True,
            "ranks": sorted(per_rank),
            "spans": sum(len(v) for v in per_rank.values()),
            "clock_note": "per-rank perf_counter bases; compare durations, "
            "not cross-lane offsets",
        }
        if extra_metadata:
            metadata.update(extra_metadata)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": metadata,
        }

    def write_fleet_trace(self, directory, filename="trace-fleet.json"):
        """Write the merged trace next to the per-rank exports and emit one
        ``training.fleet_export`` record. Returns the path (None when no
        rank shipped anything — no empty artifacts)."""
        doc = self.merged_doc()
        if not doc["otherData"]["ranks"]:
            logger.info("no fleet spans collected; skipping merged trace export")
            return None
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, filename)
        with open(path, "w") as f:
            json.dump(doc, f)
            f.write("\n")
        emit_metric(
            "training.fleet_export",
            path=path,
            spans=doc["otherData"]["spans"],
            ranks=doc["otherData"]["ranks"],
        )
        logger.info(
            "exported merged fleet trace (%d spans, ranks %s) to %s",
            doc["otherData"]["spans"],
            doc["otherData"]["ranks"],
            path,
        )
        return path

    # -------------------------------------------------------------- accept
    def _run(self):
        while not self._stop.is_set():
            try:
                conn, _addr = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # socket closed under us
            try:
                self.fold(
                    recv_message_bounded(
                        conn, self.timeout, max_bytes=_MAX_FLEET_FRAME_BYTES
                    )
                )
            except Exception as e:
                logger.debug("dropping malformed span batch: %s", e)
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
        try:
            self._server.close()
        except OSError:
            pass


# ------------------------------------------------------------ status server
class StatusServer:
    """Rank-0 live introspection endpoint (``SM_STATUS_PORT``): the
    ClusterMetricsServer plumbing serving JSON instead of exposition.

    * ``GET /status`` — round progress + ETA, rolling attribution, recent
      skew reports, elastic membership log, last checkpoint, backend init
      error, serving SLO snapshot when armed.
    * ``GET /debug/flight`` — the live span snapshot (finished ring buffer
      + in-flight spans), i.e. the flight recorder without the abort.
    * ``GET /debug/profile?ms=N`` — a bounded on-demand ``jax.profiler``
      capture into ``SM_PROFILER_TRACE_DIR`` (404 while unarmed), so a
      live wedged job can be profiled without restarting it.
    """

    def __init__(self, port, collector=None):
        from wsgiref.simple_server import WSGIRequestHandler, make_server

        self._collector = collector

        def app(environ, start_response):
            path = environ.get("PATH_INFO", "/")
            status = _HTTP_STATUS[200]
            if path in ("/", "/status"):
                body = json.dumps(self.status_doc()).encode("utf-8")
            elif path == "/debug/flight":
                body = json.dumps(self.flight_doc()).encode("utf-8")
            elif path == "/debug/profile":
                code, doc = self.profile_doc(environ.get("QUERY_STRING", ""))
                status = _HTTP_STATUS[code]
                body = json.dumps(doc).encode("utf-8")
            else:
                body = b"not found"
                start_response(
                    "404 Not Found",
                    [
                        ("Content-Type", "text/plain"),
                        ("Content-Length", str(len(body))),
                    ],
                )
                return [body]
            start_response(
                status,
                [
                    ("Content-Type", "application/json"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]

        class _Quiet(WSGIRequestHandler):
            def log_message(self, fmt, *args):
                logger.debug("%s - %s", self.address_string(), fmt % args)

        self._httpd = make_server("0.0.0.0", port, app, handler_class=_Quiet)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="fleet-status-http"
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._httpd.shutdown()
        self._thread.join(timeout)
        self._httpd.server_close()

    def status_doc(self):
        doc = {
            "schema_version": STATUS_SCHEMA_VERSION,
            "uptime_s": round(time.monotonic() - _started_at, 1),
        }
        doc.update(status_snapshot())
        snap = ROUND_STATE.snapshot()
        doc["round"] = snap
        planned = doc.get("rounds_planned")
        if planned and snap["round_ms_p50"] > 0:
            remaining = max(int(planned) - (snap["round"] + 1), 0)
            doc["eta_s"] = round(remaining * snap["round_ms_p50"] / 1000.0, 1)
        if self._collector is not None:
            doc["skew"] = self._collector.skew_snapshot(last=5)
            doc["fleet_spans"] = self._collector.span_counts()
        try:
            from ..training.elastic import membership_log

            doc["membership_log"] = membership_log()
        except Exception:  # elastic plane optional/uninitialized: omit
            pass
        from .slo import active_window

        window = active_window()
        if window is not None:
            doc["slo"] = window.snapshot()
        memory = _memory_doc(self._collector)
        if memory:
            doc["memory"] = memory
        doc.update(_model_doc())
        return doc

    def profile_doc(self, query):
        """``GET /debug/profile?ms=N`` -> (http code, doc): a bounded
        programmatic ``jax.profiler`` capture into ``SM_PROFILER_TRACE_DIR``
        so a live wedged job can be profiled without restarting it. 404
        when the trace dir isn't armed (indistinguishable from an unknown
        path, like the /metrics gate), 409 while another capture runs,
        capture length capped at ``_PROFILE_MAX_MS``."""
        from ..training.profiling import TRACE_DIR_ENV
        from urllib.parse import parse_qs

        trace_dir = os.environ.get(TRACE_DIR_ENV)
        if not trace_dir:
            return 404, {
                "error": "profiling unarmed: set {} to enable on-demand "
                "captures".format(TRACE_DIR_ENV)
            }
        try:
            ms = int(parse_qs(query or "").get("ms", ["1000"])[0])
        except (ValueError, IndexError):
            return 400, {"error": "ms must be an integer"}
        ms = max(1, min(ms, _PROFILE_MAX_MS))
        if not _profile_lock.acquire(blocking=False):
            return 409, {"error": "a profile capture is already running"}
        try:
            import jax

            with _status_lock:
                _profile_seq[0] += 1
                seq = _profile_seq[0]
            out_dir = os.path.join(trace_dir, "ondemand-{}".format(seq))
            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(ms / 1000.0)
            finally:
                jax.profiler.stop_trace()
        except Exception as e:
            logger.warning("on-demand profile capture failed: %s", e)
            return 500, {"error": str(e)[:400]}
        finally:
            _profile_lock.release()
        emit_metric("training.profile_capture", path=out_dir, ms=ms)
        logger.info("on-demand XLA profile (%d ms) captured to %s", ms, out_dir)
        return 200, {"path": out_dir, "ms": ms}

    def flight_doc(self):
        spans = [
            tracing.span_to_wire(span)
            for span in tracing.snapshot_spans(include_open=True)
        ]
        return {
            "rank": tracing.get_rank(),
            "count": len(spans),
            "spans": spans,
        }


# ---------------------------------------------------------------- lifecycle
class FleetPlane:
    """Handle bundling this host's fleet-observability components."""

    def __init__(self, rank, num_ranks, shipper=None, collector=None, status_server=None):
        self.rank = rank
        self.num_ranks = num_ranks
        self.shipper = shipper
        self.collector = collector
        self.status_server = status_server

    def stop(self, timeout=5.0):
        global _active_plane
        for part in (self.shipper, self.status_server, self.collector):
            if part is not None:
                try:
                    part.stop(timeout)
                except Exception:
                    logger.exception("error stopping fleet plane component")
        with _plane_lock:
            if _active_plane is self:
                _active_plane = None


_plane_lock = threading.Lock()
_active_plane = None


def active_plane():
    return _active_plane


def stop_fleet_plane():
    """Stop the active fleet plane (membership-reform teardown and test
    cleanup). Safe to call when inert."""
    global _active_plane
    with _plane_lock:
        plane, _active_plane = _active_plane, None
    if plane is not None:
        plane.stop()


def start_fleet_plane(hosts, current_host, registry=None):
    """Bring up this host's share of the fleet plane; wired from the same
    pre-exec/reform path as the cluster heartbeat plane.

    Inert unless ``SM_FLEET_TRACE`` is truthy (shipper on every rank,
    collector on rank 0) or ``SM_STATUS_PORT`` names a port (rank-0 status
    endpoint): with both unset it returns ``None`` having created no
    thread, no socket, and no registry series. One plane per process — a
    re-form stops the previous instance first so the ports re-bind over
    the survivor world."""
    global _active_plane
    trace_on = fleet_enabled()
    status_port = env_int(STATUS_PORT_ENV, 0, minimum=0, maximum=65535)
    if not trace_on and not status_port:
        return None
    with _plane_lock:
        prev, _active_plane = _active_plane, None
    if prev is not None:
        logger.info("restarting fleet plane (previous plane stopped)")
        prev.stop()
    ordered = sorted(hosts)
    rank = ordered.index(current_host)
    shipper = None
    collector = None
    status_server = None
    if trace_on:
        if not tracing.enabled():
            logger.warning(
                "%s is set but %s is not: no spans exist to ship — enable "
                "SM_TRACE for the fleet view",
                FLEET_TRACE_ENV,
                tracing.TRACE_ENV,
            )
        port = env_port(FLEET_TRACE_PORT_ENV, DEFAULT_FLEET_PORT)
        interval = fleet_flush_interval()
        if rank == 0:
            try:
                collector = FleetCollector(
                    num_ranks=len(ordered),
                    port=port,
                    registry=registry,
                    hosts=ordered,
                ).start()
            except OSError as e:
                logger.warning(
                    "fleet collector could not bind port %d (%s); span "
                    "batches will be dropped but training continues",
                    port,
                    e,
                )
        target_host = "127.0.0.1" if rank == 0 else ordered[0]
        shipper = SpanShipper(
            rank=rank,
            host=current_host,
            collector_addr=(target_host, port),
            interval=interval,
            registry=registry,
        ).start()
        logger.info(
            "fleet trace plane up: rank %d/%d, shipping spans every %.1fs "
            "to %s:%d%s",
            rank,
            len(ordered),
            interval,
            target_host,
            port,
            " (collecting)" if collector else "",
        )
    if status_port and rank == 0:
        try:
            status_server = StatusServer(status_port, collector=collector).start()
            logger.info(
                "status endpoint on port %d (/status, /debug/flight, "
                "/debug/profile)",
                status_server.port,
            )
        except OSError as e:
            logger.warning("status port %d unavailable: %s", status_port, e)
    plane = FleetPlane(
        rank=rank,
        num_ranks=len(ordered),
        shipper=shipper,
        collector=collector,
        status_server=status_server,
    )
    with _plane_lock:
        _active_plane = plane
    return plane


def export_fleet_trace(default_dir=None):
    """End-of-run merge: flush this rank's shipper, then (rank 0) write
    ``trace-fleet.json`` next to the per-rank exports. Best-effort and
    bounded — peers flush concurrently from their own train end, so rank 0
    grants one flush interval of grace before merging whatever arrived.
    Returns the merged path, or None (inert plane / nothing collected /
    not rank 0)."""
    plane = _active_plane
    if plane is None:
        return None
    if plane.shipper is not None:
        plane.shipper.flush()
    if plane.collector is None:
        return None
    if plane.num_ranks > 1:
        # grace for the other ranks' final flush; bounded and best-effort —
        # a dead peer costs this sleep, never a hang
        time.sleep(min(fleet_flush_interval(), 2.0))
    directory = os.environ.get(tracing.TRACE_EXPORT_DIR_ENV) or default_dir
    if not directory:
        return None
    return plane.collector.write_fleet_trace(directory)


# ------------------------------------------------------------- SIGQUIT dump
def _sigquit_dump(default_dir):
    """The kill -3 inspection dump: flight recorder + fleet/status snapshot
    to disk, WITHOUT aborting (exits 79–85 own the abort-path dump). Never
    raises — it runs on a throwaway thread next to a live job."""
    try:
        trace_path = tracing.dump_flight_recorder(
            default_dir=default_dir, reason="sigquit"
        )
        directory = (
            os.environ.get(tracing.TRACE_EXPORT_DIR_ENV) or default_dir or "."
        )
        # build the same /status view without needing a server instance
        doc = {
            "schema_version": STATUS_SCHEMA_VERSION,
            "uptime_s": round(time.monotonic() - _started_at, 1),
        }
        doc.update(status_snapshot())
        doc["round"] = ROUND_STATE.snapshot()
        plane = _active_plane
        if plane is not None and plane.collector is not None:
            doc["skew"] = plane.collector.skew_snapshot()
            doc["fleet_spans"] = plane.collector.span_counts()
        memory = _memory_doc(plane.collector if plane is not None else None)
        if memory:
            doc["memory"] = memory
        doc.update(_model_doc())
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory, "fleet-status-rank{}.json".format(tracing.get_rank())
        )
        with open(path, "w") as f:
            json.dump(doc, f)
            f.write("\n")
        emit_metric(
            "training.sigquit_dump",
            status_path=path,
            flight_path=trace_path or "",
        )
        logger.warning(
            "SIGQUIT inspection dump: status %s, flight recorder %s "
            "(job continues)",
            path,
            trace_path,
        )
    except Exception:
        logger.exception("SIGQUIT dump failed; job unaffected")


def install_sigquit_handler(default_dir=None):
    """Arm ``kill -3`` as a live inspection dump (flight recorder + fleet
    skew/status snapshot) that does NOT abort — a wedged-but-alive job can
    be inspected in place. Returns False (and stays inert) off the main
    thread or on platforms without SIGQUIT."""
    if not hasattr(signal, "SIGQUIT"):
        return False

    def _handler(signo, frame):
        # the dump takes locks and touches disk: hand it to a short-lived
        # thread so the handler itself stays async-signal-trivial
        threading.Thread(
            target=_sigquit_dump,
            args=(default_dir,),
            daemon=True,
            name="sigquit-dump",
        ).start()

    try:
        signal.signal(signal.SIGQUIT, _handler)
    except (ValueError, OSError):  # non-main thread / exotic platform
        return False
    return True


def _reset_for_tests():
    """Drop the active plane and the status dict."""
    stop_fleet_plane()
    with _status_lock:
        _status.clear()
