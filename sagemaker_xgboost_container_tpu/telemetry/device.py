"""Device-window attribution plane (``SM_DEVICE_TELEMETRY``): what the fused
round program *costs*, what HBM is actually resident, and why a dispatch
OOMs.

PR 7/10/13 split every round into compile/host/device/collective/wire, but
the ``device`` bucket itself stayed a black box. This module opens it with
four connected pieces, all env-gated like the fleet plane (zero threads,
zero records, zero registry series when ``SM_DEVICE_TELEMETRY`` is unset):

* **Compiled-cost introspection** — at session build the booster AOT-lowers
  the fused round dispatch and feeds ``cost_analysis()`` /
  ``memory_analysis()`` through :func:`cost_from_compiled` into
  :func:`note_compiled`: one ``training.compiled`` record (flops, bytes
  accessed, peak arg/output/temp HBM bytes, per mesh shape and
  ``rounds_per_dispatch``) plus the ``device_flops_per_round`` /
  ``device_hbm_peak_bytes`` gauges.
* **Per-round HBM watermark** — RoundTimer samples
  :func:`sample_device_memory` every ``SM_HBM_SAMPLE_EVERY`` rounds
  (:func:`sample_watermark`). The sampler is the ONE cached
  O(live-buffers) walk shared with the heartbeat plane
  (``telemetry/cluster.py`` delegates here), so heartbeats and round
  sampling never pay it twice per interval. Watermarks ride the fleet
  span shipper to rank 0, where ``/status`` renders a memory section and
  names a *memory*-skewed rank.
* **Roofline attribution** — :func:`roofline_fields` combines measured
  device time with the compiled cost into achieved FLOPs/s, bytes/s, and
  the binding resource (compute / memory / latency); RoundTimer emits one
  ``training.roofline`` record and mirrors it into
  ``training.attribution``, ``/status``, and bench.py's final JSON.
* **OOM forensics** — :func:`dump_oom_forensics` writes
  ``hbm-forensics-rank<r>.json`` (top live buffers by shape/size,
  allocator stats, the compiled memory analysis, the last watermark) on
  the booster's ``RESOURCE_EXHAUSTED`` path before the watchdog abort
  (exit 86, ``EXIT_DEVICE_OOM``). The forensics path is robustness, not
  telemetry: like exits 79-85 it fires regardless of the gate.

Binding-resource heuristic (deterministic, no hardware database): a round
whose device time sits under ``LATENCY_FLOOR_MS`` is dispatch-floor bound
("latency"); otherwise operational intensity (flops / bytes accessed)
against ``DEFAULT_RIDGE_FLOPS_PER_BYTE`` splits "compute" from "memory".
The ridge is a documented constant carried in every record, so a reader
can re-judge against their hardware's real ridge point.
"""

import json
import logging
import os
import threading
import time

from ..utils.envconfig import env_bool, env_int
from .emit import emit_metric
from .registry import REGISTRY

logger = logging.getLogger(__name__)

#: master gate: unset ⇒ no records, no gauges, no sampling cadence
DEVICE_TELEMETRY_ENV = "SM_DEVICE_TELEMETRY"
#: watermark cadence in rounds (>= 1); read once per training session
HBM_SAMPLE_EVERY_ENV = "SM_HBM_SAMPLE_EVERY"
DEFAULT_HBM_SAMPLE_EVERY = 8

#: operational-intensity ridge (flops per HBM byte) splitting compute- from
#: memory-bound; stamped into every roofline record so the verdict can be
#: re-judged against real hardware (v5p HBM ridge is far higher — a program
#: memory-bound at 10 is memory-bound everywhere that matters)
DEFAULT_RIDGE_FLOPS_PER_BYTE = 10.0
#: per-round device time under this is dominated by the per-dispatch floor
#: (host->device transfer, dispatch latency), not by the program itself
LATENCY_FLOOR_MS = 0.5

#: one cached device-memory walk serves every consumer inside this window
SAMPLE_MAX_AGE_S = 1.0

_state_lock = threading.Lock()
_last_compiled = None  # the note_compiled record (train round program)
_last_watermark = None  # the last sample_watermark result
_watermark_high = 0  # high-water bytes_in_use across watermark samples

_sample_lock = threading.Lock()
_sample_cache = None  # (monotonic stamp, snapshot dict)


def enabled():
    return env_bool(DEVICE_TELEMETRY_ENV, False)


def hbm_sample_every():
    return env_int(HBM_SAMPLE_EVERY_ENV, DEFAULT_HBM_SAMPLE_EVERY, minimum=1)


def sample_cadence():
    """Watermark cadence for RoundTimer: 0 (never sample) when the plane is
    unarmed, else ``SM_HBM_SAMPLE_EVERY``. Resolved once per session by the
    caller — the per-round path never reads env."""
    return hbm_sample_every() if enabled() else 0


# ------------------------------------------------------- cached memory walk
def _sample_uncached():
    """One O(devices) + O(live-buffers) walk: per-device allocator stats
    where the backend reports them (TPU), else the summed footprint of live
    jax arrays — the same ladder the heartbeat plane used before it was
    hoisted here. Never raises."""
    snap = {
        "total_bytes_in_use": 0,
        "peak_bytes_in_use": 0,
        "bytes_limit": 0,
        "source": "none",
        "devices": [],
    }
    try:
        import jax

        seen_stats = False
        for dev in jax.devices():
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats or "bytes_in_use" not in stats:
                continue
            seen_stats = True
            entry = {
                "id": getattr(dev, "id", len(snap["devices"])),
                "kind": getattr(dev, "device_kind", "unknown"),
                "bytes_in_use": int(stats["bytes_in_use"]),
                "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(stats.get("bytes_limit", 0)),
            }
            snap["devices"].append(entry)
            snap["total_bytes_in_use"] += entry["bytes_in_use"]
            snap["peak_bytes_in_use"] += entry["peak_bytes_in_use"]
            snap["bytes_limit"] += entry["bytes_limit"]
        if seen_stats:
            snap["source"] = "memory_stats"
            return snap
        snap["total_bytes_in_use"] = int(
            sum(getattr(a, "nbytes", 0) for a in jax.live_arrays())
        )
        snap["source"] = "live_arrays"
    except Exception:
        pass
    return snap


def sample_device_memory(max_age_s=SAMPLE_MAX_AGE_S):
    """The shared device-memory snapshot, cached for ``max_age_s`` seconds
    so the heartbeat sender, the round watermark, and ``/status`` together
    pay at most one live-buffer walk per interval. ``max_age_s=0`` forces a
    fresh walk (OOM forensics). Passive and ungated: creates no threads and
    emits nothing, so unarmed callers (the heartbeat plane) stay inert."""
    global _sample_cache
    now = time.monotonic()
    with _sample_lock:
        cached = _sample_cache
        if cached is not None and now - cached[0] <= max_age_s:
            return cached[1]
    snap = _sample_uncached()
    with _sample_lock:
        _sample_cache = (time.monotonic(), snap)
    return snap


# --------------------------------------------------------- compiled program
def cost_from_compiled(compiled):
    """Extract the cost/memory analyses of a jax AOT ``Compiled`` into one
    flat dict of floats/ints (absent analyses yield zeros — some backends
    return nothing for trivial programs). ``cost_analysis()`` is a dict on
    recent jax and a one-element list of dicts on older releases; both
    shapes are handled."""
    cost = {"flops": 0.0, "bytes_accessed": 0.0, "transcendentals": 0.0}
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        if isinstance(analysis, dict):
            cost["flops"] = float(analysis.get("flops", 0.0) or 0.0)
            cost["bytes_accessed"] = float(
                analysis.get("bytes accessed", 0.0) or 0.0
            )
            cost["transcendentals"] = float(
                analysis.get("transcendentals", 0.0) or 0.0
            )
    except Exception as e:
        logger.debug("cost_analysis unavailable: %s", e)
    mem = {"arg_bytes": 0, "out_bytes": 0, "temp_bytes": 0, "alias_bytes": 0}
    try:
        analysis = compiled.memory_analysis()
        mem["arg_bytes"] = int(
            getattr(analysis, "argument_size_in_bytes", 0) or 0
        )
        mem["out_bytes"] = int(getattr(analysis, "output_size_in_bytes", 0) or 0)
        mem["temp_bytes"] = int(getattr(analysis, "temp_size_in_bytes", 0) or 0)
        mem["alias_bytes"] = int(getattr(analysis, "alias_size_in_bytes", 0) or 0)
    except Exception as e:
        logger.debug("memory_analysis unavailable: %s", e)
    cost.update(mem)
    return cost


def note_compiled(
    cost,
    mesh_shape=None,
    rounds_per_dispatch=1,
    backend=None,
    kind="train_round",
    registry=None,
):
    """Fold one program's cost dict (:func:`cost_from_compiled`) into the
    plane: emit the ``training.compiled`` record, set the gauges, and keep
    the record for roofline math, ``/status``, and OOM forensics. The
    caller gates on :func:`enabled` — this function assumes the plane is
    armed. Returns the record."""
    k = max(int(rounds_per_dispatch or 1), 1)
    record = dict(cost)
    record["kind"] = kind
    record["rounds_per_dispatch"] = k
    record["flops_per_round"] = round(record.get("flops", 0.0) / k, 1)
    record["bytes_per_round"] = round(record.get("bytes_accessed", 0.0) / k, 1)
    # peak resident HBM of one dispatch: everything the executable holds at
    # once — donated/aliased args overlap outputs, so subtract the alias
    peak = (
        record.get("arg_bytes", 0)
        + record.get("out_bytes", 0)
        + record.get("temp_bytes", 0)
        - record.get("alias_bytes", 0)
    )
    record["hbm_peak_bytes"] = int(max(peak, 0))
    if mesh_shape:
        record["mesh_shape"] = {str(a): int(n) for a, n in dict(mesh_shape).items()}
    if backend:
        record["backend"] = backend
    global _last_compiled
    with _state_lock:
        if kind == "train_round" or _last_compiled is None:
            _last_compiled = record
    reg = registry or REGISTRY
    reg.gauge(
        "device_flops_per_round",
        "Compiled FLOPs of one boosting round (XLA cost_analysis / K)",
    ).set(record["flops_per_round"])
    reg.gauge(
        "device_hbm_peak_bytes",
        "Peak resident HBM bytes of one round dispatch (arg+out+temp-alias)",
    ).set(record["hbm_peak_bytes"])
    emit_metric("training.compiled", **record)
    from . import fleet

    fleet.note_status(compiled=record)
    return record


def last_compiled():
    with _state_lock:
        return dict(_last_compiled) if _last_compiled is not None else None


# ---------------------------------------------------------------- watermark
def sample_watermark(round_index, registry=None):
    """One per-round HBM watermark sample (RoundTimer, on the
    ``SM_HBM_SAMPLE_EVERY`` cadence — the caller owns the cadence check).
    Updates the ``hbm_watermark_bytes`` gauge and the wire-side state the
    fleet shipper sends to rank 0. Returns the watermark dict."""
    snap = sample_device_memory()
    watermark = {
        "round": int(round_index),
        "bytes_in_use": int(snap["total_bytes_in_use"]),
        "peak_bytes": int(snap["peak_bytes_in_use"]),
        "source": snap["source"],
    }
    global _last_watermark, _watermark_high
    with _state_lock:
        _last_watermark = watermark
        _watermark_high = max(_watermark_high, watermark["bytes_in_use"])
    (registry or REGISTRY).gauge(
        "hbm_watermark_bytes",
        "Live HBM bytes at the last per-round watermark sample",
    ).set(watermark["bytes_in_use"])
    return watermark


def watermark_wire():
    """The latest watermark for the fleet span shipper (None when the plane
    is unarmed or no round has been sampled yet — an absent key costs the
    frame nothing)."""
    if not enabled():
        return None
    with _state_lock:
        if _last_watermark is None:
            return None
        wire = dict(_last_watermark)
        wire["high_bytes"] = _watermark_high
        return wire


def memory_status():
    """The local memory section for ``/status`` and the SIGQUIT dump: a
    fresh (cached) sample plus the watermark history and the compiled
    program's predicted peak. None when the plane is unarmed."""
    if not enabled():
        return None
    doc = {"current": sample_device_memory()}
    with _state_lock:
        if _last_watermark is not None:
            doc["watermark"] = dict(_last_watermark)
            doc["high_bytes"] = _watermark_high
        if _last_compiled is not None:
            doc["compiled_hbm_peak_bytes"] = _last_compiled.get(
                "hbm_peak_bytes", 0
            )
    return doc


# ----------------------------------------------------------------- roofline
def roofline_fields(
    compiled,
    device_ms,
    rounds,
    source="residual",
    ridge=DEFAULT_RIDGE_FLOPS_PER_BYTE,
    latency_floor_ms=LATENCY_FLOOR_MS,
):
    """Pure roofline math -> the ``training.roofline`` field dict.

    ``compiled`` is a :func:`note_compiled`-shaped dict (tests inject their
    own); ``device_ms`` is the measured device-window time covering
    ``rounds`` rounds, with ``source`` naming how it was measured
    (``device_sync`` fence spans, or the ``residual`` of the round total
    minus instrumented host phases)."""
    rounds = max(int(rounds), 1)
    flops_per_round = float(compiled.get("flops_per_round", 0.0) or 0.0)
    bytes_per_round = float(compiled.get("bytes_per_round", 0.0) or 0.0)
    seconds = max(float(device_ms), 0.0) / 1000.0
    per_round_ms = device_ms / rounds if rounds else 0.0
    achieved_flops = flops_per_round * rounds / seconds if seconds > 0 else 0.0
    achieved_bytes = bytes_per_round * rounds / seconds if seconds > 0 else 0.0
    intensity = flops_per_round / bytes_per_round if bytes_per_round > 0 else 0.0
    if per_round_ms < latency_floor_ms:
        binding = "latency"
    elif intensity >= ridge:
        binding = "compute"
    else:
        binding = "memory"
    return {
        "rounds": rounds,
        "device_ms": round(float(device_ms), 3),
        "device_ms_per_round": round(per_round_ms, 3),
        "device_time_source": source,
        "flops_per_round": round(flops_per_round, 1),
        "bytes_per_round": round(bytes_per_round, 1),
        "achieved_flops_per_sec": round(achieved_flops, 1),
        "achieved_bytes_per_sec": round(achieved_bytes, 1),
        "operational_intensity": round(intensity, 3),
        "ridge_flops_per_byte": ridge,
        "binding": binding,
    }


def maybe_roofline(device_ms, rounds, source, emit=False, extra=None):
    """The gated roofline entrypoint: None when the plane is unarmed or no
    compiled cost was introspected; otherwise the field dict, optionally
    emitted as one ``training.roofline`` record and mirrored into
    ``/status``."""
    if not enabled():
        return None
    compiled = last_compiled()
    if compiled is None or rounds <= 0:
        return None
    fields = roofline_fields(compiled, device_ms, rounds, source)
    if extra:
        fields.update(extra)
    if emit:
        emit_metric("training.roofline", **fields)
        from . import fleet

        fleet.note_status(roofline=fields)
    return fields


# ------------------------------------------------------------ OOM forensics
def is_oom_error(exc):
    """Does this exception look like a device allocator exhaustion? XLA
    surfaces OOM as ``XlaRuntimeError: RESOURCE_EXHAUSTED: ...`` (the class
    is backend-private, so match text, not type)."""
    text = "{}: {}".format(type(exc).__name__, exc)
    return (
        "RESOURCE_EXHAUSTED" in text
        or "Resource exhausted" in text
        or "out of memory" in text.lower()
    )


def _top_live_buffers(top_n=32):
    """Live device buffers grouped by (shape, dtype), largest total first —
    the 'what is actually resident' table of the forensics dump."""
    import jax

    groups = {}
    for arr in jax.live_arrays():
        try:
            key = (tuple(getattr(arr, "shape", ())), str(getattr(arr, "dtype", "?")))
            entry = groups.setdefault(
                key, {"shape": list(key[0]), "dtype": key[1], "count": 0, "total_bytes": 0}
            )
            entry["count"] += 1
            entry["total_bytes"] += int(getattr(arr, "nbytes", 0))
        except Exception:
            continue
    ranked = sorted(groups.values(), key=lambda e: -e["total_bytes"])
    return ranked[:top_n]


def _forensics_dir(default_dir=None):
    """Durable-location ladder, mirroring the flight-recorder dump: the
    explicit export dir, then the caller's hint (live checkpoint dir /
    model dir), then the working directory."""
    from . import tracing

    explicit = os.environ.get(tracing.TRACE_EXPORT_DIR_ENV)
    if explicit:
        return explicit
    if default_dir:
        return default_dir
    try:
        from ..training import checkpointing

        dirs = checkpointing.active_checkpoint_dirs()
        if dirs:
            return dirs[0]
    except Exception:
        pass
    from ..constants import SM_MODEL_DIR

    return os.environ.get(SM_MODEL_DIR) or "."


def dump_oom_forensics(exc, default_dir=None, top_n=32):
    """Write ``hbm-forensics-rank<r>.json`` for a device OOM: the error,
    a fresh allocator walk, the top live buffers by footprint, the compiled
    program's memory analysis, and the last watermark. Robustness path —
    runs regardless of ``SM_DEVICE_TELEMETRY`` (an OOM'd job's last act
    should always name the buffers that killed it). Never raises; returns
    the path or None."""
    try:
        from . import tracing

        rank = tracing.get_rank()
        doc = {
            "reason": "device_oom",
            "rank": rank,
            "error": str(exc)[:2000],
        }
        try:
            doc["memory"] = sample_device_memory(max_age_s=0.0)
        except Exception:
            pass
        try:
            doc["top_live_buffers"] = _top_live_buffers(top_n)
        except Exception:
            pass
        with _state_lock:
            if _last_compiled is not None:
                doc["compiled"] = dict(_last_compiled)
            if _last_watermark is not None:
                doc["last_watermark"] = dict(_last_watermark)
                doc["watermark_high_bytes"] = _watermark_high
        directory = _forensics_dir(default_dir)
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, "hbm-forensics-rank{}.json".format(rank))
        with open(path, "w") as f:
            json.dump(doc, f, default=str)
            f.write("\n")
        logger.error(
            "device OOM: HBM forensics (top live buffers, allocator stats, "
            "compiled memory analysis) dumped to %s", path
        )
        return path
    except Exception:
        logger.exception("HBM forensics dump failed; aborting anyway")
        return None


def _reset_for_tests():
    global _last_compiled, _last_watermark, _watermark_high, _sample_cache
    with _state_lock:
        _last_compiled = None
        _last_watermark = None
        _watermark_high = 0
    with _sample_lock:
        _sample_cache = None
