"""Cluster telemetry plane: per-host heartbeats, rank-0 aggregation,
straggler/failure detection, and device-runtime gauges.

PR 1 gave every *process* a metrics registry; the north-star workload
(Criteo-1TB on a v5p-32 pod) is a multi-host job, and above the single
process it was a black box: no host emitted liveness, rank 0 could not see
per-host round latencies, and a wedged host was indistinguishable from a
slow job. The reference container's only cluster signal was Rabit tracker
wall-clock log lines (SURVEY.md §5). This module layers a proper telemetry
plane on the two things PR 0/PR 1 already built:

* the length-prefixed JSON framing of the rendezvous channel
  (``parallel/distributed.py`` — ``frame_message``/``recv_message``), reused
  verbatim as the heartbeat wire format;
* the PR-1 registry, which rank 0 folds heartbeats into as
  per-rank-labelled ``cluster_*`` gauges served through the existing
  Prometheus exposition.

Topology: every participating host runs a **HeartbeatSender** daemon that
each ``SM_HEARTBEAT_INTERVAL_S`` connects to rank 0's **HeartbeatAggregator**
and sends one framed JSON payload (round counter, round-latency p50/p95,
RSS, live device bytes, XLA compile totals, uptime). Sends are
fire-and-forget: bounded connect/send timeouts, exponential backoff after
failures, one warning per outage episode — a dead or absent aggregator can
never stall the training loop (the sender is not even on the round-loop
thread). Rank 0 additionally detects **stragglers** (a host whose last
round latency exceeds ``SM_STRAGGLER_FACTOR`` x the cluster median) and
**stale hosts** (``SM_STALE_HEARTBEATS`` missed intervals), each warned
once per episode and emitted as ``cluster.straggler`` / ``cluster.host_stale``
structured records.

Everything is env-gated: with ``SM_HEARTBEAT_INTERVAL_S`` unset the plane
is completely inert — ``start_cluster_telemetry`` returns ``None`` without
creating a single thread or socket.
"""

import collections
import logging
import os
import socket
import threading
import time

from ..parallel.distributed import frame_message
from ..utils.envconfig import env_float, env_int, env_port
from . import tracing
from .emit import emit_metric
from .registry import REGISTRY, percentile

logger = logging.getLogger(__name__)

HEARTBEAT_INTERVAL_ENV = "SM_HEARTBEAT_INTERVAL_S"
HEARTBEAT_PORT_ENV = "SM_HEARTBEAT_PORT"
HEARTBEAT_TIMEOUT_ENV = "SM_HEARTBEAT_TIMEOUT_S"
CLUSTER_METRICS_ENV = "SM_CLUSTER_METRICS"
STRAGGLER_FACTOR_ENV = "SM_STRAGGLER_FACTOR"
STALE_HEARTBEATS_ENV = "SM_STALE_HEARTBEATS"

# NOT 9100: that's node_exporter's well-known port, and a Prometheus
# scraper probing it would talk HTTP at the heartbeat framing
DEFAULT_HEARTBEAT_PORT = 9199
HEARTBEAT_VERSION = 1

# sender backoff never sleeps longer than this between attempts, so a
# recovered aggregator sees heartbeats again within a bounded delay
_MAX_BACKOFF_S = 60.0

# a heartbeat payload is <1KB of JSON; anything bigger is a stray client
# (an HTTP request line parses as a ~500MB u32 length) — reject before
# allocating or blocking on it
_MAX_FRAME_BYTES = 1 << 20


def heartbeat_interval():
    return env_float(HEARTBEAT_INTERVAL_ENV, 0.0, minimum=0.0)


def heartbeat_timeout():
    return env_float(HEARTBEAT_TIMEOUT_ENV, 2.0, minimum=0.1, maximum=30.0)


def straggler_factor():
    return env_float(STRAGGLER_FACTOR_ENV, 3.0, minimum=1.0)


def stale_heartbeats():
    return env_int(STALE_HEARTBEATS_ENV, 3, minimum=1)


# --------------------------------------------------------------- round state
class RoundState:
    """Thread-safe bridge between the training round loop and the heartbeat.

    ``RoundTimer.after_iteration`` calls :meth:`note_round` (always — the
    cost is a deque append under a lock); the sender snapshots it each
    interval. Bounded: only the most recent ``maxlen`` round times are kept
    for the p50/p95, so a week-long job costs the same bytes as a minute.

    The process-wide ``ROUND_STATE`` is last-writer-wins: sequential k-fold
    CV feeds it fold-by-fold (the heartbeat reflects the fold currently
    training, which is the honest liveness signal). There is no concurrent
    multi-fold RoundTimer path in-repo today; if one appears, its timers
    should carry private RoundStates rather than interleave this one.
    """

    def __init__(self, maxlen=512):
        self._lock = threading.Lock()
        self._times_ms = collections.deque(maxlen=maxlen)
        self._round = -1
        self._total = 0

    def note_round(self, round_index, elapsed_s):
        with self._lock:
            self._round = int(round_index)
            self._total += 1
            self._times_ms.append(float(elapsed_s) * 1000.0)

    def reset(self):
        with self._lock:
            self._times_ms.clear()
            self._round = -1
            self._total = 0

    def snapshot(self):
        """-> dict(round, rounds_total, last_round_ms, round_ms_p50/_p95)."""
        with self._lock:
            times = list(self._times_ms)
            rnd = self._round
            total = self._total
        if times:
            return {
                "round": rnd,
                "rounds_total": total,
                "last_round_ms": round(times[-1], 3),
                "round_ms_p50": round(percentile(times, 0.5), 3),
                "round_ms_p95": round(percentile(times, 0.95), 3),
            }
        return {
            "round": rnd,
            "rounds_total": total,
            "last_round_ms": 0.0,
            "round_ms_p50": 0.0,
            "round_ms_p95": 0.0,
        }


ROUND_STATE = RoundState()


# ------------------------------------------------------ device-runtime gauges
_runtime_lock = threading.Lock()
_compile_listener_installed = False
_compile_stats = {"count": 0, "seconds": 0.0}


def _on_jax_duration_event(event, duration, **_kwargs):
    # backend_compile_duration is the actual XLA compile; the other
    # /jax/core/compile/* events (tracing, MLIR lowering) are host-side prep
    if not event.endswith("backend_compile_duration"):
        return
    with _runtime_lock:
        _compile_stats["count"] += 1
        _compile_stats["seconds"] += float(duration)
    REGISTRY.counter(
        "xla_compile_total", help="XLA backend compilations"
    ).inc()
    REGISTRY.counter(
        "xla_compile_seconds_total", help="Cumulative XLA backend compile time"
    ).inc(float(duration))
    # with tracing armed, the compile becomes a span too: it lands under
    # whatever span is open on the dispatching thread (the round span for a
    # first-round compile), so compile time stops masquerading as build_eval
    tracing.record_compile(float(duration))


def register_runtime_gauges():
    """Install the ``jax.monitoring`` compile listener (idempotent, and a
    no-op when jax is absent — CPU-only paths keep working) and prime the
    process gauges. Adds zero threads; call at training and serving startup.
    """
    global _compile_listener_installed
    with _runtime_lock:
        already = _compile_listener_installed
        _compile_listener_installed = True
    if not already:
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(_on_jax_duration_event)
        except Exception:  # jax absent or monitoring API unavailable: no-op
            logger.debug("jax.monitoring unavailable; compile gauges disabled")
    refresh_runtime_gauges()


def compile_stats():
    with _runtime_lock:
        return dict(_compile_stats)


def _rss_bytes():
    try:
        import psutil

        return int(psutil.Process().memory_info().rss)
    except Exception:
        pass
    try:
        import resource

        # ru_maxrss is KB on Linux — high-water mark, not current, but an
        # honest upper bound when psutil is missing
        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024
    except Exception:
        return 0


def _open_fds():
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        try:
            import psutil

            return int(psutil.Process().num_fds())
        except Exception:
            return 0


def _device_live_bytes():
    """Live device buffer bytes: per-device allocator stats when the backend
    exposes them (TPU), else the sum of live jax array footprints. The walk
    itself lives in ``telemetry/device.py`` behind a short-lived cache so
    the heartbeat sender, the per-round HBM watermark, and ``/status`` pay
    at most one O(live-buffers) sweep per interval between them."""
    try:
        from . import device

        return int(device.sample_device_memory()["total_bytes_in_use"])
    except Exception:
        return 0


def runtime_snapshot():
    """-> dict of host/device runtime stats for the heartbeat payload."""
    comp = compile_stats()
    return {
        "rss_bytes": _rss_bytes(),
        "open_fds": _open_fds(),
        "threads": threading.active_count(),
        "device_bytes": _device_live_bytes(),
        "compile_count": comp["count"],
        "compile_seconds": round(comp["seconds"], 3),
    }


def refresh_runtime_gauges(registry=None):
    """Write the current runtime snapshot into process-level gauges. Called
    by the sender each interval and by the /metrics surfaces right before
    rendering, so scrapes always see fresh values. Safe to call anytime."""
    reg = registry or REGISTRY
    snap = runtime_snapshot()
    reg.gauge("process_rss_bytes", help="Resident set size").set(snap["rss_bytes"])
    reg.gauge("process_open_fds", help="Open file descriptors").set(snap["open_fds"])
    reg.gauge("process_threads", help="Live Python threads").set(snap["threads"])
    reg.gauge(
        "device_live_bytes", help="Live device buffer bytes (allocator or live arrays)"
    ).set(snap["device_bytes"])
    return snap


# ------------------------------------------------------------------- sender
class HeartbeatSender:
    """Per-host heartbeat daemon: one framed JSON payload per interval to
    the rank-0 aggregator. Fire-and-forget — bounded connect/send timeouts,
    exponential backoff while the aggregator is unreachable, one warning
    per outage episode — so a dead aggregator costs warnings, never rounds.
    """

    def __init__(
        self,
        rank,
        host,
        aggregator_addr,
        interval,
        timeout=None,
        round_state=None,
        registry=None,
    ):
        self.rank = rank
        self.host = host
        self.aggregator_addr = aggregator_addr
        self.interval = float(interval)
        self.timeout = timeout if timeout is not None else heartbeat_timeout()
        self.round_state = round_state or ROUND_STATE
        self._reg = registry or REGISTRY
        self._started_at = time.monotonic()
        self._stop = threading.Event()
        self._delay = self.interval
        self._outage = False
        labels = {"rank": str(rank)}
        self._m_sent = self._reg.counter(
            "cluster_heartbeats_sent_total", "Heartbeats delivered to rank 0", labels
        )
        self._m_failed = self._reg.counter(
            "cluster_heartbeat_failures_total",
            "Heartbeat sends that failed (aggregator unreachable)",
            labels,
        )
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="cluster-heartbeat-send"
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        self._thread.join(timeout)

    def build_payload(self, runtime=None):
        payload = {
            "type": "heartbeat",
            "v": HEARTBEAT_VERSION,
            "rank": self.rank,
            "host": self.host,
            "uptime_s": round(time.monotonic() - self._started_at, 1),
        }
        payload.update(self.round_state.snapshot())
        payload.update(runtime if runtime is not None else runtime_snapshot())
        return payload

    def send_once(self):
        """One bounded-timeout delivery attempt; returns True on success.
        Never raises — delivery failure is an expected, counted condition."""
        # one runtime sweep per interval, shared by the local gauges and the
        # payload (live_arrays() is O(live buffers) — don't sample it twice)
        runtime = refresh_runtime_gauges(self._reg)
        try:
            sock = socket.create_connection(self.aggregator_addr, timeout=self.timeout)
            try:
                sock.settimeout(self.timeout)
                sock.sendall(frame_message(self.build_payload(runtime)))
            finally:
                sock.close()
        except OSError as e:
            self._m_failed.inc()
            if not self._outage:
                self._outage = True
                logger.warning(
                    "heartbeat to %s:%s failed (%s); backing off — training "
                    "continues, further failures counted in "
                    "cluster_heartbeat_failures_total",
                    self.aggregator_addr[0],
                    self.aggregator_addr[1],
                    e,
                )
            # cap backoff below the default stale cutoff (3x interval): a
            # transient send failure must never silence a healthy host long
            # enough for rank 0 to declare it stale
            self._delay = min(
                max(self._delay * 2, self.interval),
                2.0 * self.interval,
                _MAX_BACKOFF_S,
            )
            return False
        self._m_sent.inc()
        if self._outage:
            self._outage = False
            logger.info("heartbeat delivery to rank 0 recovered")
        self._delay = self.interval
        return True

    def _run(self):
        while not self._stop.wait(self._delay):
            self.send_once()


def _recv_frame_bounded(sock, timeout):
    """One frame under a TOTAL deadline (trickle-proof) with the heartbeat
    size cap. The deadline machinery lives in ``recv_message_bounded``
    (parallel/distributed.py) — one implementation for every control-plane
    reader (rendezvous, heartbeats, abort frames)."""
    from ..parallel.distributed import recv_message_bounded

    return recv_message_bounded(sock, timeout, max_bytes=_MAX_FRAME_BYTES)


# --------------------------------------------------------------- aggregator
class HeartbeatAggregator:
    """Rank-0 side: accept heartbeats, fold them into per-rank ``cluster_*``
    gauges, and once per interval evaluate straggler/stale conditions and
    emit one ``cluster.heartbeat`` structured record."""

    def __init__(
        self,
        num_hosts,
        interval,
        port=0,
        registry=None,
        factor=None,
        stale_after=None,
        hosts=None,
        on_stale=None,
    ):
        self.num_hosts = num_hosts
        self.interval = float(interval)
        self.factor = factor if factor is not None else straggler_factor()
        self.stale_after = stale_after if stale_after is not None else stale_heartbeats()
        # detection -> action hook: called once per stale episode with
        # (rank, host, age_s). The supervision layer (training/watchdog.py)
        # plugs coordinate_abort in here; default None keeps PR-2 semantics
        # (observe + warn only).
        self.on_stale = on_stale
        self._reg = registry or REGISTRY
        self._stop = threading.Event()
        self._lock = threading.Lock()
        now = time.monotonic()
        # every expected rank starts "seen now": a host that never reports
        # goes stale after the same grace period as one that died mid-run
        self._hosts = {
            r: {
                "host": (hosts[r] if hosts and r < len(hosts) else None),
                "last_seen": now,
                "count": 0,
                "payload": None,
                "straggling": False,
                "stale": False,
            }
            for r in range(num_hosts)
        }
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("0.0.0.0", port))
        self._server.listen(max(num_hosts, 8))
        self._server.settimeout(min(0.2, self.interval / 4 or 0.2))
        self.port = self._server.getsockname()[1]
        self._reg.gauge("cluster_expected_hosts", "Hosts in the training cluster").set(
            num_hosts
        )
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="cluster-heartbeat-agg"
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        self._thread.join(timeout)
        try:
            self._server.close()
        except OSError:
            pass

    # ------------------------------------------------------------ fold path
    def _gauge(self, name, help_text, rank):
        return self._reg.gauge(name, help_text, {"rank": str(rank)})

    def fold(self, payload):
        """Fold one heartbeat payload into the registry; junk is dropped."""
        if not isinstance(payload, dict) or payload.get("type") != "heartbeat":
            return False
        try:
            rank = int(payload["rank"])
        except (KeyError, TypeError, ValueError):
            return False
        if not 0 <= rank < self.num_hosts:
            logger.warning("dropping heartbeat from unknown rank %r", rank)
            return False
        with self._lock:
            entry = self._hosts[rank]
            entry["last_seen"] = time.monotonic()
            entry["count"] += 1
            entry["payload"] = payload
            if payload.get("host"):
                entry["host"] = payload["host"]
        self._reg.counter(
            "cluster_heartbeats_received_total",
            "Heartbeats folded in by rank 0",
            {"rank": str(rank)},
        ).inc()
        for name, help_text, key in (
            ("cluster_round", "Last boosting round reported by the host", "round"),
            ("cluster_last_round_ms", "Host's most recent round latency", "last_round_ms"),
            ("cluster_round_ms_p50", "Host's rolling round latency p50", "round_ms_p50"),
            ("cluster_round_ms_p95", "Host's rolling round latency p95", "round_ms_p95"),
            ("cluster_rss_bytes", "Host resident set size", "rss_bytes"),
            ("cluster_device_bytes", "Host live device buffer bytes", "device_bytes"),
            ("cluster_open_fds", "Host open file descriptors", "open_fds"),
            ("cluster_threads", "Host live Python threads", "threads"),
            ("cluster_compile_count", "Host XLA compiles so far", "compile_count"),
            ("cluster_compile_seconds", "Host cumulative XLA compile time", "compile_seconds"),
            ("cluster_uptime_seconds", "Host heartbeat-daemon uptime", "uptime_s"),
        ):
            value = payload.get(key)
            if isinstance(value, (int, float)):
                self._gauge(name, help_text, rank).set(value)
        return True

    # ------------------------------------------------------- detection path
    def evaluate(self):
        """One detection tick: heartbeat ages, stale hosts, stragglers, and
        the per-interval ``cluster.heartbeat`` record."""
        now = time.monotonic()
        stale_cutoff = self.stale_after * self.interval
        with self._lock:
            entries = {r: dict(e) for r, e in self._hosts.items()}
        latencies = {}
        reporting = 0
        rounds = {}
        for rank, entry in entries.items():
            age = now - entry["last_seen"]
            self._gauge(
                "cluster_heartbeat_age_seconds",
                "Seconds since the host's last heartbeat",
                rank,
            ).set(round(age, 3))
            payload = entry["payload"]
            is_stale = age > stale_cutoff
            if not is_stale and payload is not None:
                reporting += 1
            if payload is not None:
                rounds[str(rank)] = payload.get("round", -1)
                # compare rolling p50s, not single rounds: one GC-paused
                # round must not flag a healthy host (especially at n=2,
                # where the comparison is against a single peer); a real
                # straggler drags its p50 within ~half a state window
                p50_ms = payload.get("round_ms_p50") or 0.0
                last_ms = payload.get("last_round_ms") or 0.0
                candidate = float(p50_ms if p50_ms > 0 else last_ms)
                if not is_stale and candidate > 0:
                    latencies[rank] = candidate
            self._set_episode(rank, entry, "stale", is_stale, now=now, age=age)
        median_ms = percentile(list(latencies.values()), 0.5) if latencies else 0.0
        if len(latencies) >= 2:
            for rank, cand_ms in latencies.items():
                # median of the PEERS, excluding the candidate: an
                # all-ranks median contains the straggler's own latency,
                # which at n=2 makes the trigger algebraically impossible
                # (b > factor*(a+b)/2 has no solution for factor >= 2)
                peer_median = percentile(
                    [v for r, v in latencies.items() if r != rank], 0.5
                )
                is_straggler = peer_median > 0 and cand_ms > self.factor * peer_median
                self._set_episode(
                    rank,
                    entries[rank],
                    "straggling",
                    is_straggler,
                    round_ms=cand_ms,
                    median_ms=peer_median,
                )
        else:
            # a 1-host "cluster" (or nobody reporting) has no peers to
            # compare against; clear any leftover episode flags
            for rank in latencies:
                self._set_episode(rank, entries[rank], "straggling", False)
        self._reg.gauge(
            "cluster_reporting_hosts", "Hosts with a fresh heartbeat"
        ).set(reporting)
        emit_metric(
            "cluster.heartbeat",
            hosts=self.num_hosts,
            reporting=reporting,
            median_round_ms=round(median_ms, 3),
            rounds=rounds,
        )

    def _set_episode(self, rank, entry, kind, active, **fields):
        """Edge-triggered episode bookkeeping: warn + emit once when a rank
        enters a bad state, log recovery once when it leaves."""
        with self._lock:
            was = self._hosts[rank][kind]
            self._hosts[rank][kind] = active
        if active == was:
            return
        host = entry.get("host") or "rank-{}".format(rank)
        if kind == "stale":
            counter = self._reg.counter(
                "cluster_stale_episodes_total",
                "Times a host went stale (missed heartbeats)",
                {"rank": str(rank)},
            )
            if active:
                counter.inc()
                age = fields.get("age", 0.0)
                logger.warning(
                    "host %s (rank %d) is stale: no heartbeat for %.1fs "
                    "(threshold %.1fs) — wedged host or network partition",
                    host,
                    rank,
                    age,
                    self.stale_after * self.interval,
                )
                emit_metric(
                    "cluster.host_stale",
                    rank=rank,
                    host=host,
                    age_s=round(age, 1),
                    threshold_s=round(self.stale_after * self.interval, 1),
                )
                if self.on_stale is not None:
                    try:
                        self.on_stale(rank, host, age)
                    except Exception:
                        logger.exception("on_stale hook failed; detection continues")
            else:
                logger.info("host %s (rank %d) heartbeats resumed", host, rank)
        else:
            counter = self._reg.counter(
                "cluster_straggler_episodes_total",
                "Times a host entered a straggler episode",
                {"rank": str(rank)},
            )
            if active:
                counter.inc()
                round_ms = fields.get("round_ms", 0.0)
                median_ms = fields.get("median_ms", 0.0)
                logger.warning(
                    "host %s (rank %d) is straggling: round latency p50 "
                    "%.1f ms vs peer median %.1f ms (factor %.1fx > %.1fx "
                    "threshold)",
                    host,
                    rank,
                    round_ms,
                    median_ms,
                    round_ms / median_ms if median_ms else float("inf"),
                    self.factor,
                )
                emit_metric(
                    "cluster.straggler",
                    rank=rank,
                    host=host,
                    round_ms=round(round_ms, 3),
                    median_round_ms=round(median_ms, 3),
                    factor=round(round_ms / median_ms, 2) if median_ms else 0.0,
                )
            else:
                logger.info("host %s (rank %d) caught back up", host, rank)

    # -------------------------------------------------------------- accept
    def _run(self):
        next_eval = time.monotonic() + self.interval
        while not self._stop.is_set():
            try:
                conn, _addr = self._server.accept()
            except socket.timeout:
                pass
            except OSError:
                break  # socket closed under us
            else:
                try:
                    self.fold(_recv_frame_bounded(conn, heartbeat_timeout()))
                except Exception as e:
                    logger.debug("dropping malformed heartbeat: %s", e)
                finally:
                    try:
                        conn.close()
                    except OSError:
                        pass
            if time.monotonic() >= next_eval:
                try:
                    self.evaluate()
                except Exception:
                    logger.exception("cluster evaluation failed; continuing")
                next_eval = time.monotonic() + self.interval
        try:
            self._server.close()
        except OSError:
            pass


# -------------------------------------------------------- metrics exposition
class ClusterMetricsServer:
    """Tiny Prometheus endpoint on the ``SM_CLUSTER_METRICS`` port (rank 0).

    The serving stack's ``GET /metrics`` rides the inference port and its
    WSGI middleware; training jobs have no HTTP surface at all, so the
    cluster plane brings its own single-purpose server rendering the same
    registry exposition.
    """

    def __init__(self, port, registry=None):
        from wsgiref.simple_server import WSGIRequestHandler, make_server

        from .prometheus import exposition_response

        reg = registry or REGISTRY

        def app(environ, start_response):
            if environ.get("PATH_INFO") in ("/", "/metrics"):
                status, headers, body = exposition_response(
                    reg, refresh_runtime_gauges
                )
                start_response(status, headers)
                return [body]
            body = b"not found"
            start_response(
                "404 Not Found",
                [("Content-Type", "text/plain"), ("Content-Length", str(len(body)))],
            )
            return [body]

        class _Quiet(WSGIRequestHandler):
            def log_message(self, fmt, *args):
                logger.debug("%s - %s", self.address_string(), fmt % args)

        self._httpd = make_server("0.0.0.0", port, app, handler_class=_Quiet)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="cluster-metrics-http"
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._httpd.shutdown()
        self._thread.join(timeout)
        self._httpd.server_close()


# ---------------------------------------------------------------- lifecycle
class ClusterTelemetry:
    """Handle bundling this host's cluster-plane components."""

    def __init__(self, rank, sender=None, aggregator=None, metrics_server=None):
        self.rank = rank
        self.sender = sender
        self.aggregator = aggregator
        self.metrics_server = metrics_server

    def stop(self, timeout=5.0):
        global _active_plane
        for part in (self.sender, self.metrics_server, self.aggregator):
            if part is not None:
                try:
                    part.stop(timeout)
                except Exception:
                    logger.exception("error stopping cluster telemetry component")
        with _plane_lock:
            if _active_plane is self:
                _active_plane = None


_plane_lock = threading.Lock()
_active_plane = None


def stop_cluster_telemetry():
    """Stop the active cluster plane (if any): membership-reform teardown —
    the sender/aggregator carry the OLD world's ranks and must rebind over
    the survivor list — and test cleanup. Safe to call when inert."""
    global _active_plane
    with _plane_lock:
        plane, _active_plane = _active_plane, None
    if plane is not None:
        plane.stop()


def start_cluster_telemetry(hosts, current_host, registry=None):
    """Bring up this host's share of the cluster plane; the single wiring
    entrypoint called from the distributed-training path.

    Inert unless ``SM_HEARTBEAT_INTERVAL_S`` is set > 0: returns ``None``
    having created no thread, no socket, and no registry series. Rank 0
    gets the aggregator (and, when ``SM_CLUSTER_METRICS`` names a port, the
    Prometheus endpoint); every rank — including 0, over loopback, for one
    uniform code path — gets a sender.

    One plane per process: a second call (in-process retry, test harness)
    stops the previous instance first, so the heartbeat port re-binds
    cleanly and the same rank never heartbeats twice.
    """
    global _active_plane
    interval = heartbeat_interval()
    if interval <= 0:
        return None
    with _plane_lock:
        prev, _active_plane = _active_plane, None
    if prev is not None:
        logger.info("restarting cluster telemetry (previous plane stopped)")
        prev.stop()
    register_runtime_gauges()
    ordered = sorted(hosts)
    rank = ordered.index(current_host)
    port = env_port(HEARTBEAT_PORT_ENV, DEFAULT_HEARTBEAT_PORT)
    aggregator = None
    metrics_server = None
    if rank == 0:
        on_stale = None
        from ..training.elastic import is_active as elastic_active
        from ..training.watchdog import abort_on_stale_enabled

        if abort_on_stale_enabled() or elastic_active():
            # promote detection into action: the supervision layer decides
            # between a shrink-to-continue (SM_ELASTIC) and the legacy
            # coordinated abort, once per stale episode. Lazy import inside
            # the hook keeps the telemetry package import-cycle-free.
            def on_stale(stale_rank, stale_host, age_s):
                from ..training.watchdog import handle_stale_host

                handle_stale_host(
                    ordered, current_host, stale_rank, stale_host, age_s
                )

        try:
            aggregator = HeartbeatAggregator(
                num_hosts=len(ordered),
                interval=interval,
                port=port,
                registry=registry,
                hosts=ordered,
                on_stale=on_stale,
            ).start()
        except OSError as e:
            logger.warning(
                "cluster aggregator could not bind port %d (%s); heartbeats "
                "from workers will be dropped but training continues",
                port,
                e,
            )
        metrics_port = env_int(CLUSTER_METRICS_ENV, 0, minimum=0, maximum=65535)
        if metrics_port:
            try:
                metrics_server = ClusterMetricsServer(metrics_port, registry=registry).start()
                logger.info(
                    "cluster Prometheus exposition on port %d", metrics_server.port
                )
            except OSError as e:
                logger.warning("cluster metrics port %d unavailable: %s", metrics_port, e)
    target_host = "127.0.0.1" if rank == 0 else ordered[0]
    sender = HeartbeatSender(
        rank=rank,
        host=current_host,
        aggregator_addr=(target_host, port),
        interval=interval,
        registry=registry,
    ).start()
    logger.info(
        "cluster telemetry up: rank %d/%d, heartbeat every %.1fs to %s:%d%s",
        rank,
        len(ordered),
        interval,
        target_host,
        port,
        " (aggregating)" if aggregator else "",
    )
    plane = ClusterTelemetry(
        rank=rank, sender=sender, aggregator=aggregator, metrics_server=metrics_server
    )
    with _plane_lock:
        _active_plane = plane
    return plane
