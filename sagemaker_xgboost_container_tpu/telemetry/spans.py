"""Span API: time named phases into the registry, stdout records, and an
optional per-round phase accumulator.

Three consumers, one call site:

* ``span("data_ingest", emit=True)`` — one-off phases (algorithm_train's
  ingest/train/save) record a ``training.phase`` stdout line and a
  ``training_phase_seconds{phase=...}`` histogram observation.
* ``PhaseRecorder`` — per-round breakdown: while a recorder is installed on
  this thread (``RoundTimer`` installs one for the whole training run), every
  finished span also accumulates into it; the timer drains it each round so
  the round record carries ``phases_ms``.
* the registry — every span observes ``training_phase_seconds`` so phase
  latencies show up in ``/metrics`` exposition too.

Recorders are thread-local: the booster's callback loop is single-threaded,
and parallel serving threads never share a recorder by accident.

With hierarchical tracing armed (``SM_TRACE``, telemetry/tracing.py) every
``span()`` additionally opens a tracer span, so existing call sites upgrade
in place: the flat per-round phases become children of the per-round root
span RoundTimer owns. Disabled (the default), the only added cost is one
cached-boolean check.
"""

import contextlib
import threading
import time

from . import tracing
from .emit import emit_metric
from .registry import REGISTRY

_tls = threading.local()

PHASE_HISTOGRAM = "training_phase_seconds"


class PhaseRecorder:
    """Accumulates ``{phase: seconds}`` between drains (single-thread use)."""

    def __init__(self):
        self.phases = {}

    def add(self, name, seconds):
        self.phases[name] = self.phases.get(name, 0.0) + seconds

    def drain(self):
        drained, self.phases = self.phases, {}
        return drained


def _stack():
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def push_recorder(recorder=None):
    """Install a recorder on this thread; pair with ``pop_recorder``."""
    recorder = recorder or PhaseRecorder()
    _stack().append(recorder)
    return recorder


def pop_recorder(recorder):
    stack = _stack()
    if recorder in stack:
        stack.remove(recorder)


def active_recorder():
    stack = _stack()
    return stack[-1] if stack else None


@contextlib.contextmanager
def span(name, emit=False, registry=None):
    """Time the enclosed block as phase ``name``.

    The duration always lands in the phase histogram and in this thread's
    active ``PhaseRecorder`` (if any); ``emit=True`` additionally writes one
    ``training.phase`` stdout record — use it for one-off phases, never for
    per-round work (the round record owns that).
    """
    tspan = tracing.start_span(name) if tracing.enabled() else None
    start = time.perf_counter()
    try:
        yield
    finally:
        elapsed = time.perf_counter() - start
        if tspan is not None:
            tracing.finish_span(tspan)
        (registry or REGISTRY).histogram(
            PHASE_HISTOGRAM,
            help="Wall time of named training phases",
            labels={"phase": name},
        ).observe(elapsed)
        recorder = active_recorder()
        if recorder is not None:
            recorder.add(name, elapsed)
        if emit:
            emit_metric(
                "training.phase", phase=name, seconds=round(elapsed, 6)
            )
