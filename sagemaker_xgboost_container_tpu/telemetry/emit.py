"""Structured single-line JSON metric records on stdout.

This is the CloudWatch metric-definition surface: SageMaker training jobs
declare ``{"Name": ..., "Regex": ...}`` pairs and CloudWatch scrapes the
container's stdout with them (the reference's only metric contract —
SURVEY §5). One record per line, compact JSON, ``"metric"`` first, remaining
keys sorted — so a regex like ``"round_ms": ([0-9.]+)`` is stable across
releases. Records never contain tabs, keeping them disjoint from the HPO
eval-line contract (``[<iter>]\\t<data>-<metric>:<value>``).

``SM_STRUCTURED_METRICS=false`` silences every record (default on).
"""

import json
import sys
import threading

from ..utils.envconfig import env_bool

STRUCTURED_METRICS_ENV = "SM_STRUCTURED_METRICS"

_write_lock = threading.Lock()

# Extra fields merged into every ``training.round`` record (see
# profiling.RoundTimer). Set by the training session for facts only it
# knows (e.g. the histogram-collective lowering and its per-round wire
# bytes — GRAFT_HIST_COMM); process-wide like ROUND_STATE, last writer
# wins, which matches sequential training sessions.
_round_fields = {}
_round_fields_lock = threading.Lock()


def set_round_fields(**fields):
    """Merge fields into the per-round record; a value of None removes
    the key (so a later single-device session clears a mesh session's
    comm fields instead of reporting them stale)."""
    with _round_fields_lock:
        for key, value in fields.items():
            if value is None:
                _round_fields.pop(key, None)
            else:
                _round_fields[key] = value


def get_round_fields():
    """Snapshot of the extra per-round fields (copy — safe to mutate)."""
    with _round_fields_lock:
        return dict(_round_fields)


def structured_enabled():
    return env_bool(STRUCTURED_METRICS_ENV, True)


def _jsonable(value):
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return str(value)


def emit_metric(metric, **fields):
    """Write one structured record; no-op when disabled. Returns the line
    (or None) so callers/tests can assert on it without re-capturing stdout."""
    if not structured_enabled():
        return None
    record = {"metric": metric}
    for key in sorted(fields):
        record[key] = _jsonable(fields[key])
    line = json.dumps(record, separators=(", ", ": "))
    with _write_lock:
        sys.stdout.write(line + "\n")
        sys.stdout.flush()
    return line


def snapshot_fields(registry):
    """Flatten a registry into scalar fields for one snapshot record.

    Counters/gauges become ``name{k=v,...}`` keys; histograms contribute
    ``_count``/``_sum`` plus p50/p95 estimates. Used by the serving-side
    periodic reporter (SM_METRICS_EMIT_INTERVAL_S) so CloudWatch can scrape
    serving metrics without a Prometheus stack.
    """
    fields = {}
    for name, kind, _help, series in registry.collect():
        for metric in series:
            suffix = (
                "{" + ",".join(
                    "{}={}".format(k, v) for k, v in sorted(metric.labels.items())
                ) + "}"
                if metric.labels
                else ""
            )
            key = name + suffix
            if kind == "histogram":
                fields[key + "_count"] = metric.count
                fields[key + "_sum"] = round(metric.sum, 6)
                if metric.count:
                    fields[key + "_p50"] = round(metric.quantile(0.5), 6)
                    fields[key + "_p95"] = round(metric.quantile(0.95), 6)
            else:
                fields[key] = metric.value
    return fields
