#!/usr/bin/env python
"""Serving latency benchmark: p50/p99 of POST /invocations + restart churn.

BASELINE.md's second metric ("p50 serve-predict latency"). Runs the real
threaded WSGI server in-process against a trained abalone-sized model and
measures end-to-end HTTP latency for single-row csv payloads, then a batch
payload, then a **churn leg**: a rolling SIGTERM-restart cycle (graceful
drain via serving/lifecycle.py) under continuous client load, reporting the
p95 and error rate a fleet would see across deploys. Prints one JSON line
(not the driver contract — bench.py is that; this is the measurement tool
for serving work).
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

N_REQUESTS = int(os.getenv("BENCH_SERVE_REQUESTS", "300"))
CHURN_CYCLES = int(os.getenv("BENCH_SERVE_CHURN_CYCLES", "3"))
STEADY_SECONDS = float(os.getenv("BENCH_SERVE_STEADY_S", "3"))


def _steady_leg(model_dir, single_payload):
    """Steady-state RPS/SLO leg (ROADMAP item 3's "steady-state RPS/SLO
    line"): a fresh server with the SLO window armed, two client threads at
    sustained load, reporting throughput and the window's own p95 /
    violation-rate view -> (steady_rps, slo_p95_ms, slo_violation_rate).

    The SLO target honors the operator's SM_SLO_P95_MS; unset, it defaults
    to 50 ms so the leg always exercises the violation accounting.
    """
    import urllib.request
    from wsgiref.simple_server import make_server

    from sagemaker_xgboost_container_tpu.serving.app import ScoringService, make_app
    from sagemaker_xgboost_container_tpu.serving.server import (
        _QuietHandler,
        _ThreadedWSGIServer,
    )
    from sagemaker_xgboost_container_tpu.telemetry import slo

    prior_target = os.environ.get(slo.SLO_P95_ENV)
    os.environ.setdefault(slo.SLO_P95_ENV, "50")
    slo._reset_for_tests()  # fresh window regardless of earlier legs
    app = make_app(ScoringService(model_dir))  # instrument_wsgi arms the SLO
    httpd = make_server(
        "127.0.0.1", 0, app,
        server_class=_ThreadedWSGIServer, handler_class=_QuietHandler,
    )
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = "http://127.0.0.1:{}/invocations".format(port)
    stop = threading.Event()
    counts = []
    lock = threading.Lock()

    def client():
        n = 0
        while not stop.is_set():
            req = urllib.request.Request(
                url, data=single_payload, method="POST",
                headers={"Content-Type": "text/csv"},
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    resp.read()
                    n += 1
            except Exception:
                pass
        with lock:
            counts.append(n)

    clients = [threading.Thread(target=client, daemon=True) for _ in range(2)]
    t0 = time.perf_counter()
    for t in clients:
        t.start()
    time.sleep(STEADY_SECONDS)
    stop.set()
    for t in clients:
        t.join(timeout=15)
    elapsed = time.perf_counter() - t0
    httpd.shutdown()
    httpd.server_close()
    window = slo.active_window()
    snap = window.snapshot() if window is not None else {}
    slo._reset_for_tests()
    if prior_target is None:
        os.environ.pop(slo.SLO_P95_ENV, None)
    else:
        os.environ[slo.SLO_P95_ENV] = prior_target
    total = sum(counts)
    return (
        round(total / elapsed, 1) if elapsed > 0 else 0.0,
        snap.get("p95_ms", 0.0),
        snap.get("violation_rate", 0.0),
    )


def _churn_leg(model_dir, single_payload):
    """Rolling drain-restart cycles under load -> (p95_ms, error_rate, n).

    Each cycle: a fresh server + lifecycle, two client threads hammering
    /invocations, then a mid-traffic graceful drain (the SIGTERM sequence,
    invoked directly) and a restart. Non-200s and connection errors — the
    503s clients see while draining and the refused connects in the restart
    gap — count as errors: that's the fleet's view of a deploy.
    """
    import urllib.error
    import urllib.request
    from wsgiref.simple_server import make_server

    from sagemaker_xgboost_container_tpu.serving import lifecycle
    from sagemaker_xgboost_container_tpu.serving.app import ScoringService, make_app
    from sagemaker_xgboost_container_tpu.serving.server import (
        _QuietHandler,
        _ThreadedWSGIServer,
        drain_and_shutdown,
    )

    latencies = []
    outcomes = []  # True = 200 with a body
    lock = threading.Lock()

    for _cycle in range(CHURN_CYCLES):
        lc = lifecycle.install(lifecycle.ServingLifecycle())
        app = make_app(ScoringService(model_dir))
        httpd = make_server(
            "127.0.0.1", 0, app,
            server_class=_ThreadedWSGIServer, handler_class=_QuietHandler,
        )
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        url = "http://127.0.0.1:{}/invocations".format(port)
        stop = threading.Event()

        def client():
            while not stop.is_set():
                req = urllib.request.Request(
                    url, data=single_payload, method="POST",
                    headers={"Content-Type": "text/csv"},
                )
                t0 = time.perf_counter()
                try:
                    with urllib.request.urlopen(req, timeout=10) as resp:
                        resp.read()
                        ok = resp.status == 200
                except Exception:
                    ok = False
                elapsed = time.perf_counter() - t0
                with lock:
                    outcomes.append(ok)
                    if ok:
                        latencies.append(elapsed)
                if not ok:
                    # client retry backoff: without it a refused connect in
                    # the restart gap becomes a tight error loop that swamps
                    # the rate with thousands of sub-ms failures no real
                    # load-balancer client would issue
                    time.sleep(0.02)

        clients = [threading.Thread(target=client, daemon=True) for _ in range(2)]
        for t in clients:
            t.start()
        time.sleep(0.5)  # steady-state traffic
        drain_and_shutdown(httpd, lc)  # the SIGTERM sequence, in-process
        time.sleep(0.1)  # restart gap: connects here fail, and that counts
        stop.set()
        for t in clients:
            t.join(timeout=15)
        lifecycle.uninstall()

    total = len(outcomes)
    errors = total - sum(outcomes)
    lat = sorted(latencies)
    p95 = lat[max(0, int(len(lat) * 0.95) - 1)] * 1000 if lat else float("nan")
    return round(p95, 2), round(errors / total, 4) if total else 1.0, total


def _predict_compiled_cost(forest, num_feature, rows=256):
    """Compiled cost of the device predict kernel for one padded row bucket
    (the batch-256 leg's bucket): flops / bytes / HBM footprint via the same
    AOT introspection the training device window uses. Returns None when the
    forest is empty or introspection is unavailable."""
    import jax.numpy as jnp

    from sagemaker_xgboost_container_tpu.models.forest import predict_bucket
    from sagemaker_xgboost_container_tpu.ops.predict import (
        _forest_margin,
        _stacked_args,
    )
    from sagemaker_xgboost_container_tpu.telemetry import device as device_telemetry

    stacked = forest._stack(slice(0, len(forest.trees)))
    if stacked is None:
        return None
    bucket = predict_bucket(rows)
    x = jnp.zeros((bucket, num_feature), jnp.float32)
    lowered = _forest_margin.lower(
        *_stacked_args(stacked, "leaf_value"), x, stacked["depth"]
    )
    cost = device_telemetry.cost_from_compiled(lowered.compile())
    cost["rows"] = bucket
    cost["trees"] = len(forest.trees)
    return cost


def main():
    import urllib.request
    from wsgiref.simple_server import make_server

    from sagemaker_xgboost_container_tpu.data.matrix import DataMatrix
    from sagemaker_xgboost_container_tpu.models import train
    from sagemaker_xgboost_container_tpu.serving.app import ScoringService, make_app
    from sagemaker_xgboost_container_tpu.serving.server import (
        _QuietHandler,
        _ThreadedWSGIServer,
    )

    rng = np.random.RandomState(0)
    X = rng.rand(4000, 8).astype(np.float32)
    y = (X @ rng.rand(8).astype(np.float32) * 10).astype(np.float32)
    forest = train(
        {"max_depth": 6, "objective": "reg:squarederror"}, DataMatrix(X, labels=y),
        num_boost_round=100,
    )
    import tempfile

    model_dir = tempfile.mkdtemp()
    forest.save_model(os.path.join(model_dir, "xgboost-model"))

    app = make_app(ScoringService(model_dir))
    httpd = make_server(
        "127.0.0.1", 0, app, server_class=_ThreadedWSGIServer, handler_class=_QuietHandler
    )
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = "http://127.0.0.1:{}/invocations".format(port)

    def post(body):
        req = urllib.request.Request(
            base, data=body, method="POST", headers={"Content-Type": "text/csv"}
        )
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=30) as resp:
            resp.read()
        return time.perf_counter() - t0

    import jax

    single = ",".join("%.4f" % v for v in X[0]).encode()
    batch = "\n".join(
        ",".join("%.4f" % v for v in row) for row in X[:256]
    ).encode()

    # trigger the model load, then let its background bucket warmup finish
    # BEFORE timing — an in-flight compile would pollute the first leg
    post(single)
    for t in threading.enumerate():
        if t.name == "predict-warmup":
            t.join(timeout=300)

    # A/B the small-payload strategy: host numpy traversal (pinned to a
    # cutover that definitely includes 1 row) vs forcing the compiled device
    # kernel; the operator's own env value is restored for the batch leg
    prior = os.environ.get("GRAFT_HOST_PREDICT_ROWS")
    results = {}
    for label, rows in (("host", "32"), ("device", "0")):
        os.environ["GRAFT_HOST_PREDICT_ROWS"] = rows
        post(single)  # warm (jit cache on the device side)
        lat = sorted(post(single) for _ in range(N_REQUESTS))
        results["p50_single_row_ms_" + label] = round(lat[len(lat) // 2] * 1000, 2)
        results["p99_single_row_ms_" + label] = round(
            lat[int(len(lat) * 0.99) - 1] * 1000, 2
        )
    if prior is None:
        del os.environ["GRAFT_HOST_PREDICT_ROWS"]
    else:
        os.environ["GRAFT_HOST_PREDICT_ROWS"] = prior
    post(batch)
    blat = sorted(post(batch) for _ in range(50))
    httpd.shutdown()
    httpd.server_close()

    # steady-state leg: sustained RPS + the SLO window's own p95/violation
    # view (ROADMAP item 3), then the churn leg's rolling restarts
    steady_rps, slo_p95_ms, slo_violation_rate = _steady_leg(model_dir, single)
    churn_p95_ms, churn_error_rate, churn_requests = _churn_leg(model_dir, single)
    try:
        predict_compiled = _predict_compiled_cost(forest, X.shape[1])
    except Exception as e:  # introspection must never sink the benchmark
        sys.stderr.write("predict kernel cost introspection failed: {}\n".format(e))
        predict_compiled = None
    extra = {"predict_compiled": predict_compiled} if predict_compiled else {}
    print(
        json.dumps(
            {
                "metric": "serve /invocations latency (100-tree depth-6 model) [backend={}]".format(
                    jax.default_backend()
                ),
                **results,
                "p50_batch256_ms": round(blat[len(blat) // 2] * 1000, 2),
                "steady_rps": steady_rps,
                "slo_p95_ms": slo_p95_ms,
                "slo_violation_rate": slo_violation_rate,
                "churn_p95_ms": churn_p95_ms,
                "churn_error_rate": churn_error_rate,
                "churn_requests": churn_requests,
                "churn_cycles": CHURN_CYCLES,
                "unit": "ms",
                **extra,
            }
        )
    )


if __name__ == "__main__":
    main()
