#!/usr/bin/env python
"""Serving latency benchmark: p50/p99 of POST /invocations.

BASELINE.md's second metric ("p50 serve-predict latency"). Runs the real
threaded WSGI server in-process against a trained abalone-sized model and
measures end-to-end HTTP latency for single-row csv payloads, then a batch
payload. Prints one JSON line (not the driver contract — bench.py is that;
this is the measurement tool for serving work).
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

N_REQUESTS = int(os.getenv("BENCH_SERVE_REQUESTS", "300"))


def main():
    import urllib.request
    from wsgiref.simple_server import make_server

    from sagemaker_xgboost_container_tpu.data.matrix import DataMatrix
    from sagemaker_xgboost_container_tpu.models import train
    from sagemaker_xgboost_container_tpu.serving.app import ScoringService, make_app
    from sagemaker_xgboost_container_tpu.serving.server import (
        _QuietHandler,
        _ThreadedWSGIServer,
    )

    rng = np.random.RandomState(0)
    X = rng.rand(4000, 8).astype(np.float32)
    y = (X @ rng.rand(8).astype(np.float32) * 10).astype(np.float32)
    forest = train(
        {"max_depth": 6, "objective": "reg:squarederror"}, DataMatrix(X, labels=y),
        num_boost_round=100,
    )
    import tempfile

    model_dir = tempfile.mkdtemp()
    forest.save_model(os.path.join(model_dir, "xgboost-model"))

    app = make_app(ScoringService(model_dir))
    httpd = make_server(
        "127.0.0.1", 0, app, server_class=_ThreadedWSGIServer, handler_class=_QuietHandler
    )
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = "http://127.0.0.1:{}/invocations".format(port)

    def post(body):
        req = urllib.request.Request(
            base, data=body, method="POST", headers={"Content-Type": "text/csv"}
        )
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=30) as resp:
            resp.read()
        return time.perf_counter() - t0

    import jax

    single = ",".join("%.4f" % v for v in X[0]).encode()
    batch = "\n".join(
        ",".join("%.4f" % v for v in row) for row in X[:256]
    ).encode()

    # trigger the model load, then let its background bucket warmup finish
    # BEFORE timing — an in-flight compile would pollute the first leg
    post(single)
    for t in threading.enumerate():
        if t.name == "predict-warmup":
            t.join(timeout=300)

    # A/B the small-payload strategy: host numpy traversal (pinned to a
    # cutover that definitely includes 1 row) vs forcing the compiled device
    # kernel; the operator's own env value is restored for the batch leg
    prior = os.environ.get("GRAFT_HOST_PREDICT_ROWS")
    results = {}
    for label, rows in (("host", "32"), ("device", "0")):
        os.environ["GRAFT_HOST_PREDICT_ROWS"] = rows
        post(single)  # warm (jit cache on the device side)
        lat = sorted(post(single) for _ in range(N_REQUESTS))
        results["p50_single_row_ms_" + label] = round(lat[len(lat) // 2] * 1000, 2)
        results["p99_single_row_ms_" + label] = round(
            lat[int(len(lat) * 0.99) - 1] * 1000, 2
        )
    if prior is None:
        del os.environ["GRAFT_HOST_PREDICT_ROWS"]
    else:
        os.environ["GRAFT_HOST_PREDICT_ROWS"] = prior
    post(batch)
    blat = sorted(post(batch) for _ in range(50))
    httpd.shutdown()
    print(
        json.dumps(
            {
                "metric": "serve /invocations latency (100-tree depth-6 model) [backend={}]".format(
                    jax.default_backend()
                ),
                **results,
                "p50_batch256_ms": round(blat[len(blat) // 2] * 1000, 2),
                "unit": "ms",
            }
        )
    )


if __name__ == "__main__":
    main()
