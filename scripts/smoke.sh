#!/bin/bash
# One-command end-to-end smoke: abalone train -> model -> serve -> predict.
# Runs on CPU (JAX_PLATFORMS=cpu); ~1 minute.
set -euo pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
WORK="$(mktemp -d)"
trap 'rm -rf "$WORK"; kill $SERVER_PID 2>/dev/null || true' EXIT
mkdir -p "$WORK"/{conf,model,out}

cat > "$WORK/conf/hyperparameters.json" <<'JSON'
{"num_round": "10", "objective": "reg:squarederror", "max_depth": "4", "eval_metric": "rmse"}
JSON
cat > "$WORK/conf/inputdataconfig.json" <<'JSON'
{"train": {"ContentType": "libsvm", "TrainingInputMode": "File", "S3DistributionType": "FullyReplicated"},
 "validation": {"ContentType": "libsvm", "TrainingInputMode": "File", "S3DistributionType": "FullyReplicated"}}
JSON

export JAX_PLATFORMS=cpu PYTHONPATH="$REPO"
export SM_INPUT_TRAINING_CONFIG_FILE="$WORK/conf/hyperparameters.json"
export SM_INPUT_DATA_CONFIG_FILE="$WORK/conf/inputdataconfig.json"
export SM_CHECKPOINT_CONFIG_FILE="$WORK/conf/checkpointconfig.json"
export SM_CHANNEL_TRAIN=/root/reference/test/resources/abalone/data/train
export SM_CHANNEL_VALIDATION=/root/reference/test/resources/abalone/data/validation
export SM_MODEL_DIR="$WORK/model" SM_OUTPUT_DATA_DIR="$WORK/out"
export SM_HOSTS='["algo-1"]' SM_CURRENT_HOST=algo-1

echo "== train =="
python -m sagemaker_xgboost_container_tpu.training.entry 2>/dev/null | tail -3
test -f "$WORK/model/xgboost-model"

echo "== serve =="
SAGEMAKER_BIND_TO_PORT=18099 python -m sagemaker_xgboost_container_tpu.serving.server \
  > "$WORK/serve.log" 2>&1 &
SERVER_PID=$!
for i in $(seq 1 30); do
  curl -sf localhost:18099/ping >/dev/null 2>&1 && break; sleep 1
done
echo -n "prediction: "
curl -s -X POST localhost:18099/invocations -H "Content-Type: text/libsvm" \
  -d "1:2 2:0.74 3:0.6 4:0.195 5:1.974 6:0.598 7:0.4085 8:0.71"
echo
echo "SMOKE OK"
