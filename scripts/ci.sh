#!/bin/bash
# CI entrypoint for hosts without tox (e.g. the hermetic dev image).
# Mirrors tox.ini's tiers:
#   scripts/ci.sh fast   -> unit/contract tier (skips e2e + slow markers)
#   scripts/ci.sh full   -> everything, with the coverage gate when
#                           pytest-cov is installed (tox.ini gate: 60%)
# Exits non-zero on any failure; prints a one-line verdict last.
set -uo pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
TIER="${1:-fast}"
cd "$REPO"
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"

# The 60% coverage gate (reference: tox.ini:29-30) is MANDATORY in the full
# tier: pytest-cov when installed, else the stdlib PEP 669 gate
# (scripts/covgate.py, py3.12+). If neither can arm, the tier FAILS —
# a gate that silently disarms is documentation, not CI (VERDICT r3).
COV_ARGS=()
if [ "$TIER" = "full" ]; then
  if python -c "import pytest_cov" 2>/dev/null; then
    COV_ARGS=(--cov=sagemaker_xgboost_container_tpu --cov-fail-under=60)
  elif python -c "import sys; sys.exit(0 if hasattr(sys, 'monitoring') else 1)"; then
    COV_ARGS=(-p scripts.covgate --covgate-fail-under=60)
  else
    echo "CI full TIER FAILED: no coverage gate available (need pytest-cov or python>=3.12)"
    exit 3
  fi
fi

# static analyzer (tox.ini parity): graftlint owns every machine-checked
# policy — trace-safety (no env reads / uncached jit / host syncs under
# trace), thread+socket discipline, code<->docs contract drift, and the
# legacy no-print / no-bare-except gates (docs/static-analysis.md). The
# JSON report (findings + per-rule stats) is archived as a CI artifact;
# on failure the human-readable findings are re-printed. Invoked through
# the standalone launcher (not python -m) so the gate still reports exit 2
# on a tree whose package __init__ chain doesn't import.
ARTIFACT_DIR="${CI_ARTIFACT_DIR:-$REPO/.ci-artifacts}"
mkdir -p "$ARTIFACT_DIR"
python "$REPO/scripts/graftlint.py" --format json \
  > "$ARTIFACT_DIR/graftlint.json"
lint_rc=$?
if [ $lint_rc -ne 0 ]; then
  python "$REPO/scripts/graftlint.py" --stats
  echo "CI $TIER TIER FAILED (graftlint rc=$lint_rc; report: $ARTIFACT_DIR/graftlint.json)"
  exit 1
fi
echo "graftlint: OK (report: $ARTIFACT_DIR/graftlint.json)"

case "$TIER" in
  fast)
    python -m pytest tests/ -q -x --ignore=tests/test_training_e2e.py \
      -m "not slow and not e2e"
    ;;
  full)
    PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
      python -m pytest tests/ -q "${COV_ARGS[@]}"
    ;;
  chaos)
    # failure-domain supervision + state-integrity + elastic-membership
    # drills (test_robustness, test_faults, test_integrity, test_elastic —
    # everything marked `chaos`)
    python -m pytest tests/ -q -m chaos
    rc=$?
    if [ $rc -eq 0 ]; then
      # elastic shrink drills standalone, archiving the membership-logged
      # manifests and flight-recorder dumps as CI artifacts
      if PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
        python "$REPO/scripts/elastic_drill.py" "$ARTIFACT_DIR/elastic"; then
        echo "elastic drill: OK (artifacts: $ARTIFACT_DIR/elastic)"
      else
        rc=1
        echo "CI $TIER TIER FAILED (elastic drill; see $ARTIFACT_DIR/elastic)"
      fi
    fi
    if [ $rc -eq 0 ]; then
      # serving lifecycle drills: SIGTERM drain mid-flight, wedged-predict
      # watchdog (shed + abort), archiving server logs + flight recorders
      if PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
        python "$REPO/scripts/serve_drill.py" "$ARTIFACT_DIR/serve"; then
        echo "serve drill: OK (artifacts: $ARTIFACT_DIR/serve)"
      else
        rc=1
        echo "CI $TIER TIER FAILED (serve drill; see $ARTIFACT_DIR/serve)"
      fi
    fi
    if [ $rc -eq 0 ]; then
      # resilient-ingest drills: corrupt chunks under the 2-rank skip
      # consensus (quarantined model), fail policy and budget exhaustion
      # (exit 85), archiving quarantine manifests + flight recorders
      if PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
        python "$REPO/scripts/ingest_drill.py" "$ARTIFACT_DIR/ingest"; then
        echo "ingest drill: OK (artifacts: $ARTIFACT_DIR/ingest)"
      else
        rc=1
        echo "CI $TIER TIER FAILED (ingest drill; see $ARTIFACT_DIR/ingest)"
      fi
    fi
    # the case arm's status feeds the shared rc=$? below
    (exit $rc)
    ;;
  *)
    echo "usage: $0 [fast|full|chaos]"; exit 2
    ;;
esac
rc=$?

# trace-export smoke (fast/full): train a tiny model with SM_TRACE=1 and
# archive the exported Chrome trace alongside graftlint.json — every CI run
# leaves a loadable round timeline artifact (docs/observability.md §Tracing)
if [ $rc -eq 0 ] && [ "$TIER" != "chaos" ]; then
  if python "$REPO/scripts/trace_smoke.py" "$ARTIFACT_DIR/traces"; then
    echo "trace smoke: OK (artifact: $ARTIFACT_DIR/traces)"
  else
    rc=1
    echo "CI $TIER TIER FAILED (trace smoke; see $ARTIFACT_DIR/traces)"
  fi
fi

# model-telemetry smoke (fast/full): train with SM_MODEL_TELEMETRY=1 and
# validate the model-quality loop — training.learning/.eval records, the
# manifest learning + drift_baseline stamps, and the served-drift PSI
# round-trip (trip + automatic recovery); summary JSON is archived
# (docs/observability.md §Model window)
if [ $rc -eq 0 ] && [ "$TIER" != "chaos" ]; then
  if python "$REPO/scripts/model_smoke.py" "$ARTIFACT_DIR/model"; then
    echo "model smoke: OK (artifact: $ARTIFACT_DIR/model/model_smoke.json)"
  else
    rc=1
    echo "CI $TIER TIER FAILED (model smoke; see $ARTIFACT_DIR/model)"
  fi
fi

# fleet-observability smoke (full): 2-rank loopback run validating the
# merged trace-fleet.json (pid=rank lanes), the per-round skew fold, and
# the /status endpoint; the merged trace is archived next to the per-rank
# export (docs/observability.md §Fleet view)
if [ $rc -eq 0 ] && [ "$TIER" = "full" ]; then
  if python "$REPO/scripts/fleet_smoke.py" "$ARTIFACT_DIR/traces"; then
    echo "fleet smoke: OK (artifact: $ARTIFACT_DIR/traces/trace-fleet.json)"
  else
    rc=1
    echo "CI $TIER TIER FAILED (fleet smoke; see $ARTIFACT_DIR/traces)"
  fi
fi

# bench trajectory (full): fold the per-PR BENCH_*/MULTICHIP_* snapshots at
# the repo root into one trend report so a perf regression reads as a bend
# in the curve; archived next to the bench-smoke artifact. Reporting-only
# here (no --gate) — the snapshots are driver-owned history, not this run.
if [ $rc -eq 0 ] && [ "$TIER" = "full" ]; then
  if python "$REPO/scripts/bench_trend.py" --dir "$REPO" \
      --out "$ARTIFACT_DIR/bench/bench_trend.json"; then
    echo "bench trend: OK (artifact: $ARTIFACT_DIR/bench/bench_trend.json)"
  else
    rc=1
    echo "CI $TIER TIER FAILED (bench trend; see $ARTIFACT_DIR/bench)"
  fi
fi

# fused-dispatch smoke (full): bounded K=1 vs K=4 micro-run asserting the
# fused lax.scan round pipeline is bit-identical and not slower; the
# measured JSON is archived next to the trace/graftlint artifacts
if [ $rc -eq 0 ] && [ "$TIER" = "full" ]; then
  if python "$REPO/scripts/bench_smoke.py" "$ARTIFACT_DIR/bench"; then
    echo "bench smoke: OK (artifact: $ARTIFACT_DIR/bench/bench_smoke.json)"
  else
    rc=1
    echo "CI $TIER TIER FAILED (bench smoke; see $ARTIFACT_DIR/bench)"
  fi
fi

[ $rc -eq 0 ] && echo "CI $TIER TIER OK" || echo "CI $TIER TIER FAILED (rc=$rc)"
exit $rc
