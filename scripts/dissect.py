#!/usr/bin/env python
"""Per-stage timing dissection of one boosting round on the current backend.

Times each stage of the bench configuration (bench.py: 1M x 28, depth 8,
max_bin 256, binary:logistic) in isolation under jit, so the round's ~300 ms
on TPU can be attributed: grad/hess, per-level histograms (with the sibling
subtraction that the real build does), node totals, split scan, row routing
(gather vs onehot), eval prediction, and the full fused tree build.

Prints one "stage: ms" line per stage plus a JSON summary line at the end.
Honors GRAFT_HIST_IMPL / GRAFT_HIST_MM_PREC / GRAFT_ROUTE_IMPL. Run under an
external timeout — the TPU tunnel can wedge (docs/ROUND2_STATE.md).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

N_ROWS = int(os.getenv("DISSECT_ROWS", "1000000"))
N_FEATURES = int(os.getenv("DISSECT_FEATURES", "28"))
MAX_DEPTH = int(os.getenv("DISSECT_MAX_DEPTH", "8"))
MAX_BIN = int(os.getenv("DISSECT_MAX_BIN", "256"))
REPS = int(os.getenv("DISSECT_REPS", "5"))


def _time(fn, *args):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def main():
    import jax
    import jax.numpy as jnp

    from sagemaker_xgboost_container_tpu.ops import histogram as H
    from sagemaker_xgboost_container_tpu.ops import tree_build as TB
    from sagemaker_xgboost_container_tpu.ops.split import find_best_splits

    print("backend:", jax.default_backend(), flush=True)
    print(
        "impl={} prec={} route={}".format(
            os.environ.get("GRAFT_HIST_IMPL", "flat"),
            os.environ.get("GRAFT_HIST_MM_PREC", "bf16x2"),
            os.environ.get("GRAFT_ROUTE_IMPL", "gather"),
        ),
        flush=True,
    )

    rng = np.random.RandomState(0)
    n, d, B = N_ROWS, N_FEATURES, MAX_BIN + 1
    bin_dtype = np.uint8 if B <= 256 else np.uint16  # match binning storage
    bins = jnp.asarray(rng.randint(0, MAX_BIN, size=(n, d)).astype(bin_dtype))
    margins = jnp.asarray(rng.randn(n).astype(np.float32) * 0.3)
    labels = jnp.asarray((rng.rand(n) > 0.5).astype(np.float32))
    jax.block_until_ready((bins, margins, labels))

    timings = {}

    # --- grad/hess (binary:logistic) ------------------------------------
    @jax.jit
    def gradhess(m, y):
        p = jax.nn.sigmoid(m)
        return p - y, p * (1.0 - p)

    timings["grad_hess"] = _time(gradhess, margins, labels)

    # --- per-level histogram cost, as the real build pays it ------------
    # level 0: full width-1 histogram. levels 1..max_depth-1 with
    # subtraction: only the left-child half is histogrammed (width/2
    # output), over ~all rows. last level: node_totals only.
    node_fns = {}

    def hist_at(width_out):
        key = ("hist", width_out)
        if key not in node_fns:
            node_fns[key] = jax.jit(
                lambda b, g, h, nl: H.level_histogram(b, g, h, nl, width_out, B)
            )
        return node_fns[key]

    grad, hess = gradhess(margins, labels)
    jax.block_until_ready((grad, hess))

    hist_total = 0.0
    for level in range(MAX_DEPTH):
        if level == 0:
            width_out = 1
        else:
            width_out = 2 ** (level - 1)  # subtraction: left children only
        nl = jnp.asarray(rng.randint(0, width_out, size=n).astype(np.int32))
        ms = _time(hist_at(width_out), bins, grad, hess, nl)
        timings["hist_L{}[{}]".format(level, width_out)] = ms
        hist_total += ms
    timings["hist_all_levels"] = hist_total

    # --- last-level node totals -----------------------------------------
    W_last = 2**MAX_DEPTH
    nl = jnp.asarray(rng.randint(0, W_last, size=n).astype(np.int32))
    fn_tot = jax.jit(lambda g, h, x: H.node_totals(g, h, x, W_last))
    timings["node_totals[{}]".format(W_last)] = _time(fn_tot, grad, hess, nl)

    # --- split scan across all levels -----------------------------------
    num_cuts = jnp.full((d,), MAX_BIN - 1, jnp.int32)
    split_total = 0.0
    for level in range(MAX_DEPTH):
        W = 2**level
        Gl = jnp.asarray(rng.rand(W, d, B).astype(np.float32))
        Hl = jnp.asarray(np.abs(rng.rand(W, d, B)).astype(np.float32))
        fn = jax.jit(lambda G, Hh: find_best_splits(G, Hh, num_cuts))
        ms = _time(fn, Gl, Hl)
        split_total += ms
    timings["split_scan_all_levels"] = split_total

    # --- routing (one level at full width) ------------------------------
    split_feat = jnp.asarray(rng.randint(0, d, size=n).astype(np.int32))

    @jax.jit
    def route(b, sf):
        row_bin = TB.row_bin_lookup(b, sf)
        return row_bin > 128

    timings["route_lookup[n]"] = _time(route, bins, split_feat) * MAX_DEPTH
    timings["route_one_level"] = timings["route_lookup[n]"] / MAX_DEPTH

    # --- full tree build (the real fused program) -----------------------
    @jax.jit
    def full_tree(b, g, h):
        tree, row_out = TB.build_tree(
            b, g, h, num_cuts, MAX_DEPTH, B, eta=0.2
        )
        return TB.pack_tree(tree), row_out

    timings["full_tree_build"] = _time(full_tree, bins, grad, hess)

    # --- full round incl. grad/hess + margin update ---------------------
    @jax.jit
    def full_round(b, m, y):
        g, h = gradhess(m, y)
        tree, row_out = TB.build_tree(b, g, h, num_cuts, MAX_DEPTH, B, eta=0.2)
        return TB.pack_tree(tree), m + row_out

    timings["full_round"] = _time(full_round, bins, margins, labels)

    for k, v in timings.items():
        print("{:28s} {:9.2f} ms".format(k, v), flush=True)
    print(json.dumps({"backend": jax.default_backend(), "timings_ms": timings}))


if __name__ == "__main__":
    main()
