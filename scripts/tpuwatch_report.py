#!/usr/bin/env python
"""Render .tpuwatch/latest.json (the watcher battery's aggregate) as the
BASELINE.md round table: one row per run with its headline numbers, plus
the per-stage dissect comparison across knob configs.

Usage: python scripts/tpuwatch_report.py [.tpuwatch/latest.json]
"""

import json
import os
import sys


def _fmt(v):
    if isinstance(v, float):
        return "{:.3f}".format(v)
    return str(v)


def _probe_section(out_dir):
    """Render probe_summary.json (r5 phased taxonomy): the outage evidence
    exists even when the chip never recovered and latest.json is absent."""
    path = os.path.join(out_dir, "probe_summary.json")
    if not os.path.exists(path):
        return
    with open(path) as f:
        doc = json.load(f)
    print("## Probe taxonomy ({} probes, updated {})".format(
        doc.get("probes", "?"), doc.get("updated", "?")))
    print()
    print("| Outcome | Count |")
    print("|---|---|")
    for key, n in sorted(doc.get("taxonomy", {}).items()):
        print("| {} | {} |".format(key, n))
    for label in ("first", "last"):
        rec = doc.get(label)
        if rec:
            print()
            print("_{}: {} init={} compute={}{}_".format(
                label, rec.get("t"), rec.get("init"), rec.get("compute"),
                " — " + rec["err"] if rec.get("err") else ""))
    print()


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(".tpuwatch", "latest.json")
    _probe_section(os.path.dirname(path) or ".")
    if not os.path.exists(path):
        print("_no battery aggregate ({}): the chip never recovered_".format(path))
        return
    with open(path) as f:
        doc = json.load(f)
    runs = doc.get("runs", {})
    print("_updated: {}_".format(doc.get("updated", "?")))
    print()

    # --- bench-style runs: one row each -----------------------------------
    bench_rows = []
    for name, res in sorted(runs.items()):
        if not res or "timings_ms" in res:
            continue
        headline = []
        for key in ("value", "p50_single_row_ms_host", "p99_single_row_ms_host",
                    "p50_single_row_ms_device", "p50_batch256_ms", "vs_baseline"):
            if key in res:
                headline.append("{}={}".format(key, _fmt(res[key])))
        bench_rows.append((name, res.get("metric", "?"), "; ".join(headline)))
    if bench_rows:
        print("| Run | Metric | Result |")
        print("|---|---|---|")
        for name, metric, headline in bench_rows:
            print("| {} | {} | {} |".format(name, metric, headline))
        print()

    # --- dissect runs: stages as rows, configs as columns -----------------
    dissects = {
        name: res["timings_ms"]
        for name, res in runs.items()
        if res and "timings_ms" in res
    }
    if dissects:
        names = sorted(dissects)
        stages = []
        for t in dissects.values():
            for s in t:
                if s not in stages:
                    stages.append(s)
        print("| Stage (ms) | " + " | ".join(names) + " |")
        print("|---|" + "---|" * len(names))
        for s in stages:
            cells = [
                "{:.1f}".format(dissects[n][s]) if s in dissects[n] else "-"
                for n in names
            ]
            print("| {} | ".format(s) + " | ".join(cells) + " |")
        print()

    missing = [n for n, r in sorted(runs.items()) if not r]
    if missing:
        print("_no parseable result:_ " + ", ".join(missing))


if __name__ == "__main__":
    main()
