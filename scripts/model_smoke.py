#!/usr/bin/env python
"""Model-telemetry smoke: train a tiny model with ``SM_MODEL_TELEMETRY=1``
and validate the whole model-quality observability loop end to end:

* ``training.learning`` records carry per-round on-device stats (grad/hess
  reductions, NaN/Inf counters, committed-tree shape),
* the eval curve folds into a learning summary (best iteration, final
  metrics),
* the model manifest is stamped with the learning summary AND the
  per-feature bin-occupancy drift baseline,
* a served-drift PSI round-trip: the baseline read back from the manifest
  arms a DriftWindow; in-distribution traffic stays healthy, shifted
  traffic trips ``degraded`` + a ``serving.drift`` record, and recovery is
  automatic once the shifted window ages out.

``scripts/ci.sh`` runs this in the fast tier and archives the summary JSON
under ``${CI_ARTIFACT_DIR:-.ci-artifacts}/model/``.

Exit codes: 0 OK, 1 any leg of the loop failed.
"""

import io
import json
import os
import sys
import tempfile
from contextlib import redirect_stdout

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["SM_MODEL_TELEMETRY"] = "1"

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _fail(msg):
    sys.stderr.write("model smoke FAILED: {}\n".format(msg))
    return 1


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    out_dir = argv[0] if argv else os.path.join(".ci-artifacts", "model")

    import numpy as np

    from sagemaker_xgboost_container_tpu.data.matrix import DataMatrix
    from sagemaker_xgboost_container_tpu.models import train
    from sagemaker_xgboost_container_tpu.telemetry import model as model_telemetry
    from sagemaker_xgboost_container_tpu.training.callbacks import EvaluationMonitor
    from sagemaker_xgboost_container_tpu.utils import integrity

    summary = {"smoke": "model", "ok": False}

    rng = np.random.RandomState(0)
    X = rng.rand(256, 5).astype(np.float32)
    y = (X[:, 0] + 0.25 * X[:, 1] > 0.6).astype(np.float32)
    Xv = rng.rand(96, 5).astype(np.float32)
    yv = (Xv[:, 0] + 0.25 * Xv[:, 1] > 0.6).astype(np.float32)

    # ---- leg 1: training emits structured learning + eval records --------
    captured = io.StringIO()
    with redirect_stdout(captured):
        bst = train(
            {"objective": "binary:logistic", "max_depth": 3, "max_bin": 32},
            DataMatrix(X, labels=y),
            num_boost_round=4,
            evals=[(DataMatrix(X, labels=y), "train"), (DataMatrix(Xv, labels=yv), "validation")],
            callbacks=[EvaluationMonitor()],
        )
    records = []
    for line in captured.getvalue().splitlines():
        if line.startswith("{"):
            try:
                records.append(json.loads(line))
            except ValueError:
                pass
    learning = [r for r in records if r.get("metric") == "training.learning"]
    evals_rec = [r for r in records if r.get("metric") == "training.eval"]
    if not learning:
        return _fail("no training.learning records on stdout")
    for field in ("grad_sum", "hess_sum", "grad_nonfinite", "leaves", "max_depth"):
        if field not in learning[-1]:
            return _fail("training.learning record lacks {!r}".format(field))
    if any(r["grad_nonfinite"] != 0 for r in learning):
        return _fail("clean train reported non-finite gradients")
    if not evals_rec:
        return _fail("no training.eval records on stdout")
    summary["learning_records"] = len(learning)
    summary["eval_records"] = len(evals_rec)

    curve = model_telemetry.learning_summary()
    if not curve or "best_iteration" not in curve:
        return _fail("learning summary missing after an eval'd train")
    summary["curve"] = curve

    # ---- leg 2: manifest stamp (the algorithm_train save funnel) ---------
    with tempfile.TemporaryDirectory() as tmp:
        model_path = os.path.join(tmp, "xgboost-model")
        bst.save_model(model_path)
        integrity.write_manifest(
            model_path,
            learning=model_telemetry.learning_summary(),
            drift_baseline=model_telemetry.drift_baseline(),
        )
        manifest = integrity.read_manifest(model_path)
        if not manifest or "drift_baseline" not in manifest:
            return _fail("manifest lacks the drift_baseline stamp")
        if "learning" not in manifest:
            return _fail("manifest lacks the learning-summary stamp")
        baseline = manifest["drift_baseline"]
        if len(baseline.get("features", [])) != X.shape[1]:
            return _fail(
                "baseline has {} features, expected {}".format(
                    len(baseline.get("features", [])), X.shape[1]
                )
            )
    summary["baseline_features"] = len(baseline["features"])
    summary["baseline_rows"] = baseline.get("rows")

    # ---- leg 3: served-drift PSI round-trip ------------------------------
    clock = [0.0]
    window = model_telemetry.DriftWindow(
        baseline,
        psi_max=0.2,
        window_s=60.0,
        min_rows=100,
        clock=lambda: clock[0],
    )
    # in-distribution traffic must never trip the monitor
    for _ in range(4):
        batch = rng.rand(32, 5).astype(np.float32)
        window.observe(batch, predictions=rng.rand(32))
        clock[0] += 1.0
    if window.degraded:
        return _fail("in-distribution traffic tripped the drift monitor")
    psi_clean = window.snapshot()["psi"]
    # shifted traffic must drive PSI past the threshold
    drift_line = io.StringIO()
    with redirect_stdout(drift_line):
        for _ in range(4):
            shifted = (3.0 + rng.rand(32, 5)).astype(np.float32)
            window.observe(shifted, predictions=rng.rand(32))
            clock[0] += 1.0
    if not window.degraded:
        return _fail("shifted traffic did not trip the drift monitor")
    psi_drifted = window.snapshot()["psi"]
    drift_records = [
        json.loads(line)
        for line in drift_line.getvalue().splitlines()
        if line.startswith("{") and '"serving.drift"' in line
    ]
    if not any(r.get("drifted") for r in drift_records):
        return _fail("no serving.drift record on the degraded transition")
    # recovery is automatic once the shifted batches age out of the window
    clock[0] += 120.0
    if window.degraded:
        return _fail("drift monitor did not recover after the window aged out")
    summary["psi_clean"] = psi_clean
    summary["psi_drifted"] = psi_drifted
    summary["ok"] = True

    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, "model_smoke.json")
    with open(out_path, "w") as f:
        json.dump(summary, f, indent=2, sort_keys=True)
        f.write("\n")
    print(
        "model smoke OK: {} learning records, best_iteration={}, "
        "PSI {} -> {} (drifted) -> recovered; summary at {}".format(
            len(learning), curve["best_iteration"], psi_clean, psi_drifted, out_path
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
