#!/usr/bin/env python
"""Elastic shrink-to-continue chaos drill: SIGKILL one of N ranks mid-train.

Self-spawning harness (parent mode spawns rank children of this same file)
exercising the full elastic membership plane end to end on loopback:

* ``python scripts/elastic_drill.py [artifact_dir]`` — the shrink drill:
  3 ranks train with checkpoints; rank 2 is SIGKILLed deterministically by
  the ``kill`` fault action at its 3rd round; rank 0's heartbeat aggregator
  detects the stale host and proposes a survivor set (``SM_ELASTIC=1``);
  survivors re-rendezvous at world size 2, resume from the last
  digest-verified checkpoint across the recorded world-size transition, and
  finish training. The parent asserts: survivors exit 0, the final model
  loads through serving's verified path, and its manifest's
  ``membership_log`` records exactly one 3→2 transition.
* ``--mode legacy`` — the SAME kill with ``SM_ELASTIC`` unset: survivors
  must take the legacy coordinated abort (exit 80) — the
  no-behavior-change-by-default contract.
* ``--mode reform-fail`` — the shrink drill with ``rendezvous.reform``
  faulted on every survivor: reform exhausts its retries and every survivor
  exits 82 (``EXIT_REFORM_FAILED``) leaving a flight-recorder dump.

Artifacts (membership-logged manifests, flight-recorder dumps, per-rank
stdout) are archived under the given directory — CI wires this into the
chaos tier with ``${CI_ARTIFACT_DIR:-.ci-artifacts}/elastic/``.

Exit code: 0 when every assertion holds, 1 otherwise (2 on usage errors).
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_RANKS = 3
NUM_ROUND = 40
PACE_S = 0.25
HEARTBEAT_S = 0.4
STALE_AFTER = 3


def _free_port():
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# --------------------------------------------------------------- rank child
def rank_main(args):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from sagemaker_xgboost_container_tpu.data.matrix import DataMatrix
    from sagemaker_xgboost_container_tpu.models import booster
    from sagemaker_xgboost_container_tpu.parallel.distributed import Cluster
    from sagemaker_xgboost_container_tpu.telemetry import cluster as tcluster
    from sagemaker_xgboost_container_tpu.training import elastic, watchdog
    from sagemaker_xgboost_container_tpu.training.callbacks import get_callbacks
    from sagemaker_xgboost_container_tpu.utils import integrity
    from sagemaker_xgboost_container_tpu.utils.logging_config import (
        setup_main_logger,
    )

    setup_main_logger("elastic_drill")
    rank = args.rank
    abort_ports = [int(p) for p in args.abort_ports.split(",")]
    hosts = ["algo-{}".format(i + 1) for i in range(args.n_ranks)]
    current = hosts[rank]
    peer_addrs = {
        hosts[i]: ("127.0.0.1", abort_ports[i]) for i in range(args.n_ranks)
    }
    ckpt_dir = os.path.join(args.workdir, "ckpt")
    model_dir = os.path.join(args.workdir, "model")

    # startup barrier first (the production analog: rendezvous precedes the
    # telemetry plane) so heartbeat grace windows never race process spawn
    barrier = Cluster(hosts, current, port=args.barrier_port)
    barrier.master_host = "127.0.0.1"
    barrier.synchronize({"host": current}, timeout=120.0)

    elastic.register_cluster(hosts, current, peer_addrs=peer_addrs)
    from sagemaker_xgboost_container_tpu.telemetry import tracing

    tracing.set_rank(rank)
    watchdog.start_abort_plane(hosts, current, port=abort_ports[rank])

    def start_heartbeat_plane(cur_hosts):
        ordered = sorted(cur_hosts)
        my_rank = ordered.index(current)
        aggregator = None
        if my_rank == 0:
            def on_stale(stale_rank, stale_host, age_s):
                watchdog.handle_stale_host(
                    ordered, current, stale_rank, stale_host, age_s
                )

            aggregator = tcluster.HeartbeatAggregator(
                num_hosts=len(ordered),
                interval=HEARTBEAT_S,
                port=args.hb_port,
                hosts=ordered,
                stale_after=STALE_AFTER,
                on_stale=on_stale,
            ).start()
        sender = tcluster.HeartbeatSender(
            rank=my_rank,
            host=current,
            aggregator_addr=("127.0.0.1", args.hb_port),
            interval=HEARTBEAT_S,
        ).start()
        # register as THE active plane so the reform teardown
        # (elastic._teardown_planes -> stop_cluster_telemetry) stops it
        plane = tcluster.ClusterTelemetry(
            rank=my_rank, sender=sender, aggregator=aggregator
        )
        with tcluster._plane_lock:
            tcluster._active_plane = plane
        return plane

    start_heartbeat_plane(hosts)

    rng = np.random.RandomState(rank)
    X = rng.rand(300, 4).astype(np.float32)
    y = (3 * X[:, 0] + X[:, 1]).astype(np.float32)
    dtrain = DataMatrix(X, labels=y)
    params = {"objective": "reg:squarederror", "max_depth": 2, "eta": "0.3"}
    is_master = current == sorted(hosts)[0]

    class Pacer:
        """Slow rounds to drill speed so detection/reform land mid-train."""

        def after_iteration(self, model, epoch, evals_log):
            time.sleep(PACE_S)
            return False

    def train_once():
        xgb_model, iteration, callbacks = get_callbacks(
            model_dir=model_dir,
            checkpoint_dir=ckpt_dir,
            early_stopping_data_name=None,
            early_stopping_metric=None,
            early_stopping_rounds=None,
            save_model_on_termination="false",
            is_master=is_master,
            num_round=NUM_ROUND,
            num_rows=dtrain.num_row,
            train_cfg=dict(params),
        )
        callbacks.insert(0, Pacer())
        try:
            return booster.train(
                dict(params),
                dtrain,
                num_boost_round=NUM_ROUND - iteration,
                evals=[(dtrain, "train")],
                callbacks=callbacks,
                xgb_model=xgb_model,
            )
        except elastic.ReformRequested:
            elastic.drain_callbacks(callbacks)
            raise

    def on_reform(new_hosts, current_host):
        watchdog.start_abort_plane(new_hosts, current_host, port=abort_ports[rank])
        start_heartbeat_plane(new_hosts)

    forest = elastic.supervised_train(
        train_once,
        on_reform=on_reform,
        master_addr="127.0.0.1",
        reform_port=args.reform_port,
    )

    if is_master:
        os.makedirs(model_dir, exist_ok=True)
        model_location = os.path.join(model_dir, "xgboost-model")
        forest.save_model(model_location)
        integrity.write_manifest(
            model_location,
            fingerprint=integrity.config_fingerprint(params),
            membership_log=elastic.membership_log() or None,
        )
    print(
        json.dumps(
            {
                "metric": "drill.done",
                "rank": rank,
                "world_size": elastic.world_size(),
                "generation": elastic.generation(),
                "rounds": forest.num_boosted_rounds,
            }
        ),
        flush=True,
    )
    return 0


# ------------------------------------------------------------------- parent
def _spawn(mode, workdir):
    hb_port = _free_port()
    reform_port = _free_port()
    barrier_port = _free_port()
    abort_ports = [_free_port() for _ in range(N_RANKS)]
    procs = []
    for rank in range(N_RANKS):
        env = dict(os.environ)
        for stale in ("SM_FAULT_SPEC", "SM_ROUND_DEADLINE_S", "SM_CONSENSUS_EVERY",
                      "SM_HEARTBEAT_INTERVAL_S", "SM_ELASTIC"):
            env.pop(stale, None)
        trace_dir = os.path.join(workdir, "trace-rank{}".format(rank))
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "",
                "PYTHONPATH": REPO,
                "SM_ABORT_ON_STALE": "1",
                "SM_TRACE": "1",
                "SM_TRACE_EXPORT_DIR": trace_dir,
                "SM_IO_RETRY_BACKOFF_S": "0.05",
                "SM_REFORM_TIMEOUT_S": "30",
            }
        )
        if mode != "legacy":
            env["SM_ELASTIC"] = "1"
            env["SM_ELASTIC_MIN_HOSTS"] = "2"
        if rank == N_RANKS - 1:
            # the kill-rank helper: SIGKILL this specific rank at its 3rd
            # completed round — a deterministic dead host
            env["SM_FAULT_SPEC"] = "training.round_end:kill@3"
        elif mode == "reform-fail":
            env["SM_FAULT_SPEC"] = "rendezvous.reform:error:injected reform outage"
            env["SM_IO_RETRY_ATTEMPTS"] = "2"
        out = open(os.path.join(workdir, "rank{}.out".format(rank)), "w")
        procs.append(
            (
                subprocess.Popen(
                    [
                        sys.executable,
                        os.path.abspath(__file__),
                        "--rank", str(rank),
                        "--n-ranks", str(N_RANKS),
                        "--workdir", workdir,
                        "--hb-port", str(hb_port),
                        "--reform-port", str(reform_port),
                        "--barrier-port", str(barrier_port),
                        "--abort-ports", ",".join(str(p) for p in abort_ports),
                    ],
                    env=env,
                    stdout=out,
                    stderr=subprocess.STDOUT,
                ),
                out,
            )
        )
    codes = []
    for proc, out in procs:
        try:
            proc.wait(timeout=300)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        out.close()
        codes.append(proc.returncode)
    return codes


def _read(path):
    with open(path) as f:
        return f.read()


def _records(text, metric):
    prefix = '{{"metric": "{}"'.format(metric)
    return [json.loads(l) for l in text.splitlines() if l.startswith(prefix)]


def _check(ok, message, failures):
    print(("ok: " if ok else "FAIL: ") + message, flush=True)
    if not ok:
        failures.append(message)
    return ok


def _verify_shrink(workdir, codes, failures):
    killed = -signal.SIGKILL
    _check(codes[2] == killed, "rank 2 SIGKILLed (rc={})".format(codes[2]), failures)
    for rank in (0, 1):
        out = _read(os.path.join(workdir, "rank{}.out".format(rank)))
        _check(
            codes[rank] == 0,
            "survivor rank {} completed (rc={})".format(rank, codes[rank]),
            failures,
        )
        memb = _records(out, "training.membership")
        _check(
            len(memb) == 1
            and memb[0]["old_world_size"] == 3
            and memb[0]["new_world_size"] == 2,
            "rank {} recorded one 3->2 membership transition".format(rank),
            failures,
        )
        done = _records(out, "drill.done")
        _check(
            done and done[0]["world_size"] == 2
            and done[0]["rounds"] == NUM_ROUND,
            "rank {} finished all {} rounds at world size 2".format(rank, NUM_ROUND),
            failures,
        )

    model_path = os.path.join(workdir, "model", "xgboost-model")
    manifest_path = model_path + ".manifest"
    _check(os.path.exists(model_path), "final model exists", failures)
    if os.path.exists(manifest_path):
        manifest = json.loads(_read(manifest_path))
        log = manifest.get("membership_log") or []
        _check(
            len(log) == 1
            and log[0]["old_world_size"] == 3
            and log[0]["new_world_size"] == 2
            and log[0]["reason"] == "stale_host",
            "final manifest membership_log records exactly one transition",
            failures,
        )
        _check(
            manifest.get("fingerprint", {}).get("world_size") == 2,
            "final fingerprint carries the shrunken world size",
            failures,
        )
    else:
        _check(False, "final model manifest exists", failures)

    # the model must load through serving's verified path (digest ->
    # parse -> structural validation)
    try:
        from sagemaker_xgboost_container_tpu.serving import serve_utils

        serve_utils._load_verified(model_path)
        _check(True, "final model passes serving's verified load", failures)
    except Exception as e:
        _check(False, "final model passes serving's verified load ({})".format(e), failures)


def _verify_legacy(workdir, codes, failures):
    killed = -signal.SIGKILL
    _check(codes[2] == killed, "rank 2 SIGKILLed (rc={})".format(codes[2]), failures)
    for rank in (0, 1):
        out = _read(os.path.join(workdir, "rank{}.out".format(rank)))
        _check(
            codes[rank] == 80,
            "survivor rank {} took the legacy coordinated abort "
            "(rc={}, want 80)".format(rank, codes[rank]),
            failures,
        )
        aborts = _records(out, "training.abort")
        _check(
            aborts and aborts[0]["reason"] in ("stale_host",)
            and aborts[0]["exit_code"] == 80,
            "rank {} training.abort names stale_host/80".format(rank),
            failures,
        )
        _check(
            not _records(out, "training.membership"),
            "rank {} recorded no membership transition".format(rank),
            failures,
        )


def _verify_reform_fail(workdir, codes, failures):
    killed = -signal.SIGKILL
    _check(codes[2] == killed, "rank 2 SIGKILLed (rc={})".format(codes[2]), failures)
    for rank in (0, 1):
        out = _read(os.path.join(workdir, "rank{}.out".format(rank)))
        _check(
            codes[rank] == 82,
            "survivor rank {} exits EXIT_REFORM_FAILED "
            "(rc={}, want 82)".format(rank, codes[rank]),
            failures,
        )
        aborts = _records(out, "training.abort")
        _check(
            aborts and aborts[0]["reason"] == "reform_failed"
            and aborts[0]["exit_code"] == 82,
            "rank {} training.abort names reform_failed/82".format(rank),
            failures,
        )
        dump = aborts[0].get("flight_recorder") if aborts else None
        _check(
            bool(dump) and os.path.exists(dump),
            "rank {} left a flight-recorder dump ({})".format(rank, dump),
            failures,
        )


def _archive(workdir, artifact_dir, mode):
    dest = os.path.join(artifact_dir, mode)
    os.makedirs(dest, exist_ok=True)
    for name in sorted(os.listdir(workdir)):
        src = os.path.join(workdir, name)
        if name.endswith(".out"):
            shutil.copy2(src, dest)
        elif name.startswith("trace-rank") and os.path.isdir(src):
            for f in os.listdir(src):
                shutil.copy2(os.path.join(src, f), os.path.join(dest, f))
    manifest = os.path.join(workdir, "model", "xgboost-model.manifest")
    if os.path.exists(manifest):
        shutil.copy2(manifest, dest)
    ckpt_dir = os.path.join(workdir, "ckpt")
    if os.path.isdir(ckpt_dir):
        for f in sorted(os.listdir(ckpt_dir)):
            if f.endswith(".manifest"):
                shutil.copy2(os.path.join(ckpt_dir, f), dest)
    print("artifacts archived under {}".format(dest), flush=True)


def parent_main(args):
    failures = []
    modes = [args.mode] if args.mode != "all" else ["shrink", "legacy", "reform-fail"]
    artifact_dir = os.path.abspath(args.artifact_dir)
    os.makedirs(artifact_dir, exist_ok=True)
    for mode in modes:
        print("--- elastic drill: {} ---".format(mode), flush=True)
        workdir = tempfile.mkdtemp(prefix="elastic-{}-".format(mode))
        try:
            codes = _spawn(mode, workdir)
            print("rank exit codes: {}".format(codes), flush=True)
            if mode == "shrink":
                _verify_shrink(workdir, codes, failures)
            elif mode == "legacy":
                _verify_legacy(workdir, codes, failures)
            else:
                _verify_reform_fail(workdir, codes, failures)
            _archive(workdir, artifact_dir, mode)
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
    if failures:
        print("ELASTIC DRILL FAILED ({} assertion(s))".format(len(failures)), flush=True)
        return 1
    print("ELASTIC DRILL OK", flush=True)
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("artifact_dir", nargs="?", default=".ci-artifacts/elastic")
    parser.add_argument(
        "--mode", choices=["shrink", "legacy", "reform-fail", "all"], default="all"
    )
    parser.add_argument("--rank", type=int, default=None)
    parser.add_argument("--n-ranks", type=int, default=N_RANKS)
    parser.add_argument("--workdir")
    parser.add_argument("--hb-port", type=int)
    parser.add_argument("--reform-port", type=int)
    parser.add_argument("--barrier-port", type=int)
    parser.add_argument("--abort-ports")
    args = parser.parse_args(argv)
    if args.rank is not None:
        return rank_main(args)
    return parent_main(args)


if __name__ == "__main__":
    sys.exit(main())
