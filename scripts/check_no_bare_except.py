#!/usr/bin/env python
"""Static check: no bare ``except:`` in the package.

A bare except swallows KeyboardInterrupt/SystemExit and — in a container
whose supervision layer aborts via ``os._exit`` paths and classified exit
codes (docs/robustness.md) — can eat the very control-flow exceptions the
failure-domain machinery depends on. Every handler must name a type
(``except Exception:`` at minimum, which leaves BaseException control flow
alone).

AST-based like its sibling check_no_print.py: only real ``except:`` handler
clauses trip it, not strings or comments. Exit 0 clean, 1 with findings,
2 on unparseable files. Wired into tox (fast/full), scripts/ci.sh, and the
chaos tier (tests/test_robustness.py).
"""

import ast
import os
import sys

PACKAGE = "sagemaker_xgboost_container_tpu"


def find_bare_excepts(source, filename):
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        raise RuntimeError("cannot parse {}: {}".format(filename, e))
    return [
        node.lineno
        for node in ast.walk(tree)
        if isinstance(node, ast.ExceptHandler) and node.type is None
    ]


def check(repo_root):
    pkg_root = os.path.join(repo_root, PACKAGE)
    findings = []
    errors = []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, pkg_root).replace(os.sep, "/")
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            try:
                for lineno in find_bare_excepts(source, path):
                    findings.append("{}/{}:{}".format(PACKAGE, rel, lineno))
            except RuntimeError as e:
                errors.append(str(e))
    return findings, errors


def main(argv=None):
    repo_root = (argv or sys.argv[1:] or [None])[0] or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    findings, errors = check(repo_root)
    for err in errors:
        sys.stderr.write(err + "\n")
    for finding in findings:
        sys.stderr.write(
            "bare except outside policy: {} (name the exception type — "
            "'except Exception:' at minimum)\n".format(finding)
        )
    if errors:
        return 2
    if findings:
        return 1
    sys.stderr.write("check_no_bare_except: OK\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
