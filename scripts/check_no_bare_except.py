#!/usr/bin/env python
"""DEPRECATED shim: the no-bare-except policy now lives in graftlint.

This script shipped in PR 3 as a standalone AST gate; the policy moved to
the ``no-bare-except`` rule of the repo's static analyzer
(``sagemaker_xgboost_container_tpu/toolkit/graftlint``, see
docs/static-analysis.md). The shim keeps the historical entrypoint and
module API (``find_bare_excepts``) working for existing tox/ci.sh
invocations and tests; new wiring should invoke the analyzer directly::

    python scripts/graftlint.py --select no-bare-except

(graftlint is loaded through ``scripts/graftlint.py`` rather than as a
product submodule so the gate still reports — exit 2 — on a tree whose
package ``__init__`` chain doesn't even import.)

Exit codes unchanged: 0 clean, 1 with findings, 2 on unparseable files.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)

from graftlint import load_submodule  # noqa: E402  (scripts/graftlint.py)

find_bare_excepts = load_submodule("passes.legacy").find_bare_excepts

__all__ = ["find_bare_excepts", "main"]


def main(argv=None):
    graftlint_main = load_submodule("__main__").main

    repo_root = (argv or sys.argv[1:] or [None])[0] or REPO_ROOT
    sys.stderr.write(
        "check_no_bare_except: deprecated shim over graftlint's "
        "no-bare-except rule (docs/static-analysis.md)\n"
    )
    return graftlint_main(["--root", repo_root, "--select", "no-bare-except"])


if __name__ == "__main__":
    sys.exit(main())
