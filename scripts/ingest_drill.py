#!/usr/bin/env python
"""Resilient-ingest chaos drill: corrupt chunks under a 2-rank skip consensus.

Self-spawning harness (parent mode spawns rank children of this same file)
exercising the chunked streaming-ingest plane (``data/streaming.py``) end to
end on loopback. Two ranks share one replicated CSV channel with
``SM_INGEST_SHARD=1`` (round-robin chunk assignment) and ``data.chunk``
faults are armed on rank 1's env:

* ``--mode skip`` — ``SM_INGEST_BAD_CHUNK_ACTION=skip``: rank 1's faulted
  chunk fails past its retries, the skip set is agreed cross-rank, both
  ranks finish ingest + a short training run, and the parent asserts: both
  ranks exit 0, BOTH ranks recorded the **identical** quarantine (the
  rank-consistency drill), the final model's manifest carries the
  quarantine record, ``ingest-quarantine.json`` names the bad chunk, and
  the model passes serving's verified load.
* ``--mode fail`` — the default ``fail`` policy with the same fault: every
  rank must exit 85 (``EXIT_INGEST_FAILED``) with a ``training.abort``
  record naming ``ingest_failed`` and a flight-recorder dump.
* ``--mode budget`` — ``skip`` policy but ``SM_INGEST_MAX_BAD_CHUNKS=1``
  with a persistent fault (``@2+``): the agreed bad-chunk count exceeds the
  budget and every rank exits 85 with dumps.

Artifacts (quarantine manifests, model manifest, flight-recorder dumps,
per-rank stdout) are archived under the given directory — CI wires this
into the chaos tier with ``${CI_ARTIFACT_DIR:-.ci-artifacts}/ingest/``.

Exit code: 0 when every assertion holds, 1 otherwise (2 on usage errors).
"""

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_RANKS = 2
NUM_ROUND = 4
N_FILES = 4
ROWS_PER_FILE = 700
CHUNK_BYTES = 8192


def _free_port():
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_channel(data_dir):
    os.makedirs(data_dir, exist_ok=True)
    rng = np.random.RandomState(7)
    for i in range(N_FILES):
        arr = np.column_stack(
            [rng.randint(0, 2, ROWS_PER_FILE), rng.rand(ROWS_PER_FILE, 6).round(4)]
        )
        np.savetxt(
            os.path.join(data_dir, "part-{:03d}.csv".format(i)),
            arr,
            delimiter=",",
            fmt="%.6g",
        )


# --------------------------------------------------------------- rank child
def rank_main(args):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from sagemaker_xgboost_container_tpu.data import streaming
    from sagemaker_xgboost_container_tpu.models import booster
    from sagemaker_xgboost_container_tpu.telemetry import tracing
    from sagemaker_xgboost_container_tpu.utils import integrity
    from sagemaker_xgboost_container_tpu.utils.logging_config import (
        setup_main_logger,
    )

    setup_main_logger("ingest_drill")
    rank = args.rank
    hosts = ["algo-{}".format(i + 1) for i in range(args.n_ranks)]
    current = hosts[rank]
    tracing.set_rank(rank)
    model_dir = os.path.join(args.workdir, "model")

    try:
        binned = streaming.ingest_channel(
            args.data_dir,
            "text/csv",
            256,
            channel="train",
            hosts=hosts,
            current_host=current,
            master_addr="127.0.0.1",
        )
    except streaming.IngestError as e:
        # the production wiring (algorithm_train.sagemaker_train) does
        # exactly this: coordinated flight-recorder dump + exit 85
        streaming.abort_on_ingest_failure(e)
        return 1  # unreachable: abort_on_ingest_failure hard-exits

    record = streaming.quarantine_record()
    print(
        json.dumps(
            {
                "metric": "drill.quarantine",
                "rank": rank,
                "record": record,
                "rows": binned.num_row,
            },
            sort_keys=True,
        ),
        flush=True,
    )

    params = {"objective": "binary:logistic", "max_depth": 2, "seed": 3}
    forest = booster.train(dict(params), binned, num_boost_round=NUM_ROUND)

    if rank == 0:
        os.makedirs(model_dir, exist_ok=True)
        model_location = os.path.join(model_dir, "xgboost-model")
        forest.save_model(model_location)
        integrity.write_manifest(
            model_location,
            fingerprint=integrity.config_fingerprint(params),
            quarantine=record,
        )
        streaming.write_quarantine_manifest(model_dir)
    print(
        json.dumps(
            {
                "metric": "drill.done",
                "rank": rank,
                "rounds": forest.num_boosted_rounds,
                "rows": binned.num_row,
            }
        ),
        flush=True,
    )
    return 0


# ------------------------------------------------------------------- parent
def _spawn(mode, workdir, data_dir):
    ingest_port = _free_port()
    procs = []
    for rank in range(N_RANKS):
        env = dict(os.environ)
        for stale in (
            "SM_FAULT_SPEC",
            "SM_INGEST_MODE",
            "SM_INGEST_BAD_CHUNK_ACTION",
            "SM_INGEST_MAX_BAD_CHUNKS",
            "SM_TRACE",
        ):
            env.pop(stale, None)
        trace_dir = os.path.join(workdir, "trace-rank{}".format(rank))
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO,
                "SM_INGEST_MODE": "chunked",
                "SM_INGEST_SHARD": "1",
                "SM_INGEST_CHUNK_BYTES": str(CHUNK_BYTES),
                "SM_INGEST_PORT": str(ingest_port),
                "SM_INGEST_TIMEOUT_S": "60",
                "SM_IO_RETRY_ATTEMPTS": "1",
                "SM_IO_RETRY_BACKOFF_S": "0.01",
                "SM_TRACE": "1",
                "SM_TRACE_EXPORT_DIR": trace_dir,
            }
        )
        if mode == "skip":
            env["SM_INGEST_BAD_CHUNK_ACTION"] = "skip"
        elif mode == "budget":
            env["SM_INGEST_BAD_CHUNK_ACTION"] = "skip"
            env["SM_INGEST_MAX_BAD_CHUNKS"] = "1"
        if rank == 1:
            if mode == "budget":
                # persistent corruption: every chunk from the 2nd hit on
                env["SM_FAULT_SPEC"] = "data.chunk:error:injected corruption@2+"
            else:
                env["SM_FAULT_SPEC"] = "data.chunk:error:injected corruption@2"
        out = open(os.path.join(workdir, "rank{}.out".format(rank)), "w")
        procs.append(
            (
                subprocess.Popen(
                    [
                        sys.executable,
                        os.path.abspath(__file__),
                        "--rank", str(rank),
                        "--n-ranks", str(N_RANKS),
                        "--workdir", workdir,
                        "--data-dir", data_dir,
                    ],
                    env=env,
                    stdout=out,
                    stderr=subprocess.STDOUT,
                ),
                out,
            )
        )
    codes = []
    for proc, out in procs:
        try:
            proc.wait(timeout=180)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        out.close()
        codes.append(proc.returncode)
    return codes


def _read(path):
    with open(path) as f:
        return f.read()


def _records(text, metric):
    prefix = '{{"metric": "{}"'.format(metric)
    return [json.loads(l) for l in text.splitlines() if l.startswith(prefix)]


def _check(ok, message, failures):
    print(("ok: " if ok else "FAIL: ") + message, flush=True)
    if not ok:
        failures.append(message)
    return ok


def _verify_skip(workdir, codes, failures):
    records = []
    for rank in range(N_RANKS):
        out = _read(os.path.join(workdir, "rank{}.out".format(rank)))
        _check(
            codes[rank] == 0,
            "rank {} completed ingest+train (rc={})".format(rank, codes[rank]),
            failures,
        )
        q = _records(out, "drill.quarantine")
        _check(bool(q), "rank {} emitted its quarantine record".format(rank), failures)
        records.append(q[0]["record"] if q else None)
        done = _records(out, "drill.done")
        _check(
            bool(done) and done[0]["rounds"] == NUM_ROUND,
            "rank {} trained all {} rounds on the surviving rows".format(
                rank, NUM_ROUND
            ),
            failures,
        )
    # THE rank-consistency assertion: both ranks agreed on the same skip set
    _check(
        records[0] is not None and records[0] == records[1],
        "both ranks hold the identical agreed quarantine record",
        failures,
    )
    _check(
        bool(records[0]) and records[0]["chunks_skipped"] >= 1
        and all(c["rank"] == 1 for c in records[0]["skipped_chunks"]),
        "quarantine names rank 1's corrupt chunk(s)",
        failures,
    )

    qpath = os.path.join(workdir, "model", "ingest-quarantine.json")
    _check(os.path.exists(qpath), "ingest-quarantine.json written", failures)
    model_path = os.path.join(workdir, "model", "xgboost-model")
    manifest_path = model_path + ".manifest"
    if os.path.exists(manifest_path):
        manifest = json.loads(_read(manifest_path))
        _check(
            manifest.get("quarantine", {}).get("chunks_skipped", 0) >= 1,
            "final model manifest carries the quarantine record",
            failures,
        )
    else:
        _check(False, "final model manifest exists", failures)
    try:
        from sagemaker_xgboost_container_tpu.serving import serve_utils

        serve_utils._load_verified(model_path)
        _check(True, "final model passes serving's verified load", failures)
    except Exception as e:
        _check(
            False,
            "final model passes serving's verified load ({})".format(e),
            failures,
        )


def _verify_exit85(workdir, codes, failures, mode):
    want_reason = "ingest_failed"
    for rank in range(N_RANKS):
        out = _read(os.path.join(workdir, "rank{}.out".format(rank)))
        _check(
            codes[rank] == 85,
            "{}: rank {} exits EXIT_INGEST_FAILED (rc={}, want 85)".format(
                mode, rank, codes[rank]
            ),
            failures,
        )
        aborts = _records(out, "training.abort")
        _check(
            bool(aborts)
            and aborts[0]["reason"] == want_reason
            and aborts[0]["exit_code"] == 85,
            "{}: rank {} training.abort names {}/85".format(mode, rank, want_reason),
            failures,
        )
        dump = aborts[0].get("flight_recorder") if aborts else None
        _check(
            bool(dump) and os.path.exists(dump),
            "{}: rank {} left a flight-recorder dump ({})".format(mode, rank, dump),
            failures,
        )


def _archive(workdir, artifact_dir, mode):
    dest = os.path.join(artifact_dir, mode)
    os.makedirs(dest, exist_ok=True)
    for name in sorted(os.listdir(workdir)):
        src = os.path.join(workdir, name)
        if name.endswith(".out"):
            shutil.copy2(src, dest)
        elif name.startswith("trace-rank") and os.path.isdir(src):
            for f in os.listdir(src):
                shutil.copy2(os.path.join(src, f), os.path.join(dest, f))
    for extra in ("model/ingest-quarantine.json", "model/xgboost-model.manifest"):
        p = os.path.join(workdir, extra)
        if os.path.exists(p):
            shutil.copy2(p, dest)
    print("artifacts archived under {}".format(dest), flush=True)


def parent_main(args):
    failures = []
    modes = [args.mode] if args.mode != "all" else ["skip", "fail", "budget"]
    artifact_dir = os.path.abspath(args.artifact_dir)
    os.makedirs(artifact_dir, exist_ok=True)
    for mode in modes:
        print("--- ingest drill: {} ---".format(mode), flush=True)
        workdir = tempfile.mkdtemp(prefix="ingest-{}-".format(mode))
        data_dir = os.path.join(workdir, "channel")
        try:
            _write_channel(data_dir)
            codes = _spawn(mode, workdir, data_dir)
            print("rank exit codes: {}".format(codes), flush=True)
            if mode == "skip":
                _verify_skip(workdir, codes, failures)
            else:
                _verify_exit85(workdir, codes, failures, mode)
            _archive(workdir, artifact_dir, mode)
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
    if failures:
        print("INGEST DRILL FAILED ({} assertion(s))".format(len(failures)), flush=True)
        return 1
    print("INGEST DRILL OK", flush=True)
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("artifact_dir", nargs="?", default=".ci-artifacts/ingest")
    parser.add_argument(
        "--mode", choices=["skip", "fail", "budget", "all"], default="all"
    )
    parser.add_argument("--rank", type=int, default=None)
    parser.add_argument("--n-ranks", type=int, default=N_RANKS)
    parser.add_argument("--workdir")
    parser.add_argument("--data-dir")
    args = parser.parse_args(argv)
    if args.rank is not None:
        return rank_main(args)
    return parent_main(args)


if __name__ == "__main__":
    sys.exit(main())
