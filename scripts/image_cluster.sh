#!/bin/bash
# Multi-host built-image cluster tier (VERDICT r3 missing #1): run the
# shipping image as a 2-host docker-compose cluster against the fabricated
# SageMaker filesystem — the repo analog of the reference's local_mode
# compose harness (reference test/utils/local_mode.py:477-557) and its
# strongest guarantees:
#
#   cluster  — distributed train over ShardedByS3Key data completes on both
#              hosts and EXACTLY ONE host writes the model (reference bar:
#              test_early_stopping.py:57-68 "exactly one host saved")
#   kill     — SIGTERM mid-train with save_model_on_termination: exactly one
#              host persists the intermediate model (spot semantics)
#   mme      — multi-model endpoint REST lifecycle against a real
#              `docker run` (reference test_multiple_model_endpoint.py:32-101)
#
# Usage: scripts/image_cluster.sh [cluster|kill|mme|all|dry]
# cluster/kill/mme/all need Docker + compose (v2 `docker compose` or v1
# `docker-compose`) and network for the image build; exit 75 = environment
# cannot run them (SKIP). `dry` (VERDICT r4 #5) needs NEITHER: it validates
# everything checkable without a docker daemon — Dockerfile structure and
# COPY sources, the version contract + native-parser gates the build RUNs,
# compose-file syntax, and console-script entrypoint wiring — so hosts
# without Docker degrade to partial verification instead of a full skip.
set -uo pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
DOCKER="${DOCKER:-docker}"
TAG="${IMAGE_TAG:-sagemaker-xgboost-tpu:cluster}"
DATA_SRC="${ABALONE_DATA:-/root/reference/test/resources/abalone/data}"
WHAT="${1:-all}"

require_docker() {
  command -v "$DOCKER" >/dev/null || { echo "SKIP: $DOCKER not installed"; exit 75; }
  if "$DOCKER" compose version >/dev/null 2>&1; then
    COMPOSE=("$DOCKER" compose)
  elif command -v docker-compose >/dev/null 2>&1; then
    COMPOSE=(docker-compose)
  else
    echo "SKIP: no docker compose available"; exit 75
  fi

  echo "== build =="
  "$DOCKER" build -f "$REPO/docker/Dockerfile.tpu" \
    --build-arg JAX_SPEC="${JAX_SPEC:-jax}" -t "$TAG" "$REPO" || exit 1
}
if [ "$WHAT" != dry ]; then require_docker; fi

WORK="$(mktemp -d)"
CID=""
cleanup() {
  [ -n "$CID" ] && "$DOCKER" rm -f "$CID" >/dev/null 2>&1 || true
  [ -n "${COMPOSE+x}" ] && [ -f "$WORK/docker-compose.yml" ] \
    && (cd "$WORK" && "${COMPOSE[@]}" down -t 5 >/dev/null 2>&1) || true
  rm -rf "$WORK"
}
trap cleanup EXIT

fabricate_host_tree() {  # fabricate_host_tree <host> <num_round> <extra_hp_json>
  local host=$1 rounds=$2 extra=${3:-}
  local root="$WORK/$host/opt/ml"
  mkdir -p "$root"/{input/config,input/data/train,model,output/data}
  cat > "$root/input/config/hyperparameters.json" <<JSON
{"num_round": "$rounds", "objective": "reg:squarederror", "max_depth": "4",
 "eval_metric": "rmse"${extra:+, $extra}}
JSON
  cat > "$root/input/config/inputdataconfig.json" <<'JSON'
{"train": {"ContentType": "libsvm", "TrainingInputMode": "File",
           "S3DistributionType": "ShardedByS3Key"}}
JSON
  cat > "$root/input/config/resourceconfig.json" <<JSON
{"current_host": "$host", "hosts": ["algo-1", "algo-2"]}
JSON
}

write_compose() {
  cat > "$WORK/docker-compose.yml" <<YAML
services:
  algo-1:
    image: $TAG
    hostname: algo-1
    command: train
    volumes: ["$WORK/algo-1/opt/ml:/opt/ml"]
    environment: &env
      JAX_PLATFORMS: cpu
      SM_JAX_DISTRIBUTED: "on"
      GRAFT_HEARTBEAT_TIMEOUT_S: "30"
  algo-2:
    image: $TAG
    hostname: algo-2
    command: train
    volumes: ["$WORK/algo-2/opt/ml:/opt/ml"]
    environment: *env
YAML
}

count_models() {
  local n=0
  for h in algo-1 algo-2; do
    [ -f "$WORK/$h/opt/ml/model/xgboost-model" ] && n=$((n + 1))
  done
  echo "$n"
}

run_cluster() {
  echo "== cluster: 2-host distributed train (sharded data) =="
  rm -rf "$WORK/algo-1" "$WORK/algo-2"
  fabricate_host_tree algo-1 12
  fabricate_host_tree algo-2 12
  # the reference's 2 abalone shards: one per host (ShardedByS3Key)
  cp "$DATA_SRC/train/abalone.train_0" "$WORK/algo-1/opt/ml/input/data/train/"
  cp "$DATA_SRC/train/abalone.train_1" "$WORK/algo-2/opt/ml/input/data/train/"
  write_compose
  (cd "$WORK" && "${COMPOSE[@]}" up --exit-code-from algo-1) \
    || { echo "FAIL: cluster train"; return 1; }
  local n; n="$(count_models)"
  [ "$n" = 1 ] || { echo "FAIL: expected exactly 1 host to save, got $n"; return 1; }
  echo "CLUSTER TIER OK"
}

run_kill() {
  echo "== kill: SIGTERM mid-train, save_model_on_termination =="
  rm -rf "$WORK/algo-1" "$WORK/algo-2"
  fabricate_host_tree algo-1 100000 '"save_model_on_termination": "true"'
  fabricate_host_tree algo-2 100000 '"save_model_on_termination": "true"'
  cp "$DATA_SRC/train/abalone.train_0" "$WORK/algo-1/opt/ml/input/data/train/"
  cp "$DATA_SRC/train/abalone.train_1" "$WORK/algo-2/opt/ml/input/data/train/"
  write_compose
  (cd "$WORK" && "${COMPOSE[@]}" up -d) || { echo "FAIL: compose up"; return 1; }
  # wait until boosting has demonstrably started (a metric line appeared)
  local started=0
  for _ in $(seq 1 120); do
    if (cd "$WORK" && "${COMPOSE[@]}" logs 2>/dev/null) | grep -q '^\S*algo.*\[0\]'; then
      started=1; break
    fi
    sleep 2
  done
  [ "$started" = 1 ] || { echo "FAIL: training never started"; return 1; }
  sleep 4
  # SIGTERM both containers (spot interruption); 30s grace for the save
  (cd "$WORK" && "${COMPOSE[@]}" stop -t 30) || true
  local n; n="$(count_models)"
  [ "$n" = 1 ] || { echo "FAIL: expected exactly 1 intermediate model, got $n"; return 1; }
  echo "KILL TIER OK"
}

run_mme() {
  echo "== mme: multi-model endpoint REST lifecycle (docker run) =="
  local port="${MME_PORT:-18082}"
  local mdir="$WORK/mme-models"
  # train one single-host model to load twice under different names
  rm -rf "$WORK/algo-1"
  mkdir -p "$WORK/algo-1/opt/ml"/{input/config,input/data/train,model,output/data}
  cat > "$WORK/algo-1/opt/ml/input/config/hyperparameters.json" <<'JSON'
{"num_round": "8", "objective": "reg:squarederror", "max_depth": "3"}
JSON
  cat > "$WORK/algo-1/opt/ml/input/config/inputdataconfig.json" <<'JSON'
{"train": {"ContentType": "libsvm", "TrainingInputMode": "File",
           "S3DistributionType": "FullyReplicated"}}
JSON
  cat > "$WORK/algo-1/opt/ml/input/config/resourceconfig.json" <<'JSON'
{"current_host": "algo-1", "hosts": ["algo-1"]}
JSON
  cp "$DATA_SRC"/train/* "$WORK/algo-1/opt/ml/input/data/train/"
  "$DOCKER" run --rm -v "$WORK/algo-1/opt/ml:/opt/ml" -e JAX_PLATFORMS=cpu \
    "$TAG" train || { echo "FAIL: mme seed train"; return 1; }
  mkdir -p "$mdir/m1" "$mdir/m2"
  cp "$WORK/algo-1/opt/ml/model/xgboost-model" "$mdir/m1/"
  cp "$WORK/algo-1/opt/ml/model/xgboost-model" "$mdir/m2/"

  CID="$("$DOCKER" run -d -p "$port:8080" -v "$mdir:/models" \
    -e JAX_PLATFORMS=cpu -e SAGEMAKER_MULTI_MODEL=true "$TAG" serve)"
  for i in $(seq 1 60); do
    curl -sf "localhost:$port/ping" >/dev/null 2>&1 && break
    sleep 1
    [ "$i" = 60 ] && { echo "FAIL: MME never healthy"; "$DOCKER" logs "$CID"; return 1; }
  done
  # load / list / invoke / unload / reload — the MMS REST surface
  curl -sf -X POST "localhost:$port/models" \
    -H "Content-Type: application/json" \
    -d '{"model_name": "m1", "url": "/models/m1"}' >/dev/null \
    || { echo "FAIL: load m1"; return 1; }
  curl -sf -X POST "localhost:$port/models" \
    -H "Content-Type: application/json" \
    -d '{"model_name": "m2", "url": "/models/m2"}' >/dev/null \
    || { echo "FAIL: load m2"; return 1; }
  curl -s "localhost:$port/models" | grep -q '"m1"' \
    || { echo "FAIL: list"; return 1; }
  PRED="$(curl -s -X POST "localhost:$port/models/m1/invoke" \
    -H "Content-Type: text/libsvm" \
    -d "1:2 2:0.74 3:0.6 4:0.195 5:1.974 6:0.598 7:0.4085 8:0.71")"
  python3 -c "v = float('''$PRED'''.strip()); assert 0.0 < v < 30.0, v" \
    || { echo "FAIL: invoke ($PRED)"; return 1; }
  curl -sf -X DELETE "localhost:$port/models/m1" >/dev/null \
    || { echo "FAIL: unload"; return 1; }
  curl -s -o /dev/null -w "%{http_code}" \
    -X POST "localhost:$port/models/m1/invoke" -H "Content-Type: text/libsvm" \
    -d "1:2" | grep -q 404 || { echo "FAIL: invoke after unload not 404"; return 1; }
  curl -sf -X POST "localhost:$port/models" \
    -H "Content-Type: application/json" \
    -d '{"model_name": "m1", "url": "/models/m1"}' >/dev/null \
    || { echo "FAIL: reload"; return 1; }
  "$DOCKER" rm -f "$CID" >/dev/null 2>&1; CID=""
  echo "MME TIER OK"
}

run_dry() {
  echo "== dry: image-tier checks that need no docker daemon =="

  echo "-- dockerfile structure + COPY sources"
  python3 - "$REPO/docker/Dockerfile.tpu" "$REPO" <<'EOF' || return 1
import re, sys

path, ctx = sys.argv[1], sys.argv[2]
KNOWN = {"FROM", "RUN", "COPY", "ADD", "ARG", "ENV", "ENTRYPOINT", "CMD",
         "EXPOSE", "WORKDIR", "USER", "LABEL", "VOLUME", "SHELL",
         "HEALTHCHECK", "STOPSIGNAL", "ONBUILD"}
# join line continuations, drop comments/blanks
raw = open(path).read()
lines, buf = [], ""
for line in raw.splitlines():
    if not buf and (not line.strip() or line.lstrip().startswith("#")):
        continue
    buf += line
    if buf.endswith("\\"):
        buf = buf[:-1] + " "
        continue
    lines.append(buf)
    buf = ""
assert not buf, "dangling line continuation"
instrs = []
for ln in lines:
    m = re.match(r"([A-Za-z]+)\s+(.*)", ln)
    assert m, f"unparseable line: {ln!r}"
    op = m.group(1).upper()
    assert op in KNOWN, f"unknown instruction {op}"
    instrs.append((op, m.group(2).strip()))
first_non_arg = next(op for op, _ in instrs if op != "ARG")
assert first_non_arg == "FROM", "first instruction must be FROM"
# no ENTRYPOINT/CMD by design: SageMaker invokes the image with the literal
# command "train"/"serve", resolved via PATH to the installed console
# scripts (wiring asserted in the entrypoint step below)
import os
for op, rest in instrs:
    if op in ("COPY", "ADD"):
        parts = [p for p in rest.split() if not p.startswith("--")]
        for src in parts[:-1]:
            assert os.path.exists(os.path.join(ctx, src.lstrip("/"))) or src == ".", \
                f"{op} source {src!r} missing from build context"
print(f"   {len(instrs)} instructions ok")
EOF

  echo "-- version contract + native parser (the gates the image build runs)"
  python3 -m sagemaker_xgboost_container_tpu.version_contract || return 1
  python3 -c "from sagemaker_xgboost_container_tpu.data import native; \
assert native.native_available(), 'native fastdata parser unavailable'" || return 1

  echo "-- compose file syntax"
  write_compose
  # dry skips require_docker, so detect compose here (without requiring it):
  # on docker hosts the real `compose config` validation runs even in dry
  if [ -z "${COMPOSE+x}" ]; then
    if command -v "$DOCKER" >/dev/null && "$DOCKER" compose version >/dev/null 2>&1; then
      COMPOSE=("$DOCKER" compose)
    elif command -v docker-compose >/dev/null 2>&1; then
      COMPOSE=(docker-compose)
    fi
  fi
  if [ -n "${COMPOSE+x}" ]; then
    (cd "$WORK" && "${COMPOSE[@]}" config -q) || return 1
  else
    python3 - "$WORK/docker-compose.yml" <<'EOF' || return 1
import sys

try:
    import yaml
except ImportError:  # minimal structural check without pyyaml
    text = open(sys.argv[1]).read()
    assert "services:" in text and "algo-1:" in text and "algo-2:" in text
    assert "&env" in text and "*env" in text, "anchor/alias pair missing"
    print("   structural check ok (no pyyaml)")
    sys.exit(0)
doc = yaml.safe_load(open(sys.argv[1]))
svcs = doc["services"]
assert set(svcs) == {"algo-1", "algo-2"}, svcs.keys()
for name, svc in svcs.items():
    assert svc["image"], name
    assert svc["command"] == "train", name
    assert svc["volumes"] and svc["volumes"][0].endswith(":/opt/ml"), name
    # the &env anchor must resolve to the same distributed-training env on both
    assert svc["environment"]["SM_JAX_DISTRIBUTED"] == "on", name
print("   yaml parse + anchor resolution ok")
EOF
  fi

  echo "-- entrypoint wiring (setup.py console scripts resolve + on PATH)"
  python3 - "$REPO" <<'EOF' || return 1
import configparser, importlib, os, re, shutil, sys

repo = sys.argv[1]
setup = open(os.path.join(repo, "setup.py")).read()
scripts = dict(re.findall(r"['\"](\w+)\s*=\s*([\w.:]+)['\"]", setup))
assert "train" in scripts and "serve" in scripts, scripts
for name, target in scripts.items():
    mod, func = target.split(":")
    m = importlib.import_module(mod)
    assert callable(getattr(m, func)), target
    # PATH presence is an env property (needs pip install); the image build
    # always installs, so locally it only warns
    exe = shutil.which(name)
    note = exe or "not on PATH here; image build installs it"
    print(f"   {name} -> {target} ({note})")
EOF

  echo "DRY TIER OK"
}

rc=0
case "$WHAT" in
  cluster) run_cluster || rc=1 ;;
  kill)    run_kill || rc=1 ;;
  mme)     run_mme || rc=1 ;;
  all)     run_cluster || rc=1; run_kill || rc=1; run_mme || rc=1 ;;
  dry)     run_dry || rc=1 ;;
  *) echo "usage: $0 [cluster|kill|mme|all|dry]"; exit 2 ;;
esac
[ $rc -eq 0 ] && echo "IMAGE CLUSTER OK"
exit $rc
